#!/usr/bin/env python
"""Quickstart: KMeans + PCA on the local device mesh.

Run on CPU (simulated 8-chip mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/kmeans_pca_quickstart.py
On a TPU host the same script uses every local chip automatically.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

from spark_rapids_ml_tpu.clustering import KMeans
from spark_rapids_ml_tpu.feature import PCA

rng = np.random.default_rng(0)
X = np.concatenate(
    [rng.normal(c, 1.0, (20_000, 32)).astype(np.float32) for c in (-5, 0, 5)]
)
df = pd.DataFrame({"features": list(X)})

kmeans = KMeans(k=3, maxIter=30, seed=1).fit(df)
print("centers (first dims):", np.asarray(kmeans.cluster_centers_)[:, 0])
print("inertia:", kmeans.inertia_)

pca = PCA(k=4, inputCol="features").fit(df)
print("explained variance ratio:", np.asarray(pca.explainedVariance)[:4])
out = pca.transform(df)
print("projected shape:", np.stack(out["pca_features"].to_numpy()).shape)
