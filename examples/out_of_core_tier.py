"""Out-of-core tier walkthrough: datasets larger than device memory.

The reference leans on CUDA managed memory (UVM/SAM) to fit beyond-GPU-memory
datasets (reference utils.py:184-241). The TPU rebuild replaces paging with
explicit streaming — and it is AUTOMATIC: any estimator whose input exceeds
`stream_threshold_bytes` routes onto its streamed path with identical results.
This example forces the threshold low so the routing is visible at demo sizes.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/out_of_core_tier.py
"""

import numpy as np
import pandas as pd

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.clustering import DBSCAN
from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors, NearestNeighbors

rng = np.random.default_rng(0)
n, d = 20_000, 16
centers = rng.normal(0, 10, (4, d)).astype(np.float32)
assign = rng.integers(0, 4, n)
X = (centers[assign] + rng.normal(0, 0.5, (n, d))).astype(np.float32)
df = pd.DataFrame({"features": list(X), "id": np.arange(n)})
df["label"] = (assign % 2).astype(np.float64)

# pretend the data does not fit: everything below streams (watch the log lines)
config.set("stream_threshold_bytes", 64 * 1024)
config.set("stream_batch_rows", 4096)
try:
    # allreduce family: streamed sufficient-statistics accumulation (exact)
    lr = LogisticRegression(regParam=0.01, featuresCol="features").fit(df)
    print("streamed LogReg n_iter:", lr.get_model_attributes()["n_iter"])

    # broadcast-replicate family: host-resident pairwise tiles
    labels = DBSCAN(eps=2.5, min_samples=5).fit(df).transform(df)["prediction"]
    print("streamed DBSCAN clusters:", len(set(labels) - {-1}))

    nn = NearestNeighbors(k=4, inputCol="features", idCol="id").fit(df)
    _, _, knn_df = nn.kneighbors(df.head(8))
    print("streamed exact kNN first row ids:", list(knn_df["indices"][0]))

    # ANN family: streamed IVF build, paged probe search
    ann = ApproximateNearestNeighbors(
        k=4, algorithm="ivfpq", inputCol="features", idCol="id",
        algoParams={"nlist": 32, "nprobe": 8, "M": 4, "n_bits": 6},
    ).fit(df)
    _, _, ann_df = ann.kneighbors(df.head(8))
    print("streamed IVF-PQ first row ids:", list(ann_df["indices"][0]))
finally:
    config.unset("stream_threshold_bytes")
    config.unset("stream_batch_rows")
print("out-of-core tier OK")
