#!/usr/bin/env python
"""Sparse logistic regression: CSR input trains through the O(nnz) ELL kernels and
predicts without ever densifying."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd
import scipy.sparse as sp

from spark_rapids_ml_tpu.classification import LogisticRegression

rng = np.random.default_rng(0)
X = sp.random(50_000, 512, density=0.02, format="csr", dtype=np.float32, random_state=0)
coef = rng.normal(size=512)
y = (np.asarray(X @ coef).ravel() > 0).astype(np.float64)

df = pd.DataFrame({"features": [X.getrow(i) for i in range(X.shape[0])], "label": y})
model = LogisticRegression(regParam=1e-4, maxIter=50).fit(df)
acc = (model.transform(df)["prediction"].to_numpy() == y).mean()
print(f"train accuracy: {acc:.3f} (nnz={X.nnz}, never densified)")
