#!/usr/bin/env python
"""Approximate nearest neighbors: IVF-Flat, IVF-PQ and the CAGRA-class graph index."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

from spark_rapids_ml_tpu.knn import ApproximateNearestNeighbors

rng = np.random.default_rng(0)
items = rng.normal(size=(100_000, 64)).astype(np.float32)
queries = rng.normal(size=(100, 64)).astype(np.float32)
item_df = pd.DataFrame({"features": list(items)})
query_df = pd.DataFrame({"features": list(queries)})

for algo, params in [
    ("ivfflat", {"nlist": 128, "nprobe": 16}),
    ("ivfpq", {"nlist": 128, "nprobe": 16, "M": 8, "n_bits": 8}),
    ("cagra", {"graph_degree": 32, "itopk_size": 96}),
]:
    model = ApproximateNearestNeighbors(
        k=10, inputCol="features", algorithm=algo, algoParams=params
    ).fit(item_df)
    _, _, knn_df = model.kneighbors(query_df)
    print(algo, "first query neighbors:", knn_df["indices"].iloc[0][:5])
