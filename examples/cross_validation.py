#!/usr/bin/env python
"""CrossValidator: the whole param grid fits in ONE data pass per fold and the fold
evaluates in ONE transform scan (P6 multi-model-in-one-pass)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

from spark_rapids_ml_tpu.classification import LogisticRegression
from spark_rapids_ml_tpu.evaluation import MulticlassClassificationEvaluator
from spark_rapids_ml_tpu.tuning import CrossValidator, ParamGridBuilder

rng = np.random.default_rng(0)
X = np.concatenate([rng.normal(-1, 1, (3000, 16)), rng.normal(1, 1, (3000, 16))])
y = np.repeat([0.0, 1.0], 3000)
df = pd.DataFrame({"features": list(X.astype(np.float32)), "label": y})

lr = LogisticRegression(maxIter=50)
grid = ParamGridBuilder().addGrid(lr.regParam, [0.0, 0.01, 0.1]).build()
cv = CrossValidator(
    estimator=lr,
    estimatorParamMaps=grid,
    evaluator=MulticlassClassificationEvaluator(metricName="accuracy"),
    numFolds=3,
    seed=7,
)
cv_model = cv.fit(df)
print("avg metrics per grid point:", [round(m, 4) for m in cv_model.avgMetrics])
print("best regParam:", cv_model.bestModel.getOrDefault("regParam"))
