#!/usr/bin/env bash
# Build the native host-runtime library (see native/src/srml_native.cpp).
set -euo pipefail
cd "$(dirname "$0")"
mkdir -p ../spark_rapids_ml_tpu/lib
g++ -O3 -march=native -fopenmp -fPIC -shared -std=c++17 \
    src/srml_native.cpp -o ../spark_rapids_ml_tpu/lib/libsrml_native.so
echo "built spark_rapids_ml_tpu/lib/libsrml_native.so"
