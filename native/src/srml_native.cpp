//
// Native host-side runtime kernels for spark_rapids_ml_tpu.
//
// Role: the reference keeps its host/runtime hot paths native (cuDF ingest, treelite
// forest handling, RMM allocators — SURVEY.md §2.5); the TPU build's device math is
// XLA, but the HOST preprocessing around it deserves the same treatment. This module
// provides the host hot loops, exposed through a plain C ABI consumed via ctypes
// (no pybind11 in this image):
//
//   srml_bin_features   — feature quantile-digitization for the histogram forest
//                         builder (ops/trees.py bin_features): n*d binary searches,
//                         OpenMP-parallel over rows, cache-friendly per-row layout.
//   srml_csr_to_dense   — CSR -> dense row-major densification for the sparse ingest
//                         path (core/dataset.py), parallel over rows.
//   srml_topk_merge     — k-way merge of per-shard top-k (distance, id) candidate
//                         lists on the host, for merging device results across
//                         processes (the treelite-concat analog for kNN outputs).
//
// Build: native/build.sh (g++ -O3 -fopenmp -shared). Python loads it lazily via
// ctypes with a numpy fallback when the .so is absent (spark_rapids_ml_tpu/native.py).
//

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Digitize X (n x d, row-major float32) against per-feature ascending edges
// (d x (nbins-1), row-major float32): out[i,j] = #{e in edges[j] : e < x} clamped to
// [0, nbins-1]. Matches numpy searchsorted(side='left') semantics used by
// ops/trees.py bin_features.
void srml_bin_features(const float* X, int64_t n, int64_t d, const float* edges,
                       int64_t nbins, int32_t* out) {
  const int64_t ne = nbins - 1;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < n; ++i) {
    const float* row = X + i * d;
    int32_t* orow = out + i * d;
    for (int64_t j = 0; j < d; ++j) {
      const float* e = edges + j * ne;
      // branchless-ish binary search: first index with e[idx] >= x
      int64_t lo = 0, hi = ne;
      const float x = row[j];
      while (lo < hi) {
        const int64_t mid = (lo + hi) >> 1;
        if (e[mid] < x) {
          lo = mid + 1;
        } else {
          hi = mid;
        }
      }
      orow[j] = static_cast<int32_t>(lo);
    }
  }
}

// CSR (indptr int64, indices int32, data float32) -> dense row-major float32.
void srml_csr_to_dense(const int64_t* indptr, const int32_t* indices,
                       const float* data, int64_t n, int64_t d, float* out) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(dynamic, 64)
#endif
  for (int64_t i = 0; i < n; ++i) {
    float* row = out + i * d;
    std::memset(row, 0, sizeof(float) * d);
    for (int64_t p = indptr[i]; p < indptr[i + 1]; ++p) {
      row[indices[p]] = data[p];
    }
  }
}

// Merge S sorted-or-unsorted candidate lists of length kc per query into a global
// top-k (ascending by distance). dists/ids: (nq, S*kc) row-major. out: (nq, k).
void srml_topk_merge(const float* dists, const int64_t* ids, int64_t nq,
                     int64_t n_cand, int64_t k, float* out_d, int64_t* out_i) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t q = 0; q < nq; ++q) {
    const float* dq = dists + q * n_cand;
    const int64_t* iq = ids + q * n_cand;
    std::vector<int64_t> idx(n_cand);
    for (int64_t c = 0; c < n_cand; ++c) idx[c] = c;
    const int64_t kk = std::min(k, n_cand);
    std::partial_sort(idx.begin(), idx.begin() + kk, idx.end(),
                      [&](int64_t a, int64_t b) { return dq[a] < dq[b]; });
    for (int64_t c = 0; c < kk; ++c) {
      out_d[q * k + c] = dq[idx[c]];
      out_i[q * k + c] = iq[idx[c]];
    }
    for (int64_t c = kk; c < k; ++c) {
      out_d[q * k + c] = std::numeric_limits<float>::infinity();
      out_i[q * k + c] = -1;
    }
  }
}

// CSR -> ELL (padded row-wise) layout for the sparse device kernels
// (ops/sparse.py): out_vals/out_idx are (n x r_max) row-major, padding cells
// (value 0, column 0). Parallel over rows; each row is a straight copy.
void srml_csr_to_ell(const int64_t* indptr, const int32_t* indices,
                     const float* data, int64_t n, int64_t r_max, float* out_vals,
                     int32_t* out_idx) {
#if defined(_OPENMP)
#pragma omp parallel for schedule(dynamic, 128)
#endif
  for (int64_t i = 0; i < n; ++i) {
    float* vrow = out_vals + i * r_max;
    int32_t* irow = out_idx + i * r_max;
    const int64_t beg = indptr[i], len = indptr[i + 1] - beg;
    int64_t p = 0;
    for (; p < len; ++p) {
      vrow[p] = data[beg + p];
      irow[p] = indices[beg + p];
    }
    for (; p < r_max; ++p) {
      vrow[p] = 0.0f;
      irow[p] = 0;
    }
  }
}

int srml_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
