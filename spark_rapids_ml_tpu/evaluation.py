#
# pyspark.ml.evaluation-compatible evaluators, implemented standalone (pyspark is
# optional in this environment). The reference consumes pyspark's evaluators directly
# in its CrossValidator (reference tuning.py:92-157) and re-implements their math in
# metrics/ for the one-pass transform-evaluate path; here the evaluators themselves
# sit on the metrics/ reduction classes, so evaluator math and one-pass math cannot
# diverge.
#

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    Param,
    Params,
    TypeConverters,
)
from .metrics.MulticlassMetrics import (
    SUPPORTED_MULTI_CLASS_METRIC_NAMES,
    MulticlassMetrics,
)
from .metrics.RegressionMetrics import RegressionMetrics


def _col(dataset: Any, name: str) -> np.ndarray:
    arr = dataset[name].to_numpy()
    if arr.dtype == object:
        return np.stack(arr)
    return arr


class Evaluator(Params):
    """Base evaluator (pyspark.ml.evaluation.Evaluator surface)."""

    def evaluate(self, dataset: Any, params: Optional[dict] = None) -> float:
        if params:
            return self.copy(params).evaluate(dataset)
        from .core.dataset import _is_spark_df

        if _is_spark_df(dataset):
            if self.supportsPartialAggregation():
                # per-partition partials merged on the driver; the frame is
                # never collected (reference core.py:1572-1693 executor scan)
                from .spark.evaluate import evaluate_on_spark

                return evaluate_on_spark(self, dataset)
            # non-decomposable metric (AUC sweep, silhouette): collect just the
            # evaluator's columns
            dataset = dataset.toPandas()
        return self._evaluate(dataset)

    def _evaluate(self, dataset: Any) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True

    # ---- mergeable partial aggregation (the executor/driver split behind the
    # distributed one-pass transform+evaluate; reference computes the partials
    # executor-side at classification.py:117-159 / regression.py:149-178 and
    # merges on the driver) ----

    def supportsPartialAggregation(self) -> bool:
        """Whether this evaluator's metric decomposes into mergeable per-partition
        partials. Evaluators without it (AUC sweeps, silhouette) force a
        driver-side collect in the distributed evaluate path."""
        return False

    def _partial(self, dataset: Any) -> Any:
        """Compute this partition's mergeable partial from a minimal pandas frame
        of the evaluator's columns."""
        raise NotImplementedError

    def _evaluate_partials(self, partials: Any) -> float:
        """Merge partition partials and finish the metric."""
        import functools

        return self._finish_partial(
            functools.reduce(lambda a, b: a.merge(b), partials)
        )

    def _finish_partial(self, merged: Any) -> float:
        """Turn the fully-merged partial into the metric value."""
        raise NotImplementedError


class RegressionEvaluator(Evaluator, HasLabelCol, HasPredictionCol, HasWeightCol):
    """Metrics: rmse (default), mse, r2, mae, var."""

    metricName: Param[str] = Param(
        "undefined",
        "metricName",
        "metric name in evaluation (mse|rmse|r2|mae|var)",
        TypeConverters.toString,
    )

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            metricName="rmse", labelCol="label", predictionCol="prediction"
        )
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def setMetricName(self, value: str) -> "RegressionEvaluator":
        return self._set(metricName=value)  # type: ignore[return-value]

    def isLargerBetter(self) -> bool:
        return self.getMetricName() in ("r2", "var")

    def _evaluate(self, dataset: Any) -> float:
        return self._partial(dataset).evaluate(self.getMetricName())

    def supportsPartialAggregation(self) -> bool:
        return True

    def _partial(self, dataset: Any) -> RegressionMetrics:
        w = (
            _col(dataset, self.getOrDefault("weightCol"))
            if self.isDefined("weightCol")
            else None
        )
        return RegressionMetrics.from_predictions(
            _col(dataset, self.getOrDefault("labelCol")),
            _col(dataset, self.getOrDefault("predictionCol")),
            w,
        )

    def _finish_partial(self, merged: RegressionMetrics) -> float:
        return merged.evaluate(self.getMetricName())


class MulticlassClassificationEvaluator(
    Evaluator, HasLabelCol, HasPredictionCol, HasProbabilityCol, HasWeightCol
):
    """Metrics: f1 (default), accuracy, weighted*, *ByLabel, logLoss, hammingLoss."""

    metricName: Param[str] = Param(
        "undefined",
        "metricName",
        "metric name in evaluation " + "|".join(SUPPORTED_MULTI_CLASS_METRIC_NAMES),
        TypeConverters.toString,
    )
    metricLabel: Param[float] = Param(
        "undefined",
        "metricLabel",
        "The class whose metric will be computed in *ByLabel metrics.",
        TypeConverters.toFloat,
    )
    beta: Param[float] = Param(
        "undefined",
        "beta",
        "beta value in weightedFMeasure|fMeasureByLabel.",
        TypeConverters.toFloat,
    )
    eps: Param[float] = Param(
        "undefined", "eps", "log-loss clamp epsilon.", TypeConverters.toFloat
    )

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            metricName="f1",
            metricLabel=0.0,
            beta=1.0,
            eps=1e-15,
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
        )
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def setMetricName(self, value: str) -> "MulticlassClassificationEvaluator":
        return self._set(metricName=value)  # type: ignore[return-value]

    def isLargerBetter(self) -> bool:
        return self.getMetricName() not in ("logLoss", "hammingLoss")

    def _evaluate(self, dataset: Any) -> float:
        return self._evaluate_partials([self._partial(dataset)])

    def supportsPartialAggregation(self) -> bool:
        return True

    def _partial(self, dataset: Any) -> MulticlassMetrics:
        probs = None
        if self.getMetricName() == "logLoss":
            probs = _col(dataset, self.getOrDefault("probabilityCol"))
        w = (
            _col(dataset, self.getOrDefault("weightCol"))
            if self.isDefined("weightCol")
            else None
        )
        return MulticlassMetrics.from_predictions(
            _col(dataset, self.getOrDefault("labelCol")),
            _col(dataset, self.getOrDefault("predictionCol")),
            w,
            probs,
            eps=self.getOrDefault("eps"),
        )

    def _finish_partial(self, merged: MulticlassMetrics) -> float:
        return merged.evaluate(
            self.getMetricName(),
            self.getOrDefault("metricLabel"),
            self.getOrDefault("beta"),
        )


class BinaryClassificationEvaluator(
    Evaluator, HasLabelCol, HasRawPredictionCol, HasWeightCol
):
    """Metrics: areaUnderROC (default), areaUnderPR — trapezoid integration over the
    score-sorted sweep, Spark BinaryClassificationMetrics semantics."""

    metricName: Param[str] = Param(
        "undefined",
        "metricName",
        "metric name in evaluation (areaUnderROC|areaUnderPR)",
        TypeConverters.toString,
    )

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            metricName="areaUnderROC", labelCol="label", rawPredictionCol="rawPrediction"
        )
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def _evaluate(self, dataset: Any) -> float:
        from .metrics.utils import (
            area_under_pr,
            area_under_roc,
            binary_classification_sweep,
        )

        raw = _col(dataset, self.getOrDefault("rawPredictionCol"))
        score = raw[:, 1] if raw.ndim == 2 else raw
        y = _col(dataset, self.getOrDefault("labelCol")).astype(np.float64)
        w = (
            _col(dataset, self.getOrDefault("weightCol")).astype(np.float64)
            if self.isDefined("weightCol")
            else None
        )
        tps, fps = binary_classification_sweep(score, y, w)
        if self.getMetricName() == "areaUnderROC":
            return area_under_roc(tps, fps)
        return area_under_pr(tps, fps)


class ClusteringEvaluator(Evaluator, HasFeaturesCol, HasPredictionCol, HasWeightCol):
    """Silhouette evaluator (pyspark.ml.evaluation.ClusteringEvaluator surface).

    Spark's silhouette for squaredEuclidean/cosine avoids the O(n^2) pairwise
    matrix with per-cluster sufficient statistics: the mean squared distance from a
    point to a cluster is ||x||^2 - 2 x.mu_C + mean||y||^2_C, so the whole
    computation is one (n, k) matmul against the cluster means — the MXU-shaped
    formulation of the same metric."""

    metricName: Param[str] = Param(
        "undefined", "metricName", "metric name in evaluation (silhouette)",
        TypeConverters.toString,
    )
    distanceMeasure: Param[str] = Param(
        "undefined", "distanceMeasure",
        "distance measure: squaredEuclidean or cosine",
        TypeConverters.toString,
    )

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            metricName="silhouette",
            distanceMeasure="squaredEuclidean",
            featuresCol="features",
            predictionCol="prediction",
        )
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def getDistanceMeasure(self) -> str:
        return self.getOrDefault("distanceMeasure")

    def setFeaturesCol(self, value: str) -> "ClusteringEvaluator":
        return self._set(featuresCol=value)

    def setPredictionCol(self, value: str) -> "ClusteringEvaluator":
        return self._set(predictionCol=value)

    def _evaluate(self, dataset: Any) -> float:
        if self.getMetricName() != "silhouette":
            raise ValueError(
                f"Unsupported metric '{self.getMetricName()}'; only 'silhouette'."
            )
        measure = self.getDistanceMeasure()
        if measure not in ("squaredEuclidean", "cosine"):
            raise ValueError(
                "distanceMeasure must be 'squaredEuclidean' or 'cosine', got "
                f"'{measure}'."
            )
        X = np.asarray(_col(dataset, self.getOrDefault("featuresCol")), np.float64)
        labels = np.asarray(
            _col(dataset, self.getOrDefault("predictionCol"))
        ).astype(np.int64)
        w = (
            np.asarray(_col(dataset, self.getOrDefault("weightCol")), np.float64)
            if self.isDefined("weightCol")
            else np.ones(len(labels), np.float64)
        )
        uniq, inv = np.unique(labels, return_inverse=True)
        k = len(uniq)
        if k < 2:
            raise ValueError("Silhouette requires at least 2 clusters.")
        if measure == "cosine":
            norms = np.linalg.norm(X, axis=1, keepdims=True)
            if np.any(norms == 0):
                raise ValueError("Cosine distance is undefined for zero vectors.")
            X = X / norms

        # weighted per-cluster stats: count, mean vector, mean squared norm
        Wc = np.zeros(k)
        np.add.at(Wc, inv, w)
        mu = np.zeros((k, X.shape[1]))
        np.add.at(mu, inv, X * w[:, None])
        mu /= Wc[:, None]
        x2 = np.sum(X * X, axis=1)

        if measure == "squaredEuclidean":
            m2 = np.zeros(k)
            np.add.at(m2, inv, w * x2)
            m2 /= Wc
            # meanSq[i, C] = ||x_i||^2 - 2 x_i.mu_C + mean||y||^2_C  (includes self
            # for C = own cluster; the self term contributes 0 to the sum)
            mean_d = x2[:, None] - 2.0 * (X @ mu.T) + m2[None, :]
        else:
            # mean cosine distance to cluster C = 1 - x_hat . psi_C
            mean_d = 1.0 - X @ mu.T
        mean_d = np.maximum(mean_d, 0.0)

        own = mean_d[np.arange(len(labels)), inv]
        Wown = Wc[inv]
        # exclude self from the own-cluster mean (self distance is 0)
        with np.errstate(divide="ignore", invalid="ignore"):
            a = np.where(Wown > w, Wown * own / np.maximum(Wown - w, 1e-300), 0.0)
        other = mean_d.copy()
        other[np.arange(len(labels)), inv] = np.inf
        b = other.min(axis=1)
        s = np.where(Wown > w, (b - a) / np.maximum(np.maximum(a, b), 1e-300), 0.0)
        return float(np.average(s, weights=w))
