#
# Estimator/model persistence (reference core.py:268-355).
#
# The reference saves Spark DefaultParamsWriter metadata plus a JSON attribute row;
# models are rebuilt from the attribute dict (core.py:1389-1396). The TPU format is a
# directory:
#   metadata.json      — class name, uid, user-set + default params, backend params
#   arrays.npz         — every ndarray-valued model attribute
#   attributes.json    — every non-array model attribute
# which keeps the "model == attribute dict" contract while storing arrays natively.
#

from __future__ import annotations

import importlib
import json
import os
import shutil
import time
from typing import Any, Dict, Optional, Type

import numpy as np

VERSION = "0.1.0"


def _json_default(o: Any) -> Any:
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def save_instance(instance: Any, path: str, overwrite: bool = False) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise IOError(f"Path {path} already exists; use write().overwrite().save(path).")
        shutil.rmtree(path)  # stale attribute/array files must not survive an overwrite
    os.makedirs(path, exist_ok=True)

    cls = type(instance)
    metadata: Dict[str, Any] = {
        "class": f"{cls.__module__}.{cls.__qualname__}",
        "timestamp": int(time.time() * 1000),
        "version": VERSION,
        "uid": instance.uid,
        "paramMap": {p.name: v for p, v in instance._paramMap.items()},
        "defaultParamMap": {p.name: v for p, v in instance._defaultParamMap.items()},
        "tpuParams": getattr(instance, "_tpu_params", {}),
        "numWorkers": getattr(instance, "_num_workers", None),
        "float32Inputs": getattr(instance, "_float32_inputs", True),
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(metadata, f, default=_json_default)

    attrs: Optional[Dict[str, Any]] = getattr(instance, "_model_attributes", None)
    # ANN-index-backed models (models/knn.py) store their array attributes
    # through the versioned, mmap-friendly index format instead of arrays.npz
    # (ops/ann_lifecycle.py, docs/design.md §7b): the hook returns
    # (arrays, algo, meta); those keys are excluded from the npz below and
    # load back lazily as copy-on-write memmaps.
    index_keys: set = set()
    spec_hook = getattr(instance, "_ann_index_spec", None)
    if attrs is not None and callable(spec_hook):
        spec = spec_hook()
        if spec is not None:
            from ..ops.ann_lifecycle import save_index

            index_arrays, algo, meta = spec
            save_index(
                os.path.join(path, "ann_index"), index_arrays,
                algo=algo, meta=meta,
            )
            index_keys = set(index_arrays)
    if attrs is not None:
        try:
            import scipy.sparse as sp
        except ImportError:  # pragma: no cover
            sp = None
        arrays = {}
        scalars = {}
        sparse_keys = []
        for k, v in attrs.items():
            if k in index_keys:
                continue
            if sp is not None and sp.issparse(v):
                # CSR attributes (sparse-fitted UMAP raw_data) store as their
                # component arrays; reassembled at load
                csr = v.tocsr()
                arrays[f"__csr_data__{k}"] = csr.data
                arrays[f"__csr_indices__{k}"] = csr.indices
                arrays[f"__csr_indptr__{k}"] = csr.indptr
                arrays[f"__csr_shape__{k}"] = np.asarray(csr.shape, np.int64)
                sparse_keys.append(k)
            elif isinstance(v, np.ndarray):
                arrays[k] = np.asarray(v)
            else:
                scalars[k] = v
        if sparse_keys:
            scalars["__sparse_attr_keys__"] = sparse_keys
        if arrays:
            np.savez(os.path.join(path, "arrays.npz"), **arrays)
        with open(os.path.join(path, "attributes.json"), "w") as f:
            json.dump(scalars, f, default=_json_default)


def load_metadata(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "metadata.json")) as f:
        return json.load(f)


def _resolve_class(qualname: str) -> Type:
    module_name, _, cls_name = qualname.rpartition(".")
    module = importlib.import_module(module_name)
    obj: Any = module
    for part in cls_name.split("."):
        obj = getattr(obj, part)
    return obj


def load_instance(path: str, expected_cls: Optional[Type] = None) -> Any:
    metadata = load_metadata(path)
    cls = _resolve_class(metadata["class"])
    if expected_cls is not None and not issubclass(cls, expected_cls):
        raise TypeError(
            f"Path {path} holds a {metadata['class']}, which is not a {expected_cls.__name__}"
        )

    attrs: Dict[str, Any] = {}
    attr_file = os.path.join(path, "attributes.json")
    if os.path.exists(attr_file):
        with open(attr_file) as f:
            attrs.update(json.load(f))
        npz_file = os.path.join(path, "arrays.npz")
        if os.path.exists(npz_file):
            with np.load(npz_file) as data:
                attrs.update({k: data[k] for k in data.files})
        index_dir = os.path.join(path, "ann_index")
        if os.path.isdir(index_dir):
            # lazy load: arrays come back as copy-on-write memmaps — no array
            # bytes are read until a search (or mutation) touches them
            from ..ops.ann_lifecycle import load_index

            index_arrays, manifest = load_index(index_dir)
            attrs.update(index_arrays)
            attrs["__ann_manifest__"] = manifest
        for k in attrs.pop("__sparse_attr_keys__", []):
            import scipy.sparse as sp

            attrs[k] = sp.csr_matrix(
                (
                    attrs.pop(f"__csr_data__{k}"),
                    attrs.pop(f"__csr_indices__{k}"),
                    attrs.pop(f"__csr_indptr__{k}"),
                ),
                shape=tuple(attrs.pop(f"__csr_shape__{k}")),
            )
        instance = cls._from_row(attrs)
    else:
        instance = cls()

    instance._resetUid(metadata["uid"])
    for name, value in metadata.get("defaultParamMap", {}).items():
        if instance.hasParam(name):
            instance._setDefault(**{name: value})
    for name, value in metadata.get("paramMap", {}).items():
        if instance.hasParam(name):
            instance._set(**{name: value})
    if hasattr(instance, "_tpu_params"):
        instance._tpu_params = dict(metadata.get("tpuParams", {}))
        instance._num_workers = metadata.get("numWorkers")
        instance._float32_inputs = metadata.get("float32Inputs", True)
    return instance


class ParamsWriter:
    """`instance.write().overwrite().save(path)` chain, mirroring pyspark's MLWriter."""

    def __init__(self, instance: Any):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "ParamsWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        save_instance(self._instance, path, overwrite=self._overwrite)


class ParamsReader:
    """`Cls.read().load(path)` chain, mirroring pyspark's MLReader."""

    def __init__(self, cls: Type):
        self._cls = cls

    def load(self, path: str) -> Any:
        return load_instance(path, self._cls)
