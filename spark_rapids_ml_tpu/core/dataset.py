#
# Input dataset abstraction (L3 of the layer map, SURVEY.md §1).
#
# The reference's data plane is a Spark DataFrame: `_pre_process_data` selects columns,
# casts to float32, unwraps VectorUDT / CSR (reference core.py:463-562,183-265), and
# `mapInPandas` streams Arrow batches into the worker python process (core.py:1005-1011).
#
# The TPU framework is Spark-optional: the same estimators accept
#   * pandas.DataFrame  — feature column of per-row lists/arrays, or multiple scalar
#                         columns (the reference's three feature layouts,
#                         tests/utils.py:81-147)
#   * numpy.ndarray     — a (n, d) design matrix used directly as features
#   * scipy.sparse csr  — sparse design matrix (reference sparse path core.py:220-265)
#   * pyspark DataFrame — when pyspark is installed (adapter converts via toPandas /
#                         mapInPandas in the plugin layer)
# and transform() returns the same flavor it was given with output columns appended.
#

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

import numpy as np

try:  # scipy is available in this image; keep soft anyway
    import scipy.sparse as sp

    _SCIPY = True
except ImportError:  # pragma: no cover
    _SCIPY = False

try:  # pyarrow is optional: it feeds the zero-copy ingest fast path (§6k)
    import pyarrow as pa

    _PYARROW = True
except ImportError:  # pragma: no cover
    _PYARROW = False


def _is_spark_df(dataset: Any) -> bool:
    mod = type(dataset).__module__
    return mod.startswith("pyspark.sql")


def _is_pandas_df(dataset: Any) -> bool:
    import pandas as pd

    return isinstance(dataset, pd.DataFrame)


def _is_sparse(x: Any) -> bool:
    return _SCIPY and sp.issparse(x)


def _is_arrow(dataset: Any) -> bool:
    return _PYARROW and isinstance(dataset, (pa.Table, pa.RecordBatch))


@dataclass
class FeatureData:
    """Extracted, host-side training data: the product of `_pre_process_data`."""

    features: Union[np.ndarray, "sp.csr_matrix"]  # (n, d)
    label: Optional[np.ndarray] = None  # (n,)
    weight: Optional[np.ndarray] = None  # (n,)
    row_id: Optional[np.ndarray] = None  # (n,) int64
    input_kind: str = "numpy"  # numpy | pandas | spark | sparse | arrow
    feature_layout: str = "array"  # array | multi_cols | vector | sparse
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def n_rows(self) -> int:
        return self.features.shape[0]

    @property
    def n_cols(self) -> int:
        return self.features.shape[1]

    @property
    def is_sparse(self) -> bool:
        return _is_sparse(self.features)


def _arrow_combined(col: Any) -> Any:
    """ChunkedArray/Array -> one contiguous Array. A single chunk is handed
    back as-is (zero-copy); combining multiple chunks copies — counted into
    the ingest ledger (ops/ingest.py) like any other host conversion."""
    if not isinstance(col, pa.ChunkedArray):
        return col
    if col.num_chunks == 1:
        return col.chunk(0)
    import time

    from ..ops.ingest import count_conversion

    t0 = time.perf_counter()
    out = col.combine_chunks()
    count_conversion(col.nbytes, time.perf_counter() - t0)
    return out


def _arrow_numpy(arr: Any) -> Optional[np.ndarray]:
    """Zero-copy numpy view of a primitive Arrow array; None when the buffer
    layout forbids one (nulls, non-primitive types)."""
    try:
        return arr.to_numpy(zero_copy_only=True)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, TypeError):
        return None


def _arrow_converted(arr: Any, dtype: np.dtype) -> np.ndarray:
    """Counted host-conversion fallback for an Arrow column."""
    import time

    from ..ops.ingest import count_conversion

    t0 = time.perf_counter()
    out = np.asarray(arr.to_numpy(zero_copy_only=False), dtype=dtype)
    count_conversion(out.nbytes, time.perf_counter() - t0)
    return out


def _extract_arrow(
    dataset: Any,
    input_col: Optional[str],
    input_cols: Optional[List[str]],
    label_col: Optional[str],
    weight_col: Optional[str],
    id_col: Optional[str],
    float32: bool,
) -> FeatureData:
    """Arrow Table/RecordBatch fast path (docs/design.md §6k): a null-free
    FixedSizeList feature column whose value buffer is device-castable maps
    to a (n, d) numpy VIEW in its SOURCE dtype — no densify-to-f32 — and the
    consuming accumulator kernels cast on device. Anything else (nulls,
    chunked buffers, exotic dtypes, multi-column layouts) falls back to a
    counted host conversion."""
    from ..ops.ingest import _device_castable

    dtype = np.float32 if float32 else np.float64
    names = list(dataset.schema.names)
    if dataset.num_rows == 0:
        raise RuntimeError(
            "Fit/transform input is empty (the reference raises on empty "
            "partitions too, core.py:959-962)."
        )
    label = weight = row_id = None
    if input_cols:
        missing = [c for c in input_cols if c not in names]
        if missing:
            raise ValueError(
                f"feature columns {missing} not found in dataset columns {names}"
            )
        stacked = [
            _arrow_combined(dataset.column(c)).to_numpy(zero_copy_only=False)
            for c in input_cols
        ]
        import time

        from ..ops.ingest import count_conversion

        t0 = time.perf_counter()
        X = np.stack(stacked, axis=1)
        if not _device_castable(X.dtype, dtype):
            X = X.astype(dtype)
        count_conversion(X.nbytes, time.perf_counter() - t0)
        layout = "multi_cols"
    elif input_col:
        if input_col not in names:
            raise ValueError(
                f"feature column '{input_col}' not found in dataset columns "
                f"{names}"
            )
        arr = _arrow_combined(dataset.column(input_col))
        X = None
        if pa.types.is_fixed_size_list(arr.type) and arr.null_count == 0:
            # flatten() (not .values) honors slice offsets; zero-copy when
            # the child carries no nulls
            flat = _arrow_numpy(arr.flatten())
            if flat is not None and _device_castable(flat.dtype, dtype):
                d = int(arr.type.list_size)
                X = flat.reshape(-1, d)
                from ..observability import counter_inc as obs_counter_inc

                obs_counter_inc("ingest.bytes_zero_copy", X.nbytes)
                obs_counter_inc("ingest.copies_avoided", 1)
        if X is None:
            # counted fallback through the pandas cell-stack path
            X = _stack_feature_column(dataset.column(input_col).to_pandas())
            import time

            from ..ops.ingest import count_conversion

            t0 = time.perf_counter()
            X = np.ascontiguousarray(X, dtype=dtype)
            count_conversion(X.nbytes, time.perf_counter() - t0)
        layout = "array"
    else:
        raise ValueError(
            "input_col or input_cols must be provided for Arrow input"
        )
    for col_name, kind in (
        (label_col, "label"), (weight_col, "weight"), (id_col, "id")
    ):
        if col_name is not None and col_name not in names:
            raise ValueError(
                f"{kind} column '{col_name}' not found in dataset columns "
                f"{names}"
            )
    if label_col is not None:
        arr = _arrow_combined(dataset.column(label_col))
        label = _arrow_numpy(arr) if arr.null_count == 0 else None
        if label is None or label.dtype != dtype:
            label = _arrow_converted(arr, dtype)
    if weight_col is not None:
        arr = _arrow_combined(dataset.column(weight_col))
        weight = _arrow_numpy(arr) if arr.null_count == 0 else None
        if weight is None or weight.dtype != dtype:
            weight = _arrow_converted(arr, dtype)
    if id_col is not None:
        arr = _arrow_combined(dataset.column(id_col))
        row_id = _arrow_numpy(arr) if arr.null_count == 0 else None
        if row_id is None or row_id.dtype != np.int64:
            row_id = _arrow_converted(arr, np.dtype(np.int64))
    return FeatureData(
        features=X,
        label=label,
        weight=weight,
        row_id=row_id,
        input_kind="arrow",
        feature_layout=layout,
    )


def _stack_feature_column(col: Any) -> np.ndarray:
    """A pandas column whose cells are lists/arrays/pyspark Vectors -> (n, d) float
    array (reference's ArrayType/VectorUDT unwrap, core.py:496-527)."""
    first = col.iloc[0]
    if np.isscalar(first):
        return col.to_numpy().reshape(-1, 1)
    if hasattr(first, "toArray"):  # pyspark.ml.linalg Dense/SparseVector cells
        return np.stack([v.toArray() for v in col.to_numpy()])
    return np.stack([np.asarray(v) for v in col.to_numpy()])


def extract_feature_data(
    dataset: Any,
    input_col: Optional[str] = None,
    input_cols: Optional[List[str]] = None,
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    id_col: Optional[str] = None,
    float32: bool = True,
) -> FeatureData:
    """Structural equivalent of _CumlCaller._pre_process_data (reference core.py:463-562):
    column selection + dtype casting + layout normalization, producing host arrays ready
    to shard onto the mesh."""
    dtype = np.float32 if float32 else np.float64

    if _is_spark_df(dataset):
        pdf = dataset.toPandas()
        fd = extract_feature_data(
            pdf, input_col, input_cols, label_col, weight_col, id_col, float32
        )
        fd.input_kind = "spark"
        return fd

    if _is_sparse(dataset):
        X = dataset.tocsr().astype(dtype)
        return FeatureData(features=X, input_kind="sparse", feature_layout="sparse")

    if _is_arrow(dataset):
        return _extract_arrow(
            dataset, input_col, input_cols, label_col, weight_col, id_col,
            float32,
        )

    if isinstance(dataset, np.ndarray):
        X = np.atleast_2d(np.asarray(dataset, dtype=dtype))
        return FeatureData(features=X, input_kind="numpy", feature_layout="array")

    if isinstance(dataset, (list, tuple)) and dataset and isinstance(dataset[0], np.ndarray):
        # pre-partitioned arrays (one per worker shard)
        X = np.concatenate([np.asarray(a, dtype=dtype) for a in dataset], axis=0)
        return FeatureData(features=np.atleast_2d(X), input_kind="numpy", feature_layout="array")

    if _is_pandas_df(dataset):
        if len(dataset) == 0:
            raise RuntimeError(
                "Fit/transform input is empty (the reference raises on empty partitions "
                "too, core.py:959-962)."
            )
        label = weight = row_id = None
        if input_cols:
            missing = [c for c in input_cols if c not in dataset.columns]
            if missing:
                raise ValueError(
                    f"feature columns {missing} not found in dataset columns "
                    f"{list(dataset.columns)}"
                )
            X = dataset[list(input_cols)].to_numpy(dtype=dtype)
            layout = "multi_cols"
        elif input_col:
            if input_col not in dataset.columns:
                raise ValueError(
                    f"feature column '{input_col}' not found in dataset columns "
                    f"{list(dataset.columns)}"
                )
            cell = dataset[input_col].iloc[0]
            if _is_sparse(cell):
                X = sp.vstack(list(dataset[input_col].to_numpy())).tocsr().astype(dtype)
                layout = "sparse"
            else:
                X = _stack_feature_column(dataset[input_col]).astype(dtype)
                layout = "array"
        else:
            raise ValueError("input_col or input_cols must be provided for DataFrame input")
        for col_name, kind in ((label_col, "label"), (weight_col, "weight"), (id_col, "id")):
            if col_name is not None and col_name not in dataset.columns:
                raise ValueError(
                    f"{kind} column '{col_name}' not found in dataset columns "
                    f"{list(dataset.columns)}"
                )
        if label_col is not None:
            label = dataset[label_col].to_numpy(dtype=dtype)
        if weight_col is not None:
            weight = dataset[weight_col].to_numpy(dtype=dtype)
        if id_col is not None:
            row_id = dataset[id_col].to_numpy(dtype=np.int64)
        return FeatureData(
            features=X,
            label=label,
            weight=weight,
            row_id=row_id,
            input_kind="pandas",
            feature_layout=layout,
        )

    raise TypeError(f"Unsupported dataset type: {type(dataset)}")


def densify(features: Any, float32: bool = True) -> np.ndarray:
    """Dense (n, d) view of the features: CSR input goes through the native
    densify kernel (spark_rapids_ml_tpu/native.py, numpy/scipy fallback), dense input
    passes through."""
    if not _is_sparse(features):
        return features
    from ..native import csr_to_dense

    csr = features.tocsr()
    return csr_to_dense(
        csr.indptr,
        csr.indices,
        csr.data,
        csr.shape[0],
        csr.shape[1],
        dtype=np.float32 if float32 else np.float64,
    )


def ensure_dtype(X: np.ndarray, float32: bool = True) -> np.ndarray:
    """Host-cast a deferred-dtype dense block to the compute dtype, counted as
    an ingest conversion (docs/design.md §6k). The Arrow extraction fast path
    may hand back int/low-width float arrays unconverted — the STREAMED plane
    wants them that way (its kernels cast in-program, ops/ingest.py); the
    staged in-core and transform planes normalize here instead."""
    X = np.asarray(X)
    dt = np.float32 if float32 else np.float64
    if X.dtype == dt:
        return X
    import time

    from ..ops.ingest import count_conversion

    t0 = time.perf_counter()
    out = X.astype(dt)
    count_conversion(out.nbytes, time.perf_counter() - t0)
    return out


def ensure_id_col(dataset: Any, id_col_name: str) -> Any:
    """Add a monotonically-increasing id column when absent
    (reference params.py:110-129 `_ensureIdCol`)."""
    if _is_pandas_df(dataset):
        if id_col_name not in dataset.columns:
            dataset = dataset.copy()
            dataset[id_col_name] = np.arange(len(dataset), dtype=np.int64)
        return dataset
    return dataset


def append_output_columns(
    dataset: Any,
    outputs: Dict[str, np.ndarray],
    input_col_to_drop: Optional[str] = None,
) -> Any:
    """Append transform() outputs to the input, preserving its flavor
    (the reference appends Spark columns via withColumn, core.py:1846-1899)."""
    import pandas as pd

    def _colify(v: np.ndarray) -> Any:
        if v.ndim == 1:
            return v
        return list(v)  # one array cell per row, like a Spark array column

    if _is_spark_df(dataset):
        # keep the Spark flavor: compute on pandas, hand the result back to the session
        # (the plugin layer will stream this per-partition via mapInPandas instead)
        pdf = append_output_columns(dataset.toPandas(), outputs, input_col_to_drop)
        return dataset.sparkSession.createDataFrame(pdf)

    if _is_pandas_df(dataset):
        out = dataset.copy()
        for name, v in outputs.items():
            out[name] = _colify(v)
        return out

    # numpy / sparse input: outputs as a DataFrame (no original columns to carry)
    return pd.DataFrame({name: _colify(v) for name, v in outputs.items()})
