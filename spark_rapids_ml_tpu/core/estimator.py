#
# Estimator/Model framework (L5 of the layer map, SURVEY.md §1) — the structural
# equivalent of _CumlCaller/_CumlEstimator/_CumlModel
# (reference python/src/spark_rapids_ml/core.py:435-1967).
#
# Orchestration differences from the reference, by design (TPU-first):
#   * The reference fans out one barrier task per GPU and runs an opaque cuML MG kernel
#     per rank with NCCL inside (core.py:1005-1011). Here fit is ONE SPMD program: host
#     arrays are padded + sharded onto a jax Mesh (parallel/partition.py) and a single
#     jit-compiled fit function runs across all devices, XLA inserting the collectives.
#   * `_get_tpu_fit_func` returns a host-callable that consumes FitInputs (sharded
#     device arrays + PartitionDescriptor + param dict) and returns a dict of model
#     attributes — the analog of the model "rows" the reference collects
#     (core.py:996-1003, 1244-1267).
#   * CPU fallback targets sklearn twins instead of pyspark.ml twins
#     (reference core.py:1283-1297), since pyspark is optional here.
#

from __future__ import annotations

import threading
from abc import abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..parallel.partition import PartitionDescriptor, pad_rows
from ..parallel.partitioner import active_partitioner
from ..utils import get_logger
from .backend_params import _TpuClass, _TpuParams
from .dataset import (  # re-exported surface
    FeatureData,
    append_output_columns,
    densify,
    ensure_dtype,
    extract_feature_data,
)
from .params import ParamMap
from .persistence import ParamsReader, ParamsWriter


@dataclass
class FitInputs:
    """Everything a fit kernel sees; the analog of the (inputs, params) pair handed to
    `_get_cuml_fit_func` closures (reference core.py:604-635)."""

    features: Any  # jax.Array (padded_m, n), rows sharded over the data axis
    row_weight: Any  # jax.Array (padded_m,), 1.0 real / 0.0 padding, times sample weight
    label: Optional[Any] = None  # jax.Array (padded_m,)
    # ELL sparse alternative to `features` (ops/sparse.py): values/indices row-sharded;
    # when set, `features` is None and kernels must take the sparse path
    sparse_values: Optional[Any] = None  # jax.Array (padded_m, r)
    sparse_indices: Optional[Any] = None  # jax.Array (padded_m, r) int32/int64
    desc: Optional[PartitionDescriptor] = None
    mesh: Any = None
    params: Dict[str, Any] = field(default_factory=dict)
    dtype: Any = np.float32
    # host-side originals for algorithms that need them (trees, sparse paths)
    host_features: Optional[np.ndarray] = None
    host_label: Optional[np.ndarray] = None
    host_row_weight: Optional[np.ndarray] = None
    row_id: Optional[np.ndarray] = None
    # True when row_weight is PURELY the pad_rows suffix mask (no sample weights):
    # kernels may then take prefix-mask fast paths (ops/pallas_xtwx.py) that avoid
    # streaming a weight vector entirely
    unit_weight: bool = False


# type of the value returned by _get_tpu_fit_func
FitFunc = Callable[[FitInputs], Dict[str, Any]]


class _TpuCaller(_TpuClass, _TpuParams):
    """Shared data-prep + fan-out machinery (reference _CumlCaller, core.py:435-1065)."""

    def __init__(self) -> None:
        super().__init__()
        self.logger = get_logger(self.__class__)

    # ---- subclass hooks (contract mirrors reference core.py:450-635) ----

    @abstractmethod
    def _out_schema(self) -> List[str]:
        """Names of the model attributes produced by fit (the reference's model-row
        schema, core.py:450)."""

    @abstractmethod
    def _get_tpu_fit_func(
        self, extra_params: Optional[List[Dict[str, Any]]] = None
    ) -> FitFunc:
        """Return the fit kernel closure (reference core.py:604-635)."""

    def _fit_array_order(self) -> str:
        """Row-major by default (reference core.py:1015)."""
        return "C"

    def _use_label(self) -> bool:
        return False

    def _use_sample_weight(self) -> bool:
        return self.hasParam("weightCol") and self.isDefined("weightCol")

    def _repartition_needed(self) -> bool:
        return True

    # ---- data prep + execution ----

    def _pre_process_data(self, dataset: Any) -> FeatureData:
        # Spark ParamValidators equivalent (core/backend_params.py); the reference
        # validates through a throwaway pyspark estimator (core.py:579-602)
        self._validate_param_bounds()
        input_col, input_cols = self._get_input_columns()
        label_col = (
            self.getOrDefault("labelCol")
            if self._use_label() and self.hasParam("labelCol")
            else None
        )
        weight_col = (
            self.getOrDefault("weightCol")
            if self._use_sample_weight()
            else None
        )
        id_col = (
            self.getOrDefault("idCol")
            if self.hasParam("idCol") and self.isDefined("idCol")
            else None
        )
        return extract_feature_data(
            dataset,
            input_col=input_col,
            input_cols=input_cols,
            label_col=label_col,
            weight_col=weight_col,
            id_col=id_col,
            float32=self._float32_inputs,
        )

    def _supports_sparse_fit(self) -> bool:
        """Whether this estimator has a true sparse device kernel (ops/sparse.py).
        Estimators without one densify at ingest (the pre-round-2 behavior for all)."""
        return False

    def _sparse_fit_wanted(self, fd: FeatureData) -> bool:
        """Sparse-path gate, mirroring the reference's enable_sparse_data_optim
        semantics (params.py:45-66): None/unset = auto (sparse input stays sparse),
        False = force densify, True = require the sparse path."""
        if not fd.is_sparse:
            return False
        optim = (
            self.getOrDefault("enable_sparse_data_optim")
            if self.hasParam("enable_sparse_data_optim")
            and self.isDefined("enable_sparse_data_optim")
            else None
        )
        if optim is False:
            return False
        if not self._supports_sparse_fit():
            if optim is True:
                raise ValueError(
                    f"{type(self).__name__} has no sparse device kernel but "
                    "enable_sparse_data_optim=True was requested."
                )
            return False
        return True

    def _build_sparse_fit_inputs(self, fd: FeatureData) -> FitInputs:
        """ELL-format FitInputs: O(nnz) device memory, never densified
        (ops/sparse.py; reference sparse path classification.py:1002-1055)."""
        from ..ops.sparse import csr_to_ell, pad_ell_rows

        num_workers = self.num_workers
        part = active_partitioner(num_workers)
        mesh = part.mesh
        values, indices = csr_to_ell(fd.features, float32=self._float32_inputs)
        values, indices, pad_weight, (label_p, sw_p) = pad_ell_rows(
            values, indices, num_workers, fd.label, fd.weight
        )
        row_weight = pad_weight if sw_p is None else pad_weight * sw_p
        shard = values.shape[0] // num_workers
        rank_rows = [
            max(0, min(fd.n_rows - r * shard, shard)) for r in range(num_workers)
        ]
        desc = PartitionDescriptor.build(
            rank_rows, fd.n_cols, nnz=int(fd.features.nnz), padded_m=values.shape[0]
        )
        return FitInputs(
            features=None,
            sparse_values=part.shard(values),
            sparse_indices=part.shard(indices),
            row_weight=part.shard(row_weight),
            label=part.shard(label_p) if label_p is not None else None,
            desc=desc,
            mesh=mesh,
            params=dict(self._tpu_params),
            dtype=np.float32 if self._float32_inputs else np.float64,
            host_label=fd.label,
            host_row_weight=fd.weight,
            row_id=fd.row_id,
            unit_weight=sw_p is None,
        )

    def _build_fit_inputs(self, fd: FeatureData) -> FitInputs:
        if self._sparse_fit_wanted(fd):
            return self._build_sparse_fit_inputs(fd)
        num_workers = self.num_workers
        part = active_partitioner(num_workers)
        mesh = part.mesh

        # the Arrow fast path may defer dtype conversion (core/dataset.py); the
        # staged in-core plane materializes the whole matrix anyway, so the
        # counted host cast happens here (streamed fits cast in-program instead)
        X = ensure_dtype(
            densify(fd.features, float32=self._float32_inputs),
            float32=self._float32_inputs,
        )
        X = np.asarray(X, order=self._fit_array_order())  # type: ignore[arg-type]
        Xp, pad_weight, (label_p, sw_p) = pad_rows(X, num_workers, fd.label, fd.weight)
        row_weight = pad_weight if sw_p is None else pad_weight * sw_p

        # real-row counts per rank under the actual contiguous equal-shard layout:
        # rank r owns padded rows [r*s, (r+1)*s); rows >= n_rows are padding
        shard = Xp.shape[0] // num_workers
        rank_rows = [
            max(0, min(fd.n_rows - r * shard, shard)) for r in range(num_workers)
        ]
        desc = PartitionDescriptor.build(
            rank_rows,
            fd.n_cols,
            nnz=-1,
            padded_m=Xp.shape[0],
        )

        return FitInputs(
            features=part.shard(Xp),
            row_weight=part.shard(row_weight),
            label=part.shard(label_p) if label_p is not None else None,
            desc=desc,
            mesh=mesh,
            params=dict(self._tpu_params),
            dtype=np.float32 if self._float32_inputs else np.float64,
            host_features=X,
            host_label=fd.label,
            host_row_weight=fd.weight,
            row_id=fd.row_id,
            unit_weight=sw_p is None,
        )

    def _build_fit_inputs_from_global(
        self,
        X_global: Any,
        row_weight_global: Any,
        label_global: Optional[Any],
        total_rows: int,
        mesh: Any,
        rank_rows: Optional[List[int]] = None,
        unit_weight: bool = False,
    ) -> FitInputs:
        """FitInputs from pre-placed GLOBAL arrays (multi-host Spark path,
        spark/integration.py: each process contributed its local shard via
        jax.make_array_from_process_local_data). `rank_rows` carries the true
        per-process real-row counts when the caller knows them (allGathered
        PartitionInfo); otherwise a contiguous layout is assumed. `unit_weight`
        asserts the caller built row_weight purely as per-process suffix pad
        masks (no sample weights) — each device shard is then a prefix mask and
        kernels may take the fused pallas paths (ops/pallas_xtwx.py)."""
        n_dev = mesh.devices.size
        padded_m = X_global.shape[0]
        if rank_rows is None:
            shard = padded_m // n_dev
            rank_rows = [
                max(0, min(total_rows - r * shard, shard)) for r in range(n_dev)
            ]
        desc = PartitionDescriptor.build(
            rank_rows, X_global.shape[1], padded_m=padded_m
        )
        return FitInputs(
            features=X_global,
            row_weight=row_weight_global,
            label=label_global,
            desc=desc,
            mesh=mesh,
            params=dict(self._tpu_params),
            dtype=np.float32 if self._float32_inputs else np.float64,
            unit_weight=unit_weight,
        )

    def _build_sparse_fit_inputs_from_global(
        self,
        values_global: Any,
        indices_global: Any,
        row_weight_global: Any,
        label_global: Optional[Any],
        total_rows: int,
        n_cols: int,
        mesh: Any,
        rank_rows: Optional[List[int]] = None,
        nnz: int = -1,
        unit_weight: bool = False,
    ) -> FitInputs:
        """Sparse twin of _build_fit_inputs_from_global: ELL arrays already padded to
        the global max row-width and placed on the mesh (spark/integration.py pads
        each host's local ELL to the allGathered global width first)."""
        n_dev = mesh.devices.size
        padded_m = values_global.shape[0]
        if rank_rows is None:
            shard = padded_m // n_dev
            rank_rows = [
                max(0, min(total_rows - r * shard, shard)) for r in range(n_dev)
            ]
        desc = PartitionDescriptor.build(rank_rows, n_cols, nnz=nnz, padded_m=padded_m)
        return FitInputs(
            features=None,
            sparse_values=values_global,
            sparse_indices=indices_global,
            row_weight=row_weight_global,
            label=label_global,
            desc=desc,
            mesh=mesh,
            params=dict(self._tpu_params),
            dtype=np.float32 if self._float32_inputs else np.float64,
            unit_weight=unit_weight,
        )

    def _call_tpu_fit_func(
        self, dataset: Any, extra_params: Optional[List[Dict[str, Any]]] = None
    ) -> List[Dict[str, Any]]:
        """Run the fit kernel over the mesh and return model-attribute dicts, one per
        fitted model (reference _call_cuml_fit_func, core.py:742-1011)."""
        fd = self._pre_process_data(dataset)
        if fd.n_rows == 0:
            raise RuntimeError(
                "Fit input is empty. An empty partition would hang the reference's "
                "barrier stage (core.py:959-962); here it is a direct error."
            )
        from .. import config as _config
        from ..profiling import span, trace

        verbose = bool(self.getOrDefault("verbose")) if self.hasParam("verbose") else False
        verbose = verbose or bool(_config.get("verbose"))

        # out-of-core path: stream batches through the device instead of staging the
        # whole design matrix (the reference's UVM/SAM role; ops/streaming.py)
        threshold = _config.get("stream_threshold_bytes")
        feature_bytes = fd.n_rows * fd.n_cols * (4 if self._float32_inputs else 8)
        if (
            extra_params is None
            and threshold
            and feature_bytes > threshold
            and hasattr(self, "_streaming_fit")
        ):
            self.logger.info(
                "design matrix ~%.0f MiB exceeds stream_threshold_bytes=%d; using "
                "the streamed out-of-core fit path",
                feature_bytes / 2**20,
                threshold,
            )
            # the HBM batch cache lives exactly as long as this fit: pass 1 of a
            # multi-pass streamed fit retains its device batches, later passes
            # replay them, and everything frees at fit exit (ops/device_cache.py)
            from ..ops.device_cache import batch_cache

            with trace(_config.get("trace_dir")):
                with span(f"{type(self).__name__}.fit_streaming", verbose):
                    with batch_cache():
                        return [self._streaming_fit(fd)]

        with trace(_config.get("trace_dir")):
            with span(f"{type(self).__name__}.prepare", verbose):
                inputs = self._build_fit_inputs(fd)
            fit_func = self._get_tpu_fit_func(extra_params)
            with span(f"{type(self).__name__}.fit", verbose):
                result = fit_func(inputs)
        if isinstance(result, list):
            return result
        return [result]


class _TpuEstimator(_TpuCaller):
    """Abstract estimator (reference _CumlEstimator, core.py:1067-1354)."""

    @abstractmethod
    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "_TpuModel":
        """Build the model object from fit attributes (reference core.py:1084)."""

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        """Whether fitMultiple can run every param map in one data pass
        (reference core.py:1172)."""
        return False

    def fit(self, dataset: Any, params: Optional[Union[ParamMap, List[ParamMap]]] = None) -> Any:
        if params is None:
            return self._fit(dataset)
        if isinstance(params, (list, tuple)):
            models: List[Optional[_TpuModel]] = [None] * len(params)
            for index, model in self.fitMultiple(dataset, list(params)):
                models[index] = model
            return models
        if isinstance(params, dict):
            return self.copy(params)._fit(dataset)
        raise TypeError(f"params must be a param map or list of maps, got {type(params)}")

    def fitMultiple(
        self, dataset: Any, paramMaps: List[ParamMap]
    ) -> Iterator[Tuple[int, "_TpuModel"]]:
        """Fit for each param map; in single-pass mode all models come from one sweep
        over the (already device-resident) data (reference core.py:1177-1228)."""
        per_map_estimators = [self.copy(m) for m in paramMaps]
        # single-pass mode ships each map as a backend-param dict; a map touching a
        # param with no backend mapping ("" or None — e.g. coefficient bounds,
        # column names) cannot be represented there and must fit per map
        mapping = self._param_mapping() if isinstance(self, _TpuClass) else {}
        maps_backend_repr = all(
            mapping.get(param.name) not in ("", None)
            for m in paramMaps
            for param in m
        )
        if (
            maps_backend_repr
            and self._enable_fit_multiple_in_single_pass()
            and not any(est._use_cpu_fallback() for est in per_map_estimators)
        ):
            extra = [dict(est._tpu_params) for est in per_map_estimators]
            models = self.copy()._fit_internal(dataset, extra)
            return _FitMultipleIterator(lambda i: models[i], len(paramMaps))
        else:
            def fit_single(index: int) -> "_TpuModel":
                return self.copy(paramMaps[index])._fit(dataset)

            return _FitMultipleIterator(fit_single, len(paramMaps))

    def _fit_internal(
        self, dataset: Any, extra_params: Optional[List[Dict[str, Any]]]
    ) -> List["_TpuModel"]:
        attr_rows = self._call_tpu_fit_func(dataset, extra_params)
        models = []
        for attrs in attr_rows:
            model = self._create_pyspark_model(attrs)
            model._num_workers = self._num_workers
            model._float32_inputs = self._float32_inputs
            # freshly-fit marker: training summaries exist only on fit() results,
            # never after save/load (Spark semantics)
            model._has_training_summary = True
            self._copyValues(model)
            models.append(model)
        return models

    def _fit(self, dataset: Any) -> "_TpuModel":
        # validate on the DRIVER before any dispatch — BEFORE the run scope
        # opens: a bad param is API surface, not a fit worth a report
        # (_TpuModel.transform performs the same driver-side check for the
        # transform plane)
        self._validate_param_bounds()
        from ..observability import fit_run

        # one FitRun spans the whole degradation ladder (barrier -> collect ->
        # CPU): every span/counter/event fired anywhere below — including
        # barrier-worker snapshots merged by fit_on_spark — lands in one
        # structured report, attached to the trained model as
        # `model.fit_report_` (docs/design.md §6d)
        with fit_run(algo=type(self).__name__) as run:
            model = self._fit_dispatch(dataset)
        if run is not None:
            model.fit_report_ = run.report()
        return model

    def _fit_dispatch(self, dataset: Any) -> "_TpuModel":
        armed = getattr(self, "_fallback_requested_params", set())
        if armed and not self._fallback_enabled:
            # silent wrong results are worse than a clear error: with fallback
            # disabled, a param the TPU backend can't honor must stop the fit
            # (reference raises in the same situation, core.py:1283-1297)
            raise ValueError(
                f"Params {sorted(armed)} are not supported by the TPU backend and "
                f"CPU fallback is disabled (config fallback.enabled)."
            )
        if self._use_cpu_fallback():
            return self._fallback_fit(dataset)
        if self._spark_fit_wanted(dataset):
            from .. import config as _config
            from .. import profiling
            from ..spark.integration import fit_on_spark

            try:
                return fit_on_spark(self, dataset, num_hosts=self.num_workers)
            except Exception as e:
                # degradation ladder rung 1: the barrier stage already retried
                # inside fit_on_spark; a still-failing barrier plane degrades to
                # collect mode (driver materialization) instead of aborting —
                # slower, never wrong (both planes run the same fit program).
                # Only stage-class failures degrade: param/programming errors
                # (ValueError-class) would fail identically in collect mode and
                # must surface as themselves, not as a mode switch.
                from ..reliability import is_stage_retryable

                if not (
                    is_stage_retryable(e)
                    and bool(_config.get("reliability.enabled"))
                    and bool(_config.get("reliability.degrade_to_collect"))
                ):
                    raise
                profiling.count("reliability.degrade.barrier_to_collect")
                from ..observability import current_run, event as _obs_event
                from ..observability.flight import dump_postmortem

                _obs_event(
                    "degrade", rung="barrier_to_collect",
                    error=type(e).__name__,
                )
                # degradation-ladder entry is a reliability incident: dump the
                # flight-recorder bundle now, while the ring still holds the
                # failure's trail (observability/flight.py; never raises)
                dump_postmortem(
                    current_run(), reason="degrade:barrier_to_collect"
                )
                self.logger.warning(
                    "barrier fit plane failed (%s: %s); degrading to collect "
                    "mode for this fit",
                    type(e).__name__,
                    e,
                )
        return self._fit_device_or_cpu(dataset)

    def _fit_device_or_cpu(self, dataset: Any) -> "_TpuModel":
        """Last rungs of the degradation ladder: run the local (or collect-mode)
        device fit; an UNRECOVERABLE device error — never retried, see
        reliability.faults.is_device_error — routes into the existing
        fallback.enabled CPU path instead of raising."""
        from .. import config as _config
        from .. import profiling
        from ..reliability import is_device_error

        try:
            return self._fit_internal(dataset, None)[0]
        except Exception as e:
            if not (
                is_device_error(e)
                and bool(_config.get("reliability.enabled"))
                and self._fallback_enabled
                and self._fallback_class() is not None
            ):
                raise
            profiling.count("reliability.degrade.device_to_cpu")
            from ..observability import current_run, event as _obs_event
            from ..observability.flight import dump_postmortem

            _obs_event("degrade", rung="device_to_cpu", error=type(e).__name__)
            # same forensics contract as the barrier→collect rung (§6g)
            dump_postmortem(current_run(), reason="degrade:device_to_cpu")
            self.logger.warning(
                "unrecoverable device error (%s: %s); degrading to the CPU "
                "fallback path (config fallback.enabled)",
                type(e).__name__,
                e,
            )
            try:
                return self._fallback_fit(dataset)
            except NotImplementedError:
                raise e from None

    def _spark_fit_wanted(self, dataset: Any) -> bool:
        """Whether a Spark-DataFrame fit should fan out as barrier tasks
        (spark/integration.py) instead of collecting to the driver. 'auto' uses the
        barrier plane whenever a real pyspark is importable — driver collection at
        reference scale is an OOM, not a slowdown (VERDICT r1 missing #2)."""
        from .dataset import _is_spark_df

        if not _is_spark_df(dataset):
            return False
        from .. import config as _config

        mode = str(_config.get("spark_fit_mode")).lower()
        if mode == "collect":
            return False
        if mode == "barrier":
            return True
        # auto: require a REAL pyspark distribution. `import pyspark` is not enough —
        # the no-import-change interposer (install.py) plants stub parent modules at
        # sys.modules["pyspark"] in pyspark-less environments.
        import importlib.util

        try:
            return importlib.util.find_spec("pyspark.sql") is not None
        except (ImportError, ValueError):
            return False

    # params that neither the TPU backend nor the sklearn twin can honor — the
    # reference's pyspark fallback CAN honor them (e.g. box constraints, leafCol),
    # so silently dropping them here would return wrong results, not slower ones
    _FALLBACK_CANNOT_HONOR: frozenset = frozenset()

    def _fallback_fit(self, dataset: Any) -> "_TpuModel":
        """CPU fallback via the sklearn twin (the reference falls back to pyspark.ml,
        core.py:1283-1297). Subclasses implement `_fit_fallback_model` to run the twin
        and translate its fitted attributes into this framework's model."""
        twin = self._fallback_class()
        reasons = getattr(self, "_fallback_requested_params", set())
        dishonored = reasons & self._FALLBACK_CANNOT_HONOR
        if dishonored:
            raise ValueError(
                f"Params {sorted(dishonored)} are not supported by the TPU backend, "
                f"and the sklearn fallback cannot honor them either; use Spark ML "
                f"directly for these."
            )
        if twin is None:
            raise NotImplementedError(
                f"{self.__class__.__name__} has unsupported params {reasons} "
                f"and no CPU fallback class."
            )
        self.logger.warning(
            "Falling back to CPU %s.%s for unsupported params %s "
            "(reference falls back to pyspark.ml, core.py:1283-1297).",
            twin.__module__,
            twin.__name__,
            reasons,
        )
        fd = self._pre_process_data(dataset)
        attrs = self._fit_fallback_model(twin, fd)
        model = self._create_pyspark_model(attrs)
        model._num_workers = self._num_workers
        model._float32_inputs = self._float32_inputs
        self._copyValues(model)
        return model

    def _fit_fallback_model(self, twin: type, fd: FeatureData) -> Dict[str, Any]:
        """Fit the CPU twin on host data and return this estimator's model-attribute
        dict. Subclasses with a _fallback_class must override."""
        raise NotImplementedError(
            f"{self.__class__.__name__} does not implement the CPU fallback translation."
        )

    # ---- persistence (reference core.py:268-307) ----

    def write(self) -> ParamsWriter:
        return ParamsWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> ParamsReader:
        return ParamsReader(cls)

    @classmethod
    def load(cls, path: str) -> Any:
        return cls.read().load(path)


class _FitMultipleIterator:
    """Thread-safe iterator over (index, model) (reference core.py:1022-1064)."""

    def __init__(self, fitSingleModel: Callable[[int], "_TpuModel"], numModels: int):
        self.fitSingleModel = fitSingleModel
        self.numModels = numModels
        self.counter = 0
        self.lock = threading.Lock()

    def __iter__(self) -> "_FitMultipleIterator":
        return self

    def __next__(self) -> Tuple[int, "_TpuModel"]:
        with self.lock:
            index = self.counter
            if index >= self.numModels:
                raise StopIteration("No models remaining.")
            self.counter += 1
        return index, self.fitSingleModel(index)

    next = __next__


class _TpuModel(_TpuClass, _TpuParams):
    """Abstract fitted model (reference _CumlModel, core.py:1356-1754).

    Holds the fit-produced attribute dict; transform() extracts features, runs the
    jitted predict kernel batch-wise, and appends output columns preserving the input
    dataset flavor."""

    def __init__(self, **model_attributes: Any) -> None:
        super().__init__()
        self._model_attributes: Dict[str, Any] = model_attributes
        self.logger = get_logger(self.__class__)

    def get_model_attributes(self) -> Dict[str, Any]:
        return self._model_attributes

    @property
    def n_cols(self) -> Optional[int]:
        """Number of input features, inferred from the fitted attributes (the
        reference stores n_cols on every model; here it derives from whichever
        fitted array carries the feature dimension)."""
        a = self._model_attributes
        for key in (
            "cluster_centers", "components", "coefficients", "mean", "raw_data",
            "bin_edges", "item_features", "items",
        ):
            v = a.get(key)
            if v is not None and hasattr(v, "shape") and len(v.shape) >= 1:
                return int(v.shape[-1]) if len(v.shape) > 1 else int(v.shape[0])
        return None

    @property
    def dtype(self) -> str:
        """Training dtype (reference models expose cuML's dtype attribute)."""
        return "float32" if self._float32_inputs else "float64"

    @classmethod
    def _from_row(cls, attrs: Dict[str, Any]) -> "_TpuModel":
        """Rebuild from an attribute dict (reference core.py:1389-1396)."""
        return cls(**attrs)

    # ---- transform hooks ----

    @abstractmethod
    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        """Map a feature block to named output arrays (the reference's
        _get_cuml_transform_func closure pair, core.py:1398-1428)."""

    def _input_col_for_transform(self) -> Tuple[Optional[str], Optional[List[str]]]:
        return self._get_input_columns()

    def transform(self, dataset: Any, params: Optional[ParamMap] = None) -> Any:
        if params:
            return self.copy(params).transform(dataset)
        # driver-side bounds check BEFORE any dispatch (covers transform(params=...)
        # overrides and deferred-compute models like DBSCAN)
        self._validate_param_bounds()
        from .dataset import _is_spark_df

        if _is_spark_df(dataset):
            # per-partition streaming plane: model broadcast once, partitions never
            # leave the executors (reference core.py:1846-1899)
            from ..spark.transform import transform_on_spark

            return transform_on_spark(self, dataset)
        # inference-plane scope: one TransformRun per USER call (suppressed for
        # the per-batch recursion inside the distributed plane's UDF — there the
        # driver's run is the scope and this local call is the per-batch unit).
        # transform_batch is the single place rows/batches/latency are counted,
        # so local and distributed totals share one definition (§6e).
        from ..observability.inference import transform_batch, transform_run

        try:
            n_rows = len(dataset)
        except TypeError:
            n_rows = 0
        with transform_run(type(self).__name__) as run:
            with transform_batch(self, n_rows):
                input_col, input_cols = self._input_col_for_transform()
                fd = extract_feature_data(
                    dataset,
                    input_col=input_col,
                    input_cols=input_cols,
                    float32=self._float32_inputs,
                )
                if fd.is_sparse and self._supports_sparse_transform():
                    outputs = self._transform_sparse(fd.features)
                else:
                    X = ensure_dtype(
                        densify(fd.features, float32=self._float32_inputs),
                        float32=self._float32_inputs,
                    )
                    outputs = self._transform_arrays(X)
                out = append_output_columns(dataset, outputs)
        if run is not None:
            self.transform_report_ = run.report()
        return out

    def _supports_sparse_transform(self) -> bool:
        """Whether this model predicts on CSR input without densifying (ops/sparse
        ELL contractions); models without it densify the query block."""
        return False

    # ---- serving hooks (serving/, docs/design.md §7) ----
    #
    # The online serving plane coalesces many small requests into one padded
    # fixed-shape batch and slices per-request results back out. That is only
    # correct when a model's predict is ROW-INDEPENDENT: row i of the output
    # depends on row i of the input alone (true for every matmul/scan predict
    # kernel here). Models whose transform computes a function of the WHOLE
    # query set (DBSCAN clusters it, UMAP optimizes the joint embedding)
    # override `_serving_row_independent` to opt out — batch coalescing would
    # bleed information across requests and padding would change results.

    def _serving_row_independent(self) -> bool:
        return True

    def _serving_predict(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        """One serving batch: feature block -> named output arrays. The default
        IS the batch transform path (`_transform_arrays`) so the serving plane
        reuses each family's predict kernels un-forked; models whose transform
        surface is not array-shaped (kNN) override with an equivalent routed
        through the same predict_dispatch instrumentation."""
        return self._transform_arrays(X)

    def _serving_device_attrs(self) -> Tuple[str, ...]:
        """Names of fitted attributes the serving registry keeps HBM-resident
        (uploaded once at registration, reused as device operands every batch).
        Default: every float ndarray attribute — the weight matrices predict
        kernels consume. Models whose predict consumes other dtypes as device
        operands (tree forests) or uses some arrays host-side (kNN item_ids)
        override."""
        return tuple(
            k for k, v in self._model_attributes.items()
            if isinstance(v, np.ndarray)
            and v.dtype.kind == "f"
            and v.ndim >= 1
        )

    def _transform_sparse(self, csr: Any) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def _supportsTransformEvaluate(self) -> bool:
        """Whether transform+evaluate can run in one pass for CrossValidator
        (reference core.py:1306)."""
        return True

    def _transformEvaluate(self, dataset: Any, evaluator: Any) -> float:
        """Fused transform+evaluate used by CrossValidator: features extract once,
        predictions stay arrays, and only the evaluator's columns materialize (the
        reference's one-pass _transform_evaluate_internal, core.py:1572-1693)."""
        return transform_evaluate_multi([self], dataset, evaluator)[0]

    # ---- persistence (reference core.py:310-355) ----

    def write(self) -> ParamsWriter:
        return ParamsWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> ParamsReader:
        return ParamsReader(cls)

    @classmethod
    def load(cls, path: str) -> Any:
        return cls.read().load(path)


def model_eval_frames(
    models: Sequence["_TpuModel"], pdf: Any, evaluator: Any
) -> Iterator[Any]:
    """One feature extraction over `pdf`, then per model a MINIMAL pandas frame of
    exactly the evaluator's columns (predictions + label + weight), yielded one at
    a time so only one model's frame is ever alive. Shared by the local one-pass
    evaluate and the per-partition executor scan of the distributed plane
    (spark/evaluate.py)."""
    import pandas as pd

    m0 = models[0]
    input_col, input_cols = m0._input_col_for_transform()
    label_col = (
        evaluator.getOrDefault("labelCol") if evaluator.hasParam("labelCol") else None
    )
    weight_col = (
        evaluator.getOrDefault("weightCol")
        if evaluator.hasParam("weightCol") and evaluator.isDefined("weightCol")
        else None
    )
    fd = extract_feature_data(
        pdf,
        input_col=input_col,
        input_cols=input_cols,
        label_col=label_col,
        weight_col=weight_col,
        float32=m0._float32_inputs,
    )
    X = ensure_dtype(
        densify(fd.features, float32=m0._float32_inputs),
        float32=m0._float32_inputs,
    )

    def _colify(v):
        return v if np.ndim(v) == 1 else list(v)

    for m in models:
        outputs = m._transform_arrays(X)
        cols: Dict[str, Any] = {name: _colify(v) for name, v in outputs.items()}
        if label_col is not None and fd.label is not None:
            cols[label_col] = fd.label
        if weight_col is not None and fd.weight is not None:
            cols[weight_col] = fd.weight
        yield pd.DataFrame(cols)


def transform_evaluate_multi(
    models: Sequence["_TpuModel"], dataset: Any, evaluator: Any
) -> List[float]:
    """Evaluate MANY models over ONE feature-extraction scan — the structural
    equivalent of the reference's single-scan transform+evaluate with a model_index
    column (reference core.py:1572-1693). The dataset's features/label/weight are
    extracted once; each model contributes only its prediction arrays, and the
    evaluator sees a minimal frame of exactly its columns (the input's other columns
    are never copied).

    Spark inputs with a partial-aggregating evaluator run DISTRIBUTED: partitions
    stream through a mapInPandas scan computing per-model metric partials, merged
    on the driver — the fold is never collected (reference core.py:1572-1693;
    the pre-round-3 path called dataset.toPandas() here, a driver OOM at scale).
    Evaluators whose metric does not decompose (AUC sweep, silhouette) still
    collect, matching the reference's CPU-fallback for unsupported evaluators."""
    from .dataset import _is_spark_df

    if not models:
        return []
    if _is_spark_df(dataset):
        if getattr(evaluator, "supportsPartialAggregation", lambda: False)():
            from ..spark.evaluate import transform_evaluate_on_spark

            return transform_evaluate_on_spark(models, dataset, evaluator)
        dataset = dataset.toPandas()
    return [
        evaluator.evaluate(frame)
        for frame in model_eval_frames(models, dataset, evaluator)
    ]


class _TpuEstimatorSupervised(_TpuEstimator):
    """Supervised estimator: extracts the label column too
    (reference _CumlEstimatorSupervised, core.py:1314-1354)."""

    def _use_label(self) -> bool:
        return True


class _TpuModelWithColumns(_TpuModel):
    """Model whose transform appends columns (reference _CumlModelWithColumns,
    core.py:1756-1955) — the behavior is already the _TpuModel default."""


class _TpuModelWithPredictionCol(_TpuModelWithColumns):
    """Model with a predictionCol output (reference core.py:1957-1967)."""

    def _out_schema(self) -> List[str]:
        return [self.getOrDefault("predictionCol")]


def extract_eval_columns(model: "_TpuModel", dataset: Any):
    """Shared plumbing for model.evaluate(): transform, land on pandas, and pull
    (predictions_frame, label, prediction, weight). A defined weightCol missing
    from the frame raises (Spark raises too, never silently unweights)."""
    from .dataset import _is_spark_df

    out = model.transform(dataset)
    if _is_spark_df(out):
        out = out.toPandas()
    label = np.asarray(out[model.getOrDefault("labelCol")], np.float64)
    pred = np.asarray(out[model.getOrDefault("predictionCol")], np.float64)
    weight = None
    if model.hasParam("weightCol") and model.isDefined("weightCol"):
        weight = np.asarray(out[model.getOrDefault("weightCol")], np.float64)
    return out, label, pred, weight
