#
# Param-mapping layer between the pyspark.ml-style API params and the TPU backend's
# kernel params (L4 of the layer map, SURVEY.md §1).
#
# Structural equivalent of the reference's _CumlClass/_CumlParams
# (reference python/src/spark_rapids_ml/params.py:162-487): each estimator declares
#   * _param_mapping():        Spark param name  -> backend kernel param name (or None when
#                              unsupported / '' when silently ignored)
#   * _param_value_mapping():  per-backend-param value translation functions
#   * _get_tpu_params_default(): defaults of the backend kernel params
#   * _fallback_class():       the CPU twin used for fallback — sklearn here, where the
#                              reference uses the pyspark.ml class (params.py:248-257);
#                              pyspark itself is optional in this environment.
# and `_set_params(**kwargs)` keeps the Spark-side Params and the backend dict in sync
# exactly like reference params.py:430-487.
#

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Union

from .params import Param, Params, TypeConverters
from ..utils import get_logger

P = "unsupported"


class HasEnableSparseDataOptim(Params):
    """Mirror of reference params.py:45-67: tri-state sparse-input optimization flag."""

    enable_sparse_data_optim: Param[bool] = Param(
        "undefined",
        "enable_sparse_data_optim",
        "if True, convert input to CSR before fit; if False, densify; if unset, "
        "infer from the input data.",
        TypeConverters.toBoolean,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(enable_sparse_data_optim=None)


class HasFeaturesCols(Params):
    """Mirror of reference params.py:69-89: multi-column numeric feature input."""

    featuresCols: Param[List[str]] = Param(
        "undefined",
        "featuresCols",
        "features column names for multi-column input.",
        TypeConverters.toListString,
    )

    def getFeaturesCols(self) -> List[str]:
        return self.getOrDefault(self.featuresCols)

    def setFeaturesCols(self, value: List[str]) -> "HasFeaturesCols":
        return self._set(featuresCols=value)  # type: ignore[return-value]


class HasIDCol(Params):
    """Mirror of reference params.py:91-142: row-id column for algorithms that must
    join results back to input rows (kNN, DBSCAN)."""

    idCol: Param[str] = Param(
        "undefined",
        "idCol",
        "id column name; used to identify rows in results that are returned "
        "out of input order.",
        TypeConverters.toString,
    )

    def getIdCol(self) -> str:
        return self.getOrDefault(self.idCol)

    def setIdCol(self, value: str) -> "HasIDCol":
        return self._set(idCol=value)  # type: ignore[return-value]

    def _ensureIdCol(self, df: Any) -> Any:
        """Add a monotonically-increasing id column if idCol is not set
        (reference params.py:110-129)."""
        from .dataset import ensure_id_col

        id_col_name = self.getOrDefault(self.idCol) if self.isDefined(self.idCol) else None
        if id_col_name is None:
            id_col_name = "unique_id_" + self.uid
            self._set(idCol=id_col_name)
            return ensure_id_col(df, id_col_name)
        return ensure_id_col(df, id_col_name)


class HasVerboseParam(Params):
    """Mirror of reference params.py:144-159: verbosity plumbed to backend logging."""

    verbose: Param[Union[int, bool]] = Param(
        "undefined",
        "verbose",
        "logging verbosity for the backend compute kernels.",
        TypeConverters.identity,
    )

    def __init__(self) -> None:
        super().__init__()
        self._setDefault(verbose=False)


class DictTypeConverters(TypeConverters):
    """Mirror of reference params.py:710-719: dict-typed Params."""

    @staticmethod
    def _toDict(value: Any) -> Dict[str, Any]:
        if isinstance(value, dict):
            return value
        raise TypeError("Could not convert %s to dict" % value)


class _TpuClass:
    """Declares the Spark-param ⇄ backend-param correspondence for one estimator.

    Structural equivalent of _CumlClass (reference params.py:162-257)."""

    @classmethod
    def _param_mapping(cls) -> Mapping[str, Optional[str]]:
        """Mapping of pyspark.ml param name -> backend kernel param name.

        None  => unsupported: raise (or CPU-fallback) if user sets a non-default value.
        ''    => accepted but ignored by the backend (Spark-API-only param).
        """
        return {}

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Union[None, Any]]]:
        """Mapping of backend param name -> function translating Spark value to backend
        value; return None from the function to indicate an invalid value."""
        return {}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        """Default values of the backend kernel params for this algorithm."""
        return {}

    @classmethod
    def _fallback_class(cls) -> Optional[type]:
        """The CPU estimator class used for fallback (sklearn; the reference uses the
        pyspark twin, params.py:248-257). None => no fallback available."""
        return None


# Spark ParamValidators equivalents: (lo, hi) inclusive bounds, None = unbounded.
# Checked at fit/compute time by _TpuParams._validate_param_bounds (the reference
# validates through a throwaway pyspark estimator, core.py:579-602; pyspark is
# optional here so the bounds live in the framework).
_PARAM_BOUNDS: Dict[str, Any] = {
    "k": (1, None),
    "numTrees": (1, None),
    "maxDepth": (0, None),
    "maxBins": (2, None),
    "maxIter": (0, None),
    "regParam": (0.0, None),
    "elasticNetParam": (0.0, 1.0),
    "tol": (0.0, None),
    "eps": (1e-30, None),
    "min_samples": (1, None),
    "n_neighbors": (1, None),
    # numFolds lives on CrossValidator, which is not a _TpuParams subclass — its
    # bound is enforced directly in tuning.CrossValidator._fit
}


class _TpuParams(HasVerboseParam):
    """Keeps a dict of backend params in sync with the pyspark.ml-style Params.

    Structural equivalent of _CumlParams (reference params.py:260-707). Holds:
      * _tpu_params: the kernel param dict handed to ops/ fit functions
      * num_workers: number of mesh data-parallel workers (devices); reference semantics
        at params.py:337-371 (there: 1 worker == 1 GPU; here: 1 worker == 1 TPU device
        in the jax mesh, inferred from the runtime when unset)
      * float32_inputs: cast inputs to float32 (reference params.py:286-299); float32 is
        additionally the TPU-preferred dtype (MXU native).
    """

    _tpu_params: Dict[str, Any]
    _num_workers: Optional[int] = None
    _float32_inputs: bool = True
    _fallback_enabled: bool = True

    def __init__(self) -> None:
        super().__init__()
        self._tpu_params = {}
        # process-wide config tier (config.py, the spark-conf analog) seeds the
        # per-instance settings; explicit kwargs still override
        from .. import config as _config

        self._fallback_enabled = bool(_config.get("fallback.enabled"))
        self._float32_inputs = bool(_config.get("float32_inputs"))
        if _config.get("num_workers") is not None:
            self._num_workers = int(_config.get("num_workers"))

    @property
    def tpu_params(self) -> Dict[str, Any]:
        """Backend kernel params for this estimator (reference `cuml_params`,
        params.py:330-335)."""
        return self._tpu_params

    # per-estimator overrides/additions merged over _PARAM_BOUNDS (a single global
    # table cannot express e.g. Spark's KMeans k>1 vs PCA k>=1, or the tree-depth
    # ceiling that keeps the heap-layout forest from going depth-exponential)
    _PARAM_BOUNDS_EXTRA: Dict[str, Any] = {}

    def _validate_param_bounds(self) -> None:
        """Raise a clear ValueError when a numeric param is out of its Spark-valid
        range (_PARAM_BOUNDS + class extras) instead of failing deep in a kernel."""
        for name, (lo, hi) in {**_PARAM_BOUNDS, **self._PARAM_BOUNDS_EXTRA}.items():
            if not self.hasParam(name):
                continue
            try:
                value = self.getOrDefault(name)
            except KeyError:
                continue
            if value is None:
                continue
            if lo is not None and value < lo:
                raise ValueError(f"Param {name}={value} must be >= {lo}.")
            if hi is not None and value > hi:
                raise ValueError(f"Param {name}={value} must be <= {hi}.")

    @property
    def num_workers(self) -> int:
        """Number of TPU devices (data-parallel workers) used by fit
        (reference params.py:337-371)."""
        if self._num_workers is not None:
            return self._num_workers
        return self._infer_num_workers()

    @num_workers.setter
    def num_workers(self, value: int) -> None:
        self._num_workers = value

    def _infer_num_workers(self) -> int:
        """Infer the worker count from the active runtime/mesh
        (reference params.py:556-588 infers from the Spark cluster)."""
        from ..parallel.mesh import default_num_workers

        return default_num_workers()

    @property
    def float32_inputs(self) -> bool:
        return self._float32_inputs

    def initialize_tpu_params(self) -> None:
        """Set the backend param dict to defaults, then sync Spark-side defaults in
        (reference _CumlParams._initialize... via _set_params)."""
        assert isinstance(self, _TpuClass)
        self._tpu_params = dict(self._get_tpu_params_default())
        # push spark param defaults into tpu_params
        for spark_name, backend_name in self._param_mapping().items():
            if not backend_name:
                continue
            if self.hasParam(spark_name) and self.hasDefault(spark_name):
                self._set_tpu_value(backend_name, self.getOrDefault(spark_name))

    def _set_params(self, **kwargs: Any) -> "_TpuParams":
        """Set params from either Spark names or backend names, keeping both sides in
        sync (reference params.py:430-487)."""
        assert isinstance(self, _TpuClass)
        mapping = self._param_mapping()
        for k, v in kwargs.items():
            if k == "num_workers":
                self._num_workers = int(v)
                continue
            if k == "float32_inputs":
                self._float32_inputs = bool(v)
                continue
            if self.hasParam(k):
                # spark-side name
                self._set(**{k: v})
                backend_name = mapping.get(k, "")
                if backend_name is None:
                    self._handle_unsupported(k, v)
                elif backend_name:
                    self._set_tpu_value(backend_name, v)
            elif k in self._tpu_params or k in self._get_tpu_params_default():
                # backend-side name; also sync any spark alias
                self._set_tpu_value(k, v, translate=False)
                for spark_name, backend_name in mapping.items():
                    if backend_name == k and self.hasParam(spark_name):
                        self._set(**{spark_name: v})
            else:
                raise ValueError(f"Unsupported param '{k}'.")
        return self

    def _handle_unsupported(self, name: str, value: Any) -> None:
        """User set a Spark param the backend does not support. If the set value equals
        the default it is harmless; otherwise flag for fallback at fit time
        (reference core.py:1283-1297 / params.py:690-707)."""
        param = self.getParam(name)
        if param in self._defaultParamMap and self._defaultParamMap[param] == value:
            # set back to the harmless default: clear any earlier fallback request
            getattr(self, "_fallback_requested_params", set()).discard(name)
            return
        logger = get_logger(self.__class__)
        logger.warning(
            "Param '%s' is not supported by the TPU backend; fit() will fall back to the "
            "CPU implementation if fallback is enabled.",
            name,
        )
        self._fallback_requested_params = getattr(self, "_fallback_requested_params", set())
        self._fallback_requested_params.add(name)

    def _use_cpu_fallback(self) -> bool:
        """Whether fit should fall back to the CPU twin (reference params.py:690-707)."""
        return bool(getattr(self, "_fallback_requested_params", set())) and self._fallback_enabled

    def _set_tpu_value(self, backend_name: str, value: Any, translate: bool = True) -> None:
        assert isinstance(self, _TpuClass)
        if translate:
            value_mapping = self._param_value_mapping()
            if backend_name in value_mapping:
                mapped = value_mapping[backend_name](value)
                if mapped is None:
                    # value the TPU backend can't honor: flag for CPU fallback at fit
                    # time (reference params.py:654-688 + core.py:1283-1297)
                    get_logger(self.__class__).warning(
                        "Value %r is not supported for backend param '%s'; fit() will "
                        "fall back to the CPU implementation if fallback is enabled.",
                        value,
                        backend_name,
                    )
                    self._fallback_requested_params = getattr(
                        self, "_fallback_requested_params", set()
                    )
                    self._fallback_requested_params.add(backend_name)
                    return
                value = mapped
        # a successfully-mapped value clears any earlier fallback request for this param
        getattr(self, "_fallback_requested_params", set()).discard(backend_name)
        self._tpu_params[backend_name] = value

    def _copyValues(self, to: Params, extra: Optional[Dict[Param, Any]] = None) -> Params:
        to = super()._copyValues(to, extra)
        if isinstance(to, _TpuParams):
            to._tpu_params = dict(self._tpu_params)
            to._num_workers = self._num_workers
            to._float32_inputs = self._float32_inputs
            to._fallback_requested_params = set(
                getattr(self, "_fallback_requested_params", set())
            )
            # re-sync any params that came through `extra` (CrossValidator param maps)
            if extra and isinstance(to, _TpuClass):
                mapping = to._param_mapping()
                for param, value in extra.items():
                    backend_name = mapping.get(param.name, "")
                    if backend_name:
                        to._set_tpu_value(backend_name, value)
                    elif backend_name is None:
                        to._handle_unsupported(param.name, value)
        return to

    def _get_input_columns(self) -> tuple:
        """Resolve the (single_col, multi_cols) input spec from whichever of
        inputCol/inputCols/featuresCol/featuresCols is set
        (reference params.py:489-530)."""
        input_col: Optional[str] = None
        input_cols: Optional[List[str]] = None

        if self.hasParam("inputCols") and self.isDefined("inputCols"):
            input_cols = self.getOrDefault("inputCols")
        elif self.hasParam("inputCol") and self.isDefined("inputCol"):
            input_col = self.getOrDefault("inputCol")
        elif self.hasParam("featuresCols") and self.isDefined("featuresCols"):
            input_cols = self.getOrDefault("featuresCols")
        elif self.hasParam("featuresCol") and self.isDefined("featuresCol"):
            input_col = self.getOrDefault("featuresCol")
        else:
            raise ValueError("Please set inputCol(s) or featuresCol(s)")
        return input_col, input_cols
