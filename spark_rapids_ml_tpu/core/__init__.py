from .params import Param, Params, TypeConverters
from .backend_params import _TpuClass, _TpuParams
from .estimator import (
    FitInputs,
    _TpuCaller,
    _TpuEstimator,
    _TpuEstimatorSupervised,
    _TpuModel,
    _TpuModelWithColumns,
    _TpuModelWithPredictionCol,
)
