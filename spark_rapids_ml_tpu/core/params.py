#
# pyspark.ml-compatible Param system, implemented standalone.
#
# The reference library (NVIDIA/spark-rapids-ml) inherits its Param machinery from
# pyspark.ml.param (Param, Params, TypeConverters) and mixes in the shared param traits
# (HasInputCol, HasFeaturesCol, ...). This framework must present the identical user-facing
# surface — `PCA(k=3)`, `est.setK(3)`, `est.getOrDefault(est.k)`, `est.copy(extra)`,
# `est.explainParams()` — without requiring pyspark to be installed. When pyspark IS
# installed the plugin layer can interpose over pyspark.ml directly; here we provide a
# faithful re-implementation of the subset the estimator framework needs.
#
# Behavioral parity notes (vs pyspark 3.5 pyspark/ml/param/__init__.py):
#   * Params are discovered as class attributes of type Param, copied per-instance so
#     `param.parent == instance.uid`.
#   * `_set` applies the type converter and raises on conversion failure.
#   * `copy(extra)` produces a deep param-map copy like pyspark's.
#   * `extractParamMap` merges defaults then user-set values.
#

from __future__ import annotations

import copy as _copy
import threading
import uuid
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar, Union

__all__ = [
    "Param",
    "Params",
    "TypeConverters",
    "ParamMap",
]

T = TypeVar("T")


class Param(Generic[T]):
    """A param with self-contained documentation, mirroring pyspark.ml.param.Param."""

    def __init__(
        self,
        parent: Union["Params", str],
        name: str,
        doc: str,
        typeConverter: Optional[Callable[[Any], T]] = None,
    ):
        self.parent = parent.uid if isinstance(parent, Params) else parent
        self.name = str(name)
        self.doc = str(doc)
        self.typeConverter = TypeConverters.identity if typeConverter is None else typeConverter

    def _copy_new_parent(self, parent: "Params") -> "Param":
        """Copy the current param to a new parent, must be a dummy param."""
        if self.parent == "undefined":
            param = _copy.copy(self)
            param.parent = parent.uid
            return param
        raise ValueError("Cannot copy from non-dummy parent %s." % self.parent)

    def __str__(self) -> str:
        return str(self.parent) + "__" + self.name

    def __repr__(self) -> str:
        return "Param(parent=%r, name=%r, doc=%r)" % (self.parent, self.name, self.doc)

    def __hash__(self) -> int:
        return hash(str(self))

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, Param):
            return self.parent == other.parent and self.name == other.name
        return False


ParamMap = Dict[Param, Any]


class TypeConverters:
    """Factory methods for common type conversion functions for `Param.typeConverter`.

    Mirrors pyspark.ml.param.TypeConverters semantics.
    """

    @staticmethod
    def identity(value: Any) -> Any:
        return value

    @staticmethod
    def _is_numeric(value: Any) -> bool:
        return isinstance(value, (int, float)) and not isinstance(value, bool)

    @staticmethod
    def _can_convert_to_list(value: Any) -> bool:
        import numpy as np

        return isinstance(value, (list, tuple, range, np.ndarray))

    @staticmethod
    def toList(value: Any) -> List:
        if TypeConverters._can_convert_to_list(value):
            return list(value)
        raise TypeError("Could not convert %s to list" % value)

    @staticmethod
    def toListFloat(value: Any) -> List[float]:
        value = TypeConverters.toList(value)
        if all(map(TypeConverters._is_numeric, value)):
            return [float(v) for v in value]
        raise TypeError("Could not convert %s to list of floats" % value)

    @staticmethod
    def toListInt(value: Any) -> List[int]:
        value = TypeConverters.toList(value)
        if all(map(TypeConverters._is_numeric, value)):
            return [int(v) for v in value]
        raise TypeError("Could not convert %s to list of ints" % value)

    @staticmethod
    def toListString(value: Any) -> List[str]:
        value = TypeConverters.toList(value)
        return [TypeConverters.toString(v) for v in value]

    @staticmethod
    def toVector(value: Any) -> List[float]:
        # no pyspark VectorUDT here; a plain float list is the TPU-side vector type
        return TypeConverters.toListFloat(value)

    @staticmethod
    def toFloat(value: Any) -> float:
        if TypeConverters._is_numeric(value):
            return float(value)
        raise TypeError("Could not convert %s to float" % value)

    @staticmethod
    def toInt(value: Any) -> int:
        if TypeConverters._is_numeric(value):
            if float(value) != int(value):
                raise TypeError("Could not convert %s to int without loss" % value)
            return int(value)
        raise TypeError("Could not convert %s to int" % value)

    @staticmethod
    def toString(value: Any) -> str:
        if isinstance(value, str):
            return value
        raise TypeError("Could not convert %s to string type" % type(value))

    @staticmethod
    def toBoolean(value: Any) -> bool:
        if isinstance(value, bool):
            return value
        raise TypeError("Boolean Param requires value of type bool. Found %s." % type(value))


class Params:
    """Components that take parameters. Mirrors pyspark.ml.param.Params."""

    _lock = threading.RLock()

    def __init__(self) -> None:
        self._paramMap: ParamMap = {}
        self._defaultParamMap: ParamMap = {}
        self._params: Optional[List[Param]] = None
        self.uid = self._randomUID()
        self._copy_params()

    @classmethod
    def _randomUID(cls) -> str:
        return str(cls.__name__ + "_" + uuid.uuid4().hex[:12])

    def _copy_params(self) -> None:
        """Copy class-level Param attributes to instance-level with parent=self.uid."""
        cls = type(self)
        src_params = [
            getattr(cls, name)
            for name in dir(cls)
            if isinstance(getattr(cls, name, None), Param)
        ]
        for param in src_params:
            inst_param = _copy.copy(param)
            inst_param.parent = self.uid
            setattr(self, param.name, inst_param)

    @property
    def params(self) -> List[Param]:
        """Returns all params ordered by name. Properties are skipped before
        access (pyspark does the same): a model property that raises by contract
        (e.g. `summary` when hasSummary is False) must not break introspection."""
        if self._params is None:
            self._params = list(
                filter(
                    lambda attr: isinstance(attr, Param),
                    [
                        getattr(self, x)
                        for x in dir(self)
                        if x != "params"
                        and not x.startswith("_")
                        and not isinstance(getattr(type(self), x, None), property)
                    ],
                )
            )
            self._params.sort(key=lambda p: p.name)
        return self._params

    def explainParam(self, param: Union[str, Param]) -> str:
        param = self._resolveParam(param)
        values = []
        if self.isDefined(param):
            if param in self._defaultParamMap:
                values.append("default: %s" % str(self._defaultParamMap[param]))
            if param in self._paramMap:
                values.append("current: %s" % str(self._paramMap[param]))
        else:
            values.append("undefined")
        valueStr = "(" + ", ".join(values) + ")"
        return "%s: %s %s" % (param.name, param.doc, valueStr)

    def explainParams(self) -> str:
        return "\n".join([self.explainParam(param) for param in self.params])

    def getParam(self, paramName: str) -> Param:
        param = getattr(self, paramName, None)
        if isinstance(param, Param):
            return param
        raise ValueError("Cannot find param with name %s." % paramName)

    def isSet(self, param: Union[str, Param]) -> bool:
        param = self._resolveParam(param)
        return param in self._paramMap

    def hasDefault(self, param: Union[str, Param]) -> bool:
        param = self._resolveParam(param)
        return param in self._defaultParamMap

    def isDefined(self, param: Union[str, Param]) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def hasParam(self, paramName: str) -> bool:
        if isinstance(paramName, str):
            p = getattr(self, paramName, None)
            return isinstance(p, Param)
        raise TypeError("hasParam(): paramName must be a string")

    def getOrDefault(self, param: Union[str, Param]) -> Any:
        param = self._resolveParam(param)
        if param in self._paramMap:
            return self._paramMap[param]
        if param in self._defaultParamMap:
            return self._defaultParamMap[param]
        raise KeyError("Failed to find a default value for %s" % param.name)

    def extractParamMap(self, extra: Optional[ParamMap] = None) -> ParamMap:
        if extra is None:
            extra = dict()
        paramMap = self._defaultParamMap.copy()
        paramMap.update(self._paramMap)
        paramMap.update(extra)
        return paramMap

    def copy(self, extra: Optional[ParamMap] = None) -> "Params":
        if extra is None:
            extra = dict()
        that = _copy.copy(self)
        that._paramMap = {}
        that._defaultParamMap = {}
        that._copy_params()
        return self._copyValues(that, extra)

    def set(self, param: Param, value: Any) -> None:
        self._shouldOwn(param)
        try:
            value = param.typeConverter(value)
        except ValueError as e:
            raise ValueError('Invalid param value given for param "%s". %s' % (param.name, e))
        self._paramMap[param] = value

    def clear(self, param: Param) -> None:
        if self.isSet(param):
            del self._paramMap[param]

    def _shouldOwn(self, param: Param) -> None:
        if not (self.uid == param.parent and self.hasParam(param.name)):
            raise ValueError("Param %r does not belong to %r." % (param, self))

    def _resolveParam(self, param: Union[str, Param]) -> Param:
        if isinstance(param, Param):
            self._shouldOwn(param)
            return param
        elif isinstance(param, str):
            return self.getParam(param)
        else:
            raise TypeError("Cannot resolve %r as a param." % param)

    def _set(self, **kwargs: Any) -> "Params":
        """Sets user-supplied params."""
        for param, value in kwargs.items():
            p = self.getParam(param)
            if value is not None:
                try:
                    value = p.typeConverter(value)
                except TypeError as e:
                    raise TypeError('Invalid param value given for param "%s". %s' % (p.name, e))
            self._paramMap[p] = value
        return self

    def _clear(self, param: Param) -> None:
        self.clear(param)

    def _setDefault(self, **kwargs: Any) -> "Params":
        """Sets default params."""
        for param, value in kwargs.items():
            p = self.getParam(param)
            if value is not None and not callable(value):
                try:
                    value = p.typeConverter(value)
                except TypeError as e:
                    raise TypeError(
                        'Invalid default param value given for param "%s". %s' % (p.name, e)
                    )
            self._defaultParamMap[p] = value
        return self

    def _copyValues(self, to: "Params", extra: Optional[ParamMap] = None) -> "Params":
        paramMap = self._paramMap.copy()
        if isinstance(extra, dict):
            for param, value in extra.items():
                if isinstance(param, Param):
                    paramMap[param] = value
                else:
                    raise TypeError(
                        "Expecting a valid instance of Param, but received: {}".format(param)
                    )
        elif extra is not None:
            raise TypeError(
                "Expecting a dict, but received an object of type {}.".format(type(extra))
            )
        for param in self._defaultParamMap:
            if to.hasParam(param.name):
                to._defaultParamMap[to.getParam(param.name)] = self._defaultParamMap[param]
        for param in paramMap:
            if to.hasParam(param.name):
                to._paramMap[to.getParam(param.name)] = paramMap[param]
        return to

    def _resetUid(self, newUid: Any) -> "Params":
        newUid = str(newUid)
        self.uid = newUid
        newDefaultParamMap = dict()
        newParamMap = dict()
        for param in self.params:
            newParam = _copy.copy(param)
            newParam.parent = newUid
            if param in self._defaultParamMap:
                newDefaultParamMap[newParam] = self._defaultParamMap[param]
            if param in self._paramMap:
                newParamMap[newParam] = self._paramMap[param]
            param.parent = newUid
        self._defaultParamMap = newDefaultParamMap
        self._paramMap = newParamMap
        return self


# ---------------------------------------------------------------------------
# Shared param mixins — the subset of pyspark.ml.param.shared the reference uses,
# plus reference-specific mixins (HasFeaturesCols, HasIDCol, ... from
# reference python/src/spark_rapids_ml/params.py:45-160).
# ---------------------------------------------------------------------------


class HasMaxIter(Params):
    maxIter: Param[int] = Param(
        "undefined", "maxIter", "max number of iterations (>= 0).", TypeConverters.toInt
    )

    def getMaxIter(self) -> int:
        return self.getOrDefault(self.maxIter)


class HasRegParam(Params):
    regParam: Param[float] = Param(
        "undefined", "regParam", "regularization parameter (>= 0).", TypeConverters.toFloat
    )

    def getRegParam(self) -> float:
        return self.getOrDefault(self.regParam)


class HasElasticNetParam(Params):
    elasticNetParam: Param[float] = Param(
        "undefined",
        "elasticNetParam",
        "the ElasticNet mixing parameter, in range [0, 1]. For alpha = 0, "
        "the penalty is an L2 penalty. For alpha = 1, it is an L1 penalty.",
        TypeConverters.toFloat,
    )

    def getElasticNetParam(self) -> float:
        return self.getOrDefault(self.elasticNetParam)


class HasFeaturesCol(Params):
    featuresCol: Param[str] = Param(
        "undefined", "featuresCol", "features column name.", TypeConverters.toString
    )

    def getFeaturesCol(self) -> str:
        return self.getOrDefault(self.featuresCol)


class HasLabelCol(Params):
    labelCol: Param[str] = Param(
        "undefined", "labelCol", "label column name.", TypeConverters.toString
    )

    def getLabelCol(self) -> str:
        return self.getOrDefault(self.labelCol)


class HasPredictionCol(Params):
    predictionCol: Param[str] = Param(
        "undefined", "predictionCol", "prediction column name.", TypeConverters.toString
    )

    def getPredictionCol(self) -> str:
        return self.getOrDefault(self.predictionCol)


class HasProbabilityCol(Params):
    probabilityCol: Param[str] = Param(
        "undefined",
        "probabilityCol",
        "Column name for predicted class conditional probabilities.",
        TypeConverters.toString,
    )

    def getProbabilityCol(self) -> str:
        return self.getOrDefault(self.probabilityCol)


class HasRawPredictionCol(Params):
    rawPredictionCol: Param[str] = Param(
        "undefined",
        "rawPredictionCol",
        "raw prediction (a.k.a. confidence) column name.",
        TypeConverters.toString,
    )

    def getRawPredictionCol(self) -> str:
        return self.getOrDefault(self.rawPredictionCol)


class HasInputCol(Params):
    inputCol: Param[str] = Param(
        "undefined", "inputCol", "input column name.", TypeConverters.toString
    )

    def getInputCol(self) -> str:
        return self.getOrDefault(self.inputCol)


class HasInputCols(Params):
    inputCols: Param[List[str]] = Param(
        "undefined", "inputCols", "input column names.", TypeConverters.toListString
    )

    def getInputCols(self) -> List[str]:
        return self.getOrDefault(self.inputCols)


class HasOutputCol(Params):
    outputCol: Param[str] = Param(
        "undefined", "outputCol", "output column name.", TypeConverters.toString
    )

    def getOutputCol(self) -> str:
        return self.getOrDefault(self.outputCol)


class HasOutputCols(Params):
    outputCols: Param[List[str]] = Param(
        "undefined", "outputCols", "output column names.", TypeConverters.toListString
    )

    def getOutputCols(self) -> List[str]:
        return self.getOrDefault(self.outputCols)


class HasSeed(Params):
    seed: Param[int] = Param("undefined", "seed", "random seed.", TypeConverters.toInt)

    def getSeed(self) -> int:
        return self.getOrDefault(self.seed)


class HasTol(Params):
    tol: Param[float] = Param(
        "undefined",
        "tol",
        "the convergence tolerance for iterative algorithms (>= 0).",
        TypeConverters.toFloat,
    )

    def getTol(self) -> float:
        return self.getOrDefault(self.tol)


class HasStandardization(Params):
    standardization: Param[bool] = Param(
        "undefined",
        "standardization",
        "whether to standardize the training features before fitting the model.",
        TypeConverters.toBoolean,
    )

    def getStandardization(self) -> bool:
        return self.getOrDefault(self.standardization)


class HasFitIntercept(Params):
    fitIntercept: Param[bool] = Param(
        "undefined",
        "fitIntercept",
        "whether to fit an intercept term.",
        TypeConverters.toBoolean,
    )

    def getFitIntercept(self) -> bool:
        return self.getOrDefault(self.fitIntercept)


class HasSolver(Params):
    solver: Param[str] = Param(
        "undefined",
        "solver",
        "the solver algorithm for optimization.",
        TypeConverters.toString,
    )

    def getSolver(self) -> str:
        return self.getOrDefault(self.solver)


class HasWeightCol(Params):
    weightCol: Param[str] = Param(
        "undefined",
        "weightCol",
        "weight column name. If this is not set or empty, we treat all instance "
        "weights as 1.0.",
        TypeConverters.toString,
    )

    def getWeightCol(self) -> str:
        return self.getOrDefault(self.weightCol)


class HasCheckpointInterval(Params):
    checkpointInterval: Param[int] = Param(
        "undefined",
        "checkpointInterval",
        "set checkpoint interval (>= 1) or disable checkpoint (-1).",
        TypeConverters.toInt,
    )

    def getCheckpointInterval(self) -> int:
        return self.getOrDefault(self.checkpointInterval)


class HasAggregationDepth(Params):
    aggregationDepth: Param[int] = Param(
        "undefined",
        "aggregationDepth",
        "suggested depth for treeAggregate (>= 2).",
        TypeConverters.toInt,
    )

    def getAggregationDepth(self) -> int:
        return self.getOrDefault(self.aggregationDepth)


class HasThresholds(Params):
    thresholds: Param[List[float]] = Param(
        "undefined",
        "thresholds",
        "Thresholds in multi-class classification to adjust the probability of "
        "predicting each class.",
        TypeConverters.toListFloat,
    )

    def getThresholds(self) -> List[float]:
        return self.getOrDefault(self.thresholds)


class HasParallelism(Params):
    parallelism: Param[int] = Param(
        "undefined",
        "parallelism",
        "the number of threads to use when running parallel algorithms (>= 1).",
        TypeConverters.toInt,
    )

    def getParallelism(self) -> int:
        return self.getOrDefault(self.parallelism)


class HasCollectSubModels(Params):
    collectSubModels: Param[bool] = Param(
        "undefined",
        "collectSubModels",
        "Param for whether to collect a list of sub-models trained during tuning.",
        TypeConverters.toBoolean,
    )

    def getCollectSubModels(self) -> bool:
        return self.getOrDefault(self.collectSubModels)
