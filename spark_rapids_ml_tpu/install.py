#
# No-code-change acceleration: module interposer (reference
# python/src/spark_rapids_ml/install.py:22-81).
#
# Importing this module installs proxy modules at sys.modules["pyspark.ml(.sub)"]
# whose __getattr__ serves the TPU-accelerated classes for accelerated names and
# falls through to the real pyspark for everything else. Like the reference, the
# proxy is caller-path-sensitive: lookups coming from inside spark_rapids_ml_tpu or
# pyspark itself get the original attributes, so the accelerated classes' own
# pyspark usage never self-intercepts.
#
# Bonus over the reference: when pyspark is NOT installed, the proxies are still
# created, so scripts written against pyspark.ml run standalone on the TPU backend.
#

from __future__ import annotations

import importlib
import sys
import types
from typing import Any, Dict

_accelerated_attributes: Dict[str, Dict[str, str]] = {
    # pyspark module -> {class name -> spark_rapids_ml_tpu module}
    "pyspark.ml.feature": {
        "PCA": "feature",
        "PCAModel": "feature",
        # standalone (pyspark-less) scripts need the assembler from the proxy too;
        # with a real pyspark the Pipeline bypass makes it a no-op stage anyway
        "VectorAssembler": "feature",
    },
    "pyspark.ml.clustering": {
        "KMeans": "clustering",
        "KMeansModel": "clustering",
        "DBSCAN": "clustering",
    },
    "pyspark.ml.classification": {
        "LogisticRegression": "classification",
        "LogisticRegressionModel": "classification",
        "RandomForestClassifier": "classification",
        "RandomForestClassificationModel": "classification",
    },
    "pyspark.ml.regression": {
        "LinearRegression": "regression",
        "LinearRegressionModel": "regression",
        "RandomForestRegressor": "regression",
        "RandomForestRegressionModel": "regression",
    },
    "pyspark.ml.tuning": {
        "CrossValidator": "tuning",
        "CrossValidatorModel": "tuning",
        "TrainValidationSplit": "tuning",
        "TrainValidationSplitModel": "tuning",
        "ParamGridBuilder": "tuning",
    },
    "pyspark.ml.evaluation": {
        "MulticlassClassificationEvaluator": "evaluation",
        "RegressionEvaluator": "evaluation",
        "BinaryClassificationEvaluator": "evaluation",
        "ClusteringEvaluator": "evaluation",
    },
    "pyspark.ml": {"Pipeline": "pipeline", "PipelineModel": "pipeline"},
}

_SELF_PREFIXES = ("spark_rapids_ml_tpu", "pyspark")


def _caller_is_internal() -> bool:
    import inspect

    frame = inspect.currentframe()
    try:
        # walk out of this module + the proxy __getattr__
        f = frame
        for _ in range(8):
            if f is None:
                return False
            mod = f.f_globals.get("__name__", "")
            if mod.startswith("spark_rapids_ml_tpu") and not mod.endswith("install"):
                return True
            if mod.startswith("pyspark"):
                return True
            f = f.f_back
        return False
    finally:
        del frame


def _set_mod_getattr(mod_name: str, attrs: Dict[str, str]) -> None:
    real = sys.modules.get(mod_name)

    proxy = types.ModuleType(mod_name)
    proxy.__dict__["_srml_tpu_real"] = real
    proxy.__dict__["_srml_tpu_attrs"] = dict(attrs)

    def __getattr__(name: str, _mod=mod_name, _proxy=proxy) -> Any:
        attrs_map = _proxy.__dict__["_srml_tpu_attrs"]
        real_mod = _proxy.__dict__["_srml_tpu_real"]
        if name in attrs_map and not _caller_is_internal():
            sub = importlib.import_module(f"spark_rapids_ml_tpu.{attrs_map[name]}")
            return getattr(sub, name)
        if real_mod is not None:
            return getattr(real_mod, name)
        raise AttributeError(
            f"module {_mod!r} has no attribute {name!r} "
            "(pyspark is not installed; only TPU-accelerated names are available)"
        )

    proxy.__getattr__ = __getattr__  # type: ignore[attr-defined]
    sys.modules[mod_name] = proxy
    # also rebind the submodule attribute on the parent package: attribute-chain
    # access (`import pyspark.ml.clustering; pyspark.ml.clustering.KMeans`) resolves
    # through the parent's attributes, not sys.modules
    parent_name, _, child = mod_name.rpartition(".")
    if parent_name:
        parent = sys.modules.get(parent_name)
        if parent is not None:
            setattr(parent, child, proxy)


def install() -> None:
    """Install the interposer over pyspark.ml (idempotent)."""
    try:
        import pyspark.ml  # noqa: hygiene/unused-import — materialize real modules first when present
        for mod_name in _accelerated_attributes:
            try:
                importlib.import_module(mod_name)
            except ImportError:
                pass
    except ImportError:
        # standalone mode: fabricate the pyspark/pyspark.ml package skeleton
        for pkg in ("pyspark", "pyspark.ml"):
            if pkg not in sys.modules:
                sys.modules[pkg] = types.ModuleType(pkg)
    # children before parents: a parent proxy's fallthrough resolves submodule
    # attributes on the module it wrapped, which must already hold the child proxies
    for mod_name, attrs in sorted(
        _accelerated_attributes.items(), key=lambda kv: -kv[0].count(".")
    ):
        if not isinstance(
            getattr(sys.modules.get(mod_name), "__getattr__", None), types.FunctionType
        ):
            _set_mod_getattr(mod_name, attrs)


install()
