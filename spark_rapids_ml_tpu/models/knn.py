#
# Exact and approximate k-NN estimators (L6 API) — the reference's
# spark_rapids_ml.knn surface (reference python/src/spark_rapids_ml/knn.py):
#   * NearestNeighbors: fit() just captures the item set (reference knn.py:347-367 —
#     no compute), kneighbors() runs the distributed all-to-all search (stack §3.4)
#   * exactNearestNeighborsJoin: flattened (query, item, distance) join
#     (reference knn.py:435-482)
#   * ApproximateNearestNeighbors: IVF-Flat per-device index + probe search
#     (reference knn.py:838-1723 wraps cuVS ivf_flat/ivf_pq/cagra)
#   * neither is persistable, matching the reference (knn.py:384-408)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from ..core.backend_params import HasIDCol, _TpuClass
from ..core.dataset import extract_feature_data
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModel
from ..core.params import Param, TypeConverters
from ..core.backend_params import DictTypeConverters, HasFeaturesCols
from ..core.params import HasInputCol
from ..parallel.partitioner import active_partitioner
from ..parallel.partition import pad_rows
from ..ops.knn import (
    exact_knn_distributed,
    ivfflat_build,
    ivfflat_search,
    ivfpq_build,
    ivfpq_search,
)
from ..utils import get_logger

# query sets at/above this row count switch exact kNN from the all_gather merge to
# the ring-permute path (queries stay sharded; ops/knn.exact_knn_ring)
_RING_QUERY_THRESHOLD = 65536


def _ap(algo_params: Dict[str, Any], *names: str, default: Any) -> Any:
    """First present key among the accepted spellings — cuML and cuVS names are both
    honored, like the reference's translation table (knn.py:1324-1404)."""
    for n in names:
        if n in algo_params:
            return algo_params[n]
    return default


def _normalize_or_raise(X, w):
    """Row-normalize for cosine metrics; zero-norm REAL rows raise (Spark/cuML
    cosine semantics). Works on jax arrays; padding rows (w==0) are exempt."""
    import jax.numpy as jnp

    norms = jnp.linalg.norm(X, axis=1, keepdims=True)
    min_norm = float(jnp.min(jnp.where(jnp.asarray(w)[:, None] > 0, norms, jnp.inf)))
    if min_norm <= 0.0:
        raise ValueError(
            "Cosine distance is not defined for zero-length vectors; the input "
            "contains an all-zero feature row."
        )
    return X / jnp.maximum(norms, 1e-30)


class _NNParams(HasInputCol, HasFeaturesCols, HasIDCol):
    k: Param[int] = Param(
        "undefined", "k", "number of nearest neighbors to retrieve (> 0).",
        TypeConverters.toInt,
    )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setK(self, value: int):
        return self._set_params(k=value)

    def setInputCol(self, value: str):
        return self._set(inputCol=value)


class _NearestNeighborsClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {"k": "n_neighbors", "inputCol": "", "featuresCols": "", "idCol": ""}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_neighbors": 5}


class NearestNeighbors(_NearestNeighborsClass, _TpuEstimator, _NNParams):
    """Exact k-NN: fit stores the item set; kneighbors runs the sharded all-to-all
    search over the mesh (reference knn.py:76-835)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=5)
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return []

    def _get_tpu_fit_func(self, extra_params=None):
        raise NotImplementedError("NearestNeighbors.fit stores data; no kernel runs.")

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "NearestNeighborsModel":
        return NearestNeighborsModel(**attrs)

    def _fit(self, dataset: Any) -> "NearestNeighborsModel":
        # no heavy compute at fit time (reference knn.py:347-367) — but the
        # item-norm term Σ X² IS computed once here and cached on the model,
        # so no kneighbors query block ever recomputes it (selection-plane
        # norm hoist; a refit builds a fresh model, which IS the invalidation)
        from ..ops.knn import center_norms_sq

        dataset = self._ensureIdCol(dataset)
        fd = self._pre_process_data(dataset)
        items = np.asarray(fd.features)
        model = NearestNeighborsModel(
            item_features=items,
            item_ids=(
                fd.row_id
                if fd.row_id is not None
                else np.arange(fd.n_rows, dtype=np.int64)
            ),
            item_norms_sq=center_norms_sq(items),
            item_df=dataset,
        )
        model._num_workers = self._num_workers
        self._copyValues(model)
        return model

    def write(self):
        raise NotImplementedError(
            "NearestNeighbors is not persistable (reference knn.py:384-408)."
        )


class NearestNeighborsModel(_NearestNeighborsClass, _TpuModel, _NNParams):
    def __init__(
        self,
        item_features: np.ndarray,
        item_ids: np.ndarray,
        item_df: Any = None,
        item_norms_sq: "np.ndarray | None" = None,
        item_valid: "np.ndarray | None" = None,
    ) -> None:
        attrs = dict(item_features=item_features, item_ids=item_ids)
        if item_norms_sq is not None:
            # cached Σ X² — searched-for with .get() so directly-constructed
            # models (no fit) still work, just without the hoisted norm
            attrs["item_norms_sq"] = np.asarray(item_norms_sq)
        if item_valid is not None:
            # incremental tier (docs/design.md §7b): rows are laid out in a
            # BUCKETED capacity and this mask carries live/tombstoned/slack;
            # absent on fresh fits, so the non-incremental paths are unchanged
            attrs["item_valid"] = np.asarray(item_valid, bool)
        super().__init__(**attrs)
        self._item_df = item_df
        self._tombstones = 0
        self._item_fill = None  # high-water slot count (incremental tier)
        self._setDefault(k=5)

    # ---- persistence (ANN index store, docs/design.md §7b) ----

    def _ann_index_spec(self):
        """Arrays persisted through the versioned mmap-friendly index format
        (ops/ann_lifecycle.py) instead of arrays.npz."""
        arrays = {
            n: np.asarray(self._model_attributes[n])
            for n in ("item_features", "item_ids", "item_norms_sq",
                      "item_valid")
            if self._model_attributes.get(n) is not None
        }
        return arrays, "exact", {"tombstones": int(self._tombstones)}

    @classmethod
    def _from_row(cls, attrs: Dict[str, Any]) -> "NearestNeighborsModel":
        manifest = attrs.pop("__ann_manifest__", None)
        model = cls(**attrs)
        if manifest is not None:
            model._tombstones = int(
                (manifest.get("meta") or {}).get("tombstones", 0)
            )
        return model

    # ---- incremental add/delete (docs/design.md §7b) ----

    def _live_mask(self) -> np.ndarray:
        valid = self._model_attributes.get("item_valid")
        if valid is None:
            return np.ones(
                (len(self._model_attributes["item_features"]),), bool
            )
        return np.asarray(valid, bool)

    def enable_incremental(self, capacity_rows: int = 0) -> int:
        """Re-lay the item set into a BUCKETED row capacity (power of two >=
        the live count, optionally >= capacity_rows) with an explicit valid
        mask. Paying this single shape change BEFORE the model is served is
        what makes later add/delete calls compile-free: every search
        executable's operand shapes stay fixed while the slack absorbs adds.
        Returns the capacity."""
        from ..ops.ann_lifecycle import bucket_capacity

        a = self._model_attributes
        items = np.asarray(a["item_features"], np.float32)
        n = len(items)
        cap = bucket_capacity(max(n, int(capacity_rows)))
        if a.get("item_valid") is not None and cap <= len(items):
            return len(items)  # already bucketed at (or past) this capacity
        grown = np.zeros((cap, items.shape[1]), np.float32)
        grown[:n] = items
        ids = np.full((cap,), -1, np.int64)
        ids[:n] = np.asarray(a["item_ids"], np.int64)
        valid = np.zeros((cap,), bool)
        valid[:n] = self._live_mask()[:n]
        x2 = np.zeros((cap,), np.float32)
        x2n = a.get("item_norms_sq")
        from ..ops.knn import center_norms_sq

        x2[:n] = np.asarray(x2n) if x2n is not None else center_norms_sq(items)
        a.update(
            item_features=grown, item_ids=ids, item_valid=valid,
            item_norms_sq=x2,
        )
        self._item_fill = n
        return cap

    def add_items(self, X_new: np.ndarray, ids: "np.ndarray | None" = None
                  ) -> np.ndarray:
        """Append items (reusing tombstoned slots first, then slack; the
        capacity bucket grows only when both run out — the amortized shape
        change in-slack adds avoid). Returns the user ids assigned."""
        from ..observability.runs import counter_inc as _counter_inc
        from ..ops.ann_lifecycle import bucket_capacity
        from ..ops.knn import center_norms_sq

        a = self._model_attributes
        if a.get("item_valid") is None:
            self.enable_incremental()
        X_new = np.ascontiguousarray(np.asarray(X_new), np.float32)
        m = len(X_new)
        valid = np.asarray(a["item_valid"], bool)
        item_ids = np.asarray(a["item_ids"], np.int64)
        if ids is None:
            base = int(item_ids.max(initial=-1)) + 1
            ids = np.arange(base, base + m, dtype=np.int64)
        else:
            ids = np.asarray(ids, np.int64)
        if self._item_fill is None:
            # high-water reconstruction: one past the last live slot
            self._item_fill = (
                int(len(valid) - np.argmax(valid[::-1])) if valid.any() else 0
            )
        fill = int(self._item_fill)
        holes = np.nonzero(~valid[:fill])[0][:m]
        n_virgin = m - len(holes)
        if fill + n_virgin > len(valid):
            cap = bucket_capacity(fill + n_virgin)
            self.enable_incremental(capacity_rows=cap)
            valid = np.asarray(a["item_valid"], bool)
            item_ids = np.asarray(a["item_ids"], np.int64)
        slots = np.concatenate(
            [holes, np.arange(fill, fill + n_virgin)]
        ).astype(np.int64)
        items = np.asarray(a["item_features"])
        items[slots] = X_new
        item_ids[slots] = ids
        valid[slots] = True
        np.asarray(a["item_norms_sq"])[slots] = center_norms_sq(X_new)
        a.update(item_features=items, item_ids=item_ids, item_valid=valid)
        self._item_fill = fill + n_virgin
        self._tombstones = max(self._tombstones - len(holes), 0)
        _counter_inc("ann.items_added", m)
        return ids

    def delete_items(self, ids: np.ndarray) -> int:
        """Tombstone items by user id: their valid-mask entries flip False —
        the search kernels mask them to INVALID_D2, so no shape or kernel
        changes. Compaction (tombstones past `ann.compact_tombstone_pct` of
        occupied rows) repacks the live rows into a possibly smaller bucket."""
        from ..observability.runs import counter_inc as _counter_inc
        from ..ops.ann_lifecycle import resolve_compact_tombstone_pct

        a = self._model_attributes
        if a.get("item_valid") is None:
            self.enable_incremental()
        valid = np.asarray(a["item_valid"], bool)
        item_ids = np.asarray(a["item_ids"], np.int64)
        hit = np.isin(item_ids, np.asarray(ids, np.int64)) & valid
        n = int(hit.sum())
        if n == 0:
            return 0
        valid[hit] = False
        item_ids[hit] = -1
        a.update(item_ids=item_ids, item_valid=valid)
        self._tombstones += n
        _counter_inc("ann.items_deleted", n)
        occupied = int(valid.sum()) + self._tombstones
        if occupied and (
            100 * self._tombstones
            > resolve_compact_tombstone_pct() * occupied
        ):
            self.compact_items()
        return n

    def compact_items(self) -> None:
        """Repack live rows (dropping tombstoned slots) into a fresh bucketed
        capacity. Changes shapes — a served model must be refreshed after."""
        from ..observability.runs import counter_inc as _counter_inc
        from ..ops.ann_lifecycle import bucket_capacity

        a = self._model_attributes
        valid = self._live_mask()
        live = np.nonzero(valid)[0]
        cap = bucket_capacity(max(len(live), 1))
        items = np.zeros((cap, np.asarray(a["item_features"]).shape[1]),
                         np.float32)
        items[: len(live)] = np.asarray(a["item_features"])[live]
        ids = np.full((cap,), -1, np.int64)
        ids[: len(live)] = np.asarray(a["item_ids"])[live]
        x2 = np.zeros((cap,), np.float32)
        x2_src = a.get("item_norms_sq")
        if x2_src is not None:
            x2[: len(live)] = np.asarray(x2_src)[live]
        new_valid = np.zeros((cap,), bool)
        new_valid[: len(live)] = True
        a.update(
            item_features=items, item_ids=ids, item_norms_sq=x2,
            item_valid=new_valid,
        )
        self._item_fill = len(live)
        self._tombstones = 0
        _counter_inc("ann.compactions", 1)

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        raise NotImplementedError("Use kneighbors() / exactNearestNeighborsJoin().")

    def _serving_device_attrs(self) -> Tuple[str, ...]:
        # item_features (+ the fit-cached Σ X² and the incremental tier's
        # valid mask when present) are the device operands of the serving
        # scan; item_ids stay host-side (the gather back to user ids happens
        # on the host after the top-k returns)
        return tuple(
            n for n in ("item_features", "item_norms_sq", "item_valid")
            if isinstance(self._model_attributes.get(n), np.ndarray)
            or hasattr(self._model_attributes.get(n), "shape")
        )

    def _serving_predict(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        """Serving-batch kNN: the same single-shard exact scan the production
        search path uses (ops/knn.exact_knn_single — strategy knob, sentinel
        and selection telemetry all apply), per query row independent, routed
        through predict_dispatch like every other family. Returns the
        kneighbors() column surface: per-row neighbor `indices` (user item
        ids) and EUCLIDEAN `distances`."""
        import jax.numpy as jnp

        from ..observability.inference import predict_dispatch
        from ..ops.knn import exact_knn_single

        items = self._model_attributes["item_features"]
        item_ids = np.asarray(self._model_attributes["item_ids"])
        n_items = int(items.shape[0])
        k = min(self.getK(), n_items)
        x2 = self._model_attributes.get("item_norms_sq")
        # incremental tier: the valid mask carries live/tombstoned/slack rows
        # — deleted items mask to INVALID_D2 inside the scan, and because the
        # bucketed capacity (not the live count) is the operand shape, adds
        # and deletes never mint a new executable (§7b zero-compile contract)
        valid = self._model_attributes.get("item_valid")
        d2, idx = predict_dispatch(
            self,
            exact_knn_single,
            jnp.asarray(np.asarray(X, np.float32)),
            jnp.asarray(items),
            # plain jnp.asarray: when the registry installed the HBM-resident
            # mask, this is a no-op — an np round trip would pull it to host
            # and re-upload it every micro-batch
            jnp.asarray(valid)
            if valid is not None else jnp.ones((n_items,), bool),
            k,
            x2=jnp.asarray(x2) if x2 is not None else None,
            model_name=type(self).__name__,
            shape_of=X,
        )
        d2, idx = np.asarray(d2), np.asarray(idx)
        # all items are valid here, so idx is always in range; keep the -1/inf
        # API convention anyway for callers that serve a masked index
        ids = np.where(
            idx >= 0, item_ids[np.clip(idx, 0, n_items - 1)], -1
        )
        return {
            "indices": ids,
            "distances": np.sqrt(np.maximum(d2, 0.0)).astype(np.float32),
        }

    def kneighbors(self, query_df: Any) -> Tuple[Any, Any, pd.DataFrame]:
        """Returns (item_df, query_df, knn_df): knn_df has query_id + arrays of item
        indices (ids) and euclidean distances (reference knn.py:574-660)."""
        query_df = self._ensureIdCol(query_df)
        input_col, input_cols = self._get_input_columns()
        id_col = self.getIdCol()
        fd = extract_feature_data(
            query_df, input_col=input_col, input_cols=input_cols, id_col=id_col
        )
        Q = np.asarray(fd.features)
        query_ids = (
            fd.row_id if fd.row_id is not None else np.arange(len(Q), dtype=np.int64)
        )

        items = self._model_attributes["item_features"]
        item_ids = self._model_attributes["item_ids"]
        k = min(self.getK(), items.shape[0])
        from .. import config as _config

        threshold = int(_config.get("stream_threshold_bytes"))
        item_valid = self._model_attributes.get("item_valid")
        x2 = self._model_attributes.get("item_norms_sq")
        if items.nbytes > threshold and item_valid is not None:
            # the out-of-core blocked scan has no validity operand: gather the
            # LIVE rows into locals (tombstoned + bucketed-slack rows are zero
            # vectors and must not be candidates). Side-effect-free on
            # purpose — kneighbors is a read API, and compacting here would
            # change operand shapes underneath a concurrent serving
            # registration of this same model object.
            mask = np.asarray(item_valid, bool)
            items = np.ascontiguousarray(np.asarray(items)[mask])
            item_ids = np.asarray(item_ids)[mask]
            if x2 is not None:
                x2 = np.asarray(x2)[mask]
            item_valid = None  # locals are now fully live
            k = min(self.getK(), len(items))
        if items.nbytes > threshold:
            # out-of-core tier: items stay host-resident; the device scans
            # (query_block, item_block) tiles with a running top-k merge — the
            # reference's UVM-backed brute scan made explicit
            # (reference knn.py:763-774, utils.py:184-241)
            from ..ops.pairwise_streaming import streaming_exact_knn

            self.logger.warning(
                "item set ~%.0f MiB exceeds stream_threshold_bytes=%d; using the "
                "out-of-core blocked scan (host-resident items).",
                items.nbytes / 2**20,
                threshold,
            )
            from ..observability.inference import predict_dispatch

            dists, gidx = predict_dispatch(
                self, streaming_exact_knn,
                Q, np.asarray(items), k, mesh=active_partitioner(self.num_workers).mesh,
            )
            ids = np.where(gidx >= 0, item_ids[np.maximum(gidx, 0)], -1)
            knn_df = pd.DataFrame(
                {
                    f"query_{id_col}": query_ids,
                    "indices": list(ids),
                    "distances": list(dists.astype(np.float32)),
                }
            )
            return self._item_df, query_df, knn_df
        part = active_partitioner(self.num_workers)
        mesh = part.mesh
        Xp, valid, _ = pad_rows(items, part.num_workers)
        if item_valid is not None:
            # incremental tier: tombstoned/slack rows are invalid like padding
            valid = np.asarray(valid).copy()
            valid[: len(items)] *= np.asarray(item_valid, valid.dtype)
        Xd = part.shard(Xp)
        vd = part.shard(valid)
        # cached item norms (computed once at fit) shard alongside the items —
        # no query block recomputes Σ X² (padding rows are invalid-masked, so
        # their zero norm never participates); x2 is the LOCAL sliced above,
        # kept row-aligned with items through the live-row gather
        if x2 is not None:
            x2p = np.zeros((Xp.shape[0],), np.float32)
            x2p[: len(items)] = np.asarray(x2)
            x2d = part.shard(x2p)
        else:
            x2d = None
        if len(Q) >= _RING_QUERY_THRESHOLD and mesh.devices.size > 1:
            # large query sets shard over the mesh too and the item shards rotate
            # around the ring (ops/knn.exact_knn_ring) — nothing global materializes
            from ..ops.knn import exact_knn_ring

            from ..observability.inference import predict_dispatch

            Qp, qvalid, _ = pad_rows(Q, part.num_workers)
            Qd = part.shard(Qp)
            # the query block is not the leading arg here: shape_of pins the
            # recompile-sentinel signature to the PADDED query shard
            dists, gidx = predict_dispatch(
                self, exact_knn_ring, mesh, Qd, Xd, vd, k,
                x2_sharded=x2d, shape_of=Qd,
            )
            dists, gidx = dists[: len(Q)], gidx[: len(Q)]
        else:
            from ..observability.inference import predict_dispatch

            dists, gidx = predict_dispatch(
                self, exact_knn_distributed, mesh, Q, Xd, vd, k,
                x2_sharded=x2d, shape_of=Q,
            )
        # padded positions never win (inf distance); -1 ids appear only when
        # fewer than k LIVE items exist (the incremental tier's delete path)
        ids = np.where(gidx >= 0, np.asarray(item_ids)[np.maximum(gidx, 0)], -1)

        knn_df = pd.DataFrame(
            {
                f"query_{id_col}": query_ids,
                "indices": list(ids),
                "distances": list(dists.astype(np.float32)),
            }
        )
        return self._item_df, query_df, knn_df

    def exactNearestNeighborsJoin(
        self, query_df: Any, distCol: str = "distCol"
    ) -> pd.DataFrame:
        """Flattened (query_id, item_id, distance) join (reference knn.py:435-482).
        Short-tail slots (id -1 / inf distance — reachable once the incremental
        tier's deletes leave fewer than k live items) are filtered like
        approxSimilarityJoin's: a join row must name a real item."""
        _, query_df, knn_df = self.kneighbors(query_df)
        id_col = self.getIdCol()
        rows = []
        for _, r in knn_df.iterrows():
            for item_id, dist in zip(r["indices"], r["distances"]):
                if item_id >= 0 and np.isfinite(dist):
                    rows.append((r[f"query_{id_col}"], item_id, dist))
        return pd.DataFrame(rows, columns=[f"query_{id_col}", f"item_{id_col}", distCol])

    # NearestNeighborsModel persists through the ANN index store (§7b) — the
    # estimator stays non-persistable like the reference, but a fitted model
    # (its item set IS the index) saves/loads without refit via the inherited
    # write()/read() chain + the _ann_index_spec hook above.


class _ApproxNNClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {
            "k": "n_neighbors",
            "algorithm": "algorithm",
            "algoParams": "algo_params",
            "metric": "metric",
            "inputCol": "",
            "featuresCols": "",
            "idCol": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "algorithm": lambda x: x
            if x in ("ivfflat", "ivf_flat", "ivfpq", "ivf_pq", "cagra", "brute_force")
            else None,
            "metric": lambda x: x
            if x in ("euclidean", "sqeuclidean", "l2", "cosine")
            else None,
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_neighbors": 5,
            "algorithm": "ivfflat",
            "algo_params": None,
            "metric": "euclidean",
        }


class ApproximateNearestNeighbors(_ApproxNNClass, _TpuEstimator, _NNParams):
    """ANN with an IVF-Flat index built by our distributed kmeans
    (reference knn.py:838-1723; algorithm/algoParams names follow the reference's
    cuVS translation table knn.py:1324-1404 — ivfflat params: nlist, nprobe)."""

    algorithm: Param[str] = Param(
        "undefined",
        "algorithm",
        "algorithm to use: 'ivfflat', 'ivfpq', 'cagra' or 'brute_force'.",
        TypeConverters.toString,
    )
    algoParams: Param[Dict[str, Any]] = Param(
        "undefined",
        "algoParams",
        "algorithm parameters, e.g. {'nlist': 64, 'nprobe': 8}.",
        DictTypeConverters._toDict,
    )
    metric: Param[str] = Param(
        "undefined", "metric", "distance metric.", TypeConverters.toString
    )

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(k=5, algorithm="ivfflat", metric="euclidean", algoParams=None)
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return ["centers", "center_norms", "cells", "cell_ids", "cell_sizes"]

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        algo_params = self.getOrDefault("algoParams") or {}
        # both cuML and cuVS spellings are accepted, like the reference's
        # translation table (knn.py:1370-1380: nlist/n_lists, nprobe/n_probes)
        nlist = int(_ap(algo_params, "nlist", "n_lists", default=64))
        seed = int(algo_params.get("seed", 42))
        algo = self.getOrDefault("algorithm")

        cosine = self.getOrDefault("metric") == "cosine"

        def _fit(inputs: FitInputs) -> Dict[str, Any]:
            if cosine:
                # cosine reduces to euclidean on the unit sphere (cuVS handles
                # cosine the same way): normalize items at build; queries normalize
                # at search and distances convert to 1 - cos = d^2/2
                inputs.features = _normalize_or_raise(inputs.features, inputs.row_weight)
            if algo == "cagra":
                # cuVS cagra param names (reference knn.py:1324-1404,1513-1524)
                from ..ops.knn import cagra_build

                return cagra_build(
                    inputs.features,
                    inputs.row_weight,
                    graph_degree=int(
                        _ap(
                            algo_params, "graph_degree",
                            "intermediate_graph_degree", default=32,
                        )
                    ),
                    nlist=int(_ap(algo_params, "nlist", "n_lists", default=0)),
                    seed=seed,
                )
            if algo in ("ivfpq", "ivf_pq"):
                # cuVS ivf_pq param names (reference translation table knn.py:1324-1404)
                return ivfpq_build(
                    inputs.features,
                    inputs.row_weight,
                    nlist=min(nlist, inputs.desc.m),
                    m_subvectors=int(_ap(algo_params, "M", "pq_dim", default=4)),
                    n_bits=int(_ap(algo_params, "n_bits", "pq_bits", default=8)),
                    max_iter=20,
                    seed=seed,
                )
            return ivfflat_build(
                inputs.features, inputs.row_weight, nlist=min(nlist, inputs.desc.m),
                max_iter=20, seed=seed,
            )

        return _fit

    def _streaming_fit(self, fd) -> Dict[str, Any]:
        """Out-of-core ANN builds: items stay host-resident; the device sees
        only assignment/encoding/search batches (ops/ann_streaming.py) — the
        ANN leg of the reference's UVM/SAM tier (utils.py:184-241). IVF-Flat
        streams cell assignment; IVF-PQ adds subsample codebooks + streamed
        encoding passes; CAGRA derives its graph from streamed IVF searches.
        Search then pages in only the probed cells for the IVF indexes
        (ApproximateNearestNeighborsModel.kneighbors picks the streamed search
        when the cells exceed the stream threshold). Cosine streams too: the
        builds normalize per batch (no normalized dataset copy except CAGRA,
        whose graph search needs unit items resident anyway)."""
        from ..core.dataset import densify as _densify
        from ..ops.ann_streaming import (
            resolve_build_batch_rows,
            streaming_cagra_build,
            streaming_ivfflat_build,
            streaming_ivfpq_build,
        )

        algo = self.getOrDefault("algorithm")
        if algo not in ("ivfflat", "ivf_flat", "ivfpq", "ivf_pq", "cagra"):
            self.logger.warning(
                "streamed ANN covers ivfflat/ivfpq/cagra; fitting in-core "
                "despite stream_threshold_bytes."
            )
            inputs = self._build_fit_inputs(fd)
            return self._get_tpu_fit_func(None)(inputs)
        cosine = self.getOrDefault("metric") == "cosine"
        algo_params = self.getOrDefault("algoParams") or {}
        nlist = int(_ap(algo_params, "nlist", "n_lists", default=64))
        seed = int(algo_params.get("seed", 42))
        # batch geometry is a lifecycle knob (`ann.build_batch_rows`, §7b):
        # config pin > tuning table > stream_batch_rows
        batch_rows = resolve_build_batch_rows(fd.n_rows, fd.n_cols)
        X = np.asarray(_densify(fd.features, self._float32_inputs))
        if algo == "cagra":
            # the BUILD streams, but cagra_search walks the graph with random
            # access and needs the item set device-resident — unlike the IVF
            # searches there is no paged variant, so query time will stage
            # items on device. Say so now rather than OOM-ing at kneighbors.
            self.logger.warning(
                "streamed CAGRA build keeps items host-resident, but CAGRA "
                "search requires the full item set (~%.0f MiB) on device; "
                "kneighbors() will stage it and may exhaust device memory — "
                "prefer algorithm='ivfflat'/'ivfpq' for datasets beyond HBM.",
                X.nbytes / 2**20,
            )
            return streaming_cagra_build(
                X,
                graph_degree=int(
                    _ap(
                        algo_params, "graph_degree",
                        "intermediate_graph_degree", default=32,
                    )
                ),
                nlist=int(_ap(algo_params, "nlist", "n_lists", default=0)),
                seed=seed,
                batch_rows=batch_rows,
                cosine=cosine,
            )
        if algo in ("ivfpq", "ivf_pq"):
            return streaming_ivfpq_build(
                X,
                nlist=min(nlist, fd.n_rows),
                m_subvectors=int(_ap(algo_params, "M", "pq_dim", default=4)),
                n_bits=int(_ap(algo_params, "n_bits", "pq_bits", default=8)),
                max_iter=20,
                seed=seed,
                batch_rows=batch_rows,
                cosine=cosine,
            )
        return streaming_ivfflat_build(
            X,
            nlist=min(nlist, fd.n_rows),
            max_iter=20,
            seed=seed,
            batch_rows=batch_rows,
            cosine=cosine,
        )

    def _create_pyspark_model(self, attrs) -> "ApproximateNearestNeighborsModel":
        return ApproximateNearestNeighborsModel(**attrs)

    def _fit(self, dataset: Any) -> "ApproximateNearestNeighborsModel":
        from ..observability import fit_run

        dataset = self._ensureIdCol(dataset)
        fd = self._pre_process_data(dataset)
        # one FitRun over the whole build (this override used to bypass the
        # §6d scope the generic _fit opens): the pipelined streamed builds'
        # batch counters/histograms and rank timeline land in one exported
        # report, like every other estimator's
        with fit_run(algo=type(self).__name__) as run:
            if self.getOrDefault("algorithm") == "brute_force":
                model = ApproximateNearestNeighborsModel(
                    centers=np.zeros((0, fd.n_cols), np.float32),
                    cells=np.zeros((0, 0, fd.n_cols), np.float32),
                    cell_ids=np.zeros((0, 0), np.int64),
                    cell_sizes=np.zeros((0,), np.int32),
                )
                items = np.asarray(fd.features)
                if self.getOrDefault("metric") == "cosine":
                    import jax.numpy as jnp

                    items = np.asarray(
                        _normalize_or_raise(
                            jnp.asarray(items), jnp.ones(len(items))
                        )
                    )
                model._brute_items = items
                from ..ops.knn import center_norms_sq

                model._brute_norms = center_norms_sq(items)
            else:
                model = self._fit_internal(dataset, None)[0]
        if run is not None:
            model.fit_report_ = run.report()
        model._item_row_ids = (
            fd.row_id if fd.row_id is not None else np.arange(fd.n_rows, dtype=np.int64)
        )
        model._item_df = dataset
        self._copyValues(model)
        return model

    def write(self):
        raise NotImplementedError("ApproximateNearestNeighbors is not persistable.")


class ApproximateNearestNeighborsModel(_ApproxNNClass, _TpuModel, _NNParams):
    algorithm = ApproximateNearestNeighbors.algorithm
    algoParams = ApproximateNearestNeighbors.algoParams
    metric = ApproximateNearestNeighbors.metric

    def __init__(
        self,
        centers: Optional[np.ndarray] = None,
        cells: Optional[np.ndarray] = None,
        cell_ids: Optional[np.ndarray] = None,
        cell_sizes: Optional[np.ndarray] = None,
        codebooks: Optional[np.ndarray] = None,
        codes: Optional[np.ndarray] = None,
        items: Optional[np.ndarray] = None,
        graph: Optional[np.ndarray] = None,
        center_norms: Optional[np.ndarray] = None,
        item_norms_sq: Optional[np.ndarray] = None,
    ) -> None:
        if graph is not None:
            # CAGRA-class graph index (ops/knn.py cagra_build)
            attrs = dict(items=np.asarray(items), graph=np.asarray(graph))
            if item_norms_sq is not None:
                attrs["item_norms_sq"] = np.asarray(item_norms_sq)
        else:
            attrs = dict(
                centers=np.asarray(centers),
                cells=np.asarray(cells),
                cell_ids=np.asarray(cell_ids),
                cell_sizes=np.asarray(cell_sizes),
            )
            if center_norms is not None:
                # cached Σ centers² from the build — probe scans never
                # recompute it (rebuilt on refit with the index itself)
                attrs["center_norms"] = np.asarray(center_norms)
        if codebooks is not None:
            attrs["codebooks"] = np.asarray(codebooks)
            attrs["codes"] = np.asarray(codes)
        super().__init__(**attrs)
        self._setDefault(k=5, algorithm="ivfflat", metric="euclidean", algoParams=None)
        self._brute_items: Optional[np.ndarray] = None
        self._brute_norms: Optional[np.ndarray] = None
        self._item_row_ids: Optional[np.ndarray] = None
        self._item_df: Any = None
        self._ivf_state: Any = None  # MutableIvfState once mutated (§7b)
        self._dev: Any = None  # lazy DeviceIndexCache (per-segment HBM)
        self.logger = get_logger(self.__class__)

    def __getstate__(self):
        # the device cache holds jax buffers — never pickle it; the receiver
        # re-uploads lazily on its first search
        state = dict(self.__dict__)
        state["_dev"] = None
        return state

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        raise NotImplementedError("Use kneighbors() / approxSimilarityJoin().")

    # ---- lazy device residency (ops/ann_lifecycle.py::DeviceIndexCache) ----

    def _dev_get(self, name: str, host_array: Any = None):
        """Device copy of one index segment: uploaded on FIRST search, then
        HBM-resident across searches — a loaded index stages only what the
        query path touches (cold-start never uploads the whole index)."""
        arr = (
            host_array if host_array is not None
            else self._model_attributes.get(name)
        )
        if arr is None:
            return None
        if self._dev is None:
            from ..ops.ann_lifecycle import DeviceIndexCache

            self._dev = DeviceIndexCache()
        return self._dev.get(name, arr)

    def _invalidate_device(self, *names: str) -> None:
        if self._dev is not None:
            self._dev.invalidate(*names)

    # ---- persistence (ANN index store, docs/design.md §7b) ----

    def _ann_index_spec(self):
        if self._brute_items is not None:
            raise NotImplementedError(
                "brute_force ANN models hold no index to persist; refit (or "
                "use NearestNeighborsModel, whose item set persists)."
            )
        arrays = {
            k: np.asarray(v)
            for k, v in self._model_attributes.items()
            if v is not None and hasattr(v, "shape")
        }
        if self._item_row_ids is not None:
            arrays["item_row_ids"] = np.asarray(self._item_row_ids)
        meta: Dict[str, Any] = {"tombstones": 0}
        if self._ivf_state is not None:
            arrays["item_cells"] = np.asarray(self._ivf_state.item_cells)
            arrays["cell_fill"] = np.asarray(self._ivf_state.cell_fill)
            meta["tombstones"] = int(self._ivf_state.tombstones)
        return arrays, str(self.getOrDefault("algorithm")), meta

    @classmethod
    def _from_row(cls, attrs: Dict[str, Any]
                  ) -> "ApproximateNearestNeighborsModel":
        manifest = attrs.pop("__ann_manifest__", None)
        item_row_ids = attrs.pop("item_row_ids", None)
        item_cells = attrs.pop("item_cells", None)
        cell_fill = attrs.pop("cell_fill", None)
        model = cls(**attrs)
        if item_row_ids is not None:
            model._item_row_ids = np.asarray(item_row_ids)
        if item_cells is not None and cell_fill is not None:
            from ..ops.ann_lifecycle import MutableIvfState

            model._ivf_state = MutableIvfState(
                item_cells, cell_fill,
                tombstones=int(
                    ((manifest or {}).get("meta") or {}).get("tombstones", 0)
                ),
            )
        return model

    # ---- incremental add/delete (docs/design.md §7b) ----

    def _ensure_ivf_state(self):
        if "graph" in self._model_attributes or self._brute_items is not None:
            raise NotImplementedError(
                "incremental add/delete covers the IVF indexes (ivfflat/"
                "ivfpq); CAGRA graphs and brute_force require a rebuild."
            )
        if self._item_row_ids is None:
            raise ValueError(
                "model has no item-id mapping; fit it (or load a saved "
                "index) before mutating"
            )
        if self._ivf_state is None:
            from ..ops.ann_lifecycle import MutableIvfState

            self._ivf_state = MutableIvfState.from_layout(
                np.asarray(self._model_attributes["cell_ids"]),
                len(self._item_row_ids),
            )
        return self._ivf_state

    def enable_incremental(self, slack_rows: int = 0) -> None:
        """Round the IVF list capacity up to its power-of-two bucket (plus
        optional slack): the one shape change, paid BEFORE serving, that
        makes later in-slack adds zero-compile (§7b)."""
        from ..ops.ann_lifecycle import rebucket_layout

        self._ensure_ivf_state()
        if rebucket_layout(self._model_attributes, slack_rows=slack_rows):
            self._invalidate_device("cells", "cell_ids", "codes")

    def add_items(self, X_new: np.ndarray, ids: "np.ndarray | None" = None
                  ) -> np.ndarray:
        """Append items into the IVF lists (host-side assign/encode, hole
        reuse, bucketed growth — ops/ann_lifecycle.py::ivf_add). Returns the
        user ids assigned to the new items."""
        from ..ops.ann_lifecycle import ivf_add

        state = self._ensure_ivf_state()
        X_new = np.ascontiguousarray(np.asarray(X_new), np.float32)
        m = len(X_new)
        row_ids = np.asarray(self._item_row_ids)
        if ids is None:
            base = int(row_ids.max(initial=-1)) + 1
            ids = np.arange(base, base + m, dtype=np.int64)
        else:
            ids = np.asarray(ids, np.int64)
        positions = np.arange(len(row_ids), len(row_ids) + m, dtype=np.int64)
        ivf_add(
            self._model_attributes, state, X_new, positions,
            cosine=self.getOrDefault("metric") == "cosine",
        )
        self._item_row_ids = np.concatenate([row_ids, ids])
        self._invalidate_device("cells", "cell_ids", "codes")
        self._maybe_compact()
        return ids

    def delete_items(self, ids: np.ndarray) -> int:
        """Tombstone items by user id: their list slots flip to the -1
        sentinel the probe scans already mask to INVALID_D2 — deleted items
        vanish from results with no kernel or shape change."""
        from ..ops.ann_lifecycle import ivf_delete

        state = self._ensure_ivf_state()
        positions = np.nonzero(
            np.isin(np.asarray(self._item_row_ids), np.asarray(ids, np.int64))
        )[0]
        n = ivf_delete(self._model_attributes, state, positions)
        if n:
            self._invalidate_device("cell_ids")
            self._maybe_compact()
        return n

    def _maybe_compact(self) -> None:
        from ..ops.ann_lifecycle import ivf_compact, needs_compaction

        if self._ivf_state is not None and needs_compaction(self._ivf_state):
            ivf_compact(self._model_attributes, self._ivf_state)
            self._invalidate_device("cells", "cell_ids", "codes")

    def tombstone_fraction(self) -> float:
        """Tombstoned slots / occupied slots — what the compaction trigger
        compares against `ann.compact_tombstone_pct`."""
        if self._ivf_state is None:
            return 0.0
        occupied = self._ivf_state.live_items() + max(
            self._ivf_state.tombstones, 0
        )
        return self._ivf_state.tombstones / occupied if occupied else 0.0

    def kneighbors(self, query_df: Any) -> Tuple[Any, Any, pd.DataFrame]:
        import jax.numpy as jnp

        query_df = self._ensureIdCol(query_df)
        input_col, input_cols = self._get_input_columns()
        id_col = self.getIdCol()
        fd = extract_feature_data(
            query_df, input_col=input_col, input_cols=input_cols, id_col=id_col
        )
        Q = np.asarray(fd.features)
        query_ids = (
            fd.row_id if fd.row_id is not None else np.arange(len(Q), dtype=np.int64)
        )
        k = self.getK()
        cosine = self.getOrDefault("metric") == "cosine"
        if cosine:
            # the index holds unit vectors; normalize queries the same way
            Q = np.asarray(
                _normalize_or_raise(jnp.asarray(Q), jnp.ones(len(Q)))
            )

        from ..observability.inference import predict_dispatch

        if self._brute_items is not None:
            from ..ops.knn import exact_knn_single

            items = self._brute_items
            x2b = self._brute_norms
            d2, idx = predict_dispatch(
                self, exact_knn_single,
                jnp.asarray(Q), self._dev_get("brute_items", items),
                jnp.ones((items.shape[0],), bool), min(k, items.shape[0]),
                x2=(
                    self._dev_get("brute_norms", x2b)
                    if x2b is not None else None
                ),
                model_name=type(self).__name__,
            )
            dists = np.sqrt(np.asarray(d2))
            pos = np.asarray(idx)
        elif "graph" in self._model_attributes:
            from ..ops.knn import cagra_search

            algo_params = self.getOrDefault("algoParams") or {}
            dists_j, ids_j = predict_dispatch(
                self, cagra_search,
                jnp.asarray(Q),
                # lazy per-segment device residency (§7b): items/graph upload
                # on the FIRST search and replay from HBM afterwards
                self._dev_get("items"),
                self._dev_get("graph"),
                k=k,
                itopk=int(algo_params.get("itopk_size", max(64, k))),
                iterations=int(algo_params.get("max_iterations", 32)),
                # width>1 batches the neighbor gathers: ~2.5x faster at equal
                # recall on this kernel (cuVS search_width)
                search_width=int(algo_params.get("search_width", 4)),
                x2=self._dev_get("item_norms_sq"),
                model_name=type(self).__name__,
            )
            dists = np.asarray(dists_j)
            pos = np.asarray(ids_j)
        else:
            algo_params = self.getOrDefault("algoParams") or {}
            nlist = self._model_attributes["centers"].shape[0]
            nprobe = int(
                _ap(algo_params, "nprobe", "n_probes", default=max(1, nlist // 8))
            )
            cn_j = self._dev_get("center_norms")
            if "codebooks" in self._model_attributes:
                from ..ops.knn import pq_refine

                refine_ratio = int(algo_params.get("refine_ratio", 2))
                dists_j, ids_j, flat_pos = predict_dispatch(
                    self, ivfpq_search,
                    jnp.asarray(Q),
                    self._dev_get("centers"),
                    self._dev_get("codebooks"),
                    self._dev_get("codes"),
                    self._dev_get("cell_ids"),
                    k=k * max(refine_ratio, 1),
                    nprobe=min(nprobe, nlist),
                    center_norms=cn_j,
                    model_name=type(self).__name__,
                )
                if refine_ratio > 1:
                    # exact re-rank of the ADC candidates (reference knn.py:1642-1666)
                    from .. import config as _config

                    cells_np = self._model_attributes["cells"]
                    threshold = _config.get("stream_threshold_bytes")
                    if threshold and getattr(cells_np, "nbytes", 0) > threshold:
                        # out-of-core: device_put of the full cell layout would
                        # OOM exactly when the build streamed — page in only
                        # the candidate vectors (ops/ann_streaming.py)
                        from ..ops.ann_streaming import streaming_pq_refine

                        self.logger.info(
                            "IVF-PQ cells ~%.0f MiB exceed stream_threshold_"
                            "bytes; refining with host-paged candidates",
                            cells_np.nbytes / 2**20,
                        )
                        dists_j, ids_j = streaming_pq_refine(
                            np.asarray(Q), np.asarray(cells_np),
                            np.asarray(flat_pos), np.asarray(ids_j), k=k,
                        )
                    else:
                        from ..observability import span as _obs_span

                        with _obs_span("knn.rerank", {"k": k}):
                            dists_j, ids_j = pq_refine(
                                jnp.asarray(Q),
                                self._dev_get("cells", cells_np),
                                flat_pos,
                                ids_j,
                                k=k,
                            )
            else:
                from .. import config as _config

                cells_np = self._model_attributes["cells"]
                threshold = _config.get("stream_threshold_bytes")
                if threshold and getattr(cells_np, "nbytes", 0) > threshold:
                    # out-of-core search: cells stay host-resident, only the
                    # probed cells page onto the device (ops/ann_streaming.py)
                    from ..ops.ann_streaming import streaming_ivfflat_search

                    self.logger.info(
                        "IVF cells ~%.0f MiB exceed stream_threshold_bytes; "
                        "searching with host-resident cells",
                        cells_np.nbytes / 2**20,
                    )
                    dists_j, ids_j = predict_dispatch(
                        self, streaming_ivfflat_search,
                        np.asarray(Q), self._model_attributes, k=k,
                        nprobe=min(nprobe, nlist),
                    )
                else:
                    dists_j, ids_j = predict_dispatch(
                        self, ivfflat_search,
                        jnp.asarray(Q),
                        self._dev_get("centers"),
                        self._dev_get("cells", cells_np),
                        self._dev_get("cell_ids"),
                        k=k,
                        nprobe=min(nprobe, nlist),
                        center_norms=cn_j,
                        model_name=type(self).__name__,
                    )
            dists = np.asarray(dists_j)
            pos = np.asarray(ids_j)

        ids = np.where(pos >= 0, self._item_row_ids[np.maximum(pos, 0)], -1)
        if cosine:
            # searches ran euclidean on unit vectors: cosine distance = d^2 / 2
            dists = np.where(np.isfinite(dists), (dists * dists) / 2.0, dists)
        knn_df = pd.DataFrame(
            {
                f"query_{id_col}": query_ids,
                "indices": list(ids),
                "distances": list(dists.astype(np.float32)),
            }
        )
        return self._item_df, query_df, knn_df

    def approxSimilarityJoin(
        self, query_df: Any, distCol: str = "distCol"
    ) -> pd.DataFrame:
        _, query_df, knn_df = self.kneighbors(query_df)
        id_col = self.getIdCol()
        rows = []
        for _, r in knn_df.iterrows():
            for item_id, dist in zip(r["indices"], r["distances"]):
                if item_id >= 0 and np.isfinite(dist):
                    rows.append((r[f"query_{id_col}"], item_id, dist))
        return pd.DataFrame(rows, columns=[f"query_{id_col}", f"item_{id_col}", distCol])

    def write(self):
        # brute_force holds no index (its item set lives outside the
        # attribute dict); the real indexes persist via the ANN store (§7b)
        if self._brute_items is not None:
            raise NotImplementedError(
                "brute_force ApproximateNearestNeighborsModel is not "
                "persistable; use an indexed algorithm (ivfflat/ivfpq/cagra)."
            )
        return super().write()
