#
# LinearRegression estimator/model (L6 API) — pyspark.ml.regression.LinearRegression-
# compatible surface; OLS/Ridge/ElasticNet fit as one SPMD stats pass + replicated
# solver on the TPU mesh.
#
# Structural equivalent of reference python/src/spark_rapids_ml/regression.py:181-863:
#   * param mapping incl. regParam->alpha, standardization->normalize
#     (reference regression.py:183-215)
#   * solver dispatch by regularization (reference regression.py:548-606): here
#     closed-form L2 vs FISTA elastic net (ops/linear.py)
#   * single-pass fitMultiple reusing the data pass (reference regression.py:657-674)
#   * 1-feature inputs are supported (the reference guards/raises for dim==1 because
#     of a cuML limitation, regression.py:499-505 — no such limit here)
# (RandomForestRegressor, the other member of the reference module, lives in
# models/tree.py.)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import densify
from ..core.backend_params import HasFeaturesCols, _TpuClass
from ..core.estimator import (
    FitInputs,
    _TpuEstimatorSupervised,
    _TpuModelWithPredictionCol,
)
from ..core.params import (
    HasElasticNetParam,
    HasFeaturesCol,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasRegParam,
    HasSolver,
    HasStandardization,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
)
from ..ops.linear import linreg_fit, linreg_predict


class _LinearRegressionClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        # reference regression.py:183-215
        return {
            "regParam": "alpha",
            "elasticNetParam": "l1_ratio",
            "fitIntercept": "fit_intercept",
            "standardization": "normalize",
            "maxIter": "max_iter",
            "tol": "tol",
            "loss": "loss",
            "solver": "solver",
            # huber is NATIVE here (ops/linear.huber_fit) — the reference cannot
            # run it on device at all (cuML lacks huber; regression.py:183-215)
            "epsilon": "epsilon",
            "aggregationDepth": "",
            "maxBlockSizeInMB": "",
            "featuresCol": "",
            "labelCol": "",
            "predictionCol": "",
            "weightCol": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "loss": lambda x: {
                "squaredError": "squared_loss",
                "squared_loss": "squared_loss",
                "huber": "huber",
            }.get(x),
            "solver": lambda x: {"auto": "eig", "normal": "eig", "eig": "eig", "l-bfgs": "eig"}.get(x),
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "alpha": 0.0,
            "l1_ratio": 0.0,
            "fit_intercept": True,
            "normalize": True,
            "max_iter": 100,
            "tol": 1e-6,
            "loss": "squared_loss",
            "solver": "eig",
            "epsilon": 1.35,
        }

    @classmethod
    def _fallback_class(cls):
        from sklearn.linear_model import LinearRegression as SkLR

        return SkLR


class _LinearRegressionParams(
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasSolver,
    HasWeightCol,
):
    loss: Param[str] = Param(
        "undefined",
        "loss",
        "The loss function to be optimized. Supported options: squaredError, huber.",
        TypeConverters.toString,
    )
    epsilon: Param[float] = Param(
        "undefined",
        "epsilon",
        "The shape parameter to control the amount of robustness (huber only).",
        TypeConverters.toFloat,
    )
    maxBlockSizeInMB: Param[float] = Param(
        "undefined",
        "maxBlockSizeInMB",
        "Maximum memory in MB for stacking input data into blocks.",
        TypeConverters.toFloat,
    )
    aggregationDepth: Param[int] = Param(
        "undefined",
        "aggregationDepth",
        "suggested depth for treeAggregate (>= 2).",
        TypeConverters.toInt,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)

    def setPredictionCol(self, value: str):
        return self._set(predictionCol=value)


class LinearRegression(_LinearRegressionClass, _TpuEstimatorSupervised, _LinearRegressionParams):
    """LinearRegression (OLS/Ridge/Lasso/ElasticNet/huber) on the TPU mesh.

    Squared loss: one sharded pass accumulates (XᵀWX, XᵀWy) with the psum over ICI;
    the d×d solve is replicated. Huber loss: native concomitant-scale L-BFGS
    (ops/linear.huber_fit — the reference has no device huber at all). Drop-in for
    pyspark.ml.regression.LinearRegression / reference
    spark_rapids_ml.regression.LinearRegression (reference regression.py:312-660).
    """

    # Spark ParamValidators.gt(1.0) for the huber shape parameter
    _PARAM_BOUNDS_EXTRA = {"epsilon": (1.0 + 1e-12, None)}

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            regParam=0.0,
            elasticNetParam=0.0,
            fitIntercept=True,
            standardization=True,
            maxIter=100,
            tol=1e-6,
            loss="squaredError",
            epsilon=1.35,
            solver="auto",
            aggregationDepth=2,
            maxBlockSizeInMB=0.0,
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def setRegParam(self, value: float) -> "LinearRegression":
        return self._set_params(regParam=value)  # type: ignore[return-value]

    def setElasticNetParam(self, value: float) -> "LinearRegression":
        return self._set_params(elasticNetParam=value)  # type: ignore[return-value]

    def _out_schema(self) -> List[str]:
        # scale present on huber (fallback) fits only; model defaults it to 1.0
        return ["coefficients", "intercept", "n_iter", "scale"]

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # the sufficient-statistics pass is shared across all param maps
        return True

    def _supports_sparse_fit(self) -> bool:
        # matrix-free ELL normal-equation solver in ops/sparse.py
        return True

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        p = dict(self._tpu_params)

        def _fit(inputs: FitInputs):
            # dispatch PER PARAM SET on its own loss — a fitMultiple map may flip
            # between squared and huber (each extra set is a full backend dict)
            sets = extra_params if extra_params is not None else [p]
            results: List[Optional[Dict[str, Any]]] = [None] * len(sets)
            hb = [
                i for i, s in enumerate(sets)
                if s.get("loss", "squared_loss") == "huber"
            ]
            sq = [i for i in range(len(sets)) if i not in set(hb)]

            if hb:
                from ..ops.linear import huber_fit

                if inputs.sparse_values is not None:
                    raise ValueError(
                        "loss='huber' requires dense features "
                        "(disable enable_sparse_data_optim)."
                    )
                for i in hb:
                    if float(sets[i].get("l1_ratio", 0.0)) != 0.0:
                        # Spark: huber supports only L2 regularization
                        raise ValueError(
                            "loss='huber' supports only L2 regularization "
                            "(elasticNetParam must be 0.0)."
                        )
                hres = huber_fit(
                    inputs.features, inputs.label, inputs.row_weight,
                    epsilon=float(p.get("epsilon", 1.35)),
                    reg=float(p["alpha"]),
                    fit_intercept=bool(p["fit_intercept"]),
                    standardize=bool(p["normalize"]),
                    max_iter=int(p["max_iter"]),
                    tol=float(p["tol"]),
                    extra_param_sets=[sets[i] for i in hb],
                )
                for j, i in enumerate(hb):
                    results[i] = hres[j]

            if sq:
                common = dict(
                    reg=float(p["alpha"]),
                    l1_ratio=float(p["l1_ratio"]),
                    fit_intercept=bool(p["fit_intercept"]),
                    standardize=bool(p["normalize"]),
                    max_iter=int(p["max_iter"]),
                    tol=float(p["tol"]),
                    extra_param_sets=[sets[i] for i in sq],
                )
                if inputs.sparse_values is not None:
                    from ..ops.sparse import sparse_linreg_fit

                    sqres = sparse_linreg_fit(
                        inputs.sparse_values,
                        inputs.sparse_indices,
                        inputs.desc.n,
                        inputs.label,
                        inputs.row_weight,
                        **common,
                    )
                else:
                    sqres = linreg_fit(
                        inputs.features, inputs.label, inputs.row_weight,
                        mesh=inputs.mesh, unit_weight=inputs.unit_weight, **common
                    )
                for j, i in enumerate(sq):
                    results[i] = sqres[j]
            return results if extra_params is not None else results[0]

        return _fit

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "LinearRegressionModel":
        return LinearRegressionModel(**attrs)

    def _streaming_fit(self, fd, chain_ops=None) -> Dict[str, Any]:
        """Out-of-core fit: stream batches, accumulate (XᵀWX, XᵀWy) on device
        (ops/streaming.py) — numerically identical to the in-core stats pass.
        `chain_ops` carries upstream featurizer transforms when this fit is the
        terminal stage of a fused pipeline chain (pipeline.py)."""
        from .. import config as _config
        from ..core.dataset import densify as _densify
        from ..ops.linear import solve_from_stats
        from ..ops.streaming import streaming_linreg_stats
        from ..parallel.partitioner import active_partitioner

        p = self._tpu_params
        if p.get("loss", "squared_loss") == "huber":
            if chain_ops:
                # the fuser gates on fuse-eligibility, so only a direct caller
                # can land here; in-core would silently drop the chain
                raise ValueError(
                    "loss='huber' fits in-core and cannot run a fused "
                    "featurize->fit chain."
                )
            # huber has no sufficient-statistics form; fit in-core (the robust loss
            # needs the residuals every iteration)
            self.logger.warning(
                "loss='huber' has no streamed sufficient-statistics form; "
                "fitting in-core despite stream_threshold_bytes."
            )
            inputs = self._build_fit_inputs(fd)
            return self._get_tpu_fit_func(None)(inputs)
        mesh = active_partitioner(self.num_workers).mesh
        A, b, xbar, ybar, sw = streaming_linreg_stats(
            _densify(fd.features, self._float32_inputs),
            fd.label,
            fd.weight,
            batch_rows=int(_config.get("stream_batch_rows")),
            mesh=mesh,
            float32=self._float32_inputs,
            chain_ops=chain_ops,
        )
        attrs = solve_from_stats(
            A, b, xbar, ybar, sw,
            reg=float(p["alpha"]),
            l1_ratio=float(p["l1_ratio"]),
            fit_intercept=bool(p["fit_intercept"]),
            standardize=bool(p["normalize"]),
            max_iter=int(p["max_iter"]),
            tol=float(p["tol"]),
        )[0]
        # live telemetry (§6g): one convergence record for the streamed linreg —
        # the unpenalized normal-equation residual ‖(Aβ + c·Σwx − b)/Σw‖ is the
        # squared-loss gradient norm at the solution (≈0 for an exact l2 solve,
        # the leftover prox residual for elastic net)
        from ..observability import convergence as obs_convergence

        coef = np.asarray(attrs["coefficients"], np.float64)
        grad = (
            np.asarray(A, np.float64) @ coef
            + float(attrs["intercept"]) * np.asarray(xbar, np.float64) * float(sw)
            - np.asarray(b, np.float64)
        ) / float(sw)
        obs_convergence(
            "linreg", attrs.get("n_iter", 1),
            grad_norm=float(np.linalg.norm(grad)),
        )
        return attrs

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        X = densify(fd.features, float32=self._float32_inputs)
        X64 = np.asarray(X, dtype=np.float64)
        fit_intercept = self.getOrDefault("fitIntercept")
        if self.getOrDefault("loss") == "huber":
            from sklearn.linear_model import HuberRegressor

            # sklearn's objective SUMS the data term; the native path (and Spark)
            # use the mean + lambda/2 penalty — rescale alpha for equivalence
            n_eff = float(np.sum(fd.weight)) if fd.weight is not None else float(
                fd.n_rows
            )
            sk = HuberRegressor(
                epsilon=max(self.getOrDefault("epsilon"), 1.0),
                alpha=0.5 * self.getOrDefault("regParam") * n_eff,
                fit_intercept=fit_intercept,
            ).fit(X64, fd.label, sample_weight=fd.weight)
            return {
                "coefficients": sk.coef_.astype(np.float32),
                "intercept": float(sk.intercept_),
                "n_iter": int(getattr(sk, "n_iter_", 1) or 1),
                # huber sigma — Spark's LinearRegressionModel.scale
                "scale": float(sk.scale_),
            }
        else:
            reg = self.getOrDefault("regParam")
            l1r = self.getOrDefault("elasticNetParam")
            n = fd.n_rows
            if reg == 0.0:
                sk = twin(fit_intercept=fit_intercept)
            elif l1r == 0.0:
                from sklearn.linear_model import Ridge

                sk = Ridge(alpha=reg * n, fit_intercept=fit_intercept)
            else:
                from sklearn.linear_model import ElasticNet

                sk = ElasticNet(
                    alpha=reg, l1_ratio=l1r, fit_intercept=fit_intercept,
                    max_iter=max(self.getOrDefault("maxIter"), 1000),
                )
            sk = sk.fit(X64, fd.label, sample_weight=fd.weight)
        return {
            "coefficients": sk.coef_.astype(np.float32),
            "intercept": float(sk.intercept_),
            "n_iter": int(getattr(sk, "n_iter_", 1) or 1),
        }


class LinearRegressionModel(
    _LinearRegressionClass, _TpuModelWithPredictionCol, _LinearRegressionParams
):
    """Fitted linear regression model (reference regression.py:700-863)."""

    def __init__(
        self,
        coefficients: np.ndarray,
        intercept: float,
        n_iter: int,
        scale: float = 1.0,
    ) -> None:
        super().__init__(
            coefficients=np.asarray(coefficients),
            intercept=float(intercept),
            n_iter=int(n_iter),
            scale=float(scale),
        )
        self._setDefault(featuresCol="features", labelCol="label", predictionCol="prediction")

    @property
    def coefficients(self) -> np.ndarray:
        return self._model_attributes["coefficients"]

    @property
    def intercept(self) -> float:
        return self._model_attributes["intercept"]

    @property
    def numFeatures(self) -> int:
        return int(self._model_attributes["coefficients"].shape[0])

    def partial_fit_updater(self, **kwargs):
        """Streamed continual-learning updater anchored on this model: exact
        re-solves from decayed normal-equation statistics (continual/
        partial_fit.py, docs/design.md §7d)."""
        from ..continual.partial_fit import LinearRegressionUpdater

        return LinearRegressionUpdater(self, **kwargs)

    @property
    def scale(self) -> float:
        """Huber scale sigma for huber fits; 1.0 for squared-error fits. (The
        reference hardcodes 1.0 because cuML has no huber, regression.py:760-763;
        here the huber path fits sklearn's HuberRegressor and its sigma is part of
        the model state.)"""
        return float(self._model_attributes.get("scale", 1.0))

    @property
    def hasSummary(self) -> bool:
        """No training summary is produced (reference regression.py:745-750)."""
        return False

    @property
    def summary(self):
        """Spark raises when hasSummary is False; match it."""
        raise RuntimeError(
            f"No training summary available for this {self.__class__.__name__}"
        )

    def evaluate(self, dataset: Any) -> "LinearRegressionSummary":
        """Evaluate on a labeled dataset, returning the Spark summary surface —
        computed natively (the reference exposes no evaluate/summary for
        regression at all)."""
        from ..core.estimator import extract_eval_columns

        out, label, pred, weight = extract_eval_columns(self, dataset)
        return LinearRegressionSummary(
            out, label, pred, weight,
            num_features=self.numFeatures,
            fit_intercept=bool(self.getOrDefault("fitIntercept")),
        )

    def cpu(self):
        """sklearn LinearRegression twin with the fitted state installed."""
        from sklearn.linear_model import LinearRegression as SkLinReg

        sk = SkLinReg()
        sk.coef_ = np.asarray(self._model_attributes["coefficients"], np.float64)
        sk.intercept_ = float(self._model_attributes["intercept"])
        sk.n_features_in_ = sk.coef_.shape[0]
        return sk

    def predict(self, value: np.ndarray) -> float:
        from ..observability.inference import predict_dispatch

        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return float(
            np.asarray(
                predict_dispatch(
                    self, linreg_predict, X, self.coefficients, self.intercept
                )
            )[0]
        )

    def _combine(self, models: List["LinearRegressionModel"]) -> "LinearRegressionModel":
        """Stack models fitted by fitMultiple for CV transform-evaluate
        (reference regression.py:828-846)."""
        first = models[0]
        first._combined_models = models
        return first

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        from ..observability.inference import predict_dispatch

        pred = np.asarray(
            predict_dispatch(
                self, linreg_predict, X, self.coefficients,
                np.float32(self.intercept),
            )
        )
        return {self.getOrDefault("predictionCol"): pred}

    def _supports_sparse_transform(self) -> bool:
        return True

    def _transform_sparse(self, csr) -> Dict[str, np.ndarray]:
        """Predict on CSR queries without densifying (ELL gather matvec)."""
        import jax.numpy as jnp

        from ..observability.inference import predict_dispatch
        from ..ops.sparse import csr_to_ell, ell_matvec

        values, indices = csr_to_ell(csr, float32=True)
        pred = (
            np.asarray(
                predict_dispatch(
                    self,
                    ell_matvec,
                    jnp.asarray(values),
                    jnp.asarray(indices),
                    jnp.asarray(np.asarray(self.coefficients, np.float32)),
                )
            )
            + self.intercept
        )
        return {self.getOrDefault("predictionCol"): pred}


class LinearRegressionSummary:
    """Evaluation summary over a predictions frame — the surface of
    pyspark.ml.regression.LinearRegressionSummary, computed natively on the
    metrics/ reduction classes (the reference exposes no summary at all)."""

    def __init__(
        self,
        predictions,
        label: np.ndarray,
        pred: np.ndarray,
        weight: "np.ndarray | None" = None,
        num_features: int = 0,
        fit_intercept: bool = True,
    ) -> None:
        from ..metrics.RegressionMetrics import RegressionMetrics

        self.predictions = predictions
        self._m = RegressionMetrics.from_predictions(label, pred, weight)
        self._n = len(np.asarray(label))
        self._dof = max(self._n - num_features - (1 if fit_intercept else 0), 0)

    @property
    def rootMeanSquaredError(self) -> float:
        return self._m.root_mean_squared_error

    @property
    def meanSquaredError(self) -> float:
        return self._m.mean_squared_error

    @property
    def meanAbsoluteError(self) -> float:
        return self._m.mean_absolute_error

    @property
    def r2(self) -> float:
        return self._m.r2

    @property
    def explainedVariance(self) -> float:
        return self._m.explained_variance

    @property
    def numInstances(self) -> int:
        return self._n

    @property
    def degreesOfFreedom(self) -> int:
        return self._dof
