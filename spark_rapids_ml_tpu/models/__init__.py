# Algorithm estimator/model classes (L6 API layer). Top-level compatibility modules
# (spark_rapids_ml_tpu.feature, .clustering, ...) re-export from here so imports mirror
# the reference's `spark_rapids_ml.feature.PCA` layout.
