#
# RandomForestClassifier / RandomForestRegressor (L6 API) — pyspark.ml-compatible
# surface over the TPU histogram forest builder (ops/trees.py).
#
# Structural equivalent of reference python/src/spark_rapids_ml/tree.py +
# classification.py:285-676 + regression.py:865-1147:
#   * the reference splits numTrees across workers, each training locally on its
#     shard, then treelite-concatenates (tree.py:330-341,424-457 — P2 embarrassing
#     parallelism). The TPU builder instead grows every tree on ALL the (sharded)
#     data with per-level histogram psums — same API, better statistical efficiency
#     (no per-worker data fragmentation), and the "merge" is an ICI reduction.
#   * missing-label check (reference tree.py:415-421)
#   * probability/rawPrediction columns for the classifier
#     (reference classification.py:502-515)
#   * JSON forest dump for interop (reference tree.py:534-559 treelite JSON)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import densify
from ..core.backend_params import HasFeaturesCols, _TpuClass
from ..core.estimator import (
    FitInputs,
    _TpuEstimatorSupervised,
    _TpuModelWithPredictionCol,
)
from ..core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasSeed,
    HasWeightCol,
    Param,
    TypeConverters,
)
from ..ops.trees import (
    forest_fit,
    forest_to_json,
    predict_forest,
    resolve_feature_subset,
)


class _RandomForestClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        # reference tree.py:103-156
        return {
            "numTrees": "n_estimators",
            "maxDepth": "max_depth",
            "maxBins": "n_bins",
            "minInstancesPerNode": "min_samples_leaf",
            "minInfoGain": "min_impurity_decrease",
            "featureSubsetStrategy": "max_features",
            "subsamplingRate": "max_samples",
            "bootstrap": "bootstrap",
            "impurity": "split_criterion",
            "seed": "random_state",
            "minWeightFractionPerNode": None,
            "maxMemoryInMB": "",
            "cacheNodeIds": "",
            "checkpointInterval": "",
            "leafCol": None,
            "featuresCol": "",
            "labelCol": "",
            "predictionCol": "",
            "probabilityCol": "",
            "rawPredictionCol": "",
            "weightCol": "",
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_estimators": 20,
            "max_depth": 5,
            "n_bins": 32,
            "min_samples_leaf": 1,
            "min_impurity_decrease": 0.0,
            "max_features": "auto",
            "max_samples": 1.0,
            "bootstrap": True,
            "split_criterion": "gini",
            "random_state": 0,
        }


class _RandomForestParams(
    HasFeaturesCol,
    HasFeaturesCols,
    HasLabelCol,
    HasPredictionCol,
    HasSeed,
    HasWeightCol,
):
    numTrees: Param[int] = Param(
        "undefined", "numTrees", "Number of trees to train (>= 1).", TypeConverters.toInt
    )
    maxDepth: Param[int] = Param(
        "undefined", "maxDepth", "Maximum depth of the tree (>= 0).", TypeConverters.toInt
    )
    maxBins: Param[int] = Param(
        "undefined",
        "maxBins",
        "Max number of bins for discretizing continuous features.",
        TypeConverters.toInt,
    )
    minInstancesPerNode: Param[int] = Param(
        "undefined",
        "minInstancesPerNode",
        "Minimum number of instances each child must have after split.",
        TypeConverters.toInt,
    )
    minInfoGain: Param[float] = Param(
        "undefined",
        "minInfoGain",
        "Minimum information gain for a split to be considered at a tree node.",
        TypeConverters.toFloat,
    )
    featureSubsetStrategy: Param[str] = Param(
        "undefined",
        "featureSubsetStrategy",
        "The number of features to consider for splits at each tree node: "
        "auto|all|onethird|sqrt|log2|(0.0-1.0]|[1-n].",
        TypeConverters.toString,
    )
    subsamplingRate: Param[float] = Param(
        "undefined",
        "subsamplingRate",
        "Fraction of the training data used for learning each decision tree.",
        TypeConverters.toFloat,
    )
    bootstrap: Param[bool] = Param(
        "undefined", "bootstrap", "Whether bootstrap samples are used.", TypeConverters.toBoolean
    )
    impurity: Param[str] = Param(
        "undefined", "impurity", "Criterion used for information gain calculation.",
        TypeConverters.toString,
    )
    minWeightFractionPerNode: Param[float] = Param(
        "undefined",
        "minWeightFractionPerNode",
        "Minimum fraction of the weighted sample count each child must have.",
        TypeConverters.toFloat,
    )
    # Spark executor-memory/caching knobs with no TPU meaning; accepted and ignored
    # for drop-in compatibility (reference tree.py:103-156 maps them to "")
    maxMemoryInMB: Param[int] = Param(
        "undefined", "maxMemoryInMB",
        "Maximum memory in MB allocated to histogram aggregation (ignored).",
        TypeConverters.toInt,
    )
    cacheNodeIds: Param[bool] = Param(
        "undefined", "cacheNodeIds",
        "Whether to cache node IDs for each instance (ignored).",
        TypeConverters.toBoolean,
    )
    checkpointInterval: Param[int] = Param(
        "undefined", "checkpointInterval",
        "Checkpoint interval for the node-id cache (ignored).",
        TypeConverters.toInt,
    )
    leafCol: Param[str] = Param(
        "undefined", "leafCol",
        "Leaf-index output column (unsupported -> CPU fallback when set).",
        TypeConverters.toString,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)

    def getNumTrees(self) -> int:
        return self.getOrDefault("numTrees")

    def getMaxDepth(self) -> int:
        return self.getOrDefault("maxDepth")


class _RandomForestEstimator(_RandomForestClass, _TpuEstimatorSupervised, _RandomForestParams):
    _is_classification = False
    # Spark caps tree depth at 30; the heap-layout forest (2^(depth+1) slots) makes
    # an early clear error strictly better than a depth-exponential OOM
    _PARAM_BOUNDS_EXTRA = {"maxDepth": (0, 30)}
    # sklearn forests produce no leaf-index column; a fallback would silently
    # return a model missing the output the user asked for
    _FALLBACK_CANNOT_HONOR = frozenset({"leafCol"})

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            numTrees=20,
            maxDepth=5,
            maxBins=32,
            minInstancesPerNode=1,
            minInfoGain=0.0,
            featureSubsetStrategy="auto",
            subsamplingRate=1.0,
            bootstrap=True,
            seed=0,
            minWeightFractionPerNode=0.0,
            maxMemoryInMB=256,
            cacheNodeIds=False,
            checkpointInterval=10,
            leafCol="",
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return ["feature", "threshold", "is_leaf", "value", "gain", "node_weight",
                "bin_edges", "num_classes"]

    def _row_stats(self, inputs: FitInputs) -> np.ndarray:
        raise NotImplementedError

    def _impurity_name(self) -> str:
        raise NotImplementedError

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # host rows + per-tree stats are staged once; each param map re-bins only if
        # its n_bins differs (P6 pattern, reference tree.py:475-507)
        return True

    def _streaming_fit(self, fd) -> Dict[str, Any]:
        """Out-of-core fit: X streams through host binning in row blocks and only
        the binned uint8 matrix (4x smaller than f32) + per-row stats reside on
        device (ops/trees.streaming_forest_fit) — the RandomForest analog of the
        reference's UVM/SAM path (reference utils.py:184-241). BASELINE config 4
        (50M x 64, ~12.8 GiB f32) bins to ~3.1 GiB on a 16 GiB chip. Selected by
        core/estimator.py when the design matrix exceeds stream_threshold_bytes;
        maxBins must fit uint8 (<= 256) — wider binning routes in-core."""
        from types import SimpleNamespace

        from .. import config as _config
        from ..core.dataset import densify as _densify
        from ..ops.trees import streaming_forest_fit
        from ..parallel.partition import pad_rows
        from ..parallel.partitioner import active_partitioner

        p = self._tpu_params
        if int(p["n_bins"]) > 256:
            self.logger.warning(
                "streamed RandomForest bins to uint8 (maxBins <= 256); fitting "
                "in-core despite stream_threshold_bytes."
            )
            inputs = self._build_fit_inputs(fd)
            return self._get_tpu_fit_func(None)(inputs)
        X = _densify(fd.features, self._float32_inputs)
        stats, n_classes = self._row_stats(
            SimpleNamespace(host_label=fd.label, host_row_weight=fd.weight)
        )
        part = active_partitioner(self.num_workers)
        mesh = part.mesh
        n_dev = part.num_workers

        def shard_fn(arr: np.ndarray):
            padded, _, _ = pad_rows(arr, n_dev)
            return part.shard(padded)

        attrs = streaming_forest_fit(
            np.asarray(X),
            stats,
            n_trees=int(p["n_estimators"]),
            max_depth=int(p["max_depth"]),
            max_bins=int(p["n_bins"]),
            impurity=self._impurity_name(),
            feature_subset=resolve_feature_subset(
                str(p["max_features"]), X.shape[1], self._is_classification
            ),
            min_instances=int(p["min_samples_leaf"]),
            min_info_gain=float(p["min_impurity_decrease"]),
            subsampling_rate=float(p["max_samples"]),
            bootstrap=bool(p["bootstrap"]),
            seed=int(p["random_state"]) if p["random_state"] is not None else 0,
            batch_rows=int(_config.get("stream_batch_rows")),
            shard_fn=shard_fn,
            mesh=mesh,
        )
        attrs["num_classes"] = n_classes
        return attrs

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        base = dict(self._tpu_params)
        is_cls = self._is_classification

        def _fit(inputs: FitInputs):
            X = inputs.host_features
            stats, n_classes = self._row_stats(inputs)
            d = X.shape[1]
            from ..parallel.partition import pad_rows
            from ..parallel.partitioner import partitioner_for

            mesh = inputs.mesh
            part = partitioner_for(mesh)
            n_dev = part.num_workers

            def shard_fn(arr: np.ndarray):
                padded, _, _ = pad_rows(arr, n_dev)
                return part.shard(padded)

            param_sets = extra_params if extra_params is not None else [base]
            results = []
            for ep in param_sets:
                p = {**base, **ep}
                attrs = forest_fit(
                    X,
                    stats,
                    n_trees=int(p["n_estimators"]),
                    max_depth=int(p["max_depth"]),
                    max_bins=int(p["n_bins"]),
                    impurity=self._impurity_name(),
                    feature_subset=resolve_feature_subset(
                        str(p["max_features"]), d, is_cls
                    ),
                    min_instances=int(p["min_samples_leaf"]),
                    min_info_gain=float(p["min_impurity_decrease"]),
                    subsampling_rate=float(p["max_samples"]),
                    bootstrap=bool(p["bootstrap"]),
                    seed=int(p["random_state"]) if p["random_state"] is not None else 0,
                    shard_fn=shard_fn,
                    mesh=mesh,
                )
                attrs["num_classes"] = n_classes
                results.append(attrs)
            return results if extra_params is not None else results[0]

        return _fit


def _sk_forest_to_heap(sk_model, is_classification: bool, n_features: int) -> Dict[str, Any]:
    """Translate a fitted sklearn forest into this framework's heap-layout arrays
    (the CPU-fallback model translation; the reference's equivalent converts between
    cuML and Spark tree formats, utils.py:694-809)."""

    estimators = sk_model.estimators_
    depth = max(e.tree_.max_depth for e in estimators)
    n_slots = 2 ** (depth + 1)
    v_dim = sk_model.n_classes_ if is_classification else 1

    n_trees = len(estimators)
    feature = np.full((n_trees, n_slots), -1, np.int32)
    threshold = np.zeros((n_trees, n_slots), np.float32)
    is_leaf = np.zeros((n_trees, n_slots), bool)
    value = np.zeros((n_trees, n_slots, v_dim), np.float32)
    gain = np.zeros((n_trees, n_slots), np.float32)
    node_weight = np.zeros((n_trees, n_slots), np.float32)

    for ti, est in enumerate(estimators):
        t = est.tree_
        stack = [(0, 1)]  # (sklearn node id, heap pos)
        while stack:
            nid, pos = stack.pop()
            val = t.value[nid].reshape(-1)
            if is_classification:
                s = val.sum()
                value[ti, pos] = val / s if s > 0 else val
            else:
                value[ti, pos] = val[:1]
            w = float(t.weighted_n_node_samples[nid])
            node_weight[ti, pos] = w
            if t.children_left[nid] == -1:
                is_leaf[ti, pos] = True
            else:
                feature[ti, pos] = t.feature[nid]
                threshold[ti, pos] = t.threshold[nid]
                # per-unit-weight impurity decrease (same scale the TPU builder
                # records) so featureImportances works identically on fallback fits
                left, right = t.children_left[nid], t.children_right[nid]
                wl = float(t.weighted_n_node_samples[left])
                wr = float(t.weighted_n_node_samples[right])
                gain[ti, pos] = max(
                    float(t.impurity[nid])
                    - (wl / w) * float(t.impurity[left])
                    - (wr / w) * float(t.impurity[right]),
                    0.0,
                )
                stack.append((left, 2 * pos))
                stack.append((right, 2 * pos + 1))

    return {
        "feature": feature,
        "threshold": threshold,
        "is_leaf": is_leaf,
        "value": value,
        "gain": gain,
        "node_weight": node_weight,
        "bin_edges": np.zeros((n_features, 1), np.float32),
        "num_classes": sk_model.n_classes_ if is_classification else 0,
    }


class RandomForestRegressor(_RandomForestEstimator):
    """Random forest regression on the TPU mesh (reference regression.py:865-1147)."""

    _is_classification = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(impurity="variance")
        self._set_params(**kwargs)

    @classmethod
    def _param_value_mapping(cls):
        return {"split_criterion": lambda x: x if x == "variance" else None}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        base = dict(_RandomForestClass._get_tpu_params_default())
        base["split_criterion"] = "variance"
        return base

    @classmethod
    def _fallback_class(cls):
        from sklearn.ensemble import RandomForestRegressor as SkRFR

        return SkRFR

    def _impurity_name(self) -> str:
        return "variance"

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        X = densify(fd.features, float32=self._float32_inputs)
        sk = twin(
            n_estimators=self.getOrDefault("numTrees"),
            max_depth=max(self.getOrDefault("maxDepth"), 1),
            min_samples_leaf=self.getOrDefault("minInstancesPerNode"),
            bootstrap=self.getOrDefault("bootstrap"),
            random_state=self.getOrDefault("seed") & 0x7FFFFFFF,
        ).fit(X, fd.label, sample_weight=fd.weight)
        return _sk_forest_to_heap(sk, False, X.shape[1])

    def _row_stats(self, inputs: FitInputs):
        y = inputs.host_label.astype(np.float64)
        w = np.ones_like(y) if inputs.host_row_weight is None else inputs.host_row_weight
        stats = np.stack([w, w * y, w * y * y], axis=1).astype(np.float32)
        return stats, 0

    def _create_pyspark_model(self, attrs) -> "RandomForestRegressionModel":
        return RandomForestRegressionModel(**attrs)


class RandomForestClassifier(
    _RandomForestEstimator, HasProbabilityCol, HasRawPredictionCol
):
    """Random forest classification on the TPU mesh
    (reference classification.py:285-676)."""

    _is_classification = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            impurity="gini", probabilityCol="probability", rawPredictionCol="rawPrediction"
        )
        self._set_params(**kwargs)

    @classmethod
    def _param_value_mapping(cls):
        return {"split_criterion": lambda x: x if x in ("gini", "entropy") else None}

    @classmethod
    def _fallback_class(cls):
        from sklearn.ensemble import RandomForestClassifier as SkRFC

        return SkRFC

    def _impurity_name(self) -> str:
        return self._tpu_params.get("split_criterion", "gini")

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        X = densify(fd.features, float32=self._float32_inputs)
        sk = twin(
            n_estimators=self.getOrDefault("numTrees"),
            max_depth=max(self.getOrDefault("maxDepth"), 1),
            min_samples_leaf=self.getOrDefault("minInstancesPerNode"),
            bootstrap=self.getOrDefault("bootstrap"),
            random_state=self.getOrDefault("seed") & 0x7FFFFFFF,
        ).fit(X, fd.label, sample_weight=fd.weight)
        return _sk_forest_to_heap(sk, True, X.shape[1])

    def _row_stats(self, inputs: FitInputs):
        y = inputs.host_label
        classes = np.unique(y)
        n_classes = int(classes.max()) + 1 if len(classes) else 0
        if not np.array_equal(classes, classes.astype(np.int64)) or (
            len(classes) and classes.min() < 0
        ):
            raise ValueError("Labels must be non-negative integers 0..k-1.")
        if len(classes) != n_classes:
            # reference raises with workaround text (tree.py:415-421)
            raise RuntimeError(
                f"Labels {sorted(set(range(n_classes)) - set(classes.astype(int)))} "
                "are missing from the dataset: every class in 0..k-1 must appear."
            )
        w = (
            np.ones(len(y), np.float64)
            if inputs.host_row_weight is None
            else inputs.host_row_weight.astype(np.float64)
        )
        stats = np.zeros((len(y), n_classes), np.float32)
        stats[np.arange(len(y)), y.astype(int)] = w
        return stats, n_classes

    def _create_pyspark_model(self, attrs) -> "RandomForestClassificationModel":
        return RandomForestClassificationModel(**attrs)


class _RandomForestModel(_RandomForestClass, _TpuModelWithPredictionCol, _RandomForestParams):
    _is_classification = False

    def __init__(
        self,
        feature: np.ndarray,
        threshold: np.ndarray,
        is_leaf: np.ndarray,
        value: np.ndarray,
        bin_edges: np.ndarray,
        num_classes: int,
        gain: "np.ndarray | None" = None,
        node_weight: "np.ndarray | None" = None,
    ) -> None:
        feature = np.asarray(feature)
        # gain/node_weight absent on JSON-imported forests (the dump carries
        # structure, not training statistics) -> importances are all-zero there
        super().__init__(
            feature=feature,
            threshold=np.asarray(threshold),
            is_leaf=np.asarray(is_leaf),
            value=np.asarray(value),
            bin_edges=np.asarray(bin_edges),
            num_classes=int(num_classes),
            gain=(
                np.zeros(feature.shape, np.float32)
                if gain is None
                else np.asarray(gain)
            ),
            node_weight=(
                np.zeros(feature.shape, np.float32)
                if node_weight is None
                else np.asarray(node_weight)
            ),
        )
        self._setDefault(
            featuresCol="features", labelCol="label", predictionCol="prediction",
            numTrees=20, maxDepth=5,
        )

    @property
    def numFeatures(self) -> int:
        return int(self._model_attributes["bin_edges"].shape[0])

    def getNumTrees(self) -> int:
        return int(self._model_attributes["feature"].shape[0])

    @property
    def treeWeights(self) -> List[float]:
        return [1.0] * self.getNumTrees()

    @property
    def max_depth_(self) -> int:
        import math

        return int(math.log2(self._model_attributes["feature"].shape[1])) - 1

    def _reachable_slots(self, tree_idx: int) -> List[int]:
        """Heap slots actually present in tree `tree_idx` (walk from root slot 1;
        children of leaves are padding)."""
        a = self._model_attributes
        feat = a["feature"][tree_idx]
        leaf = a["is_leaf"][tree_idx]
        n_slots = feat.shape[0]
        out: List[int] = []
        stack = [1]
        while stack:
            p = stack.pop()
            if p >= n_slots:
                continue
            out.append(p)
            if not leaf[p] and feat[p] >= 0:
                stack.extend((2 * p, 2 * p + 1))
        return out

    @property
    def totalNumNodes(self) -> int:
        """Total number of nodes, summed over all trees (Spark
        TreeEnsembleModel.totalNumNodes)."""
        return sum(len(self._reachable_slots(i)) for i in range(self.getNumTrees()))

    @property
    def featureImportances(self) -> np.ndarray:
        """Impurity-based feature importances (Spark TreeEnsembleModel semantics:
        per tree, each internal node contributes gain x node weight to its split
        feature; trees are normalized to sum 1, averaged, and renormalized). The
        reference cannot compute this without a Spark conversion and raises
        (reference tree.py:567-572); here the builder records per-node gain and
        weight, so importances come straight from the heap arrays."""
        a = self._model_attributes
        d = self.numFeatures
        total = np.zeros(d, np.float64)
        for i in range(self.getNumTrees()):
            imp = np.zeros(d, np.float64)
            feat = a["feature"][i]
            contrib = a["gain"][i] * a["node_weight"][i]
            for p in self._reachable_slots(i):
                if feat[p] >= 0 and not a["is_leaf"][i][p]:
                    imp[feat[p]] += contrib[p]
            s = imp.sum()
            if s > 0:
                total += imp / s
        s = total.sum()
        return (total / s if s > 0 else total).astype(np.float64)

    def _tree_debug_string(self, tree_idx: int) -> str:
        a = self._model_attributes
        feat = a["feature"][tree_idx]
        thr = a["threshold"][tree_idx]
        leaf = a["is_leaf"][tree_idx]
        value = a["value"][tree_idx]
        lines: List[str] = []

        def walk(p: int, depth: int) -> None:
            pad = "  " * depth
            if leaf[p] or feat[p] < 0:
                v = value[p]
                pred = float(np.argmax(v)) if self._is_classification else float(v[0])
                lines.append(f"{pad}Predict: {pred}")
                return
            lines.append(f"{pad}If (feature {int(feat[p])} <= {float(thr[p])})")
            walk(2 * p, depth + 1)
            lines.append(f"{pad}Else (feature {int(feat[p])} > {float(thr[p])})")
            walk(2 * p + 1, depth + 1)

        walk(1, 1)
        return "\n".join(lines)

    @property
    def toDebugString(self) -> str:
        """Full text description of the forest (Spark toDebugString shape)."""
        n = self.getNumTrees()
        head = (
            f"{self.__class__.__name__} with {n} trees, "
            f"{self.totalNumNodes} total nodes\n"
        )
        parts = []
        for i in range(n):
            n_nodes = len(self._reachable_slots(i))
            parts.append(f"  Tree {i} ({n_nodes} nodes):\n{self._tree_debug_string(i)}")
        return head + "\n".join(parts)

    @property
    def trees(self) -> List["_DecisionTreeView"]:
        """Per-tree views (Spark returns DecisionTreeModels; without a JVM these are
        lightweight standalone equivalents with numNodes/depth/toDebugString/
        predict)."""
        return [_DecisionTreeView(self, i) for i in range(self.getNumTrees())]

    def _serving_device_attrs(self):
        # the forest predict kernel's device operands include the int/bool
        # structure arrays, not just float weights (the estimator default)
        return ("feature", "threshold", "is_leaf", "value")

    def _forest_outputs(self, X: np.ndarray) -> np.ndarray:
        from ..observability.inference import predict_dispatch

        a = self._model_attributes
        return np.asarray(
            predict_dispatch(
                self,
                predict_forest,
                X.astype(np.float32),
                a["feature"],
                a["threshold"],
                a["is_leaf"],
                a["value"].astype(np.float32),
                self.max_depth_,
            )
        )

    def toJSON(self) -> List[Dict]:
        """Forest dump (the reference's treelite-JSON role, tree.py:534-559)."""
        return forest_to_json(self._model_attributes, self._is_classification)

    @classmethod
    def fromJSON(
        cls, trees_json: List[Dict], n_features: int, num_classes: int = 0
    ) -> "_RandomForestModel":
        """Rebuild a model from a forest JSON dump (the import half of the
        reference's treelite interop, tree.py:439-449): a roundtrip through
        toJSON()/fromJSON() predicts identically, and externally-produced dumps in
        the same shape import the same way."""
        from ..ops.trees import forest_from_json

        attrs = forest_from_json(trees_json, n_features, cls._is_classification)
        attrs["num_classes"] = int(num_classes)
        return cls(**attrs)

    @classmethod
    def fromTreeliteJSON(
        cls,
        model_json: Any,
        n_features: int | None = None,
        num_classes: int = 0,
    ) -> "_RandomForestModel":
        """Import a treelite JSON dump — the format cuML forests serialize to and
        the reference's models carry (reference tree.py:534-559 `dump_as_json`,
        utils.py:700-809 node schema). Accepts the full model dict (with `trees` +
        `num_feature`) or a bare list of tree dicts plus n_features. Classification
        leaves may be `leaf_vector` class probabilities or scalar votes."""
        from ..ops.trees import forest_from_treelite_json

        attrs = forest_from_treelite_json(
            model_json, cls._is_classification, n_features
        )
        attrs["num_classes"] = int(num_classes)
        return cls(**attrs)


class _DecisionTreeView:
    """One tree of a fitted forest: the standalone stand-in for Spark's
    DecisionTree{Classification,Regression}Model returned by `model.trees`."""

    def __init__(self, forest: "_RandomForestModel", tree_idx: int) -> None:
        self._forest = forest
        self._idx = int(tree_idx)

    @property
    def numNodes(self) -> int:
        return len(self._forest._reachable_slots(self._idx))

    @property
    def depth(self) -> int:
        # floor(log2(slot)) is the node's level (root slot 1 -> level 0)
        slots = self._forest._reachable_slots(self._idx)
        return max(int(np.floor(np.log2(p))) for p in slots) if slots else 0

    @property
    def toDebugString(self) -> str:
        return (
            f"DecisionTreeModel ({self.numNodes} nodes)\n"
            + self._forest._tree_debug_string(self._idx)
        )

    def predict(self, value: np.ndarray) -> float:
        """Route one sample through this single tree."""
        a = self._forest._model_attributes
        x = np.asarray(value, np.float32).ravel()
        feat = a["feature"][self._idx]
        thr = a["threshold"][self._idx]
        leaf = a["is_leaf"][self._idx]
        val = a["value"][self._idx]
        p = 1
        while not leaf[p] and feat[p] >= 0:
            p = 2 * p + int(x[feat[p]] > thr[p])
        v = val[p]
        return (
            float(np.argmax(v)) if self._forest._is_classification else float(v[0])
        )


class RandomForestRegressionModel(_RandomForestModel):
    def predict(self, value: np.ndarray) -> float:
        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return float(self._forest_outputs(X)[0, 0])

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        return {self.getOrDefault("predictionCol"): self._forest_outputs(X)[:, 0]}

    def evaluate(self, dataset: Any):
        """Regression summary on a labeled dataset (Spark model surface; computed
        natively — the reference exposes no evaluate for forests)."""
        from ..core.estimator import extract_eval_columns
        from .regression import LinearRegressionSummary

        out, label, pred, weight = extract_eval_columns(self, dataset)
        return LinearRegressionSummary(
            out, label, pred, weight, num_features=self.numFeatures,
            fit_intercept=False,
        )


class RandomForestClassificationModel(
    _RandomForestModel, HasProbabilityCol, HasRawPredictionCol
):
    _is_classification = True

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(probabilityCol="probability", rawPredictionCol="rawPrediction")

    @property
    def numClasses(self) -> int:
        return self._model_attributes["num_classes"]

    def predict(self, value: np.ndarray) -> float:
        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return float(np.argmax(self._forest_outputs(X)[0]))

    def predictProbability(self, value: np.ndarray) -> np.ndarray:
        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return self._forest_outputs(X)[0]

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        prob = self._forest_outputs(X)
        # normalize away any averaging drift
        prob = prob / np.maximum(prob.sum(axis=1, keepdims=True), 1e-12)
        return {
            self.getOrDefault("predictionCol"): prob.argmax(axis=1).astype(np.float64),
            self.getOrDefault("probabilityCol"): prob,
            self.getOrDefault("rawPredictionCol"): prob * self.getNumTrees(),
        }

    def evaluate(self, dataset: Any):
        """Classification summary on a labeled dataset (Spark 3.1+
        RandomForestClassificationSummary surface; binary models additionally get
        the ROC/PR sweep). Computed natively — the reference exposes no evaluate
        for forests."""
        from ..core.estimator import extract_eval_columns
        from .classification import (
            BinaryLogisticRegressionSummary,
            LogisticRegressionSummary,
        )

        out, label, pred, weight = extract_eval_columns(self, dataset)
        if self.numClasses == 2:
            prob = np.stack(out[self.getOrDefault("probabilityCol")].to_numpy())
            return BinaryLogisticRegressionSummary(out, label, pred, prob[:, 1], weight)
        return LogisticRegressionSummary(out, label, pred, weight)
