#
# KMeans estimator/model (L6 API) — pyspark.ml.clustering.KMeans-compatible surface,
# fit as one SPMD Lloyd program over the TPU mesh.
#
# Structural equivalent of reference python/src/spark_rapids_ml/clustering.py:84-604:
#   * param mapping incl. tol=0 -> tiny epsilon (reference clustering.py:84-141)
#   * n_init forced to 1 for Spark parity (reference clustering.py:317-319)
#   * fit returns cluster centers + inertia + n_iter attributes
#     (reference clustering.py:376-456)
# (DBSCAN, the other member of the reference module, lives in models/dbscan.py.)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import densify
from ..core.backend_params import HasFeaturesCols, _TpuClass
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModelWithPredictionCol
from ..core.params import (
    HasFeaturesCol,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
)
from ..ops.kmeans import kmeans_fit, kmeans_predict


class _KMeansClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        # reference clustering.py:84-141
        return {
            "k": "n_clusters",
            "maxIter": "max_iter",
            "tol": "tol",
            "initMode": "init",
            "initSteps": "init_steps",
            "seed": "random_state",
            "distanceMeasure": "metric",  # euclidean + cosine (spherical kmeans)
            "featuresCol": "",
            "predictionCol": "",
            "weightCol": "",
            "solver": None,
            "maxBlockSizeInMB": None,
        }

    @classmethod
    def _param_value_mapping(cls):
        # tol=0 would spin max_iter rounds; remap to a tiny epsilon like the reference
        return {
            "tol": lambda x: 1.0e-16 if x == 0 else float(x),
            "init": lambda x: (
                x if x in ("k-means||", "scalable-k-means++", "random") else None
            ),
            "metric": lambda x: x if x in ("euclidean", "cosine") else None,
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "n_clusters": 8,
            "max_iter": 300,
            "tol": 1e-4,
            "init": "k-means||",
            "init_steps": 2,
            "random_state": 1,
            "metric": "euclidean",
            "n_init": 1,  # Spark parity (reference clustering.py:317-319)
        }

    @classmethod
    def _fallback_class(cls):
        from sklearn.cluster import KMeans as SkKMeans

        return SkKMeans


class _KMeansParams(
    HasFeaturesCol, HasFeaturesCols, HasPredictionCol, HasMaxIter, HasTol, HasSeed, HasWeightCol
):
    k: Param[int] = Param(
        "undefined", "k", "The number of clusters to create. Must be > 1.", TypeConverters.toInt
    )
    initMode: Param[str] = Param(
        "undefined",
        "initMode",
        "The initialization algorithm. Supported options: 'k-means||' and 'random'.",
        TypeConverters.toString,
    )
    initSteps: Param[int] = Param(
        "undefined",
        "initSteps",
        "The number of steps for k-means|| initialization mode. Must be > 0.",
        TypeConverters.toInt,
    )
    distanceMeasure: Param[str] = Param(
        "undefined",
        "distanceMeasure",
        "the distance measure. Supported options: 'euclidean' and 'cosine'.",
        TypeConverters.toString,
    )
    solver: Param[str] = Param(
        "undefined",
        "solver",
        "The solver algorithm for optimization. Supported options: 'auto', 'row', 'block'.",
        TypeConverters.toString,
    )
    maxBlockSizeInMB: Param[float] = Param(
        "undefined",
        "maxBlockSizeInMB",
        "Maximum memory in MB for stacking input data into blocks.",
        TypeConverters.toFloat,
    )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setPredictionCol(self, value: str):
        return self._set(predictionCol=value)


class KMeans(_KMeansClass, _TpuEstimator, _KMeansParams):
    # Spark's KMeans validator requires k > 1 (pyspark ParamValidators.gt(1))
    _PARAM_BOUNDS_EXTRA = {"k": (2, None)}
    """KMeans on the TPU mesh: one jitted Lloyd loop, centroid psum over ICI.

    Drop-in for pyspark.ml.clustering.KMeans / reference
    spark_rapids_ml.clustering.KMeans (reference clustering.py:226-456).

    Example
    -------
    >>> from spark_rapids_ml_tpu.clustering import KMeans
    >>> model = KMeans(k=4, featuresCol="features").fit(df)
    >>> model.transform(df)   # adds 'prediction' column
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            predictionCol="prediction",
            k=2,
            maxIter=20,
            tol=1e-4,
            initMode="k-means||",
            initSteps=2,
            seed=1,
            distanceMeasure="euclidean",
            solver="auto",
            maxBlockSizeInMB=0.0,
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def setK(self, value: int) -> "KMeans":
        return self._set_params(k=value)  # type: ignore[return-value]

    def setMaxIter(self, value: int) -> "KMeans":
        return self._set_params(maxIter=value)  # type: ignore[return-value]

    def _out_schema(self) -> List[str]:
        # cluster_sizes feeds the training summary (absent on streamed/fallback
        # fits; the model tolerates it)
        return ["cluster_centers", "inertia", "n_iter", "cluster_sizes"]

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # the sharded design matrix is staged on the mesh ONCE and every param map's
        # Lloyd run reuses it (reference loops cuML fits over the concatenated data,
        # P6 pattern, SURVEY.md §2.7)
        return True

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        base = dict(self._tpu_params)

        def _fit(inputs: FitInputs):
            param_sets = extra_params if extra_params is not None else [base]
            results = []
            for ep in param_sets:
                p = {**base, **ep}
                if int(p["n_clusters"]) > inputs.desc.m:
                    raise ValueError(
                        f"k={p['n_clusters']} exceeds the number of rows "
                        f"{inputs.desc.m}; initialization would select padding rows "
                        "as centers."
                    )
                res = kmeans_fit(
                    inputs.features,
                    inputs.row_weight,
                    k=int(p["n_clusters"]),
                    max_iter=int(p["max_iter"]),
                    tol=float(p["tol"]),
                    init=str(p["init"]),
                    init_steps=int(p["init_steps"]),
                    seed=int(p["random_state"]) if p["random_state"] is not None else 1,
                    metric=str(p.get("metric", "euclidean")),
                    unit_weight=inputs.unit_weight,
                )
                # one assignment pass for the training summary's clusterSizes
                # (Spark KMeansSummary; the reference produces no summary). Done
                # HERE — not inside kmeans_fit — so the IVF index builds that call
                # the op directly never pay it. Counts ALL real rows (padding is
                # positional: rows beyond desc.m), including user weight-0 rows,
                # matching Spark's groupBy(prediction).count().
                import jax.numpy as _jnp

                from ..ops.kmeans import kmeans_predict

                assign = np.asarray(
                    kmeans_predict(
                        inputs.features,
                        _jnp.asarray(res["cluster_centers"]),
                        cosine=str(p.get("metric", "euclidean")) == "cosine",
                    )
                )[: inputs.desc.m]
                res["cluster_sizes"] = np.bincount(
                    assign, minlength=int(p["n_clusters"])
                ).astype(np.int64)
                results.append(res)
            return results if extra_params is not None else results[0]

        return _fit

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "KMeansModel":
        return KMeansModel(**attrs)

    def _streaming_fit(self, fd, chain_ops=None) -> Dict[str, Any]:
        """Out-of-core exact Lloyd (ops/streaming.py): full-pass center updates with
        one batch resident at a time — the KMeans analog of the reference's UVM/SAM
        large-dataset path (utils.py:184-241). Selected automatically when the design
        matrix exceeds stream_threshold_bytes (core/estimator.py). `chain_ops`
        carries upstream featurizer transforms when this fit is the terminal
        stage of a fused pipeline chain (pipeline.py): they apply in-program, so
        raw batches upload once and intermediates never touch the host."""
        from .. import config as _config
        from ..core.dataset import densify as _densify
        from ..ops.streaming import streaming_kmeans_fit
        from ..parallel.partitioner import active_partitioner

        p = self._tpu_params
        if int(p["n_clusters"]) > fd.n_rows:
            raise ValueError(
                f"k={p['n_clusters']} exceeds the number of rows {fd.n_rows}."
            )
        return streaming_kmeans_fit(
            _densify(fd.features, self._float32_inputs),
            fd.weight,
            k=int(p["n_clusters"]),
            max_iter=int(p["max_iter"]),
            tol=float(p["tol"]),
            seed=int(p["random_state"]) if p["random_state"] is not None else 1,
            batch_rows=int(_config.get("stream_batch_rows")),
            mesh=active_partitioner(self.num_workers).mesh,
            metric=str(p.get("metric", "euclidean")),
            float32=self._float32_inputs,
            chain_ops=chain_ops,
        )

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        if self.getOrDefault("distanceMeasure") != "euclidean":
            raise ValueError(
                "The sklearn CPU fallback cannot preserve distanceMeasure='cosine' "
                "(cosine IS supported on the TPU path; remove the other unsupported "
                f"params {getattr(self, '_fallback_requested_params', set())} to use it)."
            )
        X = densify(fd.features, float32=self._float32_inputs)
        init = self.getOrDefault("initMode")
        sk = twin(
            n_clusters=self.getOrDefault("k"),
            max_iter=self.getOrDefault("maxIter"),
            tol=self.getOrDefault("tol"),
            init="k-means++" if init != "random" else "random",
            n_init=1,
            random_state=self.getOrDefault("seed") & 0x7FFFFFFF,
        ).fit(X, sample_weight=fd.weight)
        return {
            "cluster_centers": sk.cluster_centers_.astype(np.float32),
            "inertia": float(sk.inertia_),
            "n_iter": int(sk.n_iter_),
        }


class KMeansSummary:
    """Training summary surface of pyspark.ml.clustering.KMeansSummary."""

    def __init__(
        self, k: int, cluster_sizes: np.ndarray, training_cost: float, num_iter: int
    ) -> None:
        self.k = int(k)
        self.clusterSizes = [int(s) for s in cluster_sizes]
        self.trainingCost = float(training_cost)
        self.numIter = int(num_iter)


class KMeansModel(_KMeansClass, _TpuModelWithPredictionCol, _KMeansParams):
    """Fitted KMeans model (reference clustering.py:459-604)."""

    def __init__(
        self,
        cluster_centers: np.ndarray,
        inertia: float,
        n_iter: int,
        cluster_sizes: "np.ndarray | None" = None,
    ) -> None:
        super().__init__(
            cluster_centers=np.asarray(cluster_centers),
            inertia=float(inertia),
            n_iter=int(n_iter),
            cluster_sizes=(
                np.asarray(cluster_sizes) if cluster_sizes is not None else None
            ),
        )
        self._setDefault(
            featuresCol="features",
            predictionCol="prediction",
            distanceMeasure="euclidean",
        )
        # Spark semantics: a summary exists on a freshly-fit model only; loaded
        # models have hasSummary=False. The estimator sets this flag after fit.
        self._has_training_summary = False

    def clusterCenters(self) -> List[np.ndarray]:
        """Spark MLlib KMeansModel surface."""
        return list(self._model_attributes["cluster_centers"])

    def partial_fit_updater(self, **kwargs):
        """Streamed continual-learning updater anchored on this model: mini-
        batch discounted center updates per arXiv 1505.06807 (continual/
        partial_fit.py, docs/design.md §7d)."""
        from ..continual.partial_fit import KMeansUpdater

        return KMeansUpdater(self, **kwargs)

    @property
    def hasSummary(self) -> bool:
        """True on a freshly-fit model (the reference always returns False,
        clustering.py:549-553 — the TPU fit records the sizes at no extra cost
        beyond one assignment pass)."""
        return (
            self._has_training_summary
            and self._model_attributes.get("cluster_sizes") is not None
        )

    @property
    def summary(self) -> KMeansSummary:
        """KMeansSummary (k, clusterSizes, trainingCost, numIter); raises after
        save/load like Spark."""
        if not self.hasSummary:
            raise RuntimeError(
                f"No training summary available for this {self.__class__.__name__}"
            )
        a = self._model_attributes
        return KMeansSummary(
            k=a["cluster_centers"].shape[0],
            cluster_sizes=a["cluster_sizes"],
            training_cost=a["inertia"],
            num_iter=a["n_iter"],
        )

    def cpu(self):
        """CPU twin of this model (the reference's model.cpu() builds the pyspark
        twin via py4j, clustering.py:524-544; pyspark is optional here so the twin
        is the sklearn estimator with the fitted state installed)."""
        from sklearn.cluster import KMeans as SkKMeans

        centers = np.asarray(self._model_attributes["cluster_centers"], np.float64)
        sk = SkKMeans(n_clusters=centers.shape[0], n_init=1)
        sk.cluster_centers_ = centers
        sk.inertia_ = float(self._model_attributes["inertia"])
        sk.n_iter_ = int(self._model_attributes["n_iter"])
        sk._n_threads = 1
        sk.n_features_in_ = centers.shape[1]
        sk.labels_ = None
        return sk

    @property
    def cluster_centers_(self) -> np.ndarray:
        return self._model_attributes["cluster_centers"]

    @property
    def inertia_(self) -> float:
        return self._model_attributes["inertia"]

    @property
    def _cosine(self) -> bool:
        return self.getOrDefault("distanceMeasure") == "cosine"

    def predict(self, value: np.ndarray) -> int:
        """Single-vector prediction (Spark API)."""
        from ..observability.inference import predict_dispatch

        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return int(
            np.asarray(
                predict_dispatch(
                    self, kmeans_predict, X, self.cluster_centers_, self._cosine
                )
            )[0]
        )

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        from ..observability.inference import predict_dispatch

        if self._cosine and not np.all(np.linalg.norm(X, axis=1) > 0):
            raise ValueError(
                "Cosine distance is not defined for zero-length vectors; the input "
                "contains an all-zero feature row."
            )
        pred = np.asarray(
            predict_dispatch(
                self, kmeans_predict, X, self.cluster_centers_, self._cosine
            )
        )
        return {self.getOrDefault("predictionCol"): pred.astype(np.int32)}
