#
# PCA estimator/model (L6 API) — pyspark.ml.feature.PCA-compatible surface with the
# fit/transform executing on the TPU mesh.
#
# Structural equivalent of reference python/src/spark_rapids_ml/feature.py:
#   * param mapping {k -> n_components} (reference feature.py:61-65)
#   * fit produces mean/components/explained_variance/singular_values attributes
#     (reference feature.py:260-285)
#   * transform projects raw rows for Spark parity (reference feature.py:438-451)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import densify
from ..core.backend_params import _TpuClass
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModelWithColumns
from ..core.params import (
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    Param,
    TypeConverters,
)
from ..ops.pca import pca_transform


class _PCAClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {"k": "n_components", "inputCol": "", "inputCols": "", "outputCol": ""}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_components": None, "whiten": False}

    @classmethod
    def _fallback_class(cls):
        from sklearn.decomposition import PCA as SkPCA

        return SkPCA


class _PCAParams(HasInputCol, HasInputCols, HasOutputCol):
    k: Param[int] = Param(
        "undefined",
        "k",
        "the number of principal components (> 0).",
        TypeConverters.toInt,
    )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setInputCol(self, value: str) -> "_PCAParams":
        return self._set(inputCol=value)  # type: ignore[return-value]

    def setInputCols(self, value: List[str]) -> "_PCAParams":
        return self._set(inputCols=value)  # type: ignore[return-value]

    def setOutputCol(self, value: str) -> "_PCAParams":
        return self._set(outputCol=value)  # type: ignore[return-value]


class PCA(_PCAClass, _TpuEstimator, _PCAParams):
    """PCA estimator running as one SPMD program over the TPU mesh.

    Drop-in for pyspark.ml.feature.PCA / reference spark_rapids_ml.feature.PCA
    (reference feature.py:117-253).

    Example
    -------
    >>> import pandas as pd, numpy as np
    >>> from spark_rapids_ml_tpu.feature import PCA
    >>> df = pd.DataFrame({"features": list(np.random.rand(100, 8).astype(np.float32))})
    >>> model = PCA(k=2, inputCol="features").fit(df)
    >>> out = model.transform(df)   # adds 'pca_features' column
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(outputCol="pca_features")
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def setK(self, value: int) -> "PCA":
        return self._set_params(k=value)  # type: ignore[return-value]

    def _out_schema(self) -> List[str]:
        return [
            "mean",
            "components",
            "explained_variance",
            "explained_variance_ratio",
            "singular_values",
        ]

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # the sharded covariance pass is shared; each param map re-does only the
        # tiny replicated eigh (P6 pattern)
        return True

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        base_k = self.getOrDefault("k")

        def _fit(inputs: FitInputs):
            from ..ops.pca import covariance_for_fit, pca_attrs_from_cov

            ks = (
                [int(p.get("n_components", base_k)) for p in extra_params]
                if extra_params is not None
                else [base_k]
            )
            for k in ks:
                if k > inputs.desc.n:
                    raise ValueError(
                        f"k={k} exceeds the number of features {inputs.desc.n}"
                    )
            cov, mean, wsum = covariance_for_fit(
                inputs.features,
                inputs.row_weight,
                mesh=inputs.mesh,
                unit_weight=inputs.unit_weight,
            )
            results = [pca_attrs_from_cov(cov, mean, wsum, k) for k in ks]
            return results if extra_params is not None else results[0]

        return _fit

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "PCAModel":
        return PCAModel(**attrs)

    def _streaming_fit(self, fd, chain_ops=None) -> Dict[str, Any]:
        """Out-of-core fit: stream batches, accumulate the covariance on device
        (ops/streaming.py; selected by core/estimator.py when the design matrix
        exceeds the stream threshold). `chain_ops` carries upstream featurizer
        transforms when this fit runs as a fused pipeline stage (pipeline.py):
        they apply in-program, so the raw batches upload once for the chain."""
        from .. import config as _config
        from ..ops.pca import pca_attrs_from_cov
        from ..ops.streaming import chain_out_dim, streaming_covariance
        from ..parallel.partitioner import active_partitioner

        k = self.getOrDefault("k")
        d_eff = chain_out_dim(fd.n_cols, chain_ops)
        if k > d_eff:
            raise ValueError(f"k={k} exceeds the number of features {d_eff}")
        mesh = active_partitioner(self.num_workers).mesh
        cov, mean, wsum = streaming_covariance(
            densify(fd.features, self._float32_inputs),
            fd.weight,
            batch_rows=int(_config.get("stream_batch_rows")),
            mesh=mesh,
            float32=self._float32_inputs,
            chain_ops=chain_ops,
        )
        return pca_attrs_from_cov(cov, mean, wsum, k)

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        X = densify(fd.features, float32=self._float32_inputs)
        sk = twin(n_components=self.getOrDefault("k")).fit(np.asarray(X, dtype=np.float64))
        return {
            "mean": sk.mean_.astype(np.float32),
            "components": sk.components_.astype(np.float32),
            "explained_variance": sk.explained_variance_,
            "explained_variance_ratio": sk.explained_variance_ratio_,
            "singular_values": sk.singular_values_,
        }


class PCAModel(_PCAClass, _TpuModelWithColumns, _PCAParams):
    """Fitted PCA model (reference feature.py:288-459)."""

    def __init__(
        self,
        mean: np.ndarray,
        components: np.ndarray,
        explained_variance: np.ndarray,
        explained_variance_ratio: np.ndarray,
        singular_values: np.ndarray,
    ) -> None:
        super().__init__(
            mean=np.asarray(mean),
            components=np.asarray(components),
            explained_variance=np.asarray(explained_variance),
            explained_variance_ratio=np.asarray(explained_variance_ratio),
            singular_values=np.asarray(singular_values),
        )
        self._setDefault(outputCol="pca_features")

    # --- Spark MLlib PCAModel surface ---

    @property
    def pc(self) -> np.ndarray:
        """Principal components as a (d, k) matrix, Spark's PCAModel.pc layout."""
        return self._model_attributes["components"].T

    def partial_fit_updater(self, **kwargs):
        """Streamed continual-learning updater anchored on this model:
        incremental PCA via the streamed covariance accumulators (continual/
        partial_fit.py, docs/design.md §7d)."""
        from ..continual.partial_fit import PCAUpdater

        return PCAUpdater(self, **kwargs)

    @property
    def explainedVariance(self) -> np.ndarray:
        """Proportion of variance explained per component (Spark semantics)."""
        return self._model_attributes["explained_variance_ratio"]

    # --- cuML-style surface (reference exposes these too) ---

    @property
    def mean(self) -> np.ndarray:
        return self._model_attributes["mean"]

    @property
    def components_(self) -> np.ndarray:
        return self._model_attributes["components"]

    @property
    def explained_variance_(self) -> np.ndarray:
        return self._model_attributes["explained_variance"]

    @property
    def singular_values_(self) -> np.ndarray:
        return self._model_attributes["singular_values"]

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        from ..observability.inference import predict_dispatch

        out = np.asarray(
            predict_dispatch(
                self, pca_transform, X, self._model_attributes["components"]
            )
        )
        return {self.getOrDefault("outputCol"): out}

    def _chain_op(self):
        """This transform as a fused-pipeline chain op (pipeline.py): `project`
        applies exactly pca_transform's expression in-program
        (ops/streaming.py::_apply_chain), so a fused downstream fit sees
        bit-identical inputs to the staged transform path."""
        return ("project", self._model_attributes["components"])

    def cpu(self):
        """sklearn PCA twin with the fitted state installed (the reference builds
        the pyspark PCAModel via py4j, feature.py:375-389)."""
        from sklearn.decomposition import PCA as SkPCA

        comps = np.asarray(self._model_attributes["components"], np.float64)
        k, d = comps.shape
        sk = SkPCA(n_components=k)
        sk.components_ = comps
        sk.mean_ = np.asarray(self._model_attributes["mean"], np.float64)
        sk.explained_variance_ = np.asarray(
            self._model_attributes["explained_variance"], np.float64
        )
        sk.explained_variance_ratio_ = np.asarray(
            self._model_attributes["explained_variance_ratio"], np.float64
        )
        sk.singular_values_ = np.asarray(
            self._model_attributes["singular_values"], np.float64
        )
        sk.n_components_ = k
        sk.n_features_in_ = d
        sk.noise_variance_ = 0.0
        sk.whiten = False
        return sk


class _StandardScalerClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {
            "withMean": "with_mean",
            "withStd": "with_std",
            "inputCol": "",
            "inputCols": "",
            "outputCol": "",
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"with_mean": False, "with_std": True}

    @classmethod
    def _fallback_class(cls):
        from sklearn.preprocessing import StandardScaler as SkStandardScaler

        return SkStandardScaler


class _StandardScalerParams(HasInputCol, HasInputCols, HasOutputCol):
    withMean: Param[bool] = Param(
        "undefined",
        "withMean",
        "center the data with the column means before scaling.",
        TypeConverters.toBoolean,
    )
    withStd: Param[bool] = Param(
        "undefined",
        "withStd",
        "scale the data to unit standard deviation.",
        TypeConverters.toBoolean,
    )

    def getWithMean(self) -> bool:
        return self.getOrDefault("withMean")

    def getWithStd(self) -> bool:
        return self.getOrDefault("withStd")

    def setInputCol(self, value: str) -> "_StandardScalerParams":
        return self._set(inputCol=value)  # type: ignore[return-value]

    def setInputCols(self, value: List[str]) -> "_StandardScalerParams":
        return self._set(inputCols=value)  # type: ignore[return-value]

    def setOutputCol(self, value: str) -> "_StandardScalerParams":
        return self._set(outputCol=value)  # type: ignore[return-value]


def _std_from_var(var: np.ndarray) -> np.ndarray:
    """Column std from the unbiased variance, zero-variance columns clamped to
    scale 1 (Spark's StandardScalerModel convention; also
    ops/linalg.py::standardize_columns). ONE host implementation shared by the
    in-core, streamed, and fallback fit arms so every arm lands the same bits."""
    std = np.sqrt(np.asarray(var))
    std[std <= 0.0] = 1.0
    return std


class StandardScaler(_StandardScalerClass, _TpuEstimator, _StandardScalerParams):
    """pyspark.ml.feature.StandardScaler surface with the column-moments fit
    running on the mesh (ops/linalg.py::weighted_moments in-core,
    ops/streaming.py::streaming_moments out-of-core).

    Spark defaults hold: withMean=False, withStd=True. In a Pipeline feeding a
    TPU estimator this stage is fuse-eligible (docs/design.md §6k): its
    transform becomes a "scale" chain op applied in-program by the downstream
    fit, bit-identical to the staged transform.

    Example
    -------
    >>> import pandas as pd, numpy as np
    >>> from spark_rapids_ml_tpu.feature import StandardScaler
    >>> df = pd.DataFrame({"features": list(np.random.rand(100, 8).astype(np.float32))})
    >>> model = StandardScaler(inputCol="features", withMean=True).fit(df)
    >>> out = model.transform(df)   # adds 'scaled_features' column
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(outputCol="scaled_features", withMean=False, withStd=True)
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def setWithMean(self, value: bool) -> "StandardScaler":
        return self._set_params(withMean=value)  # type: ignore[return-value]

    def setWithStd(self, value: bool) -> "StandardScaler":
        return self._set_params(withStd=value)  # type: ignore[return-value]

    def _out_schema(self) -> List[str]:
        return ["mean", "std"]

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        def _fit(inputs: FitInputs):
            from ..ops.linalg import weighted_moments

            mean, var, _ = weighted_moments(inputs.features, inputs.row_weight)
            return {
                "mean": np.asarray(mean),
                "std": _std_from_var(var).astype(inputs.dtype),
            }

        return _fit

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "StandardScalerModel":
        return StandardScalerModel(**attrs)

    def _streaming_fit(self, fd, chain_ops=None) -> Dict[str, Any]:
        """Out-of-core fit: one streamed moments pass (ops/streaming.py). The
        shared `streaming_moments` implementation is what the fused pipeline's
        in-chain scaler fit calls too, so both arms produce identical stats."""
        from .. import config as _config
        from ..ops.streaming import streaming_moments
        from ..parallel.partitioner import active_partitioner

        dt = np.float32 if self._float32_inputs else np.float64
        mean, var, _ = streaming_moments(
            densify(fd.features, self._float32_inputs),
            fd.weight,
            batch_rows=int(_config.get("stream_batch_rows")),
            mesh=active_partitioner(self.num_workers).mesh,
            float32=self._float32_inputs,
            chain_ops=chain_ops,
        )
        return {
            "mean": np.asarray(mean, dtype=dt),
            "std": _std_from_var(var).astype(dt),
        }

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        dt = np.float32 if self._float32_inputs else np.float64
        X = np.asarray(densify(fd.features, self._float32_inputs), np.float64)
        w = (
            np.asarray(fd.weight, np.float64)
            if fd.weight is not None
            else np.ones((X.shape[0],), np.float64)
        )
        wsum = w.sum()
        mean = (w[:, None] * X).sum(axis=0) / wsum
        var = np.maximum(
            ((w[:, None] * (X * X)).sum(axis=0) - wsum * mean * mean)
            / (wsum - 1.0),
            0.0,
        )
        return {"mean": mean.astype(dt), "std": _std_from_var(var).astype(dt)}


class StandardScalerModel(_StandardScalerClass, _TpuModelWithColumns, _StandardScalerParams):
    """Fitted StandardScaler (pyspark.ml.feature.StandardScalerModel surface:
    exposes both `mean` and `std` regardless of the withMean/withStd flags)."""

    def __init__(self, mean: np.ndarray, std: np.ndarray) -> None:
        super().__init__(mean=np.asarray(mean), std=np.asarray(std))
        self._setDefault(outputCol="scaled_features", withMean=False, withStd=True)

    @property
    def mean(self) -> np.ndarray:
        return self._model_attributes["mean"]

    @property
    def std(self) -> np.ndarray:
        return self._model_attributes["std"]

    def _shift_and_scale(self):
        """The (shift, scale) pair the transform ACTUALLY applies, honoring the
        withMean/withStd flags: `(x - shift) / scale`. The identity halves are
        literal zeros/ones so the flagged-off variants stay bit-identical to
        the raw input — and so `_chain_op` hands the fuser the exact arrays the
        staged transform uses."""
        mean = self._model_attributes["mean"]
        std = self._model_attributes["std"]
        shift = mean if self.getOrDefault("withMean") else np.zeros_like(mean)
        scale = std if self.getOrDefault("withStd") else np.ones_like(std)
        return shift, scale

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        from ..observability.inference import predict_dispatch
        from ..ops.linalg import scaler_transform

        shift, scale = self._shift_and_scale()
        out = np.asarray(predict_dispatch(self, scaler_transform, X, shift, scale))
        return {self.getOrDefault("outputCol"): out}

    def _chain_op(self):
        """This transform as a fused-pipeline chain op (pipeline.py): `scale`
        applies `(x - shift) / scale` in-program
        (ops/streaming.py::_apply_chain), bit-identical to scaler_transform."""
        shift, scale = self._shift_and_scale()
        return ("scale", shift, scale)

    def cpu(self):
        """sklearn StandardScaler twin with the fitted state installed."""
        from sklearn.preprocessing import StandardScaler as SkStandardScaler

        with_mean = bool(self.getOrDefault("withMean"))
        with_std = bool(self.getOrDefault("withStd"))
        sk = SkStandardScaler(with_mean=with_mean, with_std=with_std)
        mean = np.asarray(self._model_attributes["mean"], np.float64)
        std = np.asarray(self._model_attributes["std"], np.float64)
        sk.mean_ = mean if with_mean else None
        sk.scale_ = std if with_std else None
        sk.var_ = std * std if with_std else None
        sk.n_features_in_ = int(mean.shape[0])
        sk.n_samples_seen_ = 0
        return sk


class VectorAssembler(HasInputCols, HasOutputCol):
    """Combines scalar columns into one array-valued feature column —
    pyspark.ml.feature.VectorAssembler surface, provided so Pipelines written against
    pyspark port over. TPU pipelines usually skip it: Pipeline bypasses a
    VectorAssembler feeding a TPU estimator (reference pipeline.py:85-119)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(outputCol="features")
        self._set(**kwargs)

    def setInputCols(self, value: List[str]) -> "VectorAssembler":
        return self._set(inputCols=value)  # type: ignore[return-value]

    def setOutputCol(self, value: str) -> "VectorAssembler":
        return self._set(outputCol=value)  # type: ignore[return-value]

    def transform(self, dataset: Any, params: Optional[dict] = None) -> Any:
        import pandas as pd

        if params:
            return self.copy(params).transform(dataset)
        if not isinstance(dataset, pd.DataFrame):
            raise TypeError("VectorAssembler requires a pandas DataFrame input")
        cols = self.getOrDefault("inputCols")
        out = dataset.copy()
        # pyspark assembles DoubleType vectors and flattens vector-valued inputs;
        # match both (estimators downcast per their float32_inputs setting)
        blocks = []
        for c in cols:
            col = dataset[c]
            if col.dtype == object:
                blocks.append(np.stack([np.asarray(v, dtype=np.float64) for v in col]))
            else:
                blocks.append(col.to_numpy(dtype=np.float64).reshape(-1, 1))
        stacked = np.hstack(blocks)
        out[self.getOrDefault("outputCol")] = list(stacked)
        return out
