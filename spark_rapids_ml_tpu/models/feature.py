#
# PCA estimator/model (L6 API) — pyspark.ml.feature.PCA-compatible surface with the
# fit/transform executing on the TPU mesh.
#
# Structural equivalent of reference python/src/spark_rapids_ml/feature.py:
#   * param mapping {k -> n_components} (reference feature.py:61-65)
#   * fit produces mean/components/explained_variance/singular_values attributes
#     (reference feature.py:260-285)
#   * transform projects raw rows for Spark parity (reference feature.py:438-451)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import densify
from ..core.backend_params import _TpuClass
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModelWithColumns
from ..core.params import (
    HasInputCol,
    HasInputCols,
    HasOutputCol,
    Param,
    TypeConverters,
)
from ..ops.pca import pca_transform


class _PCAClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {"k": "n_components", "inputCol": "", "inputCols": "", "outputCol": ""}

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {"n_components": None, "whiten": False}

    @classmethod
    def _fallback_class(cls):
        from sklearn.decomposition import PCA as SkPCA

        return SkPCA


class _PCAParams(HasInputCol, HasInputCols, HasOutputCol):
    k: Param[int] = Param(
        "undefined",
        "k",
        "the number of principal components (> 0).",
        TypeConverters.toInt,
    )

    def getK(self) -> int:
        return self.getOrDefault("k")

    def setInputCol(self, value: str) -> "_PCAParams":
        return self._set(inputCol=value)  # type: ignore[return-value]

    def setInputCols(self, value: List[str]) -> "_PCAParams":
        return self._set(inputCols=value)  # type: ignore[return-value]

    def setOutputCol(self, value: str) -> "_PCAParams":
        return self._set(outputCol=value)  # type: ignore[return-value]


class PCA(_PCAClass, _TpuEstimator, _PCAParams):
    """PCA estimator running as one SPMD program over the TPU mesh.

    Drop-in for pyspark.ml.feature.PCA / reference spark_rapids_ml.feature.PCA
    (reference feature.py:117-253).

    Example
    -------
    >>> import pandas as pd, numpy as np
    >>> from spark_rapids_ml_tpu.feature import PCA
    >>> df = pd.DataFrame({"features": list(np.random.rand(100, 8).astype(np.float32))})
    >>> model = PCA(k=2, inputCol="features").fit(df)
    >>> out = model.transform(df)   # adds 'pca_features' column
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(outputCol="pca_features")
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def setK(self, value: int) -> "PCA":
        return self._set_params(k=value)  # type: ignore[return-value]

    def _out_schema(self) -> List[str]:
        return [
            "mean",
            "components",
            "explained_variance",
            "explained_variance_ratio",
            "singular_values",
        ]

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # the sharded covariance pass is shared; each param map re-does only the
        # tiny replicated eigh (P6 pattern)
        return True

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        base_k = self.getOrDefault("k")

        def _fit(inputs: FitInputs):
            from ..ops.pca import covariance_for_fit, pca_attrs_from_cov

            ks = (
                [int(p.get("n_components", base_k)) for p in extra_params]
                if extra_params is not None
                else [base_k]
            )
            for k in ks:
                if k > inputs.desc.n:
                    raise ValueError(
                        f"k={k} exceeds the number of features {inputs.desc.n}"
                    )
            cov, mean, wsum = covariance_for_fit(
                inputs.features,
                inputs.row_weight,
                mesh=inputs.mesh,
                unit_weight=inputs.unit_weight,
            )
            results = [pca_attrs_from_cov(cov, mean, wsum, k) for k in ks]
            return results if extra_params is not None else results[0]

        return _fit

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "PCAModel":
        return PCAModel(**attrs)

    def _streaming_fit(self, fd) -> Dict[str, Any]:
        """Out-of-core fit: stream batches, accumulate the covariance on device
        (ops/streaming.py; selected by core/estimator.py when the design matrix
        exceeds the stream threshold)."""
        from .. import config as _config
        from ..ops.pca import pca_attrs_from_cov
        from ..ops.streaming import streaming_covariance
        from ..parallel.mesh import get_mesh

        k = self.getOrDefault("k")
        if k > fd.n_cols:
            raise ValueError(f"k={k} exceeds the number of features {fd.n_cols}")
        mesh = get_mesh(self.num_workers)
        cov, mean, wsum = streaming_covariance(
            densify(fd.features, self._float32_inputs),
            fd.weight,
            batch_rows=int(_config.get("stream_batch_rows")),
            mesh=mesh,
            float32=self._float32_inputs,
        )
        return pca_attrs_from_cov(cov, mean, wsum, k)

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        X = densify(fd.features, float32=self._float32_inputs)
        sk = twin(n_components=self.getOrDefault("k")).fit(np.asarray(X, dtype=np.float64))
        return {
            "mean": sk.mean_.astype(np.float32),
            "components": sk.components_.astype(np.float32),
            "explained_variance": sk.explained_variance_,
            "explained_variance_ratio": sk.explained_variance_ratio_,
            "singular_values": sk.singular_values_,
        }


class PCAModel(_PCAClass, _TpuModelWithColumns, _PCAParams):
    """Fitted PCA model (reference feature.py:288-459)."""

    def __init__(
        self,
        mean: np.ndarray,
        components: np.ndarray,
        explained_variance: np.ndarray,
        explained_variance_ratio: np.ndarray,
        singular_values: np.ndarray,
    ) -> None:
        super().__init__(
            mean=np.asarray(mean),
            components=np.asarray(components),
            explained_variance=np.asarray(explained_variance),
            explained_variance_ratio=np.asarray(explained_variance_ratio),
            singular_values=np.asarray(singular_values),
        )
        self._setDefault(outputCol="pca_features")

    # --- Spark MLlib PCAModel surface ---

    @property
    def pc(self) -> np.ndarray:
        """Principal components as a (d, k) matrix, Spark's PCAModel.pc layout."""
        return self._model_attributes["components"].T

    @property
    def explainedVariance(self) -> np.ndarray:
        """Proportion of variance explained per component (Spark semantics)."""
        return self._model_attributes["explained_variance_ratio"]

    # --- cuML-style surface (reference exposes these too) ---

    @property
    def mean(self) -> np.ndarray:
        return self._model_attributes["mean"]

    @property
    def components_(self) -> np.ndarray:
        return self._model_attributes["components"]

    @property
    def explained_variance_(self) -> np.ndarray:
        return self._model_attributes["explained_variance"]

    @property
    def singular_values_(self) -> np.ndarray:
        return self._model_attributes["singular_values"]

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        from ..observability.inference import predict_dispatch

        out = np.asarray(
            predict_dispatch(
                self, pca_transform, X, self._model_attributes["components"]
            )
        )
        return {self.getOrDefault("outputCol"): out}

    def cpu(self):
        """sklearn PCA twin with the fitted state installed (the reference builds
        the pyspark PCAModel via py4j, feature.py:375-389)."""
        from sklearn.decomposition import PCA as SkPCA

        comps = np.asarray(self._model_attributes["components"], np.float64)
        k, d = comps.shape
        sk = SkPCA(n_components=k)
        sk.components_ = comps
        sk.mean_ = np.asarray(self._model_attributes["mean"], np.float64)
        sk.explained_variance_ = np.asarray(
            self._model_attributes["explained_variance"], np.float64
        )
        sk.explained_variance_ratio_ = np.asarray(
            self._model_attributes["explained_variance_ratio"], np.float64
        )
        sk.singular_values_ = np.asarray(
            self._model_attributes["singular_values"], np.float64
        )
        sk.n_components_ = k
        sk.n_features_in_ = d
        sk.noise_variance_ = 0.0
        sk.whiten = False
        return sk


class VectorAssembler(HasInputCols, HasOutputCol):
    """Combines scalar columns into one array-valued feature column —
    pyspark.ml.feature.VectorAssembler surface, provided so Pipelines written against
    pyspark port over. TPU pipelines usually skip it: Pipeline bypasses a
    VectorAssembler feeding a TPU estimator (reference pipeline.py:85-119)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(outputCol="features")
        self._set(**kwargs)

    def setInputCols(self, value: List[str]) -> "VectorAssembler":
        return self._set(inputCols=value)  # type: ignore[return-value]

    def setOutputCol(self, value: str) -> "VectorAssembler":
        return self._set(outputCol=value)  # type: ignore[return-value]

    def transform(self, dataset: Any, params: Optional[dict] = None) -> Any:
        import pandas as pd

        if params:
            return self.copy(params).transform(dataset)
        if not isinstance(dataset, pd.DataFrame):
            raise TypeError("VectorAssembler requires a pandas DataFrame input")
        cols = self.getOrDefault("inputCols")
        out = dataset.copy()
        # pyspark assembles DoubleType vectors and flattens vector-valued inputs;
        # match both (estimators downcast per their float32_inputs setting)
        blocks = []
        for c in cols:
            col = dataset[c]
            if col.dtype == object:
                blocks.append(np.stack([np.asarray(v, dtype=np.float64) for v in col]))
            else:
                blocks.append(col.to_numpy(dtype=np.float64).reshape(-1, 1))
        stacked = np.hstack(blocks)
        out[self.getOrDefault("outputCol")] = list(stacked)
        return out
