#
# UMAP estimator/model (L6 API) — reference spark_rapids_ml.umap
# (reference python/src/spark_rapids_ml/umap.py):
#   * fit samples the dataset by sample_fraction and runs a single-worker fit
#     (reference umap.py:923-951 coalesces to 1 partition; here: one jitted program on
#     the local device — P5 in SURVEY.md §2.7)
#   * the model is embedding + raw data (reference umap.py:1069-1298), used map-side
#     by transform (reference broadcasts them in chunks, umap.py:1404-1446)
#   * cuML-style constructor params (reference umap.py:114-137)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.backend_params import HasFeaturesCols, _TpuClass
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModelWithColumns
from ..core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasOutputCol,
    HasSeed,
    Param,
    TypeConverters,
)
from ..ops.umap_ops import umap_fit, umap_transform


class _UMAPClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {
            "n_neighbors": "n_neighbors",
            "n_components": "n_components",
            "n_epochs": "n_epochs",
            "min_dist": "min_dist",
            "spread": "spread",
            "negative_sample_rate": "negative_sample_rate",
            "learning_rate": "learning_rate",
            "sample_fraction": "",
            "seed": "random_state",
            "featuresCol": "",
            "featuresCols": "",
            # supervised UMAP (reference supports labelCol) is not yet implemented on
            # the TPU path: setting it must surface, not silently run unsupervised
            "labelCol": None,
            "outputCol": "",
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        # cuML defaults (reference umap.py:114-137)
        return {
            "n_neighbors": 15,
            "n_components": 2,
            "n_epochs": 200,
            "min_dist": 0.1,
            "spread": 1.0,
            "negative_sample_rate": 5,
            "learning_rate": 1.0,
            "random_state": 42,
        }

    @classmethod
    def _fallback_class(cls):
        return None  # umap-learn is not in the image


class _UMAPParams(HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol, HasSeed):
    n_neighbors: Param[int] = Param(
        "undefined", "n_neighbors", "size of local neighborhood.", TypeConverters.toInt
    )
    n_components: Param[int] = Param(
        "undefined", "n_components", "embedding dimension.", TypeConverters.toInt
    )
    n_epochs: Param[int] = Param(
        "undefined", "n_epochs", "number of SGD epochs.", TypeConverters.toInt
    )
    min_dist: Param[float] = Param(
        "undefined", "min_dist", "minimum embedding distance between points.",
        TypeConverters.toFloat,
    )
    spread: Param[float] = Param(
        "undefined", "spread", "effective scale of embedded points.",
        TypeConverters.toFloat,
    )
    negative_sample_rate: Param[int] = Param(
        "undefined", "negative_sample_rate", "negative samples per positive edge.",
        TypeConverters.toInt,
    )
    learning_rate: Param[float] = Param(
        "undefined", "learning_rate", "initial embedding learning rate.",
        TypeConverters.toFloat,
    )
    sample_fraction: Param[float] = Param(
        "undefined",
        "sample_fraction",
        "fraction of the input dataset used for fit (reference umap.py:923-951).",
        TypeConverters.toFloat,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)


class UMAP(_UMAPClass, _TpuEstimator, _UMAPParams):
    """UMAP: single-device fit on (sampled) data, broadcastable model for transform
    (reference umap.py:838-1304)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            outputCol="embedding",
            n_neighbors=15,
            n_components=2,
            n_epochs=200,
            min_dist=0.1,
            spread=1.0,
            negative_sample_rate=5,
            learning_rate=1.0,
            seed=42,
            sample_fraction=1.0,
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return ["embedding", "raw_data", "a", "b", "n_neighbors"]

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        p = dict(self._tpu_params)
        frac = self.getOrDefault("sample_fraction")

        def _fit(inputs: FitInputs) -> Dict[str, Any]:
            X = inputs.host_features
            seed = int(p["random_state"]) if p["random_state"] is not None else 42
            if frac < 1.0:
                rng = np.random.default_rng(seed)
                keep = rng.random(X.shape[0]) < frac
                X = X[keep]
            return umap_fit(
                X,
                n_neighbors=int(p["n_neighbors"]),
                n_components=int(p["n_components"]),
                n_epochs=int(p["n_epochs"]),
                min_dist=float(p["min_dist"]),
                spread=float(p["spread"]),
                negative_sample_rate=int(p["negative_sample_rate"]),
                learning_rate=float(p["learning_rate"]),
                seed=seed,
                mesh=inputs.mesh,
            )

        return _fit

    def _create_pyspark_model(self, attrs) -> "UMAPModel":
        return UMAPModel(**attrs)


class UMAPModel(_UMAPClass, _TpuModelWithColumns, _UMAPParams):
    def __init__(
        self,
        embedding: np.ndarray,
        raw_data: np.ndarray,
        a: float,
        b: float,
        n_neighbors: int,
    ) -> None:
        super().__init__(
            embedding=np.asarray(embedding),
            raw_data=np.asarray(raw_data),
            a=float(a),
            b=float(b),
            n_neighbors=int(n_neighbors),
        )
        self._setDefault(featuresCol="features", outputCol="embedding", n_neighbors=15)

    @property
    def embedding_(self) -> np.ndarray:
        return self._model_attributes["embedding"]

    @property
    def rawData_(self) -> np.ndarray:
        return self._model_attributes["raw_data"]

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        out = umap_transform(
            X,
            self._model_attributes["raw_data"],
            self._model_attributes["embedding"],
            self._model_attributes["n_neighbors"],
        )
        return {self.getOrDefault("outputCol"): out}
