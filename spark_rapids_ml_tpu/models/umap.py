#
# UMAP estimator/model (L6 API) — reference spark_rapids_ml.umap
# (reference python/src/spark_rapids_ml/umap.py):
#   * fit samples the dataset by sample_fraction and runs a single-worker fit
#     (reference umap.py:923-951 coalesces to 1 partition; here: one jitted program on
#     the local device — P5 in SURVEY.md §2.7)
#   * the model is embedding + raw data (reference umap.py:1069-1298), used map-side
#     by transform (reference broadcasts them in chunks, umap.py:1404-1446)
#   * cuML-style constructor params (reference umap.py:114-137)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.backend_params import HasFeaturesCols, _TpuClass
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModelWithColumns
from ..core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasOutputCol,
    HasSeed,
    Param,
    TypeConverters,
)
from ..ops.umap_ops import umap_fit, umap_transform


class _UMAPClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {
            "n_neighbors": "n_neighbors",
            "n_components": "n_components",
            "n_epochs": "n_epochs",
            "min_dist": "min_dist",
            "spread": "spread",
            "negative_sample_rate": "negative_sample_rate",
            "learning_rate": "learning_rate",
            "sample_fraction": "",
            "seed": "random_state",
            "featuresCol": "",
            "featuresCols": "",
            # supervised UMAP: labelCol switches on the categorical simplicial-set
            # intersection (ops/umap_ops.categorical_intersection)
            "labelCol": "",
            "init": "init",
            "outputCol": "",
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        # cuML defaults (reference umap.py:114-137)
        return {
            "n_neighbors": 15,
            "n_components": 2,
            "n_epochs": 200,
            "min_dist": 0.1,
            "spread": 1.0,
            "negative_sample_rate": 5,
            "learning_rate": 1.0,
            "random_state": 42,
            "init": "spectral",
        }

    @classmethod
    def _fallback_class(cls):
        return None  # umap-learn is not in the image


class _UMAPParams(HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol, HasSeed):
    n_neighbors: Param[int] = Param(
        "undefined", "n_neighbors", "size of local neighborhood.", TypeConverters.toInt
    )
    n_components: Param[int] = Param(
        "undefined", "n_components", "embedding dimension.", TypeConverters.toInt
    )
    n_epochs: Param[int] = Param(
        "undefined", "n_epochs", "number of SGD epochs.", TypeConverters.toInt
    )
    min_dist: Param[float] = Param(
        "undefined", "min_dist", "minimum embedding distance between points.",
        TypeConverters.toFloat,
    )
    spread: Param[float] = Param(
        "undefined", "spread", "effective scale of embedded points.",
        TypeConverters.toFloat,
    )
    negative_sample_rate: Param[int] = Param(
        "undefined", "negative_sample_rate", "negative samples per positive edge.",
        TypeConverters.toInt,
    )
    learning_rate: Param[float] = Param(
        "undefined", "learning_rate", "initial embedding learning rate.",
        TypeConverters.toFloat,
    )
    sample_fraction: Param[float] = Param(
        "undefined",
        "sample_fraction",
        "fraction of the input dataset used for fit (reference umap.py:923-951).",
        TypeConverters.toFloat,
    )
    init: Param[str] = Param(
        "undefined",
        "init",
        "embedding initialization: 'spectral' (graph Laplacian eigenvectors, the "
        "cuML default) or 'random'.",
        TypeConverters.toString,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)


class UMAP(_UMAPClass, _TpuEstimator, _UMAPParams):
    """UMAP: single-device fit on (sampled) data, broadcastable model for transform
    (reference umap.py:838-1304)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            outputCol="embedding",
            n_neighbors=15,
            n_components=2,
            n_epochs=200,
            min_dist=0.1,
            spread=1.0,
            negative_sample_rate=5,
            learning_rate=1.0,
            seed=42,
            sample_fraction=1.0,
            init="spectral",
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return ["embedding", "raw_data", "a", "b", "n_neighbors"]

    def _use_label(self) -> bool:
        # supervised UMAP when a labelCol is explicitly set (reference umap.py)
        return self.hasParam("labelCol") and self.isDefined("labelCol")

    def _build_fit_inputs(self, fd) -> FitInputs:
        if fd.is_sparse:
            # sparse UMAP fit keeps the CSR on host end-to-end (the kNN graph comes
            # from blocked sparse-sparse products, ops/umap_ops.sparse_knn_graph —
            # reference sparse path umap.py:955-972); no mesh staging needed
            from ..parallel.mesh import get_mesh
            from ..parallel.partition import PartitionDescriptor

            desc = PartitionDescriptor.build(
                [fd.n_rows], fd.n_cols, nnz=int(fd.features.nnz)
            )
            return FitInputs(
                features=None,
                row_weight=None,
                desc=desc,
                mesh=get_mesh(self.num_workers),
                params=dict(self._tpu_params),
                host_features=fd.features,
                host_label=fd.label,
            )
        return super()._build_fit_inputs(fd)

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        p = dict(self._tpu_params)
        frac = self.getOrDefault("sample_fraction")
        supervised = self._use_label()

        def _fit(inputs: FitInputs) -> Dict[str, Any]:
            X = inputs.host_features
            y = inputs.host_label if supervised else None
            seed = int(p["random_state"]) if p["random_state"] is not None else 42
            if frac < 1.0:
                rng = np.random.default_rng(seed)
                keep = rng.random(X.shape[0]) < frac
                X = X[keep]
                if y is not None:
                    y = y[keep]
            return umap_fit(
                X,
                n_neighbors=int(p["n_neighbors"]),
                n_components=int(p["n_components"]),
                n_epochs=int(p["n_epochs"]),
                min_dist=float(p["min_dist"]),
                spread=float(p["spread"]),
                negative_sample_rate=int(p["negative_sample_rate"]),
                learning_rate=float(p["learning_rate"]),
                seed=seed,
                mesh=inputs.mesh,
                y=y,
                init=str(p.get("init", "spectral")),
            )

        return _fit

    def _create_pyspark_model(self, attrs) -> "UMAPModel":
        return UMAPModel(**attrs)


class UMAPModel(_UMAPClass, _TpuModelWithColumns, _UMAPParams):
    def __init__(
        self,
        embedding: np.ndarray,
        raw_data: Any,
        a: float,
        b: float,
        n_neighbors: int,
    ) -> None:
        from ..core.dataset import _is_sparse

        super().__init__(
            embedding=np.asarray(embedding),
            raw_data=raw_data if _is_sparse(raw_data) else np.asarray(raw_data),
            a=float(a),
            b=float(b),
            n_neighbors=int(n_neighbors),
        )
        self._setDefault(featuresCol="features", outputCol="embedding", n_neighbors=15)

    @property
    def embedding_(self) -> np.ndarray:
        return self._model_attributes["embedding"]

    @property
    def rawData_(self) -> np.ndarray:
        return self._model_attributes["raw_data"]

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        out = umap_transform(
            X,
            self._model_attributes["raw_data"],
            self._model_attributes["embedding"],
            self._model_attributes["n_neighbors"],
        )
        return {self.getOrDefault("outputCol"): out}
