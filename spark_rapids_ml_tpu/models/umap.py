#
# UMAP estimator/model (L6 API) — reference spark_rapids_ml.umap
# (reference python/src/spark_rapids_ml/umap.py):
#   * fit samples the dataset by sample_fraction and runs a single-worker fit
#     (reference umap.py:923-951 coalesces to 1 partition; here: one jitted program on
#     the local device — P5 in SURVEY.md §2.7)
#   * the model is embedding + raw data (reference umap.py:1069-1298), used map-side
#     by transform (reference broadcasts them in chunks, umap.py:1404-1446)
#   * cuML-style constructor params (reference umap.py:114-137)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.backend_params import DictTypeConverters, HasFeaturesCols, _TpuClass
from ..core.estimator import FitInputs, _TpuEstimator, _TpuModelWithColumns
from ..core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasOutputCol,
    HasSeed,
    Param,
    TypeConverters,
)
from ..ops.umap_ops import umap_fit, umap_transform


class _UMAPClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {
            "n_neighbors": "n_neighbors",
            "n_components": "n_components",
            "n_epochs": "n_epochs",
            "min_dist": "min_dist",
            "spread": "spread",
            "negative_sample_rate": "negative_sample_rate",
            "learning_rate": "learning_rate",
            "sample_fraction": "",
            "seed": "random_state",
            # the reference exposes the cuML name `random_state` directly
            # (umap.py:114-137); accept both spellings
            "random_state": "random_state",
            "featuresCol": "",
            "featuresCols": "",
            # supervised UMAP: labelCol switches on the categorical simplicial-set
            # intersection (ops/umap_ops.categorical_intersection)
            "labelCol": "",
            "init": "init",
            "outputCol": "",
            # full cuML surface (reference umap.py:114-137)
            "a": "a",
            "b": "b",
            "metric": "metric",
            "metric_kwds": "metric_kwds",
            "local_connectivity": "local_connectivity",
            "repulsion_strength": "repulsion_strength",
            "set_op_mix_ratio": "set_op_mix_ratio",
            "build_algo": "build_algo",
            "build_kwds": "build_kwds",
            # exact transform search needs no queue-size tuning; accepted for
            # drop-in compatibility (reference umap.py `transform_queue_size`)
            "transform_queue_size": "",
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        # cuML defaults (reference umap.py:114-137)
        return {
            "n_neighbors": 15,
            "n_components": 2,
            "n_epochs": 200,
            "min_dist": 0.1,
            "spread": 1.0,
            "negative_sample_rate": 5,
            "learning_rate": 1.0,
            "random_state": 42,
            "init": "spectral",
            "a": None,
            "b": None,
            "metric": "euclidean",
            "metric_kwds": None,
            "local_connectivity": 1.0,
            "repulsion_strength": 1.0,
            "set_op_mix_ratio": 1.0,
            "build_algo": "auto",
            "build_kwds": None,
        }

    @classmethod
    def _fallback_class(cls):
        return None  # umap-learn is not in the image


class _UMAPParams(HasFeaturesCol, HasFeaturesCols, HasLabelCol, HasOutputCol, HasSeed):
    n_neighbors: Param[int] = Param(
        "undefined", "n_neighbors", "size of local neighborhood.", TypeConverters.toInt
    )
    n_components: Param[int] = Param(
        "undefined", "n_components", "embedding dimension.", TypeConverters.toInt
    )
    n_epochs: Param[int] = Param(
        "undefined", "n_epochs", "number of SGD epochs.", TypeConverters.toInt
    )
    min_dist: Param[float] = Param(
        "undefined", "min_dist", "minimum embedding distance between points.",
        TypeConverters.toFloat,
    )
    spread: Param[float] = Param(
        "undefined", "spread", "effective scale of embedded points.",
        TypeConverters.toFloat,
    )
    negative_sample_rate: Param[int] = Param(
        "undefined", "negative_sample_rate", "negative samples per positive edge.",
        TypeConverters.toInt,
    )
    learning_rate: Param[float] = Param(
        "undefined", "learning_rate", "initial embedding learning rate.",
        TypeConverters.toFloat,
    )
    sample_fraction: Param[float] = Param(
        "undefined",
        "sample_fraction",
        "fraction of the input dataset used for fit (reference umap.py:923-951).",
        TypeConverters.toFloat,
    )
    init: Param[str] = Param(
        "undefined",
        "init",
        "embedding initialization: 'spectral' (graph Laplacian eigenvectors, the "
        "cuML default) or 'random'.",
        TypeConverters.toString,
    )
    a: Param[float] = Param(
        "undefined", "a",
        "output-kernel curve parameter; unset => derived from spread/min_dist.",
        TypeConverters.toFloat,
    )
    b: Param[float] = Param(
        "undefined", "b",
        "output-kernel curve parameter; unset => derived from spread/min_dist.",
        TypeConverters.toFloat,
    )
    metric: Param[str] = Param(
        "undefined", "metric",
        "kNN-graph distance metric (euclidean, sqeuclidean, cosine, manhattan, "
        "minkowski).",
        TypeConverters.toString,
    )
    metric_kwds: Param[Dict[str, Any]] = Param(
        "undefined", "metric_kwds",
        "metric keyword args (e.g. {'p': 3} for minkowski).",
        DictTypeConverters._toDict,
    )
    local_connectivity: Param[float] = Param(
        "undefined", "local_connectivity",
        "number of nearest neighbors assumed locally connected (rho rank).",
        TypeConverters.toFloat,
    )
    repulsion_strength: Param[float] = Param(
        "undefined", "repulsion_strength",
        "weight applied to negative (repulsive) samples in layout optimization.",
        TypeConverters.toFloat,
    )
    set_op_mix_ratio: Param[float] = Param(
        "undefined", "set_op_mix_ratio",
        "blend between fuzzy union (1.0) and fuzzy intersection (0.0) when "
        "symmetrizing the graph.",
        TypeConverters.toFloat,
    )
    build_algo: Param[str] = Param(
        "undefined", "build_algo",
        "kNN graph build: 'auto'/'brute_force_knn' (exact) or 'nn_descent' "
        "(approximate, IVF-backed).",
        TypeConverters.toString,
    )
    build_kwds: Param[Dict[str, Any]] = Param(
        "undefined", "build_kwds",
        "graph-build keyword args (e.g. {'nlist': 256, 'nprobe': 32}).",
        DictTypeConverters._toDict,
    )
    transform_queue_size: Param[float] = Param(
        "undefined", "transform_queue_size",
        "search-width multiplier for transform kNN (exact search here; accepted "
        "for API compatibility).",
        TypeConverters.toFloat,
    )
    random_state: Param[int] = Param(
        "undefined", "random_state",
        "random seed (cuML spelling; equivalent to seed).",
        TypeConverters.toInt,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setOutputCol(self, value: str):
        return self._set(outputCol=value)


class UMAP(_UMAPClass, _TpuEstimator, _UMAPParams):
    """UMAP: single-device fit on (sampled) data, broadcastable model for transform
    (reference umap.py:838-1304)."""

    _PARAM_BOUNDS_EXTRA = {
        "n_components": (1, None),
        "n_epochs": (1, None),
        "min_dist": (0.0, None),
        "spread": (0.0, None),
        "negative_sample_rate": (0, None),
        "local_connectivity": (1.0, None),
        "repulsion_strength": (0.0, None),
        "set_op_mix_ratio": (0.0, 1.0),
        "transform_queue_size": (0.0, None),
    }

    def _validate_param_bounds(self) -> None:
        # string enums validated on the DRIVER before any dispatch, like the
        # numeric bounds (a bad metric must not fail inside a barrier stage)
        super()._validate_param_bounds()
        from ..ops.umap_ops import UMAP_METRICS

        metric = self._tpu_params.get("metric", "euclidean")
        if metric not in UMAP_METRICS:
            raise ValueError(
                f"Unsupported UMAP metric '{metric}'; supported: {UMAP_METRICS}"
            )
        build_algo = self._tpu_params.get("build_algo", "auto")
        if build_algo not in ("auto", "brute_force_knn", "nn_descent"):
            raise ValueError(
                "build_algo must be one of 'auto', 'brute_force_knn', 'nn_descent'"
            )
        init = self._tpu_params.get("init", "spectral")
        if init not in ("spectral", "random"):
            raise ValueError("init must be 'spectral' or 'random'")

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            outputCol="embedding",
            n_neighbors=15,
            n_components=2,
            n_epochs=200,
            min_dist=0.1,
            spread=1.0,
            negative_sample_rate=5,
            learning_rate=1.0,
            seed=42,
            sample_fraction=1.0,
            init="spectral",
            metric="euclidean",
            local_connectivity=1.0,
            repulsion_strength=1.0,
            set_op_mix_ratio=1.0,
            build_algo="auto",
            transform_queue_size=4.0,
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return ["embedding", "raw_data", "a", "b", "n_neighbors", "metric",
                "metric_kwds", "local_connectivity",
                # transform-side SGD refinement settings
                "n_epochs", "negative_sample_rate", "learning_rate",
                "repulsion_strength", "random_state"]

    def _use_label(self) -> bool:
        # supervised UMAP when a labelCol is explicitly set (reference umap.py)
        return self.hasParam("labelCol") and self.isDefined("labelCol")

    def _build_fit_inputs(self, fd) -> FitInputs:
        if fd.is_sparse:
            # sparse UMAP fit keeps the CSR on host end-to-end (the kNN graph comes
            # from blocked sparse-sparse products, ops/umap_ops.sparse_knn_graph —
            # reference sparse path umap.py:955-972); no mesh staging needed
            from ..parallel.partitioner import active_partitioner
            from ..parallel.partition import PartitionDescriptor

            desc = PartitionDescriptor.build(
                [fd.n_rows], fd.n_cols, nnz=int(fd.features.nnz)
            )
            return FitInputs(
                features=None,
                row_weight=None,
                desc=desc,
                mesh=active_partitioner(self.num_workers).mesh,
                params=dict(self._tpu_params),
                host_features=fd.features,
                host_label=fd.label,
            )
        return super()._build_fit_inputs(fd)

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        p = dict(self._tpu_params)
        frac = self.getOrDefault("sample_fraction")
        supervised = self._use_label()

        def _fit(inputs: FitInputs) -> Dict[str, Any]:
            X = inputs.host_features
            y = inputs.host_label if supervised else None
            seed = int(p["random_state"]) if p["random_state"] is not None else 42
            if frac < 1.0:
                rng = np.random.default_rng(seed)
                keep = rng.random(X.shape[0]) < frac
                X = X[keep]
                if y is not None:
                    y = y[keep]
            return umap_fit(
                X,
                n_neighbors=int(p["n_neighbors"]),
                n_components=int(p["n_components"]),
                n_epochs=int(p["n_epochs"]),
                min_dist=float(p["min_dist"]),
                spread=float(p["spread"]),
                negative_sample_rate=int(p["negative_sample_rate"]),
                learning_rate=float(p["learning_rate"]),
                seed=seed,
                mesh=inputs.mesh,
                y=y,
                init=str(p.get("init", "spectral")),
                metric=str(p.get("metric", "euclidean")),
                metric_kwds=p.get("metric_kwds"),
                a=p.get("a"),
                b=p.get("b"),
                local_connectivity=float(p.get("local_connectivity", 1.0)),
                set_op_mix_ratio=float(p.get("set_op_mix_ratio", 1.0)),
                repulsion_strength=float(p.get("repulsion_strength", 1.0)),
                build_algo=str(p.get("build_algo", "auto")),
                build_kwds=p.get("build_kwds"),
            )

        return _fit

    def _create_pyspark_model(self, attrs) -> "UMAPModel":
        return UMAPModel(**attrs)


class UMAPModel(_UMAPClass, _TpuModelWithColumns, _UMAPParams):
    def __init__(
        self,
        embedding: np.ndarray,
        raw_data: Any,
        a: float,
        b: float,
        n_neighbors: int,
        metric: str = "euclidean",
        metric_kwds: Optional[Dict[str, Any]] = None,
        local_connectivity: float = 1.0,
        n_epochs: int = 200,
        negative_sample_rate: int = 5,
        learning_rate: float = 1.0,
        repulsion_strength: float = 1.0,
        random_state: int = 42,
    ) -> None:
        from ..core.dataset import _is_sparse

        super().__init__(
            embedding=np.asarray(embedding),
            raw_data=raw_data if _is_sparse(raw_data) else np.asarray(raw_data),
            a=float(a),
            b=float(b),
            n_neighbors=int(n_neighbors),
            metric=str(metric),
            metric_kwds=dict(metric_kwds) if metric_kwds else {},
            local_connectivity=float(local_connectivity),
            n_epochs=int(n_epochs),
            negative_sample_rate=int(negative_sample_rate),
            learning_rate=float(learning_rate),
            repulsion_strength=float(repulsion_strength),
            random_state=int(random_state),
        )
        self._setDefault(featuresCol="features", outputCol="embedding", n_neighbors=15)

    @property
    def embedding_(self) -> np.ndarray:
        return self._model_attributes["embedding"]

    @property
    def rawData_(self) -> np.ndarray:
        return self._model_attributes["raw_data"]

    def _serving_row_independent(self) -> bool:
        # the transform SGD refines all query embeddings jointly (negative
        # sampling draws across the batch): padding rows and batch coalescing
        # would change per-row results — not servable through the micro-batcher
        return False

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        from ..observability.inference import predict_dispatch

        attrs = self._model_attributes
        # cuML/umap-learn transform refines new points for fit_epochs // 3 SGD
        # epochs against the frozen reference embedding
        fit_epochs = int(attrs.get("n_epochs", 200))
        out = predict_dispatch(
            self,
            umap_transform,
            X,
            attrs["raw_data"],
            attrs["embedding"],
            attrs["n_neighbors"],
            metric=str(attrs.get("metric", "euclidean")),
            metric_kwds=attrs.get("metric_kwds") or None,
            local_connectivity=float(attrs.get("local_connectivity", 1.0)),
            a=attrs.get("a"),
            b=attrs.get("b"),
            n_epochs=max(fit_epochs // 3, 1),
            negative_sample_rate=int(attrs.get("negative_sample_rate", 5)),
            learning_rate=float(attrs.get("learning_rate", 1.0)),
            repulsion_strength=float(attrs.get("repulsion_strength", 1.0)),
            seed=int(attrs.get("random_state", 42)),
        )
        return {self.getOrDefault("outputCol"): out}
