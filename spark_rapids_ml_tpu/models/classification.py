#
# LogisticRegression estimator/model (L6 API) — pyspark.ml.classification-compatible
# surface; distributed quasi-Newton fit on the TPU mesh (ops/logistic.py).
#
# Structural equivalent of reference python/src/spark_rapids_ml/classification.py:
#   * reg params -> (penalty, C, l1_ratio) mapping (reference classification.py:679-744)
#     — here mapped directly to (alpha, l1_ratio)
#   * L-BFGS with lbfgs_memory=10, linesearch_max_iter=20
#     (reference classification.py:1046-1052)
#   * missing-label validation (reference classification.py:1093-1102)
#   * single-label ±inf intercept handling (reference classification.py:1106-1121)
#   * multinomial intercept centering (reference classification.py:1135-1147)
#   * transform computes prediction/probability/rawPrediction from the decision
#     function (reference classification.py:1455-1553)
# (RandomForestClassifier, the other member of the reference module, lives in
# models/tree.py.)
#

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import densify
from ..core.backend_params import HasEnableSparseDataOptim, HasFeaturesCols, _TpuClass
from ..core.estimator import (
    FitInputs,
    _TpuEstimatorSupervised,
    _TpuModelWithPredictionCol,
)
from ..core.params import (
    HasAggregationDepth,
    HasElasticNetParam,
    HasFeaturesCol,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasStandardization,
    HasThresholds,
    HasTol,
    HasWeightCol,
    Param,
    TypeConverters,
)
from ..ops.logistic import logreg_decision, logreg_fit


def _validate_labels(y_host) -> "tuple[np.ndarray, int]":
    """Shared label validation for the in-core and streamed LogisticRegression fit
    paths: labels must be non-negative integers with every class 0..k-1 present
    (reference raises with workaround text, classification.py:1093-1102).
    Returns (classes, n_classes)."""
    classes = np.unique(y_host)
    n_classes = int(classes.max()) + 1 if len(classes) > 0 else 0
    if not np.array_equal(classes, classes.astype(np.int64)) or (
        len(classes) > 0 and classes.min() < 0
    ):
        raise ValueError("Labels must be non-negative integers 0..k-1.")
    if len(classes) != n_classes and len(classes) > 1:
        raise RuntimeError(
            f"Labels {sorted(set(range(n_classes)) - set(classes.astype(int)))} "
            "are missing from the dataset: every class in 0..k-1 must appear. "
            "Re-index labels to be consecutive."
        )
    return classes, n_classes


class _LogisticRegressionClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        # reference classification.py:679-744 (there regParam/elasticNetParam are
        # refactored into cuML's (penalty, C, l1_ratio); our backend takes them direct)
        return {
            "regParam": "alpha",
            "elasticNetParam": "l1_ratio",
            "fitIntercept": "fit_intercept",
            "standardization": "standardization",
            "maxIter": "max_iter",
            "tol": "tol",
            "family": "family",
            "threshold": "",
            "thresholds": "",
            "featuresCol": "",
            "labelCol": "",
            "predictionCol": "",
            "probabilityCol": "",
            "rawPredictionCol": "",
            "weightCol": "",
            "aggregationDepth": "",
            "maxBlockSizeInMB": "",
            # sparse inputs are accepted and densified through the native kernel
            # (core/dataset.py densify); gather-based true-sparse device kernels are
            # a round-2 item (reference sparse path: classification.py:1002-1055)
            "enable_sparse_data_optim": "",
            # box constraints run NATIVELY via the projected fit
            # (ops/logistic._projected_fit) — the reference maps these to None and
            # falls back to Spark (classification.py:694-698); values stay on the
            # Spark side (matrices don't belong in the backend kernel dict)
            "lowerBoundsOnCoefficients": "",
            "upperBoundsOnCoefficients": "",
            "lowerBoundsOnIntercepts": "",
            "upperBoundsOnIntercepts": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "family": lambda x: x if x in ("auto", "binomial", "multinomial") else None,
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "alpha": 0.0,
            "l1_ratio": 0.0,
            "fit_intercept": True,
            "standardization": True,
            "max_iter": 100,
            "tol": 1e-6,
            "family": "auto",
        }

    @classmethod
    def _fallback_class(cls):
        from sklearn.linear_model import LogisticRegression as SkLogReg

        return SkLogReg


class _LogisticRegressionParams(
    HasFeaturesCol,
    HasFeaturesCols,
    HasEnableSparseDataOptim,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasMaxIter,
    HasTol,
    HasRegParam,
    HasElasticNetParam,
    HasFitIntercept,
    HasStandardization,
    HasThresholds,
    HasWeightCol,
    HasAggregationDepth,
):
    family: Param[str] = Param(
        "undefined",
        "family",
        "The name of family which is a description of the label distribution to be "
        "used in the model. Supported options: auto, binomial, multinomial",
        TypeConverters.toString,
    )
    threshold: Param[float] = Param(
        "undefined",
        "threshold",
        "Threshold in binary classification prediction, in range [0, 1].",
        TypeConverters.toFloat,
    )
    # Spark LogisticRegression surface parity (reference classification.py:679-744):
    # aggregationDepth/maxBlockSizeInMB are Spark-executor tuning knobs with no TPU
    # meaning (accepted, ignored); the coefficient/intercept bounds run NATIVELY
    # via the projected fit (ops/logistic._projected_fit).
    maxBlockSizeInMB: Param[float] = Param(
        "undefined", "maxBlockSizeInMB",
        "Maximum stacked-block memory in MB (Spark tuning knob; ignored).",
        TypeConverters.toFloat,
    )
    lowerBoundsOnCoefficients: Param[Any] = Param(
        "undefined", "lowerBoundsOnCoefficients",
        "Lower-bound matrix ((numCoefficientSets, numFeatures)) for the "
        "box-constrained fit.",
        TypeConverters.toList,
    )
    upperBoundsOnCoefficients: Param[Any] = Param(
        "undefined", "upperBoundsOnCoefficients",
        "Upper-bound matrix ((numCoefficientSets, numFeatures)) for the "
        "box-constrained fit.",
        TypeConverters.toList,
    )
    lowerBoundsOnIntercepts: Param[Any] = Param(
        "undefined", "lowerBoundsOnIntercepts",
        "Lower-bound vector (numCoefficientSets) for the box-constrained fit.",
        TypeConverters.toList,
    )
    upperBoundsOnIntercepts: Param[Any] = Param(
        "undefined", "upperBoundsOnIntercepts",
        "Upper-bound vector (numCoefficientSets) for the box-constrained fit.",
        TypeConverters.toList,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)

    def setLabelCol(self, value: str):
        return self._set(labelCol=value)


class LogisticRegression(
    _LogisticRegressionClass, _TpuEstimatorSupervised, _LogisticRegressionParams
):
    """LogisticRegression on the TPU mesh: jitted L-BFGS (or FISTA for L1) with the
    gradient psum over ICI. Drop-in for pyspark.ml.classification.LogisticRegression /
    reference spark_rapids_ml.classification.LogisticRegression
    (reference classification.py:747-1204)."""

    def _validate_param_bounds(self) -> None:
        # bounds incompatibilities fail on the DRIVER before any dispatch, like the
        # numeric bounds (the worker-side checks remain as backstops)
        super()._validate_param_bounds()
        bound_names = (
            "lowerBoundsOnCoefficients", "upperBoundsOnCoefficients",
            "lowerBoundsOnIntercepts", "upperBoundsOnIntercepts",
        )
        any_bounds = any(self.isDefined(n) for n in bound_names)
        if not any_bounds:
            return
        if self.getOrDefault("elasticNetParam") != 0.0:
            raise ValueError(
                "Coefficient bounds support only L2 regularization "
                "(elasticNetParam must be 0.0), matching Spark."
            )
        icpt_bounded = self.isDefined("lowerBoundsOnIntercepts") or self.isDefined(
            "upperBoundsOnIntercepts"
        )
        if icpt_bounded and not self.getOrDefault("fitIntercept"):
            raise ValueError(
                "Intercept bounds require fitIntercept=True (an unbounded, "
                "unfitted intercept cannot honor them)."
            )
        if self.hasParam("enable_sparse_data_optim") and self.isDefined(
            "enable_sparse_data_optim"
        ) and self.getOrDefault("enable_sparse_data_optim"):
            raise ValueError(
                "Coefficient bounds require dense features "
                "(disable enable_sparse_data_optim)."
            )

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            regParam=0.0,
            elasticNetParam=0.0,
            fitIntercept=True,
            standardization=True,
            maxIter=100,
            tol=1e-6,
            family="auto",
            threshold=0.5,
            aggregationDepth=2,
            maxBlockSizeInMB=0.0,
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def setRegParam(self, value: float) -> "LogisticRegression":
        return self._set_params(regParam=value)  # type: ignore[return-value]

    def setMaxIter(self, value: int) -> "LogisticRegression":
        return self._set_params(maxIter=value)  # type: ignore[return-value]

    def _out_schema(self) -> List[str]:
        return ["coefficients", "intercepts", "n_iter", "objective", "num_classes"]

    def _enable_fit_multiple_in_single_pass(self) -> bool:
        # device-resident data is reused across param maps (the reference loops cuML
        # fits over the concatenated arrays, classification.py:1173-1190)
        return True

    def _supports_sparse_fit(self) -> bool:
        # matrix-free ELL kernels in ops/sparse.py (reference CSR training path,
        # classification.py:1002-1055)
        return True

    def _get_tpu_fit_func(self, extra_params: Optional[List[Dict[str, Any]]] = None):
        base = dict(self._tpu_params)
        bounds = None
        bound_vals = [
            self.getOrDefault(name) if self.isDefined(name) else None
            for name in (
                "lowerBoundsOnCoefficients", "upperBoundsOnCoefficients",
                "lowerBoundsOnIntercepts", "upperBoundsOnIntercepts",
            )
        ]
        if any(v is not None for v in bound_vals):
            bounds = tuple(bound_vals)

        def _fit(inputs: FitInputs):
            y_host = inputs.host_label
            if y_host is None and inputs.label is not None:
                # global-array path (spark/integration.py): no host copy travels;
                # recover the real labels from the device array, masking padding
                lab = np.asarray(inputs.label)
                w = np.asarray(inputs.row_weight)
                y_host = lab[w > 0]
            classes, n_classes = _validate_labels(y_host)

            param_sets = extra_params if extra_params is not None else [base]
            results = []
            for p in param_sets:
                p = {**base, **p}
                family = p["family"]
                multinomial = family == "multinomial" or (
                    family == "auto" and n_classes > 2
                )
                if not multinomial and n_classes > 2:
                    raise ValueError(
                        f"Binomial family only supports 1 or 2 outcome classes but "
                        f"found {n_classes}."
                    )
                if len(classes) == 1:
                    # single-label degenerate fit: ±inf intercept, zero coefficients
                    # (reference classification.py:1106-1121)
                    d = inputs.desc.n
                    only = int(classes[0])
                    if multinomial:
                        coef = np.zeros((max(n_classes, 1), d), np.float32)
                        intercept = np.full((max(n_classes, 1),), -np.inf, np.float32)
                        intercept[only] = np.inf
                    else:
                        coef = np.zeros((1, d), np.float32)
                        intercept = np.array(
                            [np.inf if only == 1 else -np.inf], np.float32
                        )
                    if bounds is not None:
                        # the degenerate model must still live inside the user's box
                        lb_c, ub_c, lb_i, ub_i = bounds
                        if lb_c is not None or ub_c is not None:
                            lo = -np.inf if lb_c is None else np.asarray(lb_c, np.float32)
                            hi = np.inf if ub_c is None else np.asarray(ub_c, np.float32)
                            coef = np.clip(coef, lo, hi)
                        if lb_i is not None or ub_i is not None:
                            lo = -np.inf if lb_i is None else np.asarray(lb_i, np.float32)
                            hi = np.inf if ub_i is None else np.asarray(ub_i, np.float32)
                            intercept = np.clip(intercept, lo, hi)
                    results.append(
                        {
                            "coefficients": coef,
                            "intercepts": intercept,
                            "n_iter": 0,
                            "objective": 0.0,
                            "num_classes": n_classes,
                        }
                    )
                    continue
                common = dict(
                    n_classes=n_classes,
                    reg=float(p["alpha"]),
                    l1_ratio=float(p["l1_ratio"]),
                    fit_intercept=bool(p["fit_intercept"]),
                    standardize=bool(p["standardization"]),
                    max_iter=int(p["max_iter"]),
                    tol=float(p["tol"]),
                    multinomial=multinomial,
                )
                if inputs.sparse_values is not None:
                    from ..ops.sparse import sparse_logreg_fit

                    if bounds is not None:
                        raise ValueError(
                            "Coefficient bounds require dense features "
                            "(disable enable_sparse_data_optim)."
                        )
                    attrs = sparse_logreg_fit(
                        inputs.sparse_values,
                        inputs.sparse_indices,
                        inputs.desc.n,
                        inputs.label,
                        inputs.row_weight,
                        **common,
                    )
                else:
                    attrs = logreg_fit(
                        inputs.features, inputs.label, inputs.row_weight,
                        bounds=bounds, **common,
                    )
                attrs["num_classes"] = n_classes
                results.append(attrs)
            return results if extra_params is not None else results[0]

        return _fit

    def _create_pyspark_model(self, attrs: Dict[str, Any]) -> "LogisticRegressionModel":
        return LogisticRegressionModel(**attrs)

    def _streaming_fit(self, fd, chain_ops=None) -> Dict[str, Any]:
        """Out-of-core fit: X stays host-resident, every L-BFGS objective/gradient
        evaluation streams batches through the device (ops/streaming.py) — the
        LogisticRegression analog of the reference's UVM/SAM path (reference
        utils.py:184-241) that BASELINE config 3 (500M x 256) requires.
        L1/elastic-net runs the streamed FISTA; routes in-core (with a warning)
        only for coefficient bounds, sparse features, and single-class
        degenerate fits. `chain_ops` carries upstream featurizer transforms when
        this fit is the terminal stage of a fused pipeline chain (pipeline.py)."""
        from .. import config as _config
        from ..core.dataset import _is_sparse, densify as _densify
        from ..ops.streaming import streaming_logreg_fit
        from ..parallel.partitioner import active_partitioner

        p = self._tpu_params
        bounds_set = any(
            self.isDefined(name) and self.getOrDefault(name) is not None
            for name in (
                "lowerBoundsOnCoefficients", "upperBoundsOnCoefficients",
                "lowerBoundsOnIntercepts", "upperBoundsOnIntercepts",
            )
        )
        classes, n_classes = _validate_labels(fd.label)
        if bounds_set or _is_sparse(fd.features) or len(classes) <= 1:
            if chain_ops:
                # the fuser gates on fuse-eligibility, so only a direct caller
                # can land here; in-core would silently drop the chain
                raise ValueError(
                    "This LogisticRegression configuration fits in-core and "
                    "cannot run a fused featurize->fit chain."
                )
            self.logger.warning(
                "streamed LogisticRegression covers dense multi-class fits "
                "only (no coefficient bounds); fitting in-core despite "
                "stream_threshold_bytes."
            )
            inputs = self._build_fit_inputs(fd)
            return self._get_tpu_fit_func(None)(inputs)
        family = p["family"]
        multinomial = family == "multinomial" or (family == "auto" and n_classes > 2)
        if not multinomial and n_classes > 2:
            raise ValueError(
                f"Binomial family only supports 1 or 2 outcome classes but "
                f"found {n_classes}."
            )
        attrs = streaming_logreg_fit(
            _densify(fd.features, self._float32_inputs),
            fd.label,
            fd.weight,
            n_classes=n_classes,
            reg=float(p["alpha"]),
            l1_ratio=float(p["l1_ratio"]),
            fit_intercept=bool(p["fit_intercept"]),
            standardize=bool(p["standardization"]),
            max_iter=int(p["max_iter"]),
            tol=float(p["tol"]),
            multinomial=multinomial,
            batch_rows=int(_config.get("stream_batch_rows")),
            mesh=active_partitioner(self.num_workers).mesh,
            float32=self._float32_inputs,
            chain_ops=chain_ops,
        )
        attrs["num_classes"] = n_classes
        return attrs

    def _fit_fallback_model(self, twin: type, fd) -> Dict[str, Any]:
        X = densify(fd.features, float32=self._float32_inputs)
        reg = self.getOrDefault("regParam")
        l1r = self.getOrDefault("elasticNetParam")
        kwargs: Dict[str, Any] = {
            "C": 1.0 / (reg * fd.n_rows) if reg > 0 else 1e12,
            "fit_intercept": self.getOrDefault("fitIntercept"),
            "max_iter": self.getOrDefault("maxIter"),
            "tol": self.getOrDefault("tol"),
        }
        if reg > 0 and l1r > 0:
            kwargs.update(l1_ratio=l1r, solver="saga")
        sk = twin(**kwargs).fit(
            np.asarray(X, dtype=np.float64), fd.label, sample_weight=fd.weight
        )
        coef = sk.coef_.astype(np.float32)
        return {
            "coefficients": coef,
            "intercepts": np.atleast_1d(sk.intercept_).astype(np.float32),
            "n_iter": int(np.max(sk.n_iter_)),
            "objective": 0.0,
            "num_classes": len(sk.classes_),
        }


class LogisticRegressionModel(
    _LogisticRegressionClass, _TpuModelWithPredictionCol, _LogisticRegressionParams
):
    """Fitted logistic regression model (reference classification.py:1206-1615)."""

    def __init__(
        self,
        coefficients: np.ndarray,
        intercepts: np.ndarray,
        n_iter: int,
        objective: float,
        num_classes: int,
    ) -> None:
        super().__init__(
            coefficients=np.asarray(coefficients),
            intercepts=np.asarray(intercepts),
            n_iter=int(n_iter),
            objective=float(objective),
            num_classes=int(num_classes),
        )
        self._setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            threshold=0.5,
        )

    # --- Spark MLlib surface ---

    @property
    def numClasses(self) -> int:
        return self._model_attributes["num_classes"]

    def partial_fit_updater(self, **kwargs):
        """Streamed continual-learning updater anchored on this model:
        proximal-gradient steps warm-started from the served coefficients
        (continual/partial_fit.py, docs/design.md §7d)."""
        from ..continual.partial_fit import LogisticRegressionUpdater

        return LogisticRegressionUpdater(self, **kwargs)

    @property
    def numFeatures(self) -> int:
        return int(self._model_attributes["coefficients"].shape[1])

    @property
    def _is_multinomial_layout(self) -> bool:
        return self._model_attributes["coefficients"].shape[0] > 1

    @property
    def coefficients(self) -> np.ndarray:
        """Binary-only (d,) vector, Spark semantics."""
        if self._is_multinomial_layout:
            raise RuntimeError(
                "Multinomial models use coefficientMatrix instead of coefficients."
            )
        return self._model_attributes["coefficients"][0]

    @property
    def intercept(self) -> float:
        if self._is_multinomial_layout:
            raise RuntimeError(
                "Multinomial models use interceptVector instead of intercept."
            )
        return float(self._model_attributes["intercepts"][0])

    @property
    def coefficientMatrix(self) -> np.ndarray:
        return self._model_attributes["coefficients"]

    @property
    def interceptVector(self) -> np.ndarray:
        return self._model_attributes["intercepts"]

    @property
    def hasSummary(self) -> bool:
        """No training summary is produced (reference classification.py:1575-1581)."""
        return False

    @property
    def summary(self):
        """Spark raises when hasSummary is False; match it
        (reference classification.py:1583-1591)."""
        raise RuntimeError(
            f"No training summary available for this {self.__class__.__name__}"
        )

    def _margins(self, X: np.ndarray) -> np.ndarray:
        from ..observability.inference import predict_dispatch

        coef = self._model_attributes["coefficients"].astype(np.float32)
        icpt = self._model_attributes["intercepts"].astype(np.float32)
        # guard degenerate single-label ±inf intercepts on the host path
        if not np.all(np.isfinite(icpt)):
            if self._is_multinomial_layout:
                return np.broadcast_to(icpt, (X.shape[0], icpt.shape[0])).copy()
            return np.broadcast_to(icpt[0], (X.shape[0],)).copy()
        return np.asarray(
            predict_dispatch(
                self, logreg_decision, X, coef, icpt, self._is_multinomial_layout
            )
        )

    def _supports_sparse_transform(self) -> bool:
        return True

    def _transform_sparse(self, csr: Any) -> Dict[str, np.ndarray]:
        """Predict on CSR queries without densifying: margins via the ELL gather
        contraction (ops/sparse.py), then the shared output math."""
        import jax.numpy as jnp

        from ..ops.sparse import csr_to_ell, ell_matmat, ell_matvec

        coef = self._model_attributes["coefficients"].astype(np.float32)
        icpt = self._model_attributes["intercepts"].astype(np.float32)
        if not np.all(np.isfinite(icpt)):
            n = csr.shape[0]
            if self._is_multinomial_layout:
                z = np.broadcast_to(icpt, (n, icpt.shape[0])).copy()
            else:
                z = np.broadcast_to(icpt[0], (n,)).copy()
            return self._outputs_from_margins(z)
        from ..observability.inference import predict_dispatch

        values, indices = csr_to_ell(csr, float32=True)
        vj, ij = jnp.asarray(values), jnp.asarray(indices)
        if self._is_multinomial_layout:
            z = np.asarray(
                predict_dispatch(self, ell_matmat, vj, ij, jnp.asarray(coef.T))
            ) + icpt
        else:
            z = np.asarray(
                predict_dispatch(self, ell_matvec, vj, ij, jnp.asarray(coef[0]))
            ) + icpt[0]
        return self._outputs_from_margins(z)

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        return self._outputs_from_margins(self._margins(X))

    def _outputs_from_margins(self, z: np.ndarray) -> Dict[str, np.ndarray]:
        if z.ndim == 1:  # binomial
            raw = np.stack([-z, z], axis=1)
            with np.errstate(over="ignore"):
                p1 = 1.0 / (1.0 + np.exp(-z))
            prob = np.stack([1.0 - p1, p1], axis=1)
            thr = self.getOrDefault("threshold")
            pred = (p1 > thr).astype(np.float64)
        else:
            raw = z
            # clip ±inf margins (single-label degenerate models) to softmax-safe
            # finite values so probabilities come out one-hot rather than NaN
            zf = np.clip(z, -5e2, 5e2)
            zs = zf - zf.max(axis=1, keepdims=True)
            e = np.exp(zs)
            prob = e / e.sum(axis=1, keepdims=True)
            scaled = prob
            if self.isSet("thresholds"):
                t = np.asarray(self.getOrDefault("thresholds"), dtype=np.float64)
                scaled = prob / np.where(t == 0.0, 1e-12, t)
            pred = scaled.argmax(axis=1).astype(np.float64)
        return {
            self.getOrDefault("predictionCol"): pred,
            self.getOrDefault("probabilityCol"): prob,
            self.getOrDefault("rawPredictionCol"): raw,
        }

    def cpu(self):
        """sklearn LogisticRegression twin with the fitted state installed (the
        reference builds the pyspark twin via py4j; pyspark is optional here)."""
        from sklearn.linear_model import LogisticRegression as SkLR

        coef = np.asarray(self._model_attributes["coefficients"], np.float64)
        icpt = np.asarray(self._model_attributes["intercepts"], np.float64)
        k = int(self._model_attributes["num_classes"])
        sk = SkLR()
        sk.coef_ = coef
        sk.intercept_ = icpt
        sk.classes_ = np.arange(max(k, 2), dtype=np.float64)
        sk.n_features_in_ = coef.shape[1]
        sk.n_iter_ = np.array([int(self._model_attributes["n_iter"])])
        return sk

    def predict(self, value: np.ndarray) -> float:
        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return float(self._transform_arrays(X)[self.getOrDefault("predictionCol")][0])

    def predictProbability(self, value: np.ndarray) -> np.ndarray:
        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return self._transform_arrays(X)[self.getOrDefault("probabilityCol")][0]

    def predictRaw(self, value: np.ndarray) -> np.ndarray:
        """Raw margin vector for one feature vector (pyspark model surface)."""
        X = np.asarray(value, dtype=np.float32).reshape(1, -1)
        return self._transform_arrays(X)[self.getOrDefault("rawPredictionCol")][0]

    def evaluate(self, dataset: Any) -> "LogisticRegressionSummary":
        """Evaluate on a labeled dataset, returning the Spark summary surface —
        computed natively (the reference converts to a pyspark model and
        delegates, classification.py:1597-1601)."""
        from ..core.estimator import extract_eval_columns

        out, label, pred, weight = extract_eval_columns(self, dataset)
        if self.numClasses == 2:
            prob = np.stack(out[self.getOrDefault("probabilityCol")].to_numpy())
            return BinaryLogisticRegressionSummary(
                out, label, pred, prob[:, 1], weight
            )
        return LogisticRegressionSummary(out, label, pred, weight)

    def _combine(
        self, models: List["LogisticRegressionModel"]
    ) -> "LogisticRegressionModel":
        """Keep sibling models for one-pass CV transform-evaluate
        (reference classification.py:1557-1572)."""
        first = models[0]
        first._combined_models = models
        return first


class LogisticRegressionSummary:
    """Evaluation summary over a predictions frame — the surface of
    pyspark.ml.classification.LogisticRegressionSummary, computed natively on the
    metrics/ reduction classes (the reference's model.evaluate() converts to a
    pyspark model and delegates, classification.py:1597-1601)."""

    def __init__(
        self,
        predictions,
        label: np.ndarray,
        pred: np.ndarray,
        weight: Optional[np.ndarray] = None,
    ) -> None:
        from ..metrics.MulticlassMetrics import MulticlassMetrics

        self.predictions = predictions
        self._m = MulticlassMetrics.from_predictions(label, pred, weight)
        self._labels = sorted(set(np.asarray(label, np.float64).tolist()))

    @property
    def labels(self) -> List[float]:
        return list(self._labels)

    @property
    def accuracy(self) -> float:
        return self._m.accuracy()

    @property
    def weightedPrecision(self) -> float:
        return self._m.weighted_precision()

    @property
    def weightedRecall(self) -> float:
        return self._m.weighted_recall()

    def weightedFMeasure(self, beta: float = 1.0) -> float:
        return self._m.weighted_f_measure(beta)

    @property
    def weightedTruePositiveRate(self) -> float:
        return self._m.weighted_recall()

    @property
    def weightedFalsePositiveRate(self) -> float:
        return self._m.weighted_false_positive_rate()

    @property
    def precisionByLabel(self) -> List[float]:
        return [self._m._precision(l) for l in self._labels]

    @property
    def recallByLabel(self) -> List[float]:
        return [self._m._recall(l) for l in self._labels]

    def fMeasureByLabel(self, beta: float = 1.0) -> List[float]:
        return [self._m._f_measure(l, beta) for l in self._labels]

    @property
    def truePositiveRateByLabel(self) -> List[float]:
        return self.recallByLabel

    @property
    def falsePositiveRateByLabel(self) -> List[float]:
        return [self._m._false_positive_rate(l) for l in self._labels]


class BinaryLogisticRegressionSummary(LogisticRegressionSummary):
    """Adds the threshold-sweep metrics (areaUnderROC, roc/pr curves) for binary
    models — pyspark.ml.classification.BinaryLogisticRegressionSummary surface."""

    def __init__(
        self,
        predictions,
        label: np.ndarray,
        pred: np.ndarray,
        score: np.ndarray,
        weight: Optional[np.ndarray] = None,
    ) -> None:
        from ..metrics.utils import binary_classification_sweep

        super().__init__(predictions, label, pred, weight)
        self._tps, self._fps = binary_classification_sweep(score, label, weight)
        self._P, self._N = self._tps[-1], self._fps[-1]

    @property
    def areaUnderROC(self) -> float:
        from ..metrics.utils import area_under_roc

        return area_under_roc(self._tps, self._fps)

    @property
    def roc(self):
        import pandas as pd

        return pd.DataFrame(
            {"FPR": self._fps / self._N, "TPR": self._tps / self._P}
        )

    @property
    def pr(self):
        import pandas as pd

        recall = self._tps / self._P
        precision = np.where(
            self._tps + self._fps > 0,
            self._tps / np.maximum(self._tps + self._fps, 1e-300),
            1.0,
        )
        return pd.DataFrame({"recall": recall, "precision": precision})
