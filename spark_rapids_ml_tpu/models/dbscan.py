#
# DBSCAN estimator/model (L6 API) — reference spark_rapids_ml.clustering.DBSCAN
# (reference clustering.py:607-1186):
#   * fit() does NO compute — it captures the dataset; the clustering runs at
#     transform() time (reference clustering.py:904-918: "_fit returns empty model")
#   * transform() broadcasts the (transform-time) dataset, computes labels, and joins
#     them back by idCol (reference clustering.py:1103-1186)
#   * int64 labels throughout (the reference escalates out_dtype for >2.1e9 points,
#     clustering.py:1076-1078 — int64 is simply the default here)
#

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..core.backend_params import HasFeaturesCols, HasIDCol, _TpuClass
from ..core.estimator import _TpuEstimator, _TpuModel
from ..core.params import (
    HasFeaturesCol,
    HasPredictionCol,
    Param,
    TypeConverters,
)
from ..parallel.partitioner import active_partitioner
from ..parallel.partition import pad_rows
from ..ops.dbscan import dbscan_fit_predict


class _DBSCANClass(_TpuClass):
    @classmethod
    def _param_mapping(cls):
        return {
            "eps": "eps",
            "min_samples": "min_samples",
            "metric": "metric",
            "max_mbytes_per_batch": "max_mbytes_per_batch",
            # cuML's 'algorithm' selects brute vs rbc neighbor search — both exact;
            # the TPU backend always runs the blocked-matmul brute scan (reference
            # clustering.py DBSCAN param surface)
            "algorithm": "algorithm",
            "featuresCol": "",
            "featuresCols": "",
            "predictionCol": "",
            "idCol": "",
        }

    @classmethod
    def _param_value_mapping(cls):
        return {
            "metric": lambda x: x if x in ("euclidean", "cosine") else None,
            "algorithm": lambda x: x if x in ("brute", "rbc") else None,
        }

    @classmethod
    def _get_tpu_params_default(cls) -> Dict[str, Any]:
        return {
            "eps": 0.5,
            "min_samples": 5,
            "metric": "euclidean",
            "max_mbytes_per_batch": None,
            "algorithm": "brute",
        }

    @classmethod
    def _fallback_class(cls):
        from sklearn.cluster import DBSCAN as SkDBSCAN

        return SkDBSCAN


class _DBSCANParams(HasFeaturesCol, HasFeaturesCols, HasPredictionCol, HasIDCol):
    eps: Param[float] = Param(
        "undefined",
        "eps",
        "The maximum distance between two samples for one to be considered as in the "
        "neighborhood of the other.",
        TypeConverters.toFloat,
    )
    min_samples: Param[int] = Param(
        "undefined",
        "min_samples",
        "The number of samples in a neighborhood for a point to be considered as a "
        "core point (including the point itself).",
        TypeConverters.toInt,
    )
    metric: Param[str] = Param(
        "undefined", "metric", "Distance metric (euclidean|cosine).",
        TypeConverters.toString,
    )
    max_mbytes_per_batch: Param[int] = Param(
        "undefined",
        "max_mbytes_per_batch",
        "Batch size cap for the pairwise-distance computation.",
        TypeConverters.toInt,
    )
    algorithm: Param[str] = Param(
        "undefined", "algorithm",
        "Neighbor-search algorithm ('brute' or 'rbc'; both exact — the TPU backend "
        "always runs the blocked brute scan).",
        TypeConverters.toString,
    )

    def setFeaturesCol(self, value: str):
        return self._set(featuresCol=value)


class DBSCAN(_DBSCANClass, _TpuEstimator, _DBSCANParams):
    """Density-based clustering on the TPU mesh (reference clustering.py:607-918)."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            predictionCol="prediction",
            eps=0.5,
            min_samples=5,
            metric="euclidean",
            algorithm="brute",
        )
        self.initialize_tpu_params()
        self._set_params(**kwargs)

    def _out_schema(self) -> List[str]:
        return []

    def _get_tpu_fit_func(self, extra_params=None):
        raise NotImplementedError("DBSCAN defers all compute to transform().")

    def _create_pyspark_model(self, attrs) -> "DBSCANModel":
        return DBSCANModel()

    def _fit(self, dataset: Any) -> "DBSCANModel":
        # no compute at fit (reference clustering.py:904-918) — but bad params must
        # still fail HERE on the driver, not inside the deferred transform stage
        self._validate_param_bounds()
        if self._use_cpu_fallback():
            model = DBSCANModel()
            model._use_sklearn = True
        else:
            model = DBSCANModel()
        model._num_workers = self._num_workers
        self._copyValues(model)
        return model


class DBSCANModel(_DBSCANClass, _TpuModel, _DBSCANParams):
    def __init__(self) -> None:
        super().__init__()
        self._setDefault(
            featuresCol="features",
            predictionCol="prediction",
            eps=0.5,
            min_samples=5,
            metric="euclidean",
            algorithm="brute",
        )
        self._use_sklearn = False

    def _serving_row_independent(self) -> bool:
        # DBSCAN's "predict" clusters the query set itself: labels depend on
        # the WHOLE batch, so coalescing requests (or padding rows) changes
        # results — the serving plane must refuse to register it
        return False

    def _transform_arrays(self, X: np.ndarray) -> Dict[str, np.ndarray]:
        self._validate_param_bounds()  # DBSCAN defers compute to transform
        if self._use_sklearn:
            sk = self._fallback_class()(
                eps=self.getOrDefault("eps"),
                min_samples=self.getOrDefault("min_samples"),
                metric=self.getOrDefault("metric"),
            )
            labels = sk.fit_predict(X)
            return {self.getOrDefault("predictionCol"): labels.astype(np.int64)}
        from .. import config as _config

        threshold = int(_config.get("stream_threshold_bytes"))
        if X.nbytes > threshold:
            # out-of-core tier: the dataset stays host-resident and the device
            # sees (query_block, item_block) distance tiles — the reference
            # DBSCAN instead broadcasts the whole dataset and leans on UVM
            # (reference clustering.py:1103-1163, utils.py:184-241)
            from ..ops.pairwise_streaming import streaming_dbscan_fit_predict

            self.logger.warning(
                "dataset ~%.0f MiB exceeds stream_threshold_bytes=%d; using the "
                "out-of-core blocked-pairwise DBSCAN (host-resident rows).",
                X.nbytes / 2**20,
                threshold,
            )
            from ..observability.inference import predict_dispatch

            labels = predict_dispatch(
                self,
                streaming_dbscan_fit_predict,
                X,
                eps=self.getOrDefault("eps"),
                min_samples=self.getOrDefault("min_samples"),
                metric=self.getOrDefault("metric"),
                mesh=active_partitioner(self.num_workers).mesh,
            )
            return {self.getOrDefault("predictionCol"): labels}
        from ..observability.inference import predict_dispatch

        part = active_partitioner(self.num_workers)
        mesh = part.mesh
        Xp, valid, _ = pad_rows(X, part.num_workers)
        Xd = part.shard(Xp)
        vd = part.shard(valid > 0)
        labels = predict_dispatch(
            self,
            dbscan_fit_predict,
            Xd,
            vd,
            eps=self.getOrDefault("eps"),
            min_samples=self.getOrDefault("min_samples"),
            metric=self.getOrDefault("metric"),
        )
        return {self.getOrDefault("predictionCol"): labels[: X.shape[0]]}
