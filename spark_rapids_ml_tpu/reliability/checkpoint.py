#
# Checkpoint-resume for streamed fits. Every streamed accumulator walks the
# shape  `for batch in stream: carry = accum(carry, batch)`  where `carry` is a
# small FUNCTIONAL value (a tuple of device stats arrays or host numpy arrays)
# and the stream is restartable from any batch boundary. That is exactly the
# MapReduce-over-JAX decomposition DrJAX (arXiv:2403.07128) shows admits cheap
# per-round checkpointing: a snapshot is just a REFERENCE to (carry, cursor) —
# no copy, no serialization — because accumulation never mutates a prior carry.
#
# On a transient batch failure (preempted host, dropped connection, one ingest
# batch OOM) the loop resumes the stream from the last snapshot cursor and
# replays forward. Replay performs the identical device ops on the identical
# batches in the identical order, so the resumed fit is BIT-IDENTICAL to the
# fault-free run (tests/test_reliability.py asserts this for every streamed
# estimator). Non-transient errors propagate untouched.
#

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

from .. import config as _config
from .. import profiling
from ..utils import get_logger
from .faults import is_transient
from .policy import RetryPolicy

_logger = get_logger("reliability.checkpoint")


def _copy_carry(carry: Any) -> Any:
    """Snapshot-safe copy of a carry: device-array leaves are copied, host
    leaves pass through by reference. The streamed accumulators DONATE their
    carry argument (ops/streaming.py) so the device buffers are reused in
    place batch to batch — a snapshot that merely aliased the carry would be
    invalidated by the very next accumulation and a resume would touch deleted
    buffers. Host leaves stay reference-snapshots: the host accumulators are
    functional (new objects, never +=), the original snapshot contract."""
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is a hard dep everywhere else
        return carry
    return jax.tree_util.tree_map(
        lambda leaf: leaf.copy() if isinstance(leaf, jax.Array) else leaf, carry
    )


# Public alias: the continual plane (continual/partial_fit.py) snapshots its
# persistent partial_fit carries with the exact same donation-safe copy the
# checkpoint loop uses, so snapshot/restore and checkpoint-resume share one
# definition of "a safe copy of a carry".
copy_carry = _copy_carry


def resumable_accumulate(
    site: str,
    stream_factory: Callable[[int], Iterable[Any]],
    accum: Callable[[Any, Any], Any],
    carry: Any,
    batch_rows: int,
    n_rows: int,
    start_row: int = 0,
) -> Any:
    """Fold `accum` over every batch of `stream_factory(start_row)`.

    `stream_factory(row)` must yield the batches covering rows [row, n_rows) in
    order; `accum(carry, batch) -> carry` must be functional (return a new carry,
    never mutate the old one — all the streamed accumulators already are). Every
    `reliability.checkpoint_batches` batches the (carry, cursor) pair is
    snapshotted by reference; a transient failure restores the snapshot and
    re-opens the stream at the snapshot cursor, bounded by the RetryPolicy.
    """
    if not bool(_config.get("reliability.enabled")):
        for batch in stream_factory(int(start_row)):
            carry = accum(carry, batch)
        return carry

    every = max(1, int(_config.get("reliability.checkpoint_batches")))
    policy = RetryPolicy.from_config()
    # snapshots (and the restore below) COPY device leaves: the accumulators
    # donate their carry, so an aliased snapshot would be deleted by the next
    # batch's buffer reuse (see _copy_carry)
    snap_carry, snap_row = _copy_carry(carry), int(start_row)
    failures = 0
    t0 = time.monotonic()
    while True:
        attempt_start_row = snap_row
        row = snap_row
        carry = _copy_carry(snap_carry)
        try:
            done = 0
            for batch in stream_factory(row):
                carry = accum(carry, batch)
                row = min(row + batch_rows, n_rows)
                done += 1
                if done % every == 0:
                    snap_carry, snap_row = _copy_carry(carry), row
            return carry
        except Exception as e:
            if snap_row > attempt_start_row:
                # the snapshot advanced since the last restore: this is a NEW
                # independent fault, not the same one repeating — the attempt
                # budget bounds retries PER fault, not per multi-hour stream.
                # (t0 is NOT reset: reliability.deadline_s stays per-stage.)
                failures = 0
            failures += 1
            if not is_transient(e) or policy.give_up(
                failures, time.monotonic() - t0, site
            ):
                raise
            profiling.count("reliability.resume")
            profiling.count(f"reliability.resume.{site}")
            from ..observability import event as _obs_event

            _obs_event(
                "resume", site=site, row=snap_row, attempt=failures,
                error=type(e).__name__,
            )
            _logger.warning(
                "transient failure at '%s' (%s: %s); resuming from row %d "
                "(last snapshot), attempt %d/%d",
                site, type(e).__name__, e, snap_row, failures + 1,
                policy.max_attempts,
            )
            policy.sleep(failures, site)
