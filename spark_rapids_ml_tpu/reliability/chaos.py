#
# Deterministic replica chaos — the fleet-level extension of the fault
# harness (reliability/faults.py). `fault_point` raises a chosen exception at
# a chosen call; a serving FLEET needs richer failure verbs: kill a replica's
# dispatcher outright, hang it long enough for the heartbeat monitor to
# declare it dead, or slow it into the hedging cutoff. This module adds those
# verbs behind the same config-driven, spec-string grammar so a failover test
# (and the failover bench scenario) replays identically on every run.
#
# Grammar (SRML_TPU_CHAOS_SPEC / config "reliability.chaos_spec"):
#
#   spec      := clause (';' clause)*
#   clause    := site (':' field)*
#   field     := 'replica=' INT   -- fire only on this replica index
#              | 'batch=' INT     -- fire only at this site-visit ordinal
#              | 'after=' INT     -- fire at any ordinal >= this one
#              | 'action=' NAME   -- kill | hang | slow   (default kill)
#              | 'sleep=' FLOAT   -- hang/slow duration seconds
#                                    (hang default: 4x serving.heartbeat_
#                                     timeout_s, so the monitor always fires;
#                                     slow default: 0.05)
#              | 'times=' INT     -- firings before the clause exhausts
#                                    (default 1: one transient incident)
#
#   e.g.  SRML_TPU_CHAOS_SPEC="serving_execute:replica=1:after=3:action=kill"
#         SRML_TPU_CHAOS_SPEC="serving_heartbeat:replica=0:action=hang"
#         SRML_TPU_CHAOS_SPEC="serving_dispatch:action=slow:sleep=0.02:times=8"
#
# Chaos sites planted in the serving fleet (docs/design.md §7c):
#   serving_dispatch   serving/router.py   request routing (pre-enqueue)
#   serving_execute    serving/fleet.py    per-replica batch execution
#   serving_heartbeat  serving/fleet.py    health-monitor heartbeat read
#
# The same three names are ALSO `fault_point` sites at the same calls, so the
# plain fault grammar (raise=/sleep=) composes with the chaos verbs — a test
# can raise OSError in one replica's execute path while chaos-killing another.
#
# `kill` raises ReplicaKilled — the fleet's dispatcher loop treats it (and
# only it) as replica death rather than a batch failure: the replica leaves
# rotation, its queue replays onto survivors, and recovery restarts it from
# the registry's pinned weights. Firing budgets live process-wide keyed by
# the spec string (exactly like faults.py), reset by tests via reset_chaos().
#

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .. import config as _config
from .. import profiling
from ..utils import get_logger

_logger = get_logger("reliability.chaos")

CHAOS_SITES = ("serving_dispatch", "serving_execute", "serving_heartbeat")

_ACTIONS = ("kill", "hang", "slow")

_SLOW_DEFAULT_S = 0.05
_HANG_HEARTBEAT_MULTIPLE = 4.0


class ReplicaKilled(RuntimeError):
    """A chaos `kill` verb fired: the replica's dispatcher must die (leave
    rotation, replay its queue), not merely fail one batch. Carries the site
    and replica index for the failover assertions."""

    def __init__(self, site: str, replica: Optional[int] = None,
                 batch: Optional[int] = None):
        super().__init__(
            f"chaos kill at site '{site}'"
            + (f" replica {replica}" if replica is not None else "")
            + (f" batch {batch}" if batch is not None else "")
        )
        self.site = site
        self.replica = replica
        self.batch = batch


@dataclass(frozen=True)
class ChaosSpec:
    """One parsed clause of the chaos grammar."""

    site: str
    action: str = "kill"
    replica: Optional[int] = None  # None: any replica
    batch: Optional[int] = None  # fire only at this site-visit ordinal
    after: Optional[int] = None  # fire at any ordinal >= this one
    sleep: Optional[float] = None  # hang/slow duration override
    times: int = 1


def parse_chaos_spec(raw: str) -> List[ChaosSpec]:
    specs: List[ChaosSpec] = []
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        fields = clause.split(":")
        site = fields[0].strip()
        if not site:
            raise ValueError(f"chaos clause with empty site: {clause!r}")
        action, replica, batch, after, sleep, times = "kill", None, None, None, None, 1
        for field in fields[1:]:
            key, sep, value = field.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(
                    f"malformed chaos field {field!r} in {clause!r}"
                )
            if key == "replica":
                replica = int(value)
            elif key == "batch":
                batch = int(value)
            elif key == "after":
                after = int(value)
            elif key == "action":
                if value not in _ACTIONS:
                    raise ValueError(
                        f"unknown chaos action {value!r} in {clause!r}; "
                        f"known: {list(_ACTIONS)}"
                    )
                action = value
            elif key == "sleep":
                sleep = float(value)
                if sleep < 0:
                    raise ValueError(f"negative sleep in chaos clause {clause!r}")
            elif key == "times":
                times = int(value)
            else:
                raise ValueError(f"unknown chaos field {key!r} in {clause!r}")
        if batch is not None and after is not None:
            raise ValueError(
                f"chaos clause {clause!r} combines batch= with after=; "
                "batch= fires at exactly one ordinal, after= at every "
                "ordinal from one on — pick one"
            )
        specs.append(ChaosSpec(site, action, replica, batch, after, sleep, times))
    return specs


# (spec string, parsed clauses, remaining firing counts) — re-parsed whenever
# the configured spec string changes, reset explicitly via reset_chaos(). The
# lock keeps firing budgets exact across replica dispatcher threads.
_armed: Optional[Tuple[str, List[ChaosSpec], List[int]]] = None
_armed_lock = threading.Lock()


def _active() -> Optional[Tuple[str, List[ChaosSpec], List[int]]]:
    global _armed
    raw = _config.get("reliability.chaos_spec") or ""
    if not raw:
        _armed = None
        return None
    if _armed is None or _armed[0] != raw:
        specs = parse_chaos_spec(raw)
        _armed = (raw, specs, [s.times for s in specs])
    return _armed


def reset_chaos() -> None:
    """Re-arm the configured spec (firing counts restart from `times`)."""
    global _armed
    _armed = None


def chaos_enabled() -> bool:
    return bool(_config.get("reliability.chaos_spec") or "")


def _hang_seconds(spec: ChaosSpec) -> float:
    if spec.sleep is not None:
        return spec.sleep
    return _HANG_HEARTBEAT_MULTIPLE * float(
        _config.get("serving.heartbeat_timeout_s")
    )


def chaos_point(site: str, replica: Optional[int] = None,
                batch: Optional[int] = None) -> None:
    """A named chaos site. No-op unless a configured clause matches, in which
    case the clause's verb executes and its firing budget decrements —
    deterministic: same spec + same call sequence = same incident. `kill`
    raises ReplicaKilled; `hang`/`slow` sleep and return."""
    fire: Optional[ChaosSpec] = None
    left = 0
    with _armed_lock:
        state = _active()
        if state is None:
            return
        _, specs, remaining = state
        for i, spec in enumerate(specs):
            if spec.site != site or remaining[i] <= 0:
                continue
            if spec.replica is not None and replica != spec.replica:
                continue
            if spec.batch is not None and batch != spec.batch:
                continue
            if spec.after is not None and (batch is None or batch < spec.after):
                continue
            remaining[i] -= 1
            fire, left = spec, remaining[i]
            break
    if fire is None:
        return
    profiling.count("reliability.chaos")
    profiling.count(f"reliability.chaos.{site}")
    from ..observability import event as _obs_event

    _obs_event(
        "chaos", site=site, action=fire.action, replica=replica, batch=batch,
    )
    if fire.action == "kill":
        _logger.warning(
            "chaos injection: killing replica at site '%s'%s%s (%d firings left)",
            site,
            f" replica {replica}" if replica is not None else "",
            f" batch {batch}" if batch is not None else "", left,
        )
        raise ReplicaKilled(site, replica, batch)
    sleep_s = _hang_seconds(fire) if fire.action == "hang" else (
        fire.sleep if fire.sleep is not None else _SLOW_DEFAULT_S
    )
    _logger.warning(
        "chaos injection: %s %.3fs at site '%s'%s (%d firings left)",
        fire.action, sleep_s, site,
        f" replica {replica}" if replica is not None else "", left,
    )
    time.sleep(sleep_s)


__all__ = [
    "CHAOS_SITES",
    "ChaosSpec",
    "ReplicaKilled",
    "chaos_enabled",
    "chaos_point",
    "parse_chaos_spec",
    "reset_chaos",
]
