#
# Retry/backoff policy core. One policy object serves every layer: per-batch
# retries in the streamed ANN/pairwise tiers, the barrier process-group init
# rounds (spark/integration.py), whole-stage re-runs (fit_on_spark), and the
# checkpoint-resume loop (reliability/checkpoint.py).
#
# Backoff is exponential with DETERMINISTIC jitter: the jitter fraction comes
# from a hash of (site, attempt) rather than an RNG, so a failing run replays
# identically — the property the fault-injection tests (and any production
# incident reproduction) depend on.
#

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from .. import config as _config
from .. import profiling
from ..utils import get_logger
from .faults import is_transient

_logger = get_logger("reliability.policy")


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded attempts + exponential backoff with deterministic jitter + an
    optional per-stage wall-clock deadline."""

    max_attempts: int = 3  # total attempts, first one included
    backoff_base_s: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 2.0
    jitter: float = 0.1  # +/- jitter/2 fraction applied to each delay
    deadline_s: Optional[float] = None  # give up when the next delay would cross it

    @classmethod
    def from_config(cls) -> "RetryPolicy":
        # reliability.enabled is the master kill switch: disabled means every
        # unit gets exactly one attempt — failures surface immediately
        enabled = bool(_config.get("reliability.enabled"))
        deadline = _config.get("reliability.deadline_s")
        return cls(
            max_attempts=max(1, int(_config.get("reliability.max_attempts")))
            if enabled
            else 1,
            backoff_base_s=float(_config.get("reliability.backoff_base_s")),
            backoff_max_s=float(_config.get("reliability.backoff_max_s")),
            jitter=float(_config.get("reliability.backoff_jitter")),
            deadline_s=float(deadline) if deadline is not None else None,
        )

    def delay_s(self, failures: int, site: str = "") -> float:
        """Backoff before attempt `failures + 1` (failures >= 1). Deterministic:
        the jitter fraction hashes (site, failures)."""
        base = min(
            self.backoff_base_s * self.backoff_multiplier ** (failures - 1),
            self.backoff_max_s,
        )
        digest = hashlib.sha256(f"{site}:{failures}".encode()).digest()
        frac = int.from_bytes(digest[:4], "big") / 0xFFFFFFFF  # [0, 1)
        return base * (1.0 + self.jitter * (frac - 0.5))

    def give_up(self, failures: int, elapsed_s: float, site: str = "") -> bool:
        """True when the policy is exhausted: attempt budget spent, or the next
        backoff would cross the stage deadline."""
        if failures >= self.max_attempts:
            return True
        if self.deadline_s is not None and (
            elapsed_s + self.delay_s(failures, site) >= self.deadline_s
        ):
            return True
        return False

    def sleep(self, failures: int, site: str = "") -> None:
        time.sleep(self.delay_s(failures, site))

    def run(
        self,
        fn: Callable[[], Any],
        site: str = "",
        retryable: Optional[Callable[[BaseException], bool]] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ) -> Any:
        """Call `fn` under this policy: retryable failures (default
        faults.is_transient) back off and re-run; everything else — and the last
        exhausted attempt — propagates. Each retry increments the
        `reliability.retry` / `reliability.retry.<site>` profiling counters."""
        if retryable is None:
            retryable = is_transient
        t0 = time.monotonic()
        failures = 0
        while True:
            try:
                return fn()
            except Exception as e:
                failures += 1
                if not retryable(e) or self.give_up(
                    failures, time.monotonic() - t0, site
                ):
                    raise
                profiling.count("reliability.retry")
                if site:
                    profiling.count(f"reliability.retry.{site}")
                from ..observability import event as _obs_event

                _obs_event(
                    "retry", site=site or "unnamed", attempt=failures,
                    error=type(e).__name__,
                )
                _logger.warning(
                    "transient failure at '%s' (%s: %s); retry %d/%d after backoff",
                    site or "unnamed", type(e).__name__, e, failures,
                    self.max_attempts - 1,
                )
                if on_retry is not None:
                    on_retry(failures, e)
                self.sleep(failures, site)
