#
# Deterministic, config-driven fault injection — the testability half of the
# reliability subsystem. arXiv:1612.01437 identifies straggler/failure handling
# as the dominant availability cost of Spark ML at scale; before this module the
# failure paths of the streamed fits and the barrier fit plane were untestable
# (nothing in the tree could raise at a chosen ingest batch or barrier round).
#
# Grammar (SRML_TPU_FAULT_SPEC / config "reliability.fault_spec"):
#
#   spec      := clause (';' clause)*
#   clause    := site (':' field)*
#   field     := 'batch=' INT     -- fire only when the site sees this batch ordinal
#              | 'raise=' NAME    -- exception class to raise (default OSError)
#              | 'times=' INT     -- how many firings before the fault exhausts
#                                    (default 1: a TRANSIENT fault)
#              | 'sleep=' FLOAT   -- DELAY instead of raising: sleep this many
#                                    seconds and return (a deterministic
#                                    straggler — the comm plane's rank-skew/
#                                    straggler detection is tested with it,
#                                    docs/design.md §6h)
#
#   e.g.  SRML_TPU_FAULT_SPEC="ingest:batch=3:raise=OSError"
#         SRML_TPU_FAULT_SPEC="barrier_init:raise=TimeoutError;ann_assign:batch=1"
#         SRML_TPU_FAULT_SPEC="barrier_rank:batch=3:sleep=0.5"  # rank 3 drags
#
# Named sites planted in the tree (docs/design.md "Reliability"):
#   ingest            ops/streaming.py::_batch_stream    (every streamed fit)
#   ann_assign        ops/ann_streaming.py  IVF cell-assignment batches
#   ann_encode        ops/ann_streaming.py  PQ encoding batches
#   ann_search        ops/ann_streaming.py  paged IVF search blocks
#   pairwise          ops/pairwise_streaming.py  item-block generators
#   barrier_collect   spark/integration.py  per-partition Arrow collect
#   barrier_allgather spark/integration.py  control-plane allGather round
#   barrier_init      spark/integration.py  jax.distributed process-group init
#   barrier_rank      spark/integration.py  per-rank fit body (batch = RANK:
#                     with sleep=, delays one chosen rank — straggler injection)
#   serving_dispatch  serving/fleet.py + serving/registry.py  request routing
#                     (batch = request ordinal; pre-enqueue — a raise here
#                     rejects one request)
#   serving_execute   serving/batcher.py    dispatcher batch execution (batch =
#                     that batcher's batch ordinal; in fleet mode each replica's
#                     batcher counts its own)
#   serving_heartbeat serving/fleet.py      health-monitor heartbeat read
#                     (batch = replica index)
#
# The same three serving sites are also CHAOS sites (reliability/chaos.py):
# the chaos grammar adds fleet-level verbs — kill/hang/slow a whole replica —
# on top of this module's raise/sleep.
#
# Firing state lives process-wide and is keyed by the spec string, so a fault
# with times=1 fires exactly once per configured spec — the injected failure is
# transient and the retry/resume machinery it exercises must converge.
#

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .. import config as _config
from .. import profiling
from ..utils import get_logger

_logger = get_logger("reliability.faults")


class DeviceError(RuntimeError):
    """Unrecoverable accelerator-side failure — the stand-in the fault harness
    raises for XlaRuntimeError-class errors (which cannot be constructed
    portably). `is_device_error` treats both identically: never retried, routed
    to the CPU fallback rung of the degradation ladder."""


class StreamBatchError(RuntimeError):
    """A streamed-batch failure carrying its site and batch-ordinal context, so
    the checkpoint-resume layer can catch it and resume from the last snapshot
    instead of surfacing a bare mid-pipeline exception (ops/streaming.py)."""

    def __init__(self, site: str, batch_index: int, cause: Optional[BaseException] = None):
        super().__init__(
            f"streamed batch failure at site '{site}', batch {batch_index}"
            + (f": {type(cause).__name__}: {cause}" if cause is not None else "")
        )
        self.site = site
        self.batch_index = batch_index
        if cause is not None:
            # explicit chaining: is_transient/is_device_error classify by the
            # wrapped failure, which must survive a plain `raise` too
            self.__cause__ = cause


# exceptions a fault clause may raise — a registry, not eval()
_EXC_REGISTRY = {
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "MemoryError": MemoryError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "DeviceError": DeviceError,
}


@dataclass(frozen=True)
class FaultSpec:
    """One parsed clause of the fault grammar."""

    site: str
    batch: Optional[int] = None  # None: fire at any batch
    exc: type = OSError
    times: int = 1  # firings before the fault exhausts (1 == transient)
    sleep: float = 0.0  # >0: delay this many seconds instead of raising


def parse_fault_spec(raw: str) -> List[FaultSpec]:
    specs: List[FaultSpec] = []
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        fields = clause.split(":")
        site, batch, exc, times = fields[0].strip(), None, OSError, 1
        sleep, exc_given = 0.0, False
        if not site:
            raise ValueError(f"fault clause with empty site: {clause!r}")
        for field in fields[1:]:
            key, sep, value = field.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"malformed fault field {field!r} in {clause!r}")
            if key == "batch":
                batch = int(value)
            elif key == "raise":
                if value not in _EXC_REGISTRY:
                    raise ValueError(
                        f"unknown exception {value!r} in fault clause {clause!r}; "
                        f"known: {sorted(_EXC_REGISTRY)}"
                    )
                exc = _EXC_REGISTRY[value]
                exc_given = True
            elif key == "times":
                times = int(value)
            elif key == "sleep":
                sleep = float(value)
                if sleep < 0:
                    raise ValueError(
                        f"negative sleep in fault clause {clause!r}"
                    )
            else:
                raise ValueError(f"unknown fault field {key!r} in {clause!r}")
        if sleep > 0 and exc_given:
            # contradictory clause: a sleep fault returns, so the raise= could
            # only be silently ignored — reject at parse time like every other
            # malformed field instead of handing back a delay-only fault
            raise ValueError(
                f"fault clause {clause!r} combines sleep= with raise=; "
                "a sleep fault delays instead of raising — use separate "
                "clauses for a delay and a failure"
            )
        specs.append(FaultSpec(site, batch, exc, times, sleep))
    return specs


# (spec string, parsed clauses, remaining firing counts) — re-parsed whenever the
# configured spec string changes, reset explicitly by tests via reset_faults().
# The lock keeps the firing budget exact when barrier tasks run as threads.
_armed: Optional[Tuple[str, List[FaultSpec], List[int]]] = None
_armed_lock = threading.Lock()


def _active() -> Optional[Tuple[str, List[FaultSpec], List[int]]]:
    global _armed
    raw = _config.get("reliability.fault_spec") or ""
    if not raw:
        _armed = None
        return None
    if _armed is None or _armed[0] != raw:
        specs = parse_fault_spec(raw)
        _armed = (raw, specs, [s.times for s in specs])
    return _armed


def reset_faults() -> None:
    """Re-arm the configured spec (firing counts restart from `times`)."""
    global _armed
    _armed = None


def fault_point(site: str, batch: Optional[int] = None) -> None:
    """A named injection site. No-op unless a configured fault clause matches,
    in which case the clause's exception raises and its firing budget decrements
    — deterministic: same spec + same call sequence = same failure."""
    fire: Optional[FaultSpec] = None
    left = 0
    with _armed_lock:  # budget decrements stay exact across barrier-task threads
        state = _active()
        if state is None:
            return
        _, specs, remaining = state
        for i, spec in enumerate(specs):
            if spec.site != site or remaining[i] <= 0:
                continue
            if spec.batch is not None and batch != spec.batch:
                continue
            remaining[i] -= 1
            fire, left = spec, remaining[i]
            break
    if fire is None:
        return
    profiling.count("reliability.fault")
    profiling.count(f"reliability.fault.{site}")
    from ..observability import event as _obs_event

    if fire.sleep > 0:
        # delay fault: a deterministic straggler, not a failure — the comm
        # plane's skew/straggler detection (docs/design.md §6h) is driven by it
        _obs_event("fault", site=site, batch=batch, sleep_s=fire.sleep)
        _logger.warning(
            "fault injection: sleeping %.3fs at site '%s'%s (%d firings left)",
            fire.sleep, site,
            f" batch {batch}" if batch is not None else "", left,
        )
        import time

        time.sleep(fire.sleep)
        return
    _obs_event("fault", site=site, batch=batch, exc=fire.exc.__name__)
    _logger.warning(
        "fault injection: raising %s at site '%s'%s (%d firings left)",
        fire.exc.__name__, site,
        f" batch {batch}" if batch is not None else "", left,
    )
    raise fire.exc(
        f"injected {fire.exc.__name__} at site '{site}'"
        + (f" batch {batch}" if batch is not None else "")
    )


def is_device_error(e: BaseException) -> bool:
    """Unrecoverable accelerator failure: never retried; the degradation ladder
    routes it into the fallback.enabled CPU path (core/estimator.py). A
    StreamBatchError is classified by the failure it wraps."""
    if isinstance(e, StreamBatchError) and e.__cause__ is not None:
        return is_device_error(e.__cause__)
    if isinstance(e, DeviceError):
        return True
    mod = type(e).__module__ or ""
    return type(e).__name__ == "XlaRuntimeError" or mod.startswith("jaxlib")


def is_transient(e: BaseException) -> bool:
    """Whether a failure is worth a retry/resume: host-side I/O classes
    (preempted host, dropped connection, ingest OOM) are; device errors and
    everything that looks like a programming/param error are not."""
    if isinstance(e, StreamBatchError):
        cause = e.__cause__
        return cause is None or is_transient(cause)
    if is_device_error(e):
        return False
    return isinstance(e, (OSError, TimeoutError, ConnectionError, MemoryError))


def is_stage_retryable(e: BaseException) -> bool:
    """Whether a whole barrier STAGE failure is worth re-running: broader than
    is_transient (a dropped barrier surfaces as RuntimeError-class wreckage from
    deep in the stack), but param/programming errors and device errors still
    propagate — retrying those can only fail identically."""
    if is_device_error(e):
        return False
    if isinstance(
        e, (ValueError, TypeError, NotImplementedError, AssertionError, KeyError, AttributeError)
    ):
        return False
    return isinstance(e, Exception)
