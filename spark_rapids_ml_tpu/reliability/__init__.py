#
# Reliability subsystem: retry/backoff policy, deterministic fault injection,
# and checkpoint-resume for the streamed out-of-core fits — plus the exception
# taxonomy (transient vs stage-retryable vs unrecoverable device error) that
# drives the barrier->collect->CPU degradation ladder in core/estimator.py and
# spark/integration.py.
#
# Observability: every retry/resume/degrade/fault-firing increments a
# profiling counter (profiling.counter_totals()) so the behavior under faults
# is visible, not silent. See docs/design.md "Reliability".
#

from .chaos import (
    ChaosSpec,
    ReplicaKilled,
    chaos_enabled,
    chaos_point,
    parse_chaos_spec,
    reset_chaos,
)
from .checkpoint import copy_carry, resumable_accumulate
from .faults import (
    DeviceError,
    FaultSpec,
    StreamBatchError,
    fault_point,
    is_device_error,
    is_stage_retryable,
    is_transient,
    parse_fault_spec,
    reset_faults,
)
from .policy import RetryPolicy

__all__ = [
    "ChaosSpec",
    "DeviceError",
    "FaultSpec",
    "ReplicaKilled",
    "RetryPolicy",
    "StreamBatchError",
    "chaos_enabled",
    "chaos_point",
    "copy_carry",
    "fault_point",
    "is_device_error",
    "is_stage_retryable",
    "is_transient",
    "parse_chaos_spec",
    "parse_fault_spec",
    "reset_chaos",
    "reset_faults",
    "resumable_accumulate",
]
