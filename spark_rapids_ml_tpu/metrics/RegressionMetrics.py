#
# Mergeable moment statistics for regression metrics — the structural equivalent of
# Spark's SummarizerBuffer merge that the reference re-implements
# (reference python/src/spark_rapids_ml/metrics/RegressionMetrics.py:63-98; executor
# partials at regression.py:149-178). Produces rmse/mse/r2/mae/var with Spark
# RegressionEvaluator semantics.
#

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionMetrics:
    """Holds weighted moments of (residual, label): enough to reconstruct
    rmse/mse/r2/mae/var after any number of merges."""

    def __init__(
        self,
        weight_sum: float = 0.0,
        residual_l1: float = 0.0,
        residual_l2: float = 0.0,
        label_sum: float = 0.0,
        label_sq_sum: float = 0.0,
        pred_sum: float = 0.0,
        pred_sq_sum: float = 0.0,
        pred_label_sum: float = 0.0,
    ) -> None:
        self._w = weight_sum
        self._res_l1 = residual_l1
        self._res_l2 = residual_l2
        self._label_sum = label_sum
        self._label_sq = label_sq_sum
        self._pred_sum = pred_sum
        self._pred_sq = pred_sq_sum
        self._pred_label = pred_label_sum

    @classmethod
    def from_predictions(
        cls,
        labels: np.ndarray,
        predictions: np.ndarray,
        weights: Optional[np.ndarray] = None,
    ) -> "RegressionMetrics":
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        w = (
            np.ones_like(labels)
            if weights is None
            else np.asarray(weights, dtype=np.float64)
        )
        res = labels - predictions
        return cls(
            float(w.sum()),
            float((w * np.abs(res)).sum()),
            float((w * res * res).sum()),
            float((w * labels).sum()),
            float((w * labels * labels).sum()),
            float((w * predictions).sum()),
            float((w * predictions * predictions).sum()),
            float((w * predictions * labels).sum()),
        )

    def merge(self, other: "RegressionMetrics") -> "RegressionMetrics":
        return RegressionMetrics(
            self._w + other._w,
            self._res_l1 + other._res_l1,
            self._res_l2 + other._res_l2,
            self._label_sum + other._label_sum,
            self._label_sq + other._label_sq,
            self._pred_sum + other._pred_sum,
            self._pred_sq + other._pred_sq,
            self._pred_label + other._pred_label,
        )

    @property
    def mean_squared_error(self) -> float:
        return self._res_l2 / self._w

    @property
    def mean_absolute_error(self) -> float:
        return self._res_l1 / self._w

    @property
    def root_mean_squared_error(self) -> float:
        return float(np.sqrt(self.mean_squared_error))

    @property
    def _ss_tot(self) -> float:
        mean = self._label_sum / self._w
        return self._label_sq - self._w * mean * mean

    @property
    def r2(self) -> float:
        return 1.0 - self._res_l2 / self._ss_tot

    @property
    def explained_variance(self) -> float:
        """Spark's "var" metric: SSreg/n = sum_i w_i (yhat_i - ybar)^2 / sum w —
        the mean squared deviation of predictions about the LABEL mean
        (Spark RegressionMetrics.explainedVariance)."""
        ybar = self._label_sum / self._w
        return (
            self._pred_sq - 2.0 * ybar * self._pred_sum + self._w * ybar * ybar
        ) / self._w

    def evaluate(self, metric_name: str) -> float:
        if metric_name == "rmse":
            return self.root_mean_squared_error
        if metric_name == "mse":
            return self.mean_squared_error
        if metric_name == "mae":
            return self.mean_absolute_error
        if metric_name == "r2":
            return self.r2
        if metric_name == "var":
            return self.explained_variance
        raise ValueError(f"Unsupported metric name: {metric_name}")
