#
# Metric utilities (structural equivalent of reference
# python/src/spark_rapids_ml/metrics/utils.py:14-78): the FULL logistic-regression
# objective — log-loss plus the elastic-net penalty with Spark's standardization
# convention — as an in-package utility usable by tests, examples, and users
# validating convergence parity.
#

from __future__ import annotations

from typing import Any

import numpy as np


def logistic_regression_objective(
    dataset: Any,
    lr_model: Any,
) -> float:
    """Full objective of a fitted logistic-regression model on `dataset`:

        log_loss + regParam * (0.5*(1-elasticNetParam)*||coef_s||2^2
                               + elasticNetParam*|coef_s|_1)

    where log_loss = (1/sum w) * sum_i -w_i*log(prob(y_i)) and coef_s are the
    coefficients in the standardized space when standardization=True (the penalty is
    applied to sigma-scaled coefficients, matching Spark — the reference multiplies
    by the feature stds the same way, metrics/utils.py:56-70).

    `dataset` is anything the model can transform (pandas/numpy/Spark); the label
    column follows the model's labelCol."""
    from ..core.dataset import _is_spark_df, extract_feature_data

    if _is_spark_df(dataset):
        dataset = dataset.toPandas()

    input_col, input_cols = lr_model._get_input_columns()
    label_col = lr_model.getOrDefault("labelCol")
    fd = extract_feature_data(
        dataset,
        input_col=input_col,
        input_cols=input_cols,
        label_col=label_col,
        float32=False,
    )
    from ..core.dataset import densify

    X = np.asarray(densify(fd.features, float32=False), dtype=np.float64)
    y = np.asarray(fd.label, dtype=np.int64)
    n = X.shape[0]

    outputs = lr_model._transform_arrays(X.astype(np.float32))
    prob = np.asarray(outputs[lr_model.getOrDefault("probabilityCol")], np.float64)
    eps = 1e-15
    p_true = np.clip(prob[np.arange(n), y], eps, 1.0)
    log_loss = float(np.mean(-np.log(p_true)))

    coef = np.asarray(
        lr_model.coefficientMatrix
        if getattr(lr_model, "_is_multinomial_layout", False)
        else lr_model.coefficients,
        dtype=np.float64,
    )
    if lr_model.getOrDefault("standardization"):
        std = X.std(axis=0, ddof=1)
        coef = coef * std

    reg = float(lr_model.getOrDefault("regParam"))
    l1r = float(lr_model.getOrDefault("elasticNetParam"))
    penalty = reg * (
        0.5 * (1.0 - l1r) * float(np.sum(coef**2)) + l1r * float(np.sum(np.abs(coef)))
    )
    return log_loss + penalty


def binary_classification_sweep(score, y, w=None):
    """Score-sorted cumulative (tps, fps) staircase for ROC/PR curves, with tied
    scores GROUPED into single sweep points (Spark BinaryClassificationMetrics /
    sklearn semantics — without grouping, AUC on tied scores depends on input row
    order). Returns (tps, fps) arrays with a leading 0 point."""
    import numpy as np

    score = np.asarray(score, np.float64)
    y = np.asarray(y, np.float64)
    w = np.ones_like(y) if w is None else np.asarray(w, np.float64)
    order = np.argsort(-score, kind="stable")
    s, y, w = score[order], y[order], w[order]
    tps = np.cumsum(w * y)
    fps = np.cumsum(w * (1.0 - y))
    # keep only the LAST index of each tied-score run (the threshold boundary)
    keep = np.nonzero(np.diff(s))[0]
    keep = np.concatenate([keep, [len(s) - 1]]) if len(s) else np.array([], int)
    tps, fps = tps[keep], fps[keep]
    return np.concatenate([[0.0], tps]), np.concatenate([[0.0], fps])


def area_under_roc(tps, fps) -> float:
    import numpy as np

    P, N = tps[-1], fps[-1]
    return float(np.trapezoid(tps / P, fps / N))


def area_under_pr(tps, fps) -> float:
    import numpy as np

    P = tps[-1]
    recall = tps / P
    precision = np.where(
        tps + fps > 0, tps / np.maximum(tps + fps, 1e-300), 1.0
    )
    return float(np.trapezoid(precision, recall))
