#
# Driver-side reduction of per-partition (label, prediction) confusion counts and
# log-loss sums into Spark-compatible multiclass metrics
# (reference python/src/spark_rapids_ml/metrics/MulticlassMetrics.py: the executor
# side counts per partition at classification.py:117-159; the merge happens on the
# driver). The metric formulas follow Spark MLlib's MulticlassMetrics semantics.
#

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

SUPPORTED_MULTI_CLASS_METRIC_NAMES = [
    "f1",
    "accuracy",
    "weightedPrecision",
    "weightedRecall",
    "weightedTruePositiveRate",
    "weightedFalsePositiveRate",
    "weightedFMeasure",
    "truePositiveRateByLabel",
    "falsePositiveRateByLabel",
    "precisionByLabel",
    "recallByLabel",
    "fMeasureByLabel",
    "logLoss",
    "hammingLoss",
]


class MulticlassMetrics:
    """Accumulates weighted confusion counts; `merge` combines partition partials."""

    def __init__(
        self,
        tp_by_class: Optional[Dict[float, float]] = None,
        fp_by_class: Optional[Dict[float, float]] = None,
        label_count_by_class: Optional[Dict[float, float]] = None,
        label_count: float = 0.0,
        log_loss: float = 0.0,
    ) -> None:
        self._tp = dict(tp_by_class or {})
        self._fp = dict(fp_by_class or {})
        self._label_count_by_class = dict(label_count_by_class or {})
        self._label_count = label_count
        self._log_loss = log_loss

    # ---- partial computation (executor side in the reference) ----

    @classmethod
    def from_predictions(
        cls,
        labels: np.ndarray,
        predictions: np.ndarray,
        weights: Optional[np.ndarray] = None,
        probabilities: Optional[np.ndarray] = None,
        eps: float = 1e-15,
    ) -> "MulticlassMetrics":
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        w = (
            np.ones_like(labels)
            if weights is None
            else np.asarray(weights, dtype=np.float64)
        )
        tp: Dict[float, float] = {}
        fp: Dict[float, float] = {}
        lc: Dict[float, float] = {}
        for cls_val in np.unique(np.concatenate([labels, predictions])):
            sel_l = labels == cls_val
            sel_p = predictions == cls_val
            lc[float(cls_val)] = float(w[sel_l].sum())
            tp[float(cls_val)] = float(w[sel_l & sel_p].sum())
            fp[float(cls_val)] = float(w[~sel_l & sel_p].sum())
        log_loss = 0.0
        if probabilities is not None:
            p = np.clip(
                probabilities[np.arange(len(labels)), labels.astype(int)], eps, 1 - eps
            )
            log_loss = float(-(w * np.log(p)).sum())
        return cls(tp, fp, lc, float(w.sum()), log_loss)

    def merge(self, other: "MulticlassMetrics") -> "MulticlassMetrics":
        def _madd(a: Dict[float, float], b: Dict[float, float]) -> Dict[float, float]:
            out = dict(a)
            for k, v in b.items():
                out[k] = out.get(k, 0.0) + v
            return out

        return MulticlassMetrics(
            _madd(self._tp, other._tp),
            _madd(self._fp, other._fp),
            _madd(self._label_count_by_class, other._label_count_by_class),
            self._label_count + other._label_count,
            self._log_loss + other._log_loss,
        )

    # ---- Spark MulticlassMetrics formulas ----

    def _precision(self, label: float) -> float:
        tp = self._tp.get(label, 0.0)
        fp = self._fp.get(label, 0.0)
        return 0.0 if (tp + fp) == 0 else tp / (tp + fp)

    def _recall(self, label: float) -> float:
        tp = self._tp.get(label, 0.0)
        n = self._label_count_by_class.get(label, 0.0)
        return 0.0 if n == 0 else tp / n

    def _f_measure(self, label: float, beta: float = 1.0) -> float:
        p, r = self._precision(label), self._recall(label)
        b2 = beta * beta
        return 0.0 if (p + r) == 0 else (1 + b2) * p * r / (b2 * p + r)

    def _false_positive_rate(self, label: float) -> float:
        fp = self._fp.get(label, 0.0)
        neg = self._label_count - self._label_count_by_class.get(label, 0.0)
        return 0.0 if neg == 0 else fp / neg

    def weighted_precision(self) -> float:
        return sum(
            self._precision(c) * n / self._label_count
            for c, n in self._label_count_by_class.items()
        )

    def weighted_recall(self) -> float:
        return sum(
            self._recall(c) * n / self._label_count
            for c, n in self._label_count_by_class.items()
        )

    def weighted_f_measure(self, beta: float = 1.0) -> float:
        return sum(
            self._f_measure(c, beta) * n / self._label_count
            for c, n in self._label_count_by_class.items()
        )

    def weighted_false_positive_rate(self) -> float:
        return sum(
            self._false_positive_rate(c) * n / self._label_count
            for c, n in self._label_count_by_class.items()
        )

    def accuracy(self) -> float:
        return sum(self._tp.values()) / self._label_count

    def log_loss(self) -> float:
        return self._log_loss / self._label_count

    def hamming_loss(self) -> float:
        return 1.0 - self.accuracy()

    def evaluate(self, metric_name: str, metric_label: float = 0.0, beta: float = 1.0) -> float:
        """Dispatch by Spark metric name (reference MulticlassMetrics.py:149-180)."""
        if metric_name == "f1":
            return self.weighted_f_measure()
        if metric_name == "accuracy":
            return self.accuracy()
        if metric_name == "weightedPrecision":
            return self.weighted_precision()
        if metric_name in ("weightedRecall", "weightedTruePositiveRate"):
            return self.weighted_recall()
        if metric_name == "weightedFalsePositiveRate":
            return self.weighted_false_positive_rate()
        if metric_name == "weightedFMeasure":
            return self.weighted_f_measure(beta)
        if metric_name == "truePositiveRateByLabel":
            return self._recall(metric_label)
        if metric_name == "falsePositiveRateByLabel":
            return self._false_positive_rate(metric_label)
        if metric_name == "precisionByLabel":
            return self._precision(metric_label)
        if metric_name == "recallByLabel":
            return self._recall(metric_label)
        if metric_name == "fMeasureByLabel":
            return self._f_measure(metric_label, beta)
        if metric_name == "logLoss":
            return self.log_loss()
        if metric_name == "hammingLoss":
            return self.hamming_loss()
        raise ValueError(f"Unsupported metric name: {metric_name}")
