#
# Evaluation-metric subsystem (reference python/src/spark_rapids_ml/metrics/):
# distributed partial aggregation of confusion counts / moment statistics merged on the
# driver (reference classification.py:117-159, regression.py:149-178, metrics/*).
#
# On TPU the partials are computed as sharded jnp reductions (psum implicit) or plain
# numpy for host-resident outputs; the merge algebra is identical.
#

from dataclasses import dataclass
from typing import Optional

from .MulticlassMetrics import MulticlassMetrics
from .RegressionMetrics import RegressionMetrics


@dataclass
class EvalMetricInfo:
    """Tags a transform-with-evaluation pass with what the evaluator needs
    (reference metrics/__init__.py:22-41)."""

    eval_metric: str = ""  # "accuracy_like" | "log_loss" | "regression"
    eval_metric_name: Optional[str] = None


__all__ = ["EvalMetricInfo", "MulticlassMetrics", "RegressionMetrics"]
