#
# Offline autotune CLI (docs/design.md §6i):
#
#   python -m spark_rapids_ml_tpu.autotune \
#       --knobs selection.tile,selection.strategy --shape 65536,64,16
#
# Searches the requested knobs over the requested shape buckets on the
# CURRENT backend and persists the winners into the per-platform tuning
# table under --dir / SRML_TPU_TUNE_DIR / autotune.dir. `--list` prints the
# knob registry. Runs inside a FitRun so, with SRML_TPU_METRICS_DIR set, the
# sweep exports a full structured run report (trial spans with measured
# mfu/roofline verdicts) like every other unit of work in this library.
#

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Tuple


def _parse_shape(raw: str) -> Tuple[int, int, int]:
    parts = [int(p) for p in raw.replace("x", ",").split(",") if p.strip()]
    if len(parts) != 3:
        raise argparse.ArgumentTypeError(
            f"--shape wants N,D,K (got '{raw}')"
        )
    return parts[0], parts[1], parts[2]


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_ml_tpu.autotune",
        description="Search tuning-table entries for the current platform.",
    )
    ap.add_argument(
        "--knobs",
        help="comma-separated knob names (default: every searchable knob)",
    )
    ap.add_argument(
        "--shape", action="append", type=_parse_shape, metavar="N,D,K",
        help="shape bucket(s) to search (repeatable; default 65536,64,16)",
    )
    ap.add_argument("--dir", help="tuning-table directory (over config/env)")
    ap.add_argument("--replicates", type=int, help="timed reps per candidate")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--list", action="store_true",
                    help="print the knob registry and exit")
    ap.add_argument("--json", action="store_true",
                    help="print the full summary as JSON")
    args = ap.parse_args(argv)

    from . import knobs as _knobs

    if args.list:
        for name in sorted(_knobs.KNOBS):
            kb = _knobs.KNOBS[name]
            flags = []
            if kb.searchable:
                flags.append("searchable")
            if kb.exactness != "bit":
                flags.append(f"exactness={kb.exactness}")
            if kb.config_key:
                flags.append(f"pinned-by={kb.config_key}")
            print(f"{name:<24} [{kb.kind}] {' '.join(flags)}")
            print(f"{'':<24} {kb.description}")
        return 0

    from .. import config as _config

    if args.dir:
        _config.set("autotune.dir", args.dir)
    knob_names = (
        [k.strip() for k in args.knobs.split(",") if k.strip()]
        if args.knobs
        else None
    )

    from ..observability import fit_run

    from .search import run_search

    with fit_run(algo="autotune_search", site="autotune"):
        summary = run_search(
            knob_names, shapes=args.shape, dtype=args.dtype,
            replicates=args.replicates,
        )
    if args.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
        return 0
    print(
        f"autotune: platform={summary['platform']} "
        f"device_kind={summary['device_kind']} "
        f"table={summary['table_path'] or '(in-memory only)'} "
        f"entries={summary['table_entries']} "
        f"search_s={summary['search_s']}"
    )
    for e in summary["results"]:
        print(
            f"  {e['knob']:<24} {e['bucket']:<20} -> {e['value']!r:<14} "
            f"speedup={e['speedup']:.3f} "
            f"(median {e['median_s'] * 1e3:.2f} ms vs default "
            f"{e['baseline_s'] * 1e3:.2f} ms, {e['trials']} trials)"
        )
    for s in summary["skipped"]:
        print(f"  {s['knob']:<24} skipped: {s['reason']}")
    if summary["table_path"] is None:
        print(
            "autotune: WARNING no table directory configured "
            "(--dir / SRML_TPU_TUNE_DIR); results were not persisted"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
