#
# Knob-registry defaults — THE home for the numeric tile/block/threshold
# defaults the closed-loop autotuner (docs/design.md §6i) overrides with
# measured per-platform tuning-table entries.
#
# These used to live as magic constants scattered through the ops/ host
# wrappers, each justified by a one-off measurement baked into a comment.
# Now: the DEFAULT lives here (one module, import-light, no jax), the
# MEASURED choice lives in a tuning table entry whose `provenance` field
# records the search that produced it (platform, device_kind, shape bucket,
# trial stats), and the analyzer's fence/hardcoded-tunable rule bans new
# literals in ops/ so the split cannot silently regress.
#
# Nothing here reads config or the tables — that is knobs.lookup()'s job.
# Callers fall through to these values when autotune is off, the table has
# no entry for the bucket, or the table failed to load (corrupt/stale).
#

from __future__ import annotations

# --------------------------------------------------------- selection plane
# exact_tiled tile width (ops/selection.py::_auto_tile): on TPU small fixed
# tiles vectorize the per-tile select on the VPU; on CPU each XLA TopK custom
# call pays per-call overhead, so few large tiles win (see the tuning table
# for any measured per-bucket override of this folklore).
TPU_SELECT_TILE = 2048
CPU_SELECT_TILE_FLOOR = 8192
CPU_SELECT_TILE_DENOM = 4  # CPU tile = max(floor, ceil(n / denom))


def default_select_tile(n: int, backend: str) -> int:
    """The pre-autotuner platform tile heuristic, verbatim."""
    if backend == "tpu":
        return TPU_SELECT_TILE
    return max(CPU_SELECT_TILE_FLOOR, -(-int(n) // CPU_SELECT_TILE_DENOM))


# ---------------------------------------------- fused pallas scan geometry
# (ops/pallas_select.py) — the query block bounds the (block, tile) distance
# tile in VMEM (256*1024*4 = 1 MiB) next to one double-buffered X tile; the
# assignment form streams ROWS against resident centers. Floors are what the
# VMEM-budget shrink loops halve toward; a floor-sized scan always fits.
DEFAULT_QUERY_BLOCK = 256
DEFAULT_ITEM_TILE = 1024
DEFAULT_ASSIGN_BLOCK = 2048
MIN_ASSIGN_BLOCK = 256
MIN_QUERY_BLOCK = 8
MIN_ITEM_TILE = 128

# k >= this engages the fused assignment/Lloyd paths under `auto` on TPU:
# below it the (B, k) tiles pad k to the 128-lane MXU width and the XLA
# path's two-read formulation is already at its HBM roofline (the measured
# small-k loss region of ops/pallas_kmeans.py).
FUSED_ASSIGN_MIN_K = 128
LLOYD_FUSED_MIN_K = 128

# ----------------------------------------------------- other pallas kernels
# segment-reduce histogram (ops/pallas_histogram.py)
PALLAS_HISTOGRAM_BLOCK_ROWS = 512
PALLAS_HISTOGRAM_MAX_SEG_TILE = 2048

# ------------------------------------------------------------ ANN lifecycle
# (ops/ann_streaming.py + ops/ann_lifecycle.py, docs/design.md §7b)
#
# ANN_BUILD_BATCH_ROWS: the pipelined build's row-batch geometry when neither
# config (`ann.build_batch_rows`) nor a tuning-table entry decides. Provenance:
# 64k f32 rows at the BASELINE 256-col shape is a 64 MiB staging buffer — two
# in flight (prefetch depth 1) stay far under the 2 GiB default cache budget
# while each batch still amortizes dispatch overhead; the streamed-fit default
# (`stream_batch_rows`, 1M rows) remains the fallback when the caller already
# sized batches for a whole fit.
ANN_BUILD_BATCH_ROWS = 1 << 16
# --------------------------------------------------------- ingest / fusion
# (ops/ingest.py + pipeline.py, docs/design.md §6k)
#
# INGEST_STAGING_POOL_ROWS: rows per pooled staging buffer backing the counted
# copy fallback of the zero-copy ingest plane. Provenance: matches
# ANN_BUILD_BATCH_ROWS' rationale — 64k f32 rows at the BASELINE 256-col shape
# is a 64 MiB buffer; one per (dtype, width) key covers the double-buffered
# prefetch without the pool itself rivaling the HBM cache budget.
INGEST_STAGING_POOL_ROWS = 1 << 16
# PIPELINE_FUSE_MIN_ROWS: rows below which the pipeline fuser leaves a
# featurize->fit chain staged. Provenance: under ~4k rows a staged chain's
# extra host round-trip is < 1 ms on every measured platform — less than the
# fused chain's extra accumulator compile — and the staged trace is the one
# worth reading when debugging toy inputs.
PIPELINE_FUSE_MIN_ROWS = 4096

# ANN_LIST_BUCKET_MIN_ROWS: smallest bucketed IVF list capacity. Provenance:
# mirrors `serving.bucket_min_rows`'s floor rationale — below 8 slots the
# pow-2 ladder would re-layout on nearly every add; at 8 the padded-slot waste
# is bounded by one sub-KiB row block per list at d=16.
ANN_LIST_BUCKET_MIN_ROWS = 8
# ANN_COMPACT_TOMBSTONE_PCT: tombstoned slots as a percentage of occupied
# slots that triggers list compaction. Provenance: at 30% the probe scan's
# wasted candidate width stays under ~1.4x live width (the select is
# width-bound, not item-bound), while compaction — a full re-layout — stays
# rare under churny delete/add traffic.
ANN_COMPACT_TOMBSTONE_PCT = 30

# ------------------------------------------------- continuous-learning plane
# (spark_rapids_ml_tpu/continual/, docs/design.md §7d)
#
# CONTINUAL_DECAY: per-update discount applied to the persistent sufficient-
# statistics carry before each partial_fit fold. Provenance: 1.0 is the
# streaming-kmeans paper's a=1 "infinite memory" setting (arxiv 1505.06807)
# — forgetting is an opt-in policy decision, so the default never silently
# down-weights history; half-life h maps to decay = 0.5 ** (1 / h) updates.
CONTINUAL_DECAY = 1.0
# CONTINUAL_UPDATE_BATCH_ROWS: fixed block geometry partial_fit re-blocks
# every update batch to (zero-weight padding on the ragged tail). Provenance:
# 16k f32 rows at the BASELINE 256-col shape is a 16 MiB block — small enough
# that an update cycle stays sub-second (continual updates are latency-bound,
# unlike the 64k-row throughput-bound ANN builds), and a single power-of-two
# geometry keeps the whole update stream inside ONE compiled executable per
# accumulator kernel.
CONTINUAL_UPDATE_BATCH_ROWS = 1 << 14
# CONTINUAL_DRIFT_MADS: MADs of separation above the baseline median a fresh
# per-row signal needs to fire drift. Provenance: mirrors the measurement
# discipline everywhere else in the tree — `autotune.noise_mads` and
# ci/bench_check.py both demand 3 MADs before calling two samples different,
# and drift is the same judgment (is this batch's loss a new distribution or
# the old one's noise?).
CONTINUAL_DRIFT_MADS = 3.0

# ------------------------------------------------------------- trace plane
# (spark_rapids_ml_tpu/observability/tracing.py, docs/design.md §6l)
#
# TRACING_SAMPLE_RATE: fraction of UNFLAGGED request traces the tail sampler
# keeps (error/hedged/failed-over/expired/shed and the rolling-slowest
# tracing.slow_frac are always kept regardless). Provenance: 1.0 — the ring
# is already bounded (tracing.ring_traces docs) and a finished trace document
# costs ~1-2 KiB to assemble, so at bench-measured request rates keeping
# everything sits inside the <2% tracing_overhead budget the CI gate
# enforces; the 0.05/0.25 grid points exist for high-QPS deployments where
# the tuning table can dial retention down once the bench shows the document
# build on the scatter path matters.
TRACING_SAMPLE_RATE = 1.0
