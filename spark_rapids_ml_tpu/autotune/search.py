#
# Measurement loop — the search half of the closed-loop autotuner
# (docs/design.md §6i).
#
# Candidates are timed through the EXISTING observability machinery, not a
# parallel harness: every trial kernel is a `compiled_kernel` (the §6f AOT
# cache), so the warmup pass compiles exactly once per candidate signature
# and the timed reps run cached executables; each timed rep runs inside an
# `autotune.trial` span, so the device plane attributes analyzed flops/bytes
# and closes the span with measured mfu / roofline_bound / comm_frac — every
# table entry carries the roofline story of its winner, not just wall time.
#
# Noise handling mirrors ci/bench_check.py's MAD logic: reps are taken
# round-robin across candidates (a monotone warming trend cannot flatter
# late candidates), each candidate keeps its median + median-absolute-
# deviation, and a challenger only displaces the default when its median win
# clears `autotune.noise_mads` MADs of the noisier of the two — otherwise
# the DEFAULT is persisted (speedup 1.0), so `load` mode never re-searches
# a bucket the loop already judged inconclusive.
#

from __future__ import annotations

import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import defaults as _defaults
from . import knobs as _knobs
from . import table as _table

# trial operands are capped so an online search triggered by a huge live
# shape stays bounded (the entry still keys on the REAL bucket; the win on
# the capped width is the same per-tile story)
_MAX_TRIAL_N = 1 << 20
_MAX_TRIAL_D = 512
_MAX_TRIAL_K = 1024
_TRIAL_QUERIES = 64

# tile-first: the strategy search times exact_tiled at the freshly tuned
# tile, so a combined run must resolve the tile before judging the strategy
SEARCH_ORDER = (
    "selection.tile",
    "selection.strategy",
    "pallas.topk_geometry",
    "pallas.assign_block",
)


def _backend() -> str:
    from ..ops.selection import _backend as b

    return b()


def _sync(out: Any) -> None:
    """Force completion by pulling values to host (the bench.py lesson:
    block_until_ready can acknowledge dispatch early under remote tunnels)."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(out):
        np.asarray(leaf)


def _seed_for(key: str) -> int:
    return zlib.crc32(key.encode("utf-8")) & 0x7FFFFFFF


# ------------------------------------------------------------ trial kernels


def _select_trial_kernel():
    """The d2-level selection trial, AOT-cached per (strategy, tile, k)
    signature like every library kernel (defined lazily so importing the
    autotune package never imports jax)."""
    global _SELECT_TRIAL
    if _SELECT_TRIAL is None:
        from ..observability.device import compiled_kernel

        @compiled_kernel(
            "autotune.select_trial", static_argnames=("k", "strategy", "tile")
        )
        def _run(d2, k: int, strategy: str, tile: int):
            from ..ops.selection import select_topk

            return select_topk(d2, k, strategy=strategy, tile=tile)

        _SELECT_TRIAL = _run
    return _SELECT_TRIAL


_SELECT_TRIAL = None


# -------------------------------------------------------------- measurement


def _measure_candidates(
    cands: Dict[str, Callable[[], Any]],
    replicates: int,
    knob: str,
) -> Dict[str, Dict[str, Any]]:
    """Round-robin timed reps per candidate; per-candidate median/MAD plus
    the span-attributed device verdicts of the timed reps."""
    import numpy as np

    from ..observability import runs as _runs

    for fn in cands.values():  # warmup: AOT compile, untimed
        _sync(fn())
    times: Dict[str, List[float]] = {label: [] for label in cands}
    devices: Dict[str, List[Dict[str, Any]]] = {label: [] for label in cands}
    for rep in range(max(int(replicates), 1)):
        for label, fn in cands.items():
            with _runs.span(
                "autotune.trial",
                {"knob": knob, "candidate": label, "rep": rep},
            ):
                node = _runs._span_stack()[-1]
                t0 = time.perf_counter()
                _sync(fn())
                times[label].append(time.perf_counter() - t0)
            dev = node.attrs.get("device")
            if isinstance(dev, dict):
                devices[label].append(dev)
    stats: Dict[str, Dict[str, Any]] = {}
    for label, ts in times.items():
        arr = np.asarray(ts, dtype=np.float64)
        med = float(np.median(arr))
        st: Dict[str, Any] = {
            "median_s": med,
            "mad_s": float(np.median(np.abs(arr - med))),
            "trials": len(ts),
        }
        devs = devices[label]
        mfus = [d["mfu"] for d in devs if d.get("mfu") is not None]
        if mfus:
            st["mfu"] = float(np.median(np.asarray(mfus)))
        bounds = [d.get("roofline_bound") for d in devs if d.get("roofline_bound")]
        if bounds:
            st["roofline_bound"] = max(set(bounds), key=bounds.count)
        fracs = [d["comm_frac"] for d in devs if d.get("comm_frac") is not None]
        if fracs:
            st["comm_frac"] = float(np.median(np.asarray(fracs)))
        stats[label] = st
    return stats


def _choose(stats: Dict[str, Dict[str, Any]], default_label: str,
            noise_mads: float) -> Tuple[str, float]:
    """(winner label, speedup vs default). A challenger needs its median win
    to clear `noise_mads` MADs of the noisier arm; otherwise the default
    stands and the persisted speedup is exactly 1.0."""
    best = min(stats, key=lambda lb: stats[lb]["median_s"])
    dflt = stats[default_label]
    if best != default_label:
        gap = dflt["median_s"] - stats[best]["median_s"]
        noise = noise_mads * max(stats[best]["mad_s"], dflt["mad_s"])
        if gap <= noise:
            best = default_label
    return best, dflt["median_s"] / max(stats[best]["median_s"], 1e-12)


def _entry(knob: str, bucket: str, dtype: str, value: Any, winner: str,
           speedup: float, stats: Dict[str, Dict[str, Any]],
           default_label: str, trial_shape: Dict[str, int]) -> Dict[str, Any]:
    platform, kind = _table.platform_key()
    st = stats[winner]
    return {
        "knob": knob,
        "bucket": bucket,
        "dtype": dtype,
        "value": value,
        "platform": platform,
        "device_kind": kind,
        "median_s": round(st["median_s"], 6),
        "mad_s": round(st["mad_s"], 6),
        "baseline_s": round(stats[default_label]["median_s"], 6),
        "baseline_mad_s": round(stats[default_label]["mad_s"], 6),
        "speedup": round(speedup, 4),
        "trials": st["trials"],
        **{f: st[f] for f in ("mfu", "roofline_bound", "comm_frac") if f in st},
        "candidates": {
            lb: round(s["median_s"], 6) for lb, s in sorted(stats.items())
        },
        "trial_shape": trial_shape,
        "searched_ts": round(time.time(), 3),
        "provenance": (
            "spark_rapids_ml_tpu.autotune search "
            f"(table v{_table.TABLE_VERSION}); defaults in "
            "spark_rapids_ml_tpu/autotune/defaults.py; docs/design.md §6i"
        ),
    }


# ---------------------------------------------------------------- searchers


def _trial_dims(n: Optional[int], d: Optional[int], k: Optional[int]
                ) -> Tuple[int, int, int]:
    """Trial operand sizes: the REAL requested dims, capped. The entry still
    keys on the pow2 bucket, but candidates must be judged at the triggering
    workload's true width — a tile that wins at the padded bucket width can
    lose at the real one (ragged last-tile padding), and persisting that
    winner would slow the very workload that asked for the search."""
    n_t = min(int(n) if n else 1 << 16, _MAX_TRIAL_N)
    d_t = min(int(d) if d else 64, _MAX_TRIAL_D)
    k_t = min(int(k) if k else 16, _MAX_TRIAL_K)
    return max(n_t, 8), max(d_t, 2), max(k_t, 1)


def _search_selection_tile(n, d, k, dtype, replicates, noise_mads):
    import jax.numpy as jnp
    import numpy as np

    n_t, _, k_t = _trial_dims(n, d, k)
    if n_t <= 4 * k_t:
        return None  # resolve() degrades this bucket to exact_full anyway
    rng = np.random.default_rng(_seed_for(f"selection.tile|{n_t}|{k_t}"))
    d2 = jnp.asarray(
        (rng.normal(size=(_TRIAL_QUERIES, n_t)) ** 2).astype(np.float32)
    )
    backend = _backend()
    default_tile = _defaults.default_select_tile(n_t, backend)
    grid = set(_knobs.KNOBS["selection.tile"].grid)
    grid.update((n_t // 8, n_t // 4, n_t // 2, default_tile))
    # candidate bound mirrors resolve(): any tile < n is legal (resolve's
    # 4k degradation is on n, not the tile); sub-k tiles make degenerate
    # per-tile pools, so floor at k
    cands_vals = sorted(t for t in grid if k_t < t < n_t)[:10]
    if not cands_vals:
        return None
    run = _select_trial_kernel()
    cands: Dict[str, Callable[[], Any]] = {
        str(t): (lambda t=t: run(d2, k_t, "exact_tiled", t))
        for t in cands_vals
    }
    if default_tile in cands_vals:
        default_label = str(default_tile)
    else:
        # default_tile >= n_t: the platform default degrades to exact_full
        # at this bucket (resolve's n <= tile rule) — measure the full-width
        # arm AS the baseline so speedup compares against real default
        # behavior, and a "full" win persists the default tile (which keeps
        # degrading to exact_full: a true behavioral no-op entry)
        cands["full"] = lambda: run(d2, k_t, "exact_full", 0)
        default_label = "full"
    if len(cands) < 2:
        return None
    stats = _measure_candidates(cands, replicates, "selection.tile")
    winner, speedup = _choose(stats, default_label, noise_mads)
    # a "full" winner means no tile beats the default path: persist the
    # default tile (a behavioral no-op entry) so load mode never re-searches
    value = default_tile if winner == "full" else int(winner)
    bucket = _knobs.bucket_for(_knobs.KNOBS["selection.tile"], n, None, k)
    return _entry(
        "selection.tile", bucket, dtype, value, winner, speedup, stats,
        default_label, {"n": n_t, "k": k_t, "nq": _TRIAL_QUERIES},
    )


def _search_selection_strategy(n, d, k, dtype, replicates, noise_mads):
    import jax.numpy as jnp
    import numpy as np

    n_t, _, k_t = _trial_dims(n, d, k)
    if n_t <= 4 * k_t:
        return None
    rng = np.random.default_rng(_seed_for(f"selection.strategy|{n_t}|{k_t}"))
    d2 = jnp.asarray(
        (rng.normal(size=(_TRIAL_QUERIES, n_t)) ** 2).astype(np.float32)
    )
    backend = _backend()
    # tile for the exact_tiled arm: the freshly searched table entry when one
    # exists (SEARCH_ORDER runs the tile first), else the platform default
    tbl = _table.load_table()
    tile_entry = tbl.get(_table.entry_key(
        "selection.tile",
        _knobs.bucket_for(_knobs.KNOBS["selection.tile"], n, None, k), dtype,
    ))
    tile = None
    if tile_entry is not None:
        tile = _knobs._coerce_value(
            _knobs.KNOBS["selection.tile"], tile_entry.get("value")
        )
    if tile is None:
        tile = _defaults.default_select_tile(n_t, backend)
    tile = min(int(tile), max(n_t - 1, 1))
    # exactness="bit": the search may only choose among strategies whose
    # outputs are bit-identical to each other AND to the default path. Where
    # the platform default is `approx` (TPU auto), ANY exact winner would
    # return a different id set than a table-less run — faster and more
    # accurate, but not reproducible across table-present/absent
    # environments — so the knob is simply not searched there: the
    # approx-vs-exact tradeoff belongs to the user (knn.recall_target), not
    # to a wall-time search.
    default_strategy = "approx" if backend == "tpu" else "exact_tiled"
    if default_strategy not in ("exact_full", "exact_tiled"):
        return None
    cand_strategies = ["exact_full", "exact_tiled"]
    run = _select_trial_kernel()
    cands = {
        s: (lambda s=s: run(d2, k_t, s, tile if s == "exact_tiled" else 0))
        for s in cand_strategies
    }
    stats = _measure_candidates(cands, replicates, "selection.strategy")
    winner, speedup = _choose(stats, default_strategy, noise_mads)
    bucket = _knobs.bucket_for(_knobs.KNOBS["selection.strategy"], n, None, k)
    return _entry(
        "selection.strategy", bucket, dtype, winner, winner, speedup, stats,
        default_strategy, {"n": n_t, "k": k_t, "nq": _TRIAL_QUERIES, "tile": tile},
    )


def _search_topk_geometry(n, d, k, dtype, replicates, noise_mads):
    if _backend() != "tpu":
        return None  # off-TPU the fused scan runs the interpreter: no signal
    import jax.numpy as jnp
    import numpy as np

    from ..ops.pallas_select import _topk_geometry, fused_topk, topk_fits_vmem

    n_t, d_t, k_t = _trial_dims(n, d, k)
    rng = np.random.default_rng(_seed_for(f"pallas.topk_geometry|{n_t}|{d_t}"))
    X = jnp.asarray(rng.normal(size=(n_t, d_t)).astype(np.float32))
    Q = X[:_TRIAL_QUERIES]
    ones = jnp.ones((n_t,), bool)
    dq, dt = _topk_geometry(_TRIAL_QUERIES, n_t, d_t, k_t, None, None)
    geoms = {(dq, dt)}
    for qb in (dq // 2, dq, dq * 2):
        for t in (dt // 2, dt, dt * 2):
            # candidates run as PINNED values (pins bypass the shrink
            # loop), so each must pass the kernel's own fit predicate
            if (
                _defaults.MIN_QUERY_BLOCK <= qb
                and _defaults.MIN_ITEM_TILE <= t <= n_t
                and topk_fits_vmem(qb, t, d_t, k_t)
            ):
                geoms.add((qb, t))
    cands = {
        f"{qb}x{t}": (lambda qb=qb, t=t: fused_topk(
            Q, X, ones, k_t, q_block=qb, item_tile=t
        ))
        for qb, t in sorted(geoms)
    }
    default_label = f"{dq}x{dt}"
    stats = _measure_candidates(cands, replicates, "pallas.topk_geometry")
    winner, speedup = _choose(stats, default_label, noise_mads)
    wq, wt = (int(x) for x in winner.split("x"))
    bucket = _knobs.bucket_for(_knobs.KNOBS["pallas.topk_geometry"], n, d, k)
    return _entry(
        "pallas.topk_geometry", bucket, dtype, [wq, wt], winner, speedup,
        stats, default_label, {"n": n_t, "d": d_t, "k": k_t},
    )


def _search_assign_block(n, d, k, dtype, replicates, noise_mads):
    if _backend() != "tpu":
        return None
    import jax.numpy as jnp
    import numpy as np

    from ..ops.pallas_select import (
        _assign_geometry,
        _assign_n_split,
        assign_block_fits_vmem,
        fused_assign,
    )

    n_t, d_t, k_t = _trial_dims(n, d, k)
    n_split = _assign_n_split()
    rng = np.random.default_rng(_seed_for(f"pallas.assign_block|{d_t}|{k_t}"))
    X = jnp.asarray(rng.normal(size=(n_t, d_t)).astype(np.float32))
    centers = X[:k_t]
    default_blk = _assign_geometry(d_t, k_t, n_split, n_t)
    if default_blk is None:
        return None  # nothing placeable: the XLA path owns this bucket
    grid = {
        b for b in _knobs.KNOBS["pallas.assign_block"].grid
        if _defaults.MIN_ASSIGN_BLOCK <= b <= n_t
        # candidates run as PINNED blocks, so each must pass the kernel's
        # own fit predicate — including blocks ABOVE the default start,
        # which _assign_geometry itself would never propose
        and assign_block_fits_vmem(b, d_t, k_t, n_split)
    }
    grid.add(default_blk)
    if len(grid) < 2:
        return None
    cands = {
        str(b): (lambda b=b: fused_assign(X, centers, block=b))
        for b in sorted(grid)
    }
    stats = _measure_candidates(cands, replicates, "pallas.assign_block")
    winner, speedup = _choose(stats, str(default_blk), noise_mads)
    bucket = _knobs.bucket_for(_knobs.KNOBS["pallas.assign_block"], n, d, k)
    return _entry(
        "pallas.assign_block", bucket, dtype, int(winner), winner, speedup,
        stats, str(default_blk), {"n": n_t, "d": d_t, "k": k_t},
    )


_SEARCHERS: Dict[str, Callable] = {
    "selection.tile": _search_selection_tile,
    "selection.strategy": _search_selection_strategy,
    "pallas.topk_geometry": _search_topk_geometry,
    "pallas.assign_block": _search_assign_block,
}


# ------------------------------------------------------------ entry points


def search_knob(name: str, *, n: Optional[int] = None, d: Optional[int] = None,
                k: Optional[int] = None, dtype: str = "float32",
                replicates: Optional[int] = None) -> Optional[Dict[str, Any]]:
    """Search ONE knob for one shape bucket: run its measurement trials,
    persist the winning entry into the platform table (atomic write), and
    return the entry. None when the knob has no searcher, the platform gives
    no signal (e.g. pallas geometry off-TPU), or the bucket degenerates.

    Trials run under the `searching` thread-local, so any lookup() a trial's
    own host wrapper makes resolves to pure defaults — a search can never
    recurse into itself."""
    searcher = _SEARCHERS.get(name)
    if searcher is None:
        return None
    from .. import config as _config

    if replicates is None:
        replicates = int(_config.get("autotune.replicates"))
    noise_mads = float(_config.get("autotune.noise_mads"))
    _knobs._tl.searching = True
    try:
        entry = searcher(n, d, k, dtype, replicates, noise_mads)
    finally:
        _knobs._tl.searching = False
    if entry is None:
        return None
    tbl = _table.load_table()
    tbl.put(_table.entry_key(name, entry["bucket"], dtype), entry)
    tbl.save()
    return entry


def run_search(knob_names: Optional[List[str]] = None,
               shapes: Optional[List[Tuple[int, int, int]]] = None,
               dtype: str = "float32",
               replicates: Optional[int] = None) -> Dict[str, Any]:
    """The offline CLI's search sweep: every requested searchable knob over
    every (n, d, k) shape, tile before strategy (SEARCH_ORDER). Returns the
    summary the CLI prints; entries are persisted as each knob finishes, so
    an interrupted sweep keeps its completed work."""
    if knob_names is None:
        knob_names = [
            kn for kn in SEARCH_ORDER if _knobs.KNOBS[kn].searchable
        ]
    for kn in knob_names:
        if kn not in _knobs.KNOBS:
            raise KeyError(
                f"unknown knob '{kn}'; known: {sorted(_knobs.KNOBS)}"
            )
    ordered = sorted(
        knob_names,
        key=lambda kn: SEARCH_ORDER.index(kn) if kn in SEARCH_ORDER else 99,
    )
    if shapes is None:
        shapes = [(1 << 16, 64, 16)]
    t0 = time.perf_counter()
    results: List[Dict[str, Any]] = []
    skipped: List[Dict[str, str]] = []
    done: set = set()  # (knob, bucket, dtype) searched THIS sweep
    for n, d, k in shapes:
        for kn in ordered:
            knob = _knobs.KNOBS[kn]
            if not knob.searchable:
                skipped.append({"knob": kn, "reason": "not searchable"})
                continue
            # two requested shapes can land in one bucket (a knob may key on
            # a subset of the dims): re-searching it would just overwrite
            # the first result with duplicate work
            key = (kn, _knobs.bucket_for(knob, n, d, k), dtype)
            if key in done:
                skipped.append(
                    {"knob": kn, "reason": f"bucket {key[1]} already searched"}
                )
                continue
            entry = search_knob(
                kn, n=n, d=d, k=k, dtype=dtype, replicates=replicates
            )
            done.add(key)
            if entry is None:
                skipped.append(
                    {"knob": kn, "reason": "no signal on this platform/shape"}
                )
            else:
                results.append(entry)
    tbl = _table.load_table()
    return {
        "table_path": tbl.path,
        "table_entries": len(tbl),
        "platform": tbl.platform,
        "device_kind": tbl.device_kind,
        "results": results,
        "skipped": skipped,
        "search_s": round(time.perf_counter() - t0, 3),
    }
