#
# Knob registry + table lookup — the resolution half of the closed-loop
# autotuner (docs/design.md §6i).
#
# Every tunable the ops/serving host wrappers consult is DECLARED here: its
# kind, the config key that pins it (set()/env always beat the table — the
# resolution-order contract is programmatic set() > env > table > default),
# which shape dimensions key its bucket, and whether the search loop
# (autotune/search.py) knows how to measure it. `lookup()` is the single
# entry point the resolution sites call; it returns a table value on a hit
# and None otherwise — callers fall through to their defaults module value
# (autotune/defaults.py), so a missing/corrupt/stale table is always safe.
#
# Exactness: knobs marked exactness="bit" only ever take values whose
# outputs are bit-identical to the default path (exact selection strategies,
# tile widths, kernel geometry). `pallas.precision` is exactness="rerank" —
# its non-f32 values are legal ONLY because every consuming site pairs them
# with the parity_rerank_sq invariant (returned distances stay exact-f32;
# the id set carries the approximation). The search loop never explores
# rerank-class candidates unless explicitly asked (CLI --allow-approx).
#

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from . import table as _table

_STRATEGY_VALUES = ("exact_full", "exact_tiled", "approx", "pallas_fused")
_PRECISION_VALUES = ("float32", "bfloat16", "int8")


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # int | float | str | int_pair
    description: str
    config_key: Optional[str] = None  # config key that PINS the knob
    # config values that mean "choose for me" rather than a real pin: a
    # deployment restating the documented sentinel (env knn.selection=auto,
    # knn.select_tile=0) must NOT silently disable table resolution
    auto_values: Tuple = ()
    dims: Tuple[str, ...] = ()  # subset of ("n", "d", "k") keying the bucket
    values: Optional[Tuple[str, ...]] = None  # legal values for kind == str
    searchable: bool = False  # search.py implements a trial runner
    exactness: str = "bit"  # bit | rerank (see module header)
    grid: Tuple = field(default=())  # candidate hints for the search loop


KNOBS: Dict[str, Knob] = {
    k.name: k
    for k in (
        Knob(
            "selection.strategy", "str",
            "top-k strategy at auto-resolved search-plane sites "
            "(ops/selection.py::resolve)",
            config_key="knn.selection", auto_values=("auto",),
            dims=("n", "k"), values=_STRATEGY_VALUES, searchable=True,
        ),
        Knob(
            "selection.tile", "int",
            "exact_tiled tile width (replaces _auto_tile's platform folklore)",
            config_key="knn.select_tile", auto_values=(0,),
            dims=("n", "k"), searchable=True,
            grid=(512, 1024, 2048, 4096, 8192, 16384, 32768),
        ),
        Knob(
            "pallas.min_items", "int",
            "item width above which auto hands a fusable scan to the fused "
            "pallas kernel (ops/selection.py::_fused_auto)",
            config_key="knn.pallas_min_items", dims=(),
            grid=(1 << 14, 1 << 15, 1 << 16, 1 << 17, 1 << 18),
        ),
        Knob(
            "pallas.precision", "str",
            "fused-scan distance accumulation mode; non-f32 values are gated "
            "by the parity_rerank_sq exactness invariant",
            config_key="knn.pallas_precision", dims=(),
            values=_PRECISION_VALUES, exactness="rerank",
        ),
        Knob(
            "pallas.topk_geometry", "int_pair",
            "(q_block, item_tile) of the fused top-k scan "
            "(ops/pallas_select.py::_topk_geometry); tuned values still pass "
            "the VMEM-budget shrink",
            dims=("n", "d", "k"), searchable=True,
        ),
        Knob(
            "pallas.assign_block", "int",
            "row block of the fused KMeans assignment "
            "(ops/pallas_select.py::_assign_geometry)",
            dims=("d", "k"), searchable=True,
            grid=(512, 1024, 2048, 4096, 8192),
        ),
        Knob(
            "assign.fused_min_k", "int",
            "k threshold where auto routes KMeans assignment to the fused "
            "kernel (ops/pallas_select.py::use_fused_assign)",
            dims=("d",), grid=(32, 64, 128, 256),
        ),
        Knob(
            "lloyd.fused_min_k", "int",
            "k threshold where the auto Lloyd gate engages the fused pallas "
            "iteration (ops/kmeans.py::kmeans_fit)",
            dims=("d",), grid=(32, 64, 128, 256),
        ),
        Knob(
            "serving.bucket_min_rows", "int",
            "smallest serving padding bucket (serving/batcher.py::bucket_rows)",
            config_key="serving.bucket_min_rows", dims=(),
            grid=(8, 16, 32, 64),
        ),
        Knob(
            "serving.replicas", "int",
            "dispatcher replicas per served model "
            "(serving/fleet.py::resolve_replicas)",
            config_key="serving.replicas", auto_values=(0,), dims=(),
            grid=(1, 2, 4),
        ),
        Knob(
            "serving.hedge_after_p99_frac", "float",
            "queue-wait fraction of the observed p99 beyond which a queued "
            "request hedges to a second replica (serving/fleet.py; 0 off)",
            config_key="serving.hedge_after_p99_frac", dims=(),
            grid=(1.0, 1.5, 2.0),
        ),
        Knob(
            "cache.budget_bytes", "int",
            "HBM batch-cache byte budget / prefix split "
            "(ops/device_cache.py::batch_cache)",
            config_key="cache.hbm_budget_bytes", dims=(),
        ),
        Knob(
            "ann.build_batch_rows", "int",
            "row-batch geometry of the pipelined out-of-core ANN builds "
            "(ops/ann_streaming.py::resolve_build_batch_rows)",
            config_key="ann.build_batch_rows", auto_values=(0,),
            dims=("n", "d"),
            grid=(1 << 14, 1 << 15, 1 << 16, 1 << 17, 1 << 18),
        ),
        Knob(
            "ann.list_bucket_rows", "int",
            "minimum bucketed IVF list capacity — max_cell rounds up to a "
            "power-of-two bucket >= this so in-slack incremental adds never "
            "change search-executable shapes (ops/ann_lifecycle.py)",
            config_key="ann.list_bucket_rows", auto_values=(0,), dims=(),
            grid=(8, 16, 32, 64),
        ),
        Knob(
            "ingest.staging_pool_rows", "int",
            "rows per pooled staging buffer backing the zero-copy ingest "
            "plane's counted copy fallback "
            "(ops/ingest.py::resolve_staging_pool_rows)",
            config_key="ingest.staging_pool_rows", auto_values=(0,),
            dims=("n", "d"),
            grid=(1 << 14, 1 << 15, 1 << 16, 1 << 17, 1 << 18),
        ),
        Knob(
            "pipeline.fuse_min_rows", "int",
            "rows below which the pipeline fuser leaves a featurize->fit "
            "chain staged (pipeline.py::_resolve_fuse_min_rows)",
            config_key="pipeline.fuse_min_rows", auto_values=(0,),
            dims=("n",),
            grid=(1 << 10, 1 << 12, 1 << 14, 1 << 16),
        ),
        Knob(
            "continual.decay", "float",
            "per-update discount on the partial_fit sufficient-statistics "
            "carry — 1.0 = infinite memory, half-life h updates = "
            "0.5 ** (1 / h) (continual/partial_fit.py::resolve_decay)",
            config_key="continual.decay", auto_values=(0.0,), dims=(),
            grid=(0.9, 0.99, 0.999, 1.0),
        ),
        Knob(
            "continual.update_batch_rows", "int",
            "fixed block geometry partial_fit re-blocks every update batch "
            "to, zero-weight padded, so the update stream stays inside one "
            "compiled executable per accumulator "
            "(continual/partial_fit.py::resolve_update_batch_rows)",
            config_key="continual.update_batch_rows", auto_values=(0,),
            dims=("n", "d"),
            grid=(1 << 12, 1 << 13, 1 << 14, 1 << 15, 1 << 16),
        ),
        Knob(
            "continual.drift_mads", "float",
            "MADs above the baseline median a fresh per-row signal must land "
            "to fire `continual.drift` "
            "(continual/drift.py::resolve_drift_mads)",
            config_key="continual.drift_mads", auto_values=(0.0,), dims=(),
            grid=(2.0, 3.0, 4.0, 5.0),
        ),
        Knob(
            "ann.compact_tombstone_pct", "int",
            "tombstoned-slot percentage of occupied slots that triggers IVF "
            "list compaction (ops/ann_lifecycle.py::needs_compaction)",
            config_key="ann.compact_tombstone_pct", dims=(),
            grid=(10, 20, 30, 50),
        ),
        Knob(
            "partition.feature_axis", "int",
            "feature-axis width of the 2-D SPMD partitioner mesh (wide-k "
            "kNN / feature-sharded covariance layouts; "
            "parallel/partitioner.py::resolve_feature_axis)",
            config_key="partition.feature_axis", auto_values=(0,),
            dims=("n", "d"),
            grid=(1, 2, 4),
        ),
        Knob(
            "partition.batch_rows_per_process", "int",
            "LOCAL rows each process stages per streamed batch on multi-host "
            "runs (parallel/partitioner.py::resolve_batch_rows_per_process)",
            config_key="partition.batch_rows_per_process", auto_values=(0,),
            dims=("n", "d"),
            grid=(1 << 14, 1 << 15, 1 << 16, 1 << 17, 1 << 18),
        ),
        Knob(
            "tracing.sample_rate", "float",
            "fraction of unflagged (non-error/hedged/failed-over/expired, "
            "non-slow) request traces the tail sampler retains "
            "(observability/tracing.py::sample_rate)",
            config_key="tracing.sample_rate", dims=(),
            grid=(0.05, 0.25, 1.0),
        ),
    )
}


# ----------------------------------------------------------- shape buckets


def _pow2_bucket(x: int) -> int:
    x = int(x)
    if x <= 1:
        return max(x, 0)
    return 1 << (x - 1).bit_length()


def shape_bucket(n: Optional[int] = None, d: Optional[int] = None,
                 k: Optional[int] = None) -> str:
    """The shape-bucket key: each provided dim rounds UP to its power of two
    (`n131072-d64-k16` style). Per-exact-shape entries would never be
    consulted twice; pow2 buckets match how XLA padding/compile costs
    actually step."""
    parts = []
    for tag, v in (("n", n), ("d", d), ("k", k)):
        if v is not None:
            parts.append(f"{tag}{_pow2_bucket(v)}")
    return "-".join(parts) or "any"


def bucket_for(knob: Knob, n: Optional[int], d: Optional[int],
               k: Optional[int]) -> str:
    return shape_bucket(
        n=n if "n" in knob.dims else None,
        d=d if "d" in knob.dims else None,
        k=k if "k" in knob.dims else None,
    )


# ------------------------------------------------------- resolution records

_state_lock = threading.Lock()
# (knob, bucket, dtype) -> how it last resolved: the report section's source
_resolutions: Dict[str, Dict[str, Any]] = {}
_MAX_RESOLUTIONS = 256

_tl = threading.local()  # .searching: trials must resolve to pure defaults


def _in_search() -> bool:
    return bool(getattr(_tl, "searching", False))


def _note(knob: str, bucket: Optional[str], dtype: str, value: Any,
          source: str) -> None:
    key = _table.entry_key(knob, bucket or "-", dtype)
    with _state_lock:
        if key not in _resolutions and len(_resolutions) >= _MAX_RESOLUTIONS:
            return
        _resolutions[key] = {
            "knob": knob,
            "bucket": bucket,
            "dtype": dtype,
            "value": value,
            "source": source,
        }


def _counter(name: str, **labels: Any) -> None:
    try:
        from ..observability.runs import counter_inc

        counter_inc(name, 1, **labels)
    except Exception:  # noqa: fence/silent-except — telemetry is best-effort here
        pass


# strategies whose outputs are bit-identical to the exact_full reference —
# the only values a TABLE entry may introduce for the bit-class strategy
# knob. "approx" is accepted solely where it IS the platform auto default
# (TPU), where a table entry saying so changes nothing.
_BIT_SAFE_STRATEGIES = ("exact_full", "exact_tiled", "pallas_fused")


def _coerce_value(knob: Knob, raw: Any) -> Optional[Any]:
    """Validate/coerce a table value against the knob's declared kind AND its
    exactness class; None for anything malformed or exactness-violating
    (counted `autotune.table_invalid`, treated as a miss — a hand-edited or
    truncated entry must not crash a fit, and a bit-class knob must never be
    steered onto an approximate path by a table no test ever vetted)."""
    try:
        if knob.kind == "int":
            v = int(raw)
            return v if v > 0 else None
        if knob.kind == "float":
            v = float(raw)
            return v if v > 0 else None
        if knob.kind == "str":
            v = str(raw)
            if knob.values is not None and v not in knob.values:
                return None
            if (
                knob.name == "selection.strategy"
                and v not in _BIT_SAFE_STRATEGIES
                and _table.platform_key()[0] != "tpu"
            ):
                return None  # exactness="bit": approx only where it's default
            return v
        if knob.kind == "int_pair":
            a, b = (int(raw[0]), int(raw[1]))
            return (a, b) if a > 0 and b > 0 else None
    except (TypeError, ValueError, IndexError, KeyError):
        return None
    return None


# ------------------------------------------------------------------ lookup


def lookup(name: str, *, n: Optional[int] = None, d: Optional[int] = None,
           k: Optional[int] = None, dtype: str = "float32") -> Optional[Any]:
    """Resolve a knob from the tuning table; None means 'use your default'.

    Order of precedence (docs/design.md §6i): a knob whose config key is
    pinned (programmatic set() or env) returns None WITHOUT touching the
    table — config always wins; `autotune.mode=off` returns None without
    loading anything; a table hit returns the validated value (counted
    `autotune.table_hit{knob=}`); a miss counts `autotune.table_miss{knob=}`
    and, in `search` mode at a searchable knob, triggers the one-shot online
    search for this bucket (counted `autotune.searches{knob=}`), persisting
    and returning the winner. Host-side only — the resolution sites are the
    PR-5 host wrappers, so cached traces never bake a stale choice."""
    knob = KNOBS[name]
    from .. import config as _config

    mode = str(_config.get("autotune.mode"))
    if mode == "off" or _in_search():
        return None
    if knob.config_key is not None and _config.source(knob.config_key) != "default":
        # a pin to the knob's "choose for me" sentinel (env restating
        # `auto`/0) is not a real pin — the table still resolves
        if _config.get(knob.config_key) not in knob.auto_values:
            _note(name, None, dtype, None, "config")
            return None
    bucket = bucket_for(knob, n, d, k)
    tbl = _table.load_table()
    key = _table.entry_key(name, bucket, dtype)
    entry = tbl.get(key)
    if entry is not None:
        value = _coerce_value(knob, entry.get("value"))
        if value is None:
            _counter("autotune.table_invalid", knob=name)
        else:
            _counter("autotune.table_hit", knob=name)
            _note(name, bucket, dtype, value, "table")
            return value
    _counter("autotune.table_miss", knob=name)
    if mode == "search" and knob.searchable:
        value = _online_search(knob, n=n, d=d, k=k, dtype=dtype)
        if value is not None:
            _note(name, bucket, dtype, value, "searched")
            return value
    _note(name, bucket, dtype, None, "default")
    return None


_search_lock = threading.Lock()


def _online_search(knob: Knob, *, n: Optional[int], d: Optional[int],
                   k: Optional[int], dtype: str) -> Optional[Any]:
    """Online `search` mode: first sight of an uncovered (knob, bucket) runs
    the measurement loop synchronously, persists the winner, and returns it.
    Serialized — concurrent first sights re-check the table under the lock."""
    with _search_lock:
        tbl = _table.load_table()
        key = _table.entry_key(knob.name, bucket_for(knob, n, d, k), dtype)
        entry = tbl.get(key)
        if entry is not None:  # another thread searched while we waited
            return _coerce_value(knob, entry.get("value"))
        try:
            from . import search as _search

            entry = _search.search_knob(
                knob.name, n=n, d=d, k=k, dtype=dtype
            )
        except Exception as e:
            from ..utils import get_logger

            get_logger("autotune").warning(
                "online search for %s failed: %s; using defaults", knob.name, e
            )
            return None
        if entry is None:
            return None
        _counter("autotune.searches", knob=knob.name)
        return _coerce_value(knob, entry.get("value"))


# ---------------------------------------------------------- report section


def report_section(registry: Any = None) -> Optional[Dict[str, Any]]:
    """The run report's `autotune` section (observability/runs.py): mode,
    table identity/version, every knob resolution this process has made, and
    this RUN's hit/miss/search counts parsed from its scoped registry — the
    join key between a perf regression and the knob choice that caused it."""
    from .. import config as _config

    with _state_lock:
        resolutions = {k: dict(v) for k, v in _resolutions.items()}
    mode = str(_config.get("autotune.mode"))
    if mode == "off" and not resolutions:
        return None
    tbl = _table.peek_table()
    hits: Dict[str, int] = {}
    misses: Dict[str, int] = {}
    searches = 0
    if registry is not None:
        try:
            from ..observability.registry import split_label_key

            for key, v in (registry.snapshot().get("counters") or {}).items():
                cname, labels = split_label_key(key)
                knob = labels.get("knob", "")
                if cname == "autotune.table_hit":
                    hits[knob] = hits.get(knob, 0) + int(v)
                elif cname == "autotune.table_miss":
                    misses[knob] = misses.get(knob, 0) + int(v)
                elif cname == "autotune.searches":
                    searches += int(v)
        except Exception:  # noqa: fence/silent-except — report assembly best-effort
            pass
    return {
        "mode": mode,
        "table_version": _table.TABLE_VERSION,
        "table_path": tbl.path if tbl is not None else None,
        "table_status": tbl.status if tbl is not None else "unloaded",
        "table_entries": len(tbl) if tbl is not None else 0,
        "knobs": resolutions,
        "table_hits": hits,
        "table_misses": misses,
        "searches": searches,
    }


def reset() -> None:
    """Tests: drop cached tables and resolution notes."""
    _table.reset_tables()
    with _state_lock:
        _resolutions.clear()
