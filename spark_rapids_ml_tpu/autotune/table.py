#
# Persisted tuning tables — the durable half of the closed-loop autotuner
# (docs/design.md §6i).
#
# One versioned JSON file per (platform, device_kind) under `autotune.dir`
# (`SRML_TPU_TUNE_DIR`): `tuning_<platform>_<device_kind>.json`. Entries are
# keyed `<knob>|<shape-bucket>|<dtype>` and carry the measured winner plus
# its trial statistics and a `provenance` field (the search that produced
# it) — the stale one-off-measurement comments the defaults module replaced.
#
# Contracts:
#   * atomic writes: tmp file + os.replace, the JSONL-exporter discipline —
#     a reader never observes a torn table;
#   * corrupt or stale tables NEVER fail a fit: a JSON parse error counts
#     `autotune.table_corrupt`, a version (or platform) mismatch counts
#     `autotune.table_stale`, and either falls through to the in-code
#     defaults exactly like a missing file (mirroring `load_run_reports`'s
#     corrupt-line handling);
#   * loaded ONCE per process (per directory+platform) and consulted at the
#     HOST-wrapper resolution points only, so cached traces never bake a
#     stale choice — the PR-5 resolution contract.
#

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from typing import Any, Dict, Optional, Tuple

TABLE_VERSION = 1

_lock = threading.Lock()
# process cache: one loaded table per (dir-or-None, platform, device_kind)
_tables: Dict[Tuple[Optional[str], str, str], "TuningTable"] = {}
_platform_cache: Optional[Tuple[str, str]] = None


def _counter(name: str, n: int = 1, **labels: Any) -> None:
    """Best-effort observability counter: table handling must never fail a
    fit because the metrics plane is mid-teardown."""
    try:
        from ..observability.runs import counter_inc

        counter_inc(name, n, **labels)
    except Exception:  # noqa: fence/silent-except — telemetry is best-effort here
        pass


def platform_key() -> Tuple[str, str]:
    """(platform, device_kind) of device 0 — the table file identity. Cached:
    the backend cannot change within a process, and jax.devices() is not free."""
    global _platform_cache
    if _platform_cache is None:
        try:
            import jax

            dev = jax.devices()[0]
            kind = str(getattr(dev, "device_kind", "") or dev.platform)
            _platform_cache = (str(dev.platform), kind)
        except Exception:  # pragma: no cover - backend probe must never fail
            _platform_cache = ("cpu", "cpu")
    return _platform_cache


def _safe_name(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", s.strip()) or "unknown"


def table_path(tune_dir: str, platform: str, device_kind: str) -> str:
    return os.path.join(
        tune_dir, f"tuning_{_safe_name(platform)}_{_safe_name(device_kind)}.json"
    )


def entry_key(knob: str, bucket: str, dtype: str) -> str:
    return f"{knob}|{bucket}|{dtype}"


class TuningTable:
    """One platform's knob table. `status` records how it materialized:
    'loaded' (file parsed), 'missing' (no file yet), 'memory' (no tune dir
    configured), 'corrupt' / 'stale' (fell through to empty)."""

    def __init__(self, path: Optional[str], platform: str, device_kind: str):
        self.path = path
        self.platform = platform
        self.device_kind = device_kind
        self.version = TABLE_VERSION
        self.status = "memory" if path is None else "missing"
        self.entries: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- access

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self.entries.get(key)
            return dict(e) if e is not None else None

    def put(self, key: str, entry: Dict[str, Any]) -> None:
        with self._lock:
            self.entries[key] = dict(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self.entries)

    # -------------------------------------------------------- persistence

    def as_doc(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "version": TABLE_VERSION,
                "platform": self.platform,
                "device_kind": self.device_kind,
                "updated_ts": round(time.time(), 3),
                "entries": {k: dict(v) for k, v in self.entries.items()},
            }

    def save(self) -> Optional[str]:
        """Atomic write (tmp + os.replace). No-op for in-memory tables. A
        STALE on-disk table (e.g. written by a newer schema before a library
        rollback) is moved aside to `<path>.stale` instead of clobbered —
        rolling forward again must be able to recover its accumulated
        entries; corrupt files hold no data worth preserving."""
        if self.path is None:
            return None
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        if self.status == "stale" and os.path.exists(self.path):
            os.replace(self.path, self.path + ".stale")
            _warn_once(
                self.path + ".stale",
                f"preserved version-mismatched tuning table as "
                f"{self.path}.stale before writing v{TABLE_VERSION}",
            )
        doc = self.as_doc()
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.status = "loaded"
        return self.path


def _load_file(path: str, platform: str, device_kind: str) -> TuningTable:
    tbl = TuningTable(path, platform, device_kind)
    if not os.path.exists(path):
        return tbl  # status 'missing': every lookup is a clean miss
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or not isinstance(doc.get("entries"), dict):
            raise ValueError("tuning table is not an object with entries")
    except (json.JSONDecodeError, ValueError, OSError) as e:
        # corrupt table: fall through to defaults, never fail the fit
        tbl.status = "corrupt"
        _counter("autotune.table_corrupt", 1)
        _warn_once(path, f"corrupt tuning table {path}: {e}; using defaults")
        return tbl
    if doc.get("version") != TABLE_VERSION or (
        doc.get("platform") and doc["platform"] != platform
    ):
        # a table written by a different schema generation (or copied from
        # another backend) must not steer this process's knobs
        tbl.status = "stale"
        _counter("autotune.table_stale", 1)
        _warn_once(
            path,
            f"stale tuning table {path} (version={doc.get('version')}, "
            f"platform={doc.get('platform')}; want v{TABLE_VERSION} "
            f"{platform}); using defaults",
        )
        return tbl
    tbl.entries = {
        str(k): dict(v) for k, v in doc["entries"].items() if isinstance(v, dict)
    }
    tbl.status = "loaded"
    return tbl


_warned: set = set()


def _warn_once(key: str, msg: str) -> None:
    with _lock:
        if key in _warned:
            return
        _warned.add(key)
    from ..utils import get_logger

    get_logger("autotune.table").warning("%s", msg)


def load_table(tune_dir: Optional[str] = None) -> TuningTable:
    """The process's tuning table for the current platform: loaded once per
    (dir, platform) and cached. `tune_dir=None` reads `autotune.dir`; with no
    directory configured an in-memory table is returned (searches still work
    for the life of the process, nothing persists)."""
    if tune_dir is None:
        from .. import config as _config

        raw = _config.get("autotune.dir")
        tune_dir = str(raw) if raw else None
    platform, kind = platform_key()
    cache_key = (tune_dir, platform, kind)
    with _lock:
        tbl = _tables.get(cache_key)
    if tbl is not None:
        return tbl
    if tune_dir is None:
        tbl = TuningTable(None, platform, kind)
    else:
        tbl = _load_file(table_path(tune_dir, platform, kind), platform, kind)
    with _lock:
        # racing loaders: first one in wins so every caller shares one object
        tbl = _tables.setdefault(cache_key, tbl)
    return tbl


def peek_table() -> Optional[TuningTable]:
    """The already-loaded table for the current config, or None — the report
    path uses this so building a report never triggers a table load."""
    from .. import config as _config

    raw = _config.get("autotune.dir")
    tune_dir = str(raw) if raw else None
    platform, kind = platform_key()
    with _lock:
        return _tables.get((tune_dir, platform, kind))


def reset_tables() -> None:
    """Drop every cached table (tests; a directory change mid-process)."""
    with _lock:
        _tables.clear()
        _warned.clear()
