#
# Closed-loop autotuner (docs/design.md §6i): telemetry-driven knob search
# with persisted per-platform tuning tables.
#
# The observability arc (§6f device roofline, §6g live telemetry, §6h comm
# plane) measured everything a tuner needs; this package spends it. Three
# pieces:
#
#   knobs.py    the knob REGISTRY — every tunable the ops/serving host
#               wrappers consult (selection strategy/tile, pallas geometry
#               and thresholds, Lloyd gate, serving buckets, cache budget),
#               with its candidate grid and exactness class — and lookup(),
#               the single resolution entry point. Resolution order:
#               programmatic config.set() > env > tuning table > default.
#   table.py    persisted per-(platform, device_kind) tables: versioned
#               JSON under `autotune.dir` / SRML_TPU_TUNE_DIR, atomic
#               writes, corrupt/stale fall-through to defaults (counted),
#               loaded once per process.
#   search.py   the measurement loop: candidates timed through the §6f
#               compiled_kernel AOT cache inside `autotune.trial` spans (so
#               every entry carries measured mfu/roofline_bound/comm_frac),
#               MAD noise floor mirroring ci/bench_check.py.
#   defaults.py the knob-registry defaults module — the one home for the
#               numeric tile/threshold defaults ops/ used to hard-code
#               (the analyzer, tools/analysis, enforces the split).
#
# Offline: `python -m spark_rapids_ml_tpu.autotune` searches and persists.
# Online: `autotune.mode` = off | load (default) | search.
#
# This __init__ stays import-light (no jax): ops modules import it at call
# time inside host wrappers.
#

from .defaults import default_select_tile
from .knobs import (
    KNOBS,
    Knob,
    bucket_for,
    lookup,
    report_section,
    reset,
    shape_bucket,
)
from .table import (
    TABLE_VERSION,
    TuningTable,
    entry_key,
    load_table,
    platform_key,
    table_path,
)

__all__ = [
    "KNOBS",
    "Knob",
    "TABLE_VERSION",
    "TuningTable",
    "bucket_for",
    "default_select_tile",
    "entry_key",
    "load_table",
    "lookup",
    "platform_key",
    "report_section",
    "reset",
    "shape_bucket",
    "table_path",
]
