#
# Inference-plane observability: TransformRun scopes, the instrumented predict
# dispatch every model family routes through, and shape-bucket telemetry with a
# recompile sentinel (docs/design.md §6e).
#
# PR 3 lit the fit plane; the serving path stayed dark. Three things live here:
#
#   * TransformRun — the transform-plane mirror of FitRun (observability/
#     runs.py): a scoped registry delta + trace tree + event log around one
#     user-level `.transform()` call, exported to `transform_reports.jsonl`.
#     The per-partition metrics of the distributed plane (spark/transform.py)
#     are delivered as worker snapshots and fold in through the same
#     process-aware merge the barrier fit plane uses.
#
#   * predict_dispatch — one choke point for every model family's jitted
#     predict kernel call, so KMeans/LogReg/PCA/forest/UMAP/kNN/DBSCAN all
#     report the SAME metric names: `transform.predict_calls{model=}`,
#     `transform.predict_rows{model=}`, a `transform.predict_s{model=}`
#     latency histogram, and the shape-bucket telemetry below. The analyzer
#     flags direct jax.jit use in models/*.py that bypasses this helper.
#
#   * Shape buckets + recompile sentinel — a per-model registry of distinct
#     (rows, cols, dtype) signatures seen by the predict kernels. Each NEW
#     signature is (to XLA) a new compile: `transform.compile{model=}` counts
#     them, and once distinct signatures exceed
#     `observability.recompile_warn_threshold` every further one increments
#     `transform.recompile_storm{model=}` and lands a `recompile_storm` event —
#     the silent failure mode of un-bucketed pandas-UDF batch sizes, where every
#     ragged partition tail forces a fresh XLA compile (DrJAX, arXiv:2403.07128:
#     MapReduce-over-JAX lives or dies on compiled-program reuse).
#

from __future__ import annotations

import contextlib
import itertools
import math
import threading
import time
from typing import Any, Dict, Iterator, Mapping, Optional, Tuple

from .. import config as _config
from ..utils import get_logger
from . import runs as _runs
from .export import TRANSFORM_REPORT_FILENAME
from .runs import FitRun, counter_inc, event, observe, span

_logger = get_logger("observability.inference")


class TransformRun(FitRun):
    """One transform call's observability scope — the inference-plane mirror of
    FitRun. `algo` is the model class name; the report exports to
    `transform_reports.jsonl` and attaches to the model as
    `model.transform_report_` (the latest transform wins)."""

    kind = "transform"
    _id_prefix = "transform"
    _root_suffix = "transform_run"
    _report_filename = TRANSFORM_REPORT_FILENAME


# ------------------------------------------------------------- run scope gates

_tls = threading.local()


def _suppress_depth() -> int:
    return getattr(_tls, "suppress_depth", 0)


@contextlib.contextmanager
def suppress_transform_runs() -> Iterator[None]:
    """Mark this thread as inside an inference-plane worker (a transform UDF
    batch, the one-row schema probe): nested `model.transform()` calls keep
    writing counters/spans through the fan-out but must NOT open their own
    TransformRun — one user call, one run."""
    _tls.suppress_depth = _suppress_depth() + 1
    try:
        yield
    finally:
        _tls.suppress_depth = _suppress_depth() - 1


def _bucketed_depth() -> int:
    return getattr(_tls, "bucketed_depth", 0)


@contextlib.contextmanager
def bucketed_signatures() -> Iterator[None]:
    """Mark this thread's predict shape signatures as BUCKETED BY DESIGN (the
    serving plane's finite power-of-two bucket table, serving/batcher.py):
    each new signature still counts `transform.compile{model=}` — it IS a
    compile — but is exempt from the recompile-storm sentinel. The sentinel
    exists to catch unbounded ragged-batch signature growth; a fixed bucket
    table is the fix it recommends, and warming that table must not trip it."""
    _tls.bucketed_depth = _bucketed_depth() + 1
    try:
        yield
    finally:
        _tls.bucketed_depth = _bucketed_depth() - 1


@contextlib.contextmanager
def transform_run(algo: str, site: str = "driver") -> Iterator[Optional[TransformRun]]:
    """TransformRun gated on `observability.enabled` AND on not already being
    inside a transform worker scope on this thread (see suppress_transform_runs)."""
    if not bool(_config.get("observability.enabled")) or _suppress_depth() > 0:
        yield None
        return
    with TransformRun(algo, site=site) as run:
        yield run


# ------------------------------------------------------- sampling (latency obs)

_sample_lock = threading.Lock()
_sample_counts: Dict[str, int] = {}


def _should_sample(key: str) -> bool:
    """Deterministic rate limiter for latency observations: with
    `observability.transform_sample_rate` = r, record observation n iff
    floor(n*r) advanced — every counter still counts, only histogram pressure
    drops. r>=1 short-circuits without touching the shared counter."""
    rate = float(_config.get("observability.transform_sample_rate"))
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    with _sample_lock:
        n = _sample_counts.get(key, 0) + 1
        _sample_counts[key] = n
    return math.floor(n * rate) > math.floor((n - 1) * rate)


# ------------------------------------------- shape buckets + recompile sentinel

_shape_lock = threading.Lock()
_shape_sigs: Dict[str, set] = {}
# signatures registered under bucketed_signatures() (the serving plane's
# finite bucket table): remembered for compile dedup, EXCLUDED from the storm
# count — a served model's 9-bucket table must not push an unrelated ragged
# transform over the threshold
_bucketed_sigs: Dict[str, set] = {}
_storm_warned: set = set()

# membership cap per model: a pathological fully-ragged serving stream (every
# batch a new row count) must not grow the registry forever. At the cap every
# unseen signature still counts as a compile (it IS one) — it just stops being
# remembered, which can only over-count, never hide, a storm.
_MAX_TRACKED_SIGS = 65536


def reset_shape_buckets() -> None:
    """Clear the per-model shape-signature registry (tests / long-lived workers
    that reload models)."""
    with _shape_lock:
        _shape_sigs.clear()
        _bucketed_sigs.clear()
        _storm_warned.clear()


def shape_signatures(model_name: str) -> Tuple[Tuple[Any, ...], ...]:
    with _shape_lock:
        return tuple(sorted(_shape_sigs.get(model_name, ()), key=repr))


def _shape_signature(x: Any) -> Tuple[int, int, str]:
    """(padded_rows, cols, dtype) of a predict operand — the triple XLA keys a
    compiled program on. Rows are whatever padding the caller applied (none, for
    raw pandas-UDF batches — which is exactly what the sentinel detects)."""
    shape = getattr(x, "shape", None)
    if not shape:
        try:
            return len(x), 1, "object"
        except TypeError:
            return 1, 1, "object"
    rows = int(shape[0])
    cols = 1
    for d in shape[1:]:
        cols *= int(d)
    return rows, cols, str(getattr(x, "dtype", "object"))


def record_shape_signature(model_name: str, sig: Tuple[int, int, str]) -> bool:
    """Register one predict-call shape signature. Returns True when the
    signature is NEW for this model (== one more XLA compile of its predict
    program) and fires the recompile sentinel once the distinct count exceeds
    `observability.recompile_warn_threshold`."""
    bucketed = _bucketed_depth() > 0
    with _shape_lock:
        sigs = _shape_sigs.setdefault(model_name, set())
        if sig in sigs:
            return False
        if len(sigs) < _MAX_TRACKED_SIGS:
            sigs.add(sig)
            if bucketed:
                _bucketed_sigs.setdefault(model_name, set()).add(sig)
        # the storm judges only UN-bucketed growth: a served model's finite
        # bucket table is the sentinel's recommended fix, not evidence
        n_distinct = len(sigs) - len(_bucketed_sigs.get(model_name, ()))
    counter_inc("transform.compile", 1, model=model_name)
    if bucketed:
        return True  # bucketed by design (serving plane): no storm accounting
    threshold = int(_config.get("observability.recompile_warn_threshold"))
    if threshold > 0 and n_distinct > threshold:
        counter_inc("transform.recompile_storm", 1, model=model_name)
        event(
            "recompile_storm",
            model=model_name,
            signatures=n_distinct,
            threshold=threshold,
            rows=sig[0],
            cols=sig[1],
            dtype=sig[2],
        )
        with _shape_lock:
            first = model_name not in _storm_warned
            _storm_warned.add(model_name)
        if first:
            _logger.warning(
                "recompile storm: %s predict has seen %d distinct "
                "(rows, cols, dtype) shape signatures (> threshold %d) — "
                "un-bucketed batch sizes force one XLA compile per batch; pad "
                "batches to a fixed set of sizes or raise "
                "observability.recompile_warn_threshold.",
                model_name, n_distinct, threshold,
            )
    return True


# ------------------------------------------------------------ predict dispatch


def predict_dispatch(model: Any, kernel: Any, *args: Any,
                     shape_of: Any = None, **kwargs: Any) -> Any:
    """Run one model family's predict kernel under the inference-plane
    instrumentation. `args`/`kwargs` pass through to `kernel` untouched; the
    shape signature is read from `shape_of` when the query block is not the
    first positional (kNN ring kernels lead with the mesh), else from the first
    array-like argument.

    Reported per call, uniformly across families:
      * `transform.predict_calls{model=}` / `transform.predict_rows{model=}`
      * span `transform.predict` (lands in any open Fit/Transform run's trace)
      * histogram `transform.predict_s{model=}` (sampled via
        `observability.transform_sample_rate`)
      * shape-bucket registration + recompile sentinel (see module header)

    The recorded latency covers the kernel call as issued from Python; jax
    dispatch is asynchronous, so on accelerators it bounds dispatch+compile,
    while the per-batch `transform.batch_s` histogram (which wraps the whole
    batch including the host materialization) bounds end-to-end time.
    """
    mname = type(model).__name__
    ref = shape_of
    if ref is None:
        for a in args:
            if hasattr(a, "shape") and getattr(a, "shape", None):
                ref = a
                break
    sig = _shape_signature(ref if ref is not None else args[0] if args else None)
    record_shape_signature(mname, sig)
    counter_inc("transform.predict_calls", 1, model=mname)
    counter_inc("transform.predict_rows", sig[0], model=mname)
    t0 = time.perf_counter()
    with span("transform.predict", {"model": mname, "rows": sig[0]}):
        out = kernel(*args, **kwargs)
    if _should_sample("predict:" + mname):
        observe("transform.predict_s", time.perf_counter() - t0, model=mname)
    return out


@contextlib.contextmanager
def transform_batch(model: Any, n_rows: int,
                    nbytes: Optional[int] = None) -> Iterator[None]:
    """Instrument one transform batch (a whole local `.transform()` call, or
    one pandas-UDF batch of the distributed plane — the local call IS the
    per-batch unit there, so rows/batches/latency are counted in exactly one
    place and the partition totals can never double-count)."""
    mname = type(model).__name__
    counter_inc("transform.batches", 1, model=mname)
    counter_inc("transform.rows", int(n_rows), model=mname)
    if nbytes:
        counter_inc("transform.bytes", int(nbytes), model=mname)
    t0 = time.perf_counter()
    with span("transform.batch", {"model": mname, "rows": int(n_rows)}):
        yield
    if _should_sample("batch:" + mname):
        observe("transform.batch_s", time.perf_counter() - t0, model=mname)


# ------------------------------------------- partition sidecar (spark plane)

_rank_counter = itertools.count(0)


def partition_rank() -> int:
    """Partition ordinal for a transform UDF worker scope: the real Spark
    TaskContext partition id when one exists, else a process-local ordinal (the
    eager protocol-mock plane runs partitions sequentially in-process)."""
    try:
        from pyspark import TaskContext  # type: ignore

        tc = TaskContext.get()
        if tc is not None:
            return int(tc.partitionId())
    except Exception:  # noqa: fence/silent-except — pyspark absent or stubbed
        pass
    return next(_rank_counter)


def deliver_partition_snapshot(run_id: Optional[str], driver_token: str,
                               snapshot: Mapping[str, Any],
                               metrics_dir: Optional[str] = None) -> bool:
    """Hand one transform partition's worker-scope snapshot back to its run.

    * Driver-side run still open in THIS process (the eager local-mode plane):
      fold it in via the process-aware merge — same-process snapshots record
      the per-partition breakdown only (their writes already fanned out live),
      foreign ones merge into the run (spark/integration.py semantics).
    * Run not reachable (real lazy cluster: partitions execute after the
      driver's run closed, usually in another process): append the snapshot to
      `<metrics_dir>/transform_partials.jsonl` tagged with the run id — the
      durable half of the sidecar; `load_transform_partials` reads it back.
      The worker's writes already landed in its process-global registry, so
      nothing is merged twice here.
    Returns True when the snapshot reached a live run."""
    if run_id is None:
        return False
    run = _runs.find_run(run_id)
    if run is not None:
        run.add_worker_snapshot(snapshot)
        return True
    if metrics_dir:
        from .export import append_transform_partial

        try:
            append_transform_partial(
                dict(snapshot, run_id=run_id, driver=driver_token), metrics_dir
            )
        except OSError as e:
            _logger.warning("could not write transform partial: %s", e)
    return False
