#
# Per-fit run scopes, trace trees, and the fan-out write path — the collection
# half of the observability subsystem (docs/design.md §6d).
#
# Write path: every instrumentation call (`counter_inc`, `gauge_*`, `observe`,
# `add_span_total`, `span`, `event`) fans out to every active SINK:
#
#   * the process-global registry    — always; backs profiling.counter_totals()
#   * each open FitRun's registry    — process-global scope: barrier tasks run
#     as THREADS in the local-mode fit plane, and their metrics belong to the
#     driver thread's run
#   * this thread's worker_scope()   — thread-local: one barrier task's private
#     delta, serialized to the driver alongside the fit result
#
# A FitRun additionally collects a structured TRACE TREE (parent/child span
# nodes from the thread-local span stack) and an EVENT LOG (retries, fault
# firings, cache evictions, degradations) instead of the flat name-keyed sums
# profiling.py kept — arXiv:1612.01437's point that per-stage attribution, not
# end-to-end wall clock, is what localizes distributed-fit bottlenecks.
#
# Process identity: each snapshot carries (pid, boot token). The driver merges
# a worker snapshot into its own registries ONLY when the identity differs —
# in the threaded local-mode harness the worker already wrote through the
# fan-out path and a second merge would double-count; under a real multi-host
# fit the executor's counters never touched the driver process and the merge
# is exactly the fix for counter_totals() being silently process-local.
#

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence

from .. import config as _config
from ..utils import get_logger
from .registry import DEFAULT_TIME_BUCKETS, MetricsRegistry

_logger = get_logger("observability")

# identity of THIS process's metric stream (pid alone collides across hosts)
PROCESS_TOKEN = f"{os.getpid()}:{uuid.uuid4().hex[:12]}"

_GLOBAL = MetricsRegistry()

_span_ids = itertools.count(1)
_run_ids = itertools.count(1)

_state_lock = threading.RLock()
_active_runs: List["FitRun"] = []

_tls = threading.local()


def global_registry() -> MetricsRegistry:
    return _GLOBAL


_device_mod = None


def _device():
    """Lazy device-plane import (observability/device.py imports THIS module at
    its top; the reverse edge must resolve at call time)."""
    global _device_mod
    if _device_mod is None:
        from . import device as dev

        _device_mod = dev
    return _device_mod


def _worker_scopes() -> List["WorkerScope"]:
    scopes = getattr(_tls, "worker_scopes", None)
    if scopes is None:
        scopes = _tls.worker_scopes = []
    return scopes


def _span_stack() -> List["SpanNode"]:
    stack = getattr(_tls, "span_stack", None)
    if stack is None:
        stack = _tls.span_stack = []
    return stack


def _sink_registries() -> List[MetricsRegistry]:
    regs = [_GLOBAL]
    with _state_lock:
        regs.extend(run.registry for run in _active_runs)
    regs.extend(scope.registry for scope in _worker_scopes())
    return regs


# --------------------------------------------------------------- write fan-out


def counter_inc(name: str, n: int = 1, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.counter(name).inc(n, **labels)


def legacy_count(name: str, n: int) -> None:
    """Signed fan-out for the legacy profiling.count() surface (see
    MetricsRegistry.legacy_count): kind is discovered from usage per sink."""
    for reg in _sink_registries():
        reg.legacy_count(name, n)


def gauge_set(name: str, value: Any, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.gauge(name).set(value, **labels)


def gauge_inc(name: str, n: Any = 1, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.gauge(name).inc(n, **labels)


def gauge_dec(name: str, n: Any = 1, **labels: Any) -> None:
    gauge_inc(name, -n, **labels)


def observe(name: str, value: float,
            buckets: Sequence[float] = DEFAULT_TIME_BUCKETS, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.histogram(name, buckets=buckets).observe(value, **labels)


def add_span_total(name: str, seconds: float) -> None:
    """Legacy flat accumulation (profiling.add_time) PLUS a same-named
    exponential latency histogram: every per-batch `add_time` call site gains a
    distribution for free, not just a sum."""
    for reg in _sink_registries():
        reg.add_span_total(name, seconds)
        reg.histogram(name).observe(seconds)


def event(kind: str, **fields: Any) -> None:
    """Append a structured event (retry, fault, cache_evict, degrade, ...) to
    every open FitRun and this thread's worker scopes. No-op otherwise — events
    have no meaning outside a run context."""
    entry: Optional[Dict[str, Any]] = None
    stack = _span_stack()
    with _state_lock:
        targets: List[Any] = list(_active_runs)
    targets.extend(_worker_scopes())
    for t in targets:
        if entry is None:
            entry = {
                "ts": round(time.time(), 6),
                "kind": kind,
                "span_id": stack[-1].span_id if stack else None,
                **fields,
            }
        t.add_event(entry)


# ----------------------------------------------------------------- trace spans


class SpanNode:
    """One node of a run's trace tree. Identity is process-unique so nodes from
    any thread link into the same tree; parentage comes from the thread-local
    span stack (a span opened inside another ON THE SAME THREAD is its child;
    a barrier-task thread's top-level spans become roots of that task's own
    subtree in the run)."""

    __slots__ = ("span_id", "parent_id", "name", "attrs", "t0", "start_ts",
                 "duration_s", "status", "thread")

    def __init__(self, name: str, attrs: Optional[Mapping[str, Any]], parent_id):
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.start_ts = time.time()
        self.t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self.thread = threading.current_thread().name

    def as_dict(self) -> Dict[str, Any]:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ts": round(self.start_ts, 6),
            "duration_s": self.duration_s,
            "status": self.status,
            "thread": self.thread,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


@contextlib.contextmanager
def span(name: str, attrs: Optional[Mapping[str, Any]] = None) -> Iterator[None]:
    """Cheap structured span: perf_counter + thread-local parent linkage, no
    jax import anywhere near it. Failure-safe by construction (try/finally):
    a span whose body raises records its elapsed time with status='error' and
    counts toward `span.errors` — the exact timing the old profiling.span()
    dropped on the floor when a pass failed."""
    node = SpanNode(name, attrs, parent_id=(
        _span_stack()[-1].span_id if _span_stack() else None
    ))
    _span_stack().append(node)
    try:
        yield
    except BaseException:
        node.status = "error"
        raise
    finally:
        node.duration_s = time.perf_counter() - node.t0
        stack = _span_stack()
        if stack and stack[-1] is node:
            stack.pop()
        else:  # defensive: mis-nested exit must not corrupt the stack
            try:
                stack.remove(node)
            except ValueError:
                pass
        # device plane (observability/device.py): roofline-classify any kernel
        # work attributed to this span + keep the HBM gauge fresh. Runs BEFORE
        # add_span so the stored span dicts carry the finalized attrs.
        _device().on_span_close(node)
        for reg in _sink_registries():
            reg.add_span_total(name, node.duration_s)
            reg.histogram(name).observe(node.duration_s, status=node.status)
        if node.status == "error":
            counter_inc("span.errors", 1, span=name)
        with _state_lock:
            runs = list(_active_runs)
        for run in runs:
            run.add_span(node)
        for scope in _worker_scopes():
            scope.add_span(node)


def _tree(nodes: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Assemble flat span dicts into a nested tree (children sorted by start)."""
    by_id = {n["span_id"]: dict(n, children=[]) for n in nodes}
    roots: List[Dict[str, Any]] = []
    for n in by_id.values():
        parent = by_id.get(n["parent_id"])
        if parent is not None:
            parent["children"].append(n)
        else:
            roots.append(n)
    for n in by_id.values():
        n["children"].sort(key=lambda c: c["start_ts"])
    roots.sort(key=lambda c: c["start_ts"])
    return roots


# ------------------------------------------------------------------- run scope


class FitRun:
    """One fit's observability scope: a scoped MetricsRegistry delta, a trace
    tree, an event log, and the per-worker snapshots the driver folds in from
    the barrier plane. Opened by core/estimator.py::_fit around the whole
    degradation ladder; the finished report attaches to the trained model as
    `model.fit_report_` and (when `observability.metrics_dir` is set) appends
    to the JSONL run log (observability/export.py).

    The class attributes below are the subclass surface: TransformRun
    (observability/inference.py) reuses the whole scope/fan-out/aggregation
    machinery for the inference plane and only swaps identity + export file."""

    kind = "fit"
    _id_prefix = "fit"
    _root_suffix = "fit_run"
    # None -> the exporter's default (fit_reports.jsonl); subclasses override
    _report_filename: Optional[str] = None

    def __init__(self, algo: str, site: str = "driver",
                 max_spans: Optional[int] = None):
        self.algo = algo
        self.site = site
        self.run_id = f"{self._id_prefix}-{next(_run_ids)}-{uuid.uuid4().hex[:8]}"
        self.registry = MetricsRegistry()
        self.max_spans = (
            int(_config.get("observability.max_spans"))
            if max_spans is None
            else int(max_spans)
        )
        self._lock = threading.Lock()
        self._spans: List[Dict[str, Any]] = []
        self._dropped_spans = 0
        self._events: List[Dict[str, Any]] = []
        # events are bounded like spans: an eviction-heavy fit (dataset far
        # over the cache budget) fires a cache_evict per cross-stream eviction
        # per pass and must not grow run memory / snapshot size without limit
        self.max_events = max(self.max_spans, 1024)
        self._dropped_events = 0
        self._workers: List[Dict[str, Any]] = []
        self.started_ts: Optional[float] = None
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self._t0: Optional[float] = None
        self._root: Optional[Any] = None

    # ---- sink surface (runs.py fan-out calls these) ----

    def add_span(self, node: SpanNode) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._dropped_spans += 1
                return
            self._spans.append(node.as_dict())

    def add_event(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped_events += 1
                return
            self._events.append(entry)

    # ---- worker aggregation (spark/integration.py) ----

    def add_worker_snapshot(self, worker: Mapping[str, Any]) -> None:
        """Fold one barrier worker's serialized scope into this run. Foreign-
        process snapshots merge into the run AND global registries (their
        counters never flowed through this process's fan-out); same-process
        snapshots (threaded local-mode harness) are recorded for the per-worker
        breakdown only — their writes already landed here live."""
        foreign = worker.get("process") != PROCESS_TOKEN
        with self._lock:
            self._workers.append(
                {
                    "rank": worker.get("rank"),
                    "process": worker.get("process"),
                    "merged": foreign,
                    "metrics": worker.get("metrics") or {},
                    "events": worker.get("events") or [],
                    "spans": worker.get("spans") or [],
                }
            )
        if foreign:
            snap = worker.get("metrics") or {}
            self.registry.merge_snapshot(snap)
            _GLOBAL.merge_snapshot(snap)
            for entry in worker.get("events") or []:
                self.add_event(dict(entry, worker_rank=worker.get("rank")))

    # ---- lifecycle ----

    def __enter__(self) -> "FitRun":
        self.started_ts = time.time()
        self._t0 = time.perf_counter()
        # root trace node: named `.fit_run` (not `.fit`) so the legacy
        # span_totals entry for the estimator's own `{Algo}.fit` kernel span
        # is not double-counted by its enclosing run scope
        self._root = span(f"{self.algo}.{self._root_suffix}", {"site": self.site})
        with _state_lock:
            _active_runs.append(self)
        _device().note_run_start(self)
        self._root.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            self._root.__exit__(exc_type, exc, tb)
        finally:
            with _state_lock:
                try:
                    _active_runs.remove(self)
                except ValueError:
                    pass
            _device().note_run_end(self)
            self.duration_s = time.perf_counter() - (self._t0 or time.perf_counter())
            if exc_type is not None:
                self.status = "error"
            metrics_dir = _config.get("observability.metrics_dir")
            if metrics_dir:
                from .export import write_run_report

                try:
                    write_run_report(
                        self.report(), metrics_dir, filename=self._report_filename
                    )
                except OSError as e:
                    _logger.warning("could not write %s report: %s", self.kind, e)

    def report(self) -> Dict[str, Any]:
        """The structured fit report (finalized numbers after __exit__; callable
        mid-run for a live view)."""
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            workers = [
                {k: v for k, v in w.items() if k != "spans"} for w in self._workers
            ]
            dropped = self._dropped_spans
            dropped_events = self._dropped_events
        device_section = _device().device_report_section(self.registry)
        return {
            **({"device": device_section} if device_section else {}),
            "schema": 1,
            "kind": self.kind,
            "run_id": self.run_id,
            "algo": self.algo,
            "site": self.site,
            "process": PROCESS_TOKEN,
            "started_ts": self.started_ts,
            "duration_s": (
                self.duration_s
                if self.duration_s is not None
                else (time.perf_counter() - self._t0 if self._t0 else None)
            ),
            "status": self.status,
            "trace": _tree(spans),
            "dropped_spans": dropped,
            "events": events,
            "dropped_events": dropped_events,
            "metrics": self.registry.snapshot(),
            "workers": workers,
        }


def current_run() -> Optional[FitRun]:
    """The most recently opened still-active FitRun, if any."""
    with _state_lock:
        return _active_runs[-1] if _active_runs else None


def find_run(run_id: str) -> Optional[FitRun]:
    """A still-active run by id — how a transform partition's metrics sidecar
    finds its driver-side run when both execute in one process (the eager
    local-mode plane; observability/inference.py)."""
    with _state_lock:
        for run in _active_runs:
            if run.run_id == run_id:
                return run
    return None


@contextlib.contextmanager
def fit_run(algo: str, site: str = "driver") -> Iterator[Optional[FitRun]]:
    """FitRun gated on `observability.enabled`: yields None (and collects
    nothing run-scoped) when the subsystem is off — the global registry keeps
    accumulating either way, so the legacy counter surface never degrades."""
    if not bool(_config.get("observability.enabled")):
        yield None
        return
    with FitRun(algo, site=site) as run:
        yield run


# ---------------------------------------------------------------- worker scope


class WorkerScope:
    """One barrier task's thread-local metric delta: everything this thread
    writes while the scope is open, snapshot-able to the payload shipped to the
    driver (spark/integration.py serializes it next to the fit result)."""

    def __init__(self, rank: Optional[int] = None, max_spans: int = 256,
                 max_events: int = 512):
        self.rank = rank
        self.registry = MetricsRegistry()
        self.max_spans = max_spans
        self.max_events = max_events
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped_events = 0
        self._spans: List[Dict[str, Any]] = []
        self._dropped_spans = 0

    def add_event(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped_events += 1
                return
            self._events.append(entry)

    def add_span(self, node: SpanNode) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._dropped_spans += 1
                return
            self._spans.append(node.as_dict())

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "schema": 1,
                "process": PROCESS_TOKEN,
                "rank": self.rank,
                "metrics": self.registry.snapshot(),
                "events": list(self._events),
                "dropped_events": self._dropped_events,
                "spans": list(self._spans),
                "dropped_spans": self._dropped_spans,
            }


@contextlib.contextmanager
def worker_scope(rank: Optional[int] = None) -> Iterator[WorkerScope]:
    """Open a thread-local capture scope (stackable; inner scopes see the same
    writes). The barrier UDF wraps its whole body in one so each task's metric
    delta travels to the driver regardless of which process it ran in."""
    scope = WorkerScope(rank=rank)
    _worker_scopes().append(scope)
    try:
        yield scope
    finally:
        scopes = _worker_scopes()
        try:
            scopes.remove(scope)
        except ValueError:
            pass
