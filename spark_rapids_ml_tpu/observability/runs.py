#
# Per-fit run scopes, trace trees, and the fan-out write path — the collection
# half of the observability subsystem (docs/design.md §6d).
#
# Write path: every instrumentation call (`counter_inc`, `gauge_*`, `observe`,
# `add_span_total`, `span`, `event`) fans out to every active SINK:
#
#   * the process-global registry    — always; backs profiling.counter_totals()
#   * each open FitRun's registry    — process-global scope: barrier tasks run
#     as THREADS in the local-mode fit plane, and their metrics belong to the
#     driver thread's run
#   * this thread's worker_scope()   — thread-local: one barrier task's private
#     delta, serialized to the driver alongside the fit result
#
# A FitRun additionally collects a structured TRACE TREE (parent/child span
# nodes from the thread-local span stack) and an EVENT LOG (retries, fault
# firings, cache evictions, degradations) instead of the flat name-keyed sums
# profiling.py kept — arXiv:1612.01437's point that per-stage attribution, not
# end-to-end wall clock, is what localizes distributed-fit bottlenecks.
#
# Process identity: each snapshot carries (pid, boot token). The driver merges
# a worker snapshot into its own registries ONLY when the identity differs —
# in the threaded local-mode harness the worker already wrote through the
# fan-out path and a second merge would double-count; under a real multi-host
# fit the executor's counters never touched the driver process and the merge
# is exactly the fix for counter_totals() being silently process-local.
#

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence

from .. import config as _config
from ..utils import get_logger
from .registry import DEFAULT_TIME_BUCKETS, MetricsRegistry

_logger = get_logger("observability")

# identity of THIS process's metric stream (pid alone collides across hosts)
PROCESS_TOKEN = f"{os.getpid()}:{uuid.uuid4().hex[:12]}"

_GLOBAL = MetricsRegistry()

_span_ids = itertools.count(1)
_run_ids = itertools.count(1)

_state_lock = threading.RLock()
_active_runs: List["FitRun"] = []

_tls = threading.local()


def global_registry() -> MetricsRegistry:
    return _GLOBAL


_device_mod = None


def _device():
    """Lazy device-plane import (observability/device.py imports THIS module at
    its top; the reverse edge must resolve at call time)."""
    global _device_mod
    if _device_mod is None:
        from . import device as dev

        _device_mod = dev
    return _device_mod


_flight_mod = None


def _flight():
    """Lazy flight-recorder import (observability/flight.py imports this module
    inside dump_postmortem; same cycle-breaking as _device)."""
    global _flight_mod
    if _flight_mod is None:
        from . import flight as fl

        _flight_mod = fl
    return _flight_mod


_server_mod = None


def _server():
    """Lazy telemetry-server import (observability/server.py reads run state
    from this module at request time)."""
    global _server_mod
    if _server_mod is None:
        from . import server as srv

        _server_mod = srv
    return _server_mod


_comm_mod = None


def _comm():
    """Lazy communication-plane import (observability/comm.py, §6h: rank-skew
    gauges + straggler events on worker-snapshot merge; same cycle-breaking
    as _device)."""
    global _comm_mod
    if _comm_mod is None:
        from . import comm as cm

        _comm_mod = cm
    return _comm_mod


def _worker_scopes() -> List["WorkerScope"]:
    scopes = getattr(_tls, "worker_scopes", None)
    if scopes is None:
        scopes = _tls.worker_scopes = []
    return scopes


def _span_stack() -> List["SpanNode"]:
    stack = getattr(_tls, "span_stack", None)
    if stack is None:
        stack = _tls.span_stack = []
    return stack


def _sink_registries() -> List[MetricsRegistry]:
    regs = [_GLOBAL]
    with _state_lock:
        regs.extend(run.registry for run in _active_runs)
    regs.extend(scope.registry for scope in _worker_scopes())
    return regs


# --------------------------------------------------------------- write fan-out


def counter_inc(name: str, n: int = 1, **labels: Any) -> None:
    # fast path: outside any fit run / worker scope (the serving loop's
    # steady state) there is exactly one sink, so skip the fan-out list
    # build and its lock. The unlocked emptiness reads are GIL-atomic; a
    # racing run-open at worst misses one best-effort increment.
    if not _active_runs and not getattr(_tls, "worker_scopes", None):
        _GLOBAL.counter(name).inc(n, **labels)
        return
    for reg in _sink_registries():
        reg.counter(name).inc(n, **labels)


def legacy_count(name: str, n: int) -> None:
    """Signed fan-out for the legacy profiling.count() surface (see
    MetricsRegistry.legacy_count): kind is discovered from usage per sink."""
    for reg in _sink_registries():
        reg.legacy_count(name, n)


def gauge_set(name: str, value: Any, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.gauge(name).set(value, **labels)


def gauge_inc(name: str, n: Any = 1, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.gauge(name).inc(n, **labels)


def gauge_dec(name: str, n: Any = 1, **labels: Any) -> None:
    gauge_inc(name, -n, **labels)


def observe(name: str, value: float,
            buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
            exemplar: Any = None, **labels: Any) -> None:
    for reg in _sink_registries():
        reg.histogram(name, buckets=buckets).observe(
            value, exemplar=exemplar, **labels)


def add_span_total(name: str, seconds: float) -> None:
    """Legacy flat accumulation (profiling.add_time) PLUS a same-named
    exponential latency histogram: every per-batch `add_time` call site gains a
    distribution for free, not just a sum."""
    for reg in _sink_registries():
        reg.add_span_total(name, seconds)
        reg.histogram(name).observe(seconds)


def event(kind: str, **fields: Any) -> None:
    """Append a structured event (retry, fault, cache_evict, degrade, ...) to
    every open FitRun, this thread's worker scopes, AND the process flight
    recorder (observability/flight.py) — the ring buffer is exactly the place
    an event fired outside any run context still matters (postmortems)."""
    with _state_lock:
        targets: List[Any] = list(_active_runs)
    targets.extend(_worker_scopes())
    fl = _flight()
    if not targets and not fl.enabled():
        return  # no sink anywhere: skip building the entry entirely
    stack = _span_stack()
    entry = {
        "ts": round(time.time(), 6),
        "kind": kind,
        "span_id": stack[-1].span_id if stack else None,
        **fields,
    }
    for t in targets:
        t.add_event(entry)
    fl.note_event(entry)


# ------------------------------------------------------ progress & convergence


def progress(phase: str, done: Any, total: Any = None,
             unit: str = "units") -> None:
    """Publish live fit progress: gauges `fit.progress{phase=}` /
    `fit.progress_total{phase=}` / `fit.eta_s{phase=}` through the normal
    fan-out (global registry + open runs + worker scopes), plus a structured
    per-phase record on every open run (EMA-rate ETA) that /runs/<id> serves
    mid-fit (observability/server.py). Streamed-fit loops call this per pass
    and per batch (ops/streaming.py, ops/pairwise_streaming.py)."""
    done = int(done)
    gauge_set("fit.progress", done, phase=phase)
    if total is not None:
        gauge_set("fit.progress_total", int(total), phase=phase)
    with _state_lock:
        runs = list(_active_runs)
    eta = None
    for run in runs:
        e = run.note_progress(phase, done, total, unit)
        if e is not None:
            eta = e  # innermost (most recently opened) run's estimate wins
    if eta is not None:
        gauge_set("fit.eta_s", round(float(eta), 3), phase=phase)


# Process-wide monotonic sequence over ALL convergence records — fit-time
# iterations and later partial_fit updates land on ONE ordered axis, so drift
# trend windows can be compared across a fit run and the continual updates
# that follow it (iteration numbers restart per fit; `seq` never does).
_conv_seq = itertools.count()


def convergence(algo: str, iteration: Any, **fields: Any) -> None:
    """Append one per-iteration convergence record (KMeans inertia + center
    shift, logreg/linreg loss + grad norm, ...) to every open run — exported in
    the report's `convergence` section and visible mid-fit via /runs/<id>.
    Numeric fields coerce to plain floats so records stay JSON-clean."""
    rec: Dict[str, Any] = {
        "ts": round(time.time(), 6),
        "seq": next(_conv_seq),
        "algo": algo,
        "iteration": int(iteration),
    }
    for k, v in fields.items():
        try:
            rec[k] = float(v)
        except (TypeError, ValueError):
            rec[k] = v
    with _state_lock:
        runs = list(_active_runs)
    for run in runs:
        run.note_convergence(rec)
    _flight().note("convergence", **{k: v for k, v in rec.items() if k != "ts"})


# ----------------------------------------------------------------- trace spans


class SpanNode:
    """One node of a run's trace tree. Identity is process-unique so nodes from
    any thread link into the same tree; parentage comes from the thread-local
    span stack (a span opened inside another ON THE SAME THREAD is its child;
    a barrier-task thread's top-level spans become roots of that task's own
    subtree in the run)."""

    __slots__ = ("span_id", "parent_id", "name", "attrs", "t0", "start_ts",
                 "duration_s", "status", "thread")

    def __init__(self, name: str, attrs: Optional[Mapping[str, Any]], parent_id):
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.name = name
        self.attrs = dict(attrs) if attrs else {}
        self.start_ts = time.time()
        self.t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self.thread = threading.current_thread().name

    def as_dict(self) -> Dict[str, Any]:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ts": round(self.start_ts, 6),
            "duration_s": self.duration_s,
            "status": self.status,
            "thread": self.thread,
            **({"attrs": self.attrs} if self.attrs else {}),
        }


@contextlib.contextmanager
def span(name: str, attrs: Optional[Mapping[str, Any]] = None) -> Iterator[SpanNode]:
    """Cheap structured span: perf_counter + thread-local parent linkage, no
    jax import anywhere near it. Failure-safe by construction (try/finally):
    a span whose body raises records its elapsed time with status='error' and
    counts toward `span.errors` — the exact timing the old profiling.span()
    dropped on the floor when a pass failed."""
    node = SpanNode(name, attrs, parent_id=(
        _span_stack()[-1].span_id if _span_stack() else None
    ))
    _span_stack().append(node)
    # open-span registration: every open run tracks the node so /runs/<id> can
    # serve the CURRENT span stack mid-fit, and the flight recorder keeps the
    # open in its ring (observability/server.py, observability/flight.py)
    with _state_lock:
        open_runs = list(_active_runs)
    for run in open_runs:
        run.note_span_open(node)
    _flight().note_span_open(node)
    try:
        yield node
    except BaseException:
        node.status = "error"
        raise
    finally:
        node.duration_s = time.perf_counter() - node.t0
        stack = _span_stack()
        if stack and stack[-1] is node:
            stack.pop()
        else:  # defensive: mis-nested exit must not corrupt the stack
            try:
                stack.remove(node)
            except ValueError:
                pass
        # inclusive device accounting: raw kernel cost rolls up into the
        # enclosing span on this thread, so a wrapper span opened ABOVE the
        # dispatch layer (serving.batch around transform.predict) still
        # carries the §6f cost of the kernels it caused. Raw fields only —
        # each level gets its own roofline classification at its own close.
        dev = node.attrs.get("device")
        if dev and stack:
            pdev = stack[-1].attrs.get("device")
            if pdev is None:
                pdev = stack[-1].attrs["device"] = {
                    "flops": 0.0, "bytes": 0.0, "transcendentals": 0.0,
                    "comm_bytes": 0.0, "calls": 0, "kernels": {},
                }
            for k in ("flops", "bytes", "transcendentals", "comm_bytes"):
                pdev[k] = pdev.get(k, 0.0) + float(dev.get(k, 0.0) or 0.0)
            pdev["calls"] = pdev.get("calls", 0) + int(dev.get("calls", 0) or 0)
            agg = pdev.setdefault("kernels", {})
            for kname, c in (dev.get("kernels") or {}).items():
                agg[kname] = agg.get(kname, 0) + c
        # device plane (observability/device.py): roofline-classify any kernel
        # work attributed to this span + keep the HBM gauge fresh. Runs BEFORE
        # add_span so the stored span dicts carry the finalized attrs.
        _device().on_span_close(node)
        _flight().note_span_close(node)
        for reg in _sink_registries():
            reg.add_span_total(name, node.duration_s)
            reg.histogram(name).observe(node.duration_s, status=node.status)
        if node.status == "error":
            counter_inc("span.errors", 1, span=name)
        with _state_lock:
            runs = list(_active_runs)
        for run in runs:
            run.add_span(node)
        for scope in _worker_scopes():
            scope.add_span(node)


def _tree(nodes: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Assemble flat span dicts into a nested tree (children sorted by start)."""
    by_id = {n["span_id"]: dict(n, children=[]) for n in nodes}
    roots: List[Dict[str, Any]] = []
    for n in by_id.values():
        parent = by_id.get(n["parent_id"])
        if parent is not None:
            parent["children"].append(n)
        else:
            roots.append(n)
    for n in by_id.values():
        n["children"].sort(key=lambda c: c["start_ts"])
    roots.sort(key=lambda c: c["start_ts"])
    return roots


# ------------------------------------------------------------------- run scope


class FitRun:
    """One fit's observability scope: a scoped MetricsRegistry delta, a trace
    tree, an event log, and the per-worker snapshots the driver folds in from
    the barrier plane. Opened by core/estimator.py::_fit around the whole
    degradation ladder; the finished report attaches to the trained model as
    `model.fit_report_` and (when `observability.metrics_dir` is set) appends
    to the JSONL run log (observability/export.py).

    The class attributes below are the subclass surface: TransformRun
    (observability/inference.py) reuses the whole scope/fan-out/aggregation
    machinery for the inference plane and only swaps identity + export file."""

    kind = "fit"
    _id_prefix = "fit"
    _root_suffix = "fit_run"
    # None -> the exporter's default (fit_reports.jsonl); subclasses override
    _report_filename: Optional[str] = None

    def __init__(self, algo: str, site: str = "driver",
                 max_spans: Optional[int] = None):
        self.algo = algo
        self.site = site
        self.run_id = f"{self._id_prefix}-{next(_run_ids)}-{uuid.uuid4().hex[:8]}"
        # every run is born with a trace context (docs/design.md §6l) so
        # barrier-fit / transform-partition worker snapshots can join the
        # driver's trace across process boundaries (the run_id discipline)
        try:
            from .tracing import format_traceparent, mint_span_id, mint_trace_id

            self.traceparent: Optional[str] = format_traceparent(
                mint_trace_id(), mint_span_id())
        except Exception:
            self.traceparent = None
        self.registry = MetricsRegistry()
        self.max_spans = (
            int(_config.get("observability.max_spans"))
            if max_spans is None
            else int(max_spans)
        )
        self._lock = threading.Lock()
        self._spans: List[Dict[str, Any]] = []
        self._dropped_spans = 0
        self._events: List[Dict[str, Any]] = []
        # events are bounded like spans: an eviction-heavy fit (dataset far
        # over the cache budget) fires a cache_evict per cross-stream eviction
        # per pass and must not grow run memory / snapshot size without limit
        self.max_events = max(self.max_spans, 1024)
        self._dropped_events = 0
        self._workers: List[Dict[str, Any]] = []
        # ranks already flagged as stragglers (§6h): one event per rank per run
        self._straggler_ranks: set = set()
        # live-telemetry state (docs/design.md §6g): the open-span stack the
        # /runs/<id> endpoint serves mid-run, per-phase progress with EMA ETA,
        # and the bounded per-iteration convergence record list
        self._open_spans: Dict[int, Dict[str, Any]] = {}
        self._progress: Dict[str, Dict[str, Any]] = {}
        self._convergence: List[Dict[str, Any]] = []
        self.max_convergence = max(
            0, int(_config.get("observability.max_convergence_records"))
        )
        self._dropped_convergence = 0
        self._orphan_snapshots = 0
        self.started_ts: Optional[float] = None
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self._t0: Optional[float] = None
        self._root: Optional[Any] = None

    # ---- sink surface (runs.py fan-out calls these) ----

    def note_span_open(self, node: SpanNode) -> None:
        with self._lock:
            if len(self._open_spans) < self.max_spans:
                self._open_spans[node.span_id] = {
                    "span_id": node.span_id,
                    "parent_id": node.parent_id,
                    "name": node.name,
                    "start_ts": round(node.start_ts, 6),
                    "thread": node.thread,
                }

    def add_span(self, node: SpanNode) -> None:
        with self._lock:
            self._open_spans.pop(node.span_id, None)
            if len(self._spans) >= self.max_spans:
                self._dropped_spans += 1
                return
            self._spans.append(node.as_dict())

    def add_event(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped_events += 1
                return
            self._events.append(entry)

    # ---- live progress & convergence (runs.progress / runs.convergence) ----

    def note_progress(self, phase: str, done: int, total: Optional[int],
                      unit: str) -> Optional[float]:
        """Fold one progress observation into the per-phase record; returns the
        EMA-based ETA in seconds (None until a rate is established). The EMA
        smooths per-unit rate over updates (alpha 0.3) so the ETA tracks the
        steady-state pass rate instead of the compile-heavy first pass."""
        now = time.monotonic()
        with self._lock:
            st = self._progress.get(phase)
            if st is None:
                st = self._progress[phase] = {
                    "phase": phase, "done": 0, "total": None, "unit": unit,
                    "ema_rate": None, "eta_s": None, "updated_ts": None,
                    "_t": now,
                }
            delta = done - st["done"]
            dt = now - st["_t"]
            if delta > 0 and dt > 0:
                rate = delta / dt
                st["ema_rate"] = (
                    rate if st["ema_rate"] is None
                    else 0.3 * rate + 0.7 * st["ema_rate"]
                )
            st["done"] = done
            if total is not None:
                st["total"] = int(total)
            st["unit"] = unit
            st["_t"] = now
            st["updated_ts"] = round(time.time(), 6)
            if st["total"] and st["ema_rate"]:
                st["eta_s"] = round(
                    max(st["total"] - done, 0) / st["ema_rate"], 3
                )
            return st["eta_s"]

    def note_convergence(self, rec: Dict[str, Any]) -> None:
        # Copy before annotating: `rec` is shared across every open run, and
        # `rel_s` (run-relative timestamp) is per-run by definition.
        rec = dict(rec)
        if self.started_ts is not None and "ts" in rec:
            rec["rel_s"] = round(float(rec["ts"]) - self.started_ts, 6)
        with self._lock:
            if len(self._convergence) >= self.max_convergence:
                self._dropped_convergence += 1
                return
            self._convergence.append(rec)

    def progress_snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                phase: {k: v for k, v in st.items() if not k.startswith("_")}
                for phase, st in self._progress.items()
            }

    def live_view(self, summary: bool = False) -> Dict[str, Any]:
        """The /runs JSON surface: a mid-run view (observability/server.py).
        `summary` yields the /runs index row; the full view adds the open-span
        stack, convergence/event tails, and a full metrics snapshot."""
        base = {
            "run_id": self.run_id,
            "kind": self.kind,
            "algo": self.algo,
            "site": self.site,
            "status": self.status,
            "process": PROCESS_TOKEN,
            "started_ts": self.started_ts,
            "duration_s": (
                round(time.perf_counter() - self._t0, 6)
                if self._t0 is not None and self.duration_s is None
                else self.duration_s
            ),
            "progress": self.progress_snapshot(),
        }
        if summary:
            return base
        with self._lock:
            open_spans = sorted(
                self._open_spans.values(), key=lambda s: s["span_id"]
            )
            convergence = list(self._convergence[-64:])
            events_tail = list(self._events[-64:])
            n_workers = len(self._workers)
        base.update(
            open_spans=open_spans,
            convergence=convergence,
            events_tail=events_tail,
            workers=n_workers,
            metrics=self.registry.snapshot(),
        )
        return base

    # ---- worker aggregation (spark/integration.py) ----

    def add_worker_snapshot(self, worker: Mapping[str, Any]) -> None:
        """Fold one barrier worker's serialized scope into this run. Foreign-
        process snapshots merge into the run AND global registries (their
        counters never flowed through this process's fan-out); same-process
        snapshots (threaded local-mode harness) are recorded for the per-worker
        breakdown only — their writes already landed here live.

        Trace context (§6g): snapshots stamped with a `run_id` join on it — a
        snapshot carrying a DIFFERENT run's id is an ORPHAN (a stale sidecar
        replay, a crossed wire in a shared executor): it is recorded for
        forensics but its counters are NOT merged, and
        `observability.orphan_snapshots` counts it. Legacy snapshots without a
        run_id keep the old process-token-only semantics."""
        snap_run_id = worker.get("run_id")
        orphan = snap_run_id is not None and snap_run_id != self.run_id
        foreign = worker.get("process") != PROCESS_TOKEN
        with self._lock:
            self._workers.append(
                {
                    "rank": worker.get("rank"),
                    "process": worker.get("process"),
                    "run_id": snap_run_id,
                    "orphan": orphan,
                    "merged": foreign and not orphan,
                    # per-rank timing (§6h): the skew/straggler/timeline inputs
                    "started_ts": worker.get("started_ts"),
                    "wall_s": worker.get("wall_s"),
                    "phases": worker.get("phases") or {},
                    "metrics": worker.get("metrics") or {},
                    "events": worker.get("events") or [],
                    "spans": worker.get("spans") or [],
                }
            )
            if orphan:
                self._orphan_snapshots += 1
        if orphan:
            counter_inc("observability.orphan_snapshots", 1, run=self.run_id)
            return
        if foreign:
            snap = worker.get("metrics") or {}
            self.registry.merge_snapshot(snap)
            _GLOBAL.merge_snapshot(snap)
            for entry in worker.get("events") or []:
                self.add_event(dict(entry, worker_rank=worker.get("rank")))
        # communication plane (§6h): refresh rank-skew gauges and emit
        # straggler events for newly slow ranks; a telemetry failure must
        # never fail a merge whose barrier stage already succeeded
        try:
            _comm().note_worker_merge(self)
        except Exception as e:
            _logger.warning("rank-skew update failed: %s", e)

    def rank_view(self) -> Dict[str, Any]:
        """The per-rank barrier timeline of this run's merged worker
        snapshots (observability/comm.py::rank_timeline): served live by
        `/runs/<run_id>/ranks`, exported as the report's `ranks` section, and
        carried by postmortem bundles. Orphan snapshots are excluded — they
        belong to some OTHER run's timeline."""
        with self._lock:
            workers = [
                {
                    "rank": w.get("rank"),
                    "started_ts": w.get("started_ts"),
                    "wall_s": w.get("wall_s"),
                    "phases": w.get("phases") or {},
                }
                for w in self._workers
                if not w.get("orphan")
            ]
        return _comm().rank_timeline(workers)

    # ---- lifecycle ----

    def __enter__(self) -> "FitRun":
        self.started_ts = time.time()
        self._t0 = time.perf_counter()
        # root trace node: named `.fit_run` (not `.fit`) so the legacy
        # span_totals entry for the estimator's own `{Algo}.fit` kernel span
        # is not double-counted by its enclosing run scope
        self._root = span(f"{self.algo}.{self._root_suffix}", {"site": self.site})
        with _state_lock:
            _active_runs.append(self)
        _device().note_run_start(self)
        try:
            # live telemetry endpoint (observability/server.py): held up by
            # refcount while any run is open; no-op when http_port is unset
            _server().on_run_start(self)
        except Exception as e:
            _logger.warning("telemetry endpoint start failed: %s", e)
        self._root.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            self._root.__exit__(exc_type, exc, tb)
        finally:
            with _state_lock:
                try:
                    _active_runs.remove(self)
                except ValueError:
                    pass
            _device().note_run_end(self)
            self.duration_s = time.perf_counter() - (self._t0 or time.perf_counter())
            if exc_type is not None:
                self.status = "error"
                # failure flight recorder (observability/flight.py): an
                # unhandled fit/transform failure dumps the postmortem bundle
                # next to the JSONL reports; never raises
                _flight().dump_postmortem(
                    self, reason=f"{self.kind}_error:{exc_type.__name__}"
                )
            try:
                metrics_dir = _config.get("observability.metrics_dir")
                if metrics_dir:
                    from .export import write_run_report

                    try:
                        write_run_report(
                            self.report(), metrics_dir,
                            filename=self._report_filename,
                        )
                    except OSError as e:
                        _logger.warning(
                            "could not write %s report: %s", self.kind, e
                        )
            finally:
                # endpoint release must never be skipped — a leaked refcount
                # would leave the server thread and socket alive after fit
                try:
                    _server().on_run_end(self)
                except Exception as e:
                    _logger.warning("telemetry endpoint release failed: %s", e)

    def report(self) -> Dict[str, Any]:
        """The structured fit report (finalized numbers after __exit__; callable
        mid-run for a live view)."""
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
            workers = [
                {k: v for k, v in w.items() if k != "spans"} for w in self._workers
            ]
            dropped = self._dropped_spans
            dropped_events = self._dropped_events
            convergence = list(self._convergence)
            dropped_convergence = self._dropped_convergence
            orphans = self._orphan_snapshots
            have_workers = bool(self._workers)
        device_section = _device().device_report_section(self.registry)
        # autotune section (docs/design.md §6i): the resolved knob values,
        # table identity/version, and this run's table hit/miss/search counts
        # — the join key between a perf regression and the knob choice that
        # caused it. Best-effort: a tuner failure must never fail a report.
        autotune_section = None
        try:
            from .. import autotune as _autotune

            autotune_section = _autotune.report_section(self.registry)
        except Exception as e:
            _logger.warning("autotune report section failed: %s", e)
        # ingest section (docs/design.md §6k/§6f): this run's zero-copy vs
        # copied staging byte split and the before/after bytes-per-row cost
        # analysis. Best-effort, like the autotune section.
        ingest_section = None
        try:
            from ..ops import ingest as _ingest

            ingest_section = _ingest.report_section(self.registry)
        except Exception as e:
            _logger.warning("ingest report section failed: %s", e)
        ranks_section = None
        if have_workers:
            try:
                ranks_section = self.rank_view()
            except Exception as e:
                _logger.warning("rank timeline assembly failed: %s", e)
        # a run whose only snapshots were orphans has an EMPTY timeline —
        # exporting it would read as "this run had ranks, none reported"
        have_ranks = bool(ranks_section and ranks_section.get("ranks"))
        return {
            **({"device": device_section} if device_section else {}),
            **({"autotune": autotune_section} if autotune_section else {}),
            **({"ingest": ingest_section} if ingest_section else {}),
            **({"ranks": ranks_section} if have_ranks else {}),
            "schema": 1,
            "kind": self.kind,
            "run_id": self.run_id,
            "traceparent": self.traceparent,
            "algo": self.algo,
            "site": self.site,
            "process": PROCESS_TOKEN,
            "started_ts": self.started_ts,
            "duration_s": (
                self.duration_s
                if self.duration_s is not None
                else (time.perf_counter() - self._t0 if self._t0 else None)
            ),
            "status": self.status,
            "trace": _tree(spans),
            "dropped_spans": dropped,
            "events": events,
            "dropped_events": dropped_events,
            "convergence": convergence,
            "dropped_convergence": dropped_convergence,
            "progress": self.progress_snapshot(),
            "orphan_snapshots": orphans,
            "metrics": self.registry.snapshot(),
            "workers": workers,
        }


def current_run() -> Optional[FitRun]:
    """The most recently opened still-active FitRun, if any."""
    with _state_lock:
        return _active_runs[-1] if _active_runs else None


def active_runs() -> List[FitRun]:
    """All currently-open run scopes, oldest first (the /runs index)."""
    with _state_lock:
        return list(_active_runs)


def find_run(run_id: str) -> Optional[FitRun]:
    """A still-active run by id — how a transform partition's metrics sidecar
    finds its driver-side run when both execute in one process (the eager
    local-mode plane; observability/inference.py)."""
    with _state_lock:
        for run in _active_runs:
            if run.run_id == run_id:
                return run
    return None


@contextlib.contextmanager
def fit_run(algo: str, site: str = "driver") -> Iterator[Optional[FitRun]]:
    """FitRun gated on `observability.enabled`: yields None (and collects
    nothing run-scoped) when the subsystem is off — the global registry keeps
    accumulating either way, so the legacy counter surface never degrades."""
    if not bool(_config.get("observability.enabled")):
        yield None
        return
    with FitRun(algo, site=site) as run:
        yield run


# ---------------------------------------------------------------- worker scope


class WorkerScope:
    """One barrier task's thread-local metric delta: everything this thread
    writes while the scope is open, snapshot-able to the payload shipped to the
    driver (spark/integration.py serializes it next to the fit result).

    `run_id` is the TRACE CONTEXT (§6g): the driver's run id, carried through
    the barrier/transform closure into the scope and stamped on every exported
    snapshot, so driver-side merge and offline `load_run_reports` join
    per-worker rows to exactly one run instead of guessing by process token."""

    def __init__(self, rank: Optional[int] = None, max_spans: int = 256,
                 max_events: int = 512, run_id: Optional[str] = None,
                 traceparent: Optional[str] = None):
        self.rank = rank
        self.run_id = run_id
        self.traceparent = traceparent
        self.registry = MetricsRegistry()
        self.max_spans = max_spans
        self.max_events = max_events
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped_events = 0
        self._spans: List[Dict[str, Any]] = []
        self._dropped_spans = 0
        # per-rank timing for the communication plane (§6h): the scope's own
        # wall clock plus named phase records (collect, fit_program, transform
        # partition, ...) with rows/bytes — the raw material of the driver's
        # skew ratios, straggler events and barrier timeline
        self.started_ts = time.time()
        self._t0 = time.perf_counter()
        self._phases: Dict[str, Dict[str, Any]] = {}

    def note_phase(self, phase: str, wall_s: Optional[float] = None,
                   rows: Optional[int] = None, nbytes: Optional[int] = None,
                   start_ts: Optional[float] = None,
                   end_ts: Optional[float] = None) -> None:
        """Record (accumulating) one named phase's wall time / rows ingested /
        bytes for this rank. Callers pass measured wall_s; start/end default to
        a window ending NOW of that length, so merged timelines always carry
        usable start/end stamps."""
        now = time.time()
        if end_ts is None:
            end_ts = now
        if start_ts is None and wall_s is not None:
            start_ts = end_ts - float(wall_s)
        with self._lock:
            st = self._phases.setdefault(phase, {
                "wall_s": 0.0, "rows": 0, "bytes": 0,
                "start_ts": None, "end_ts": None,
            })
            if wall_s is not None:
                st["wall_s"] = round(st["wall_s"] + float(wall_s), 6)
            if rows:
                st["rows"] += int(rows)
            if nbytes:
                st["bytes"] += int(nbytes)
            if start_ts is not None:
                st["start_ts"] = (
                    round(start_ts, 6) if st["start_ts"] is None
                    else min(st["start_ts"], round(start_ts, 6))
                )
            st["end_ts"] = (
                round(end_ts, 6) if st["end_ts"] is None
                else max(st["end_ts"], round(end_ts, 6))
            )

    def add_event(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self._dropped_events += 1
                return
            self._events.append(entry)

    def add_span(self, node: SpanNode) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._dropped_spans += 1
                return
            self._spans.append(node.as_dict())

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "schema": 1,
                "process": PROCESS_TOKEN,
                "rank": self.rank,
                "run_id": self.run_id,
                "traceparent": self.traceparent,
                "started_ts": round(self.started_ts, 6),
                "wall_s": round(time.perf_counter() - self._t0, 6),
                "phases": {k: dict(v) for k, v in self._phases.items()},
                "metrics": self.registry.snapshot(),
                "events": list(self._events),
                "dropped_events": self._dropped_events,
                "spans": list(self._spans),
                "dropped_spans": self._dropped_spans,
            }


def note_rank_phase(phase: str, wall_s: Optional[float] = None,
                    rows: Optional[int] = None, nbytes: Optional[int] = None,
                    start_ts: Optional[float] = None,
                    end_ts: Optional[float] = None) -> None:
    """Record one per-rank phase observation (wall time, rows ingested, bytes)
    on every worker scope open on THIS thread — the communication plane's
    (§6h) raw skew material. No-op outside a worker scope, so instrumented
    code paths (barrier task body, transform partitions) need no gating."""
    for scope in _worker_scopes():
        scope.note_phase(phase, wall_s=wall_s, rows=rows, nbytes=nbytes,
                         start_ts=start_ts, end_ts=end_ts)


@contextlib.contextmanager
def worker_scope(rank: Optional[int] = None,
                 run_id: Optional[str] = None,
                 traceparent: Optional[str] = None) -> Iterator[WorkerScope]:
    """Open a thread-local capture scope (stackable; inner scopes see the same
    writes). The barrier UDF wraps its whole body in one so each task's metric
    delta travels to the driver regardless of which process it ran in;
    `run_id` (and since §6l the W3C `traceparent`) stamps the driver's trace
    context on the exported snapshot."""
    scope = WorkerScope(rank=rank, run_id=run_id, traceparent=traceparent)
    _worker_scopes().append(scope)
    try:
        yield scope
    finally:
        scopes = _worker_scopes()
        try:
            scopes.remove(scope)
        except ValueError:
            pass
