#
# Failure flight recorder — the forensics half of the live telemetry plane
# (docs/design.md §6g).
#
# Run reports (§6d/§6e) answer "what did this fit do" AFTER it finished; a fit
# that dies mid-stream, wedges, or enters the degradation ladder leaves only
# whatever was flushed. This module keeps a bounded per-process RING BUFFER of
# the most recent telemetry transitions — span opens/closes, structured events
# (retry/fault/degrade/cache_evict), HBM samples — cheap enough to be always on
# (`observability.flight_recorder_events`, default 256; <=0 disables).
#
# On an unhandled fit/transform failure (FitRun.__exit__ with an exception) or
# on ENTRY into the degradation ladder (core/estimator.py's degrade rungs), the
# ring dumps as an atomic postmortem bundle next to the JSONL reports:
#
#   <metrics_dir>/postmortem_<run_id>.json
#     { schema, ts, reason, run_id, kind, algo, process, ring: [...],
#       open_spans: [...], config: {...}, device: {...} }
#
# PR 1's deterministic fault sites make the dump path testable end to end: an
# injected DeviceError at `ingest` drives the device→CPU rung and the resulting
# bundle must contain both the `fault` and `degrade` ring entries (ci/test.sh
# live-telemetry smoke). Writes are tmp-file + os.replace, so a concurrent
# reader only ever sees a whole bundle.
#

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Mapping, Optional

from .. import config as _config
from ..utils import get_logger

_logger = get_logger("observability.flight")

_lock = threading.Lock()
_ring: Optional[deque] = None
_ring_cap = -1  # cap the current ring was built with (rebuilt when config moves)
_dropped = 0  # entries evicted by the bound since the last reset (diagnostic)


def _capacity() -> int:
    try:
        return int(_config.get("observability.flight_recorder_events"))
    except (TypeError, ValueError):
        return 0


def _buffer() -> Optional[deque]:
    """The live ring, rebuilt if the configured capacity changed; None when the
    recorder is disabled (cap <= 0)."""
    global _ring, _ring_cap
    cap = _capacity()
    if cap <= 0:
        return None
    if _ring is None or _ring_cap != cap:
        old = list(_ring) if _ring is not None else []
        _ring = deque(old[-cap:], maxlen=cap)
        _ring_cap = cap
    return _ring


def enabled() -> bool:
    return _capacity() > 0


def _append(entry: Dict[str, Any]) -> None:
    """The one ring-append path (lock, disabled-check, bound accounting) —
    both the envelope-building note() and the pass-through note_event() go
    through here so the accounting can never diverge between them."""
    with _lock:
        ring = _buffer()
        if ring is None:
            return
        global _dropped
        if len(ring) == ring.maxlen:
            _dropped += 1
        ring.append(entry)


def note(kind: str, **fields: Any) -> None:
    """Append one transition to the ring. Must stay cheap (it sits on every
    span open/close) and must never raise."""
    _append({"ts": round(time.time(), 6), "kind": kind, **fields})


def note_span_open(node: Any) -> None:
    note("span_open", span_id=node.span_id, name=node.name,
         thread=node.thread)


def note_span_close(node: Any) -> None:
    note("span_close", span_id=node.span_id, name=node.name,
         duration_s=node.duration_s, status=node.status)


def note_event(entry: Mapping[str, Any]) -> None:
    """Mirror a structured run event into the ring. The entry keeps its own
    kind (`retry`/`fault`/`degrade`/`cache_evict`/...) — those ARE the
    transitions a postmortem reader greps for."""
    _append(dict(entry))


def note_hbm(total_bytes: int) -> None:
    note("hbm_sample", bytes_in_use=int(total_bytes))


def snapshot() -> List[Dict[str, Any]]:
    """Copy of the ring, oldest first."""
    with _lock:
        ring = _buffer()
        return [dict(e) for e in ring] if ring is not None else []


def reset_flight_recorder() -> None:
    """Drop all recorded transitions (tests / long-lived workers)."""
    global _ring, _ring_cap, _dropped
    with _lock:
        _ring = None
        _ring_cap = -1
        _dropped = 0


def _config_snapshot() -> Dict[str, Any]:
    """config.all(), coerced to JSON-safe values (every key is a primitive
    today; the str() fallback keeps a future exotic value from killing a dump
    that exists precisely to debug failures)."""
    out: Dict[str, Any] = {}
    for k, v in _config.all().items():
        out[k] = v if isinstance(v, (type(None), bool, int, float, str)) else str(v)
    return out


def dump_postmortem(run: Any = None, reason: str = "failure",
                    metrics_dir: Optional[str] = None) -> Optional[str]:
    """Write the postmortem bundle for `run` (an open or just-failed
    Fit/TransformRun; None dumps a process-scoped bundle). Returns the path, or
    None when no metrics dir is configured / the recorder is disabled. Never
    raises — this runs on failure paths that must keep propagating the ORIGINAL
    error."""
    try:
        if metrics_dir is None:
            metrics_dir = _config.get("observability.metrics_dir")
        if not metrics_dir or not enabled():
            return None
        from . import device as _device
        from . import runs as _runs
        from .export import _json_fallback

        open_spans = [n.as_dict() for n in _runs._span_stack()]
        run_id = getattr(run, "run_id", None) or "process"
        with _lock:
            dropped = _dropped
        bundle = {
            "schema": 1,
            "ts": round(time.time(), 6),
            "reason": reason,
            "run_id": run_id,
            "kind": getattr(run, "kind", None),
            "algo": getattr(run, "algo", None),
            "process": _runs.PROCESS_TOKEN,
            "ring": snapshot(),
            "ring_dropped": dropped,
            "open_spans": open_spans,
            "progress": (
                run.progress_snapshot() if hasattr(run, "progress_snapshot")
                else {}
            ),
            "config": _config_snapshot(),
        }
        device_section = _device.device_report_section(
            getattr(run, "registry", None)
        )
        if device_section:
            bundle["device"] = device_section
        # tail-sampled trace ring (§6l): the requests that died WITH the
        # process — error/hedged/failed-over/slowest traces — ride along so a
        # postmortem reader can walk causality without a live /traces endpoint
        from .tracing import ring_snapshot

        traces = ring_snapshot()
        if traces:
            bundle["traces"] = traces
        # per-rank barrier timeline (§6h): a degraded/failed barrier fit's
        # postmortem must show WHICH rank was slow, not just that one was
        if hasattr(run, "rank_view"):
            try:
                ranks = run.rank_view()
            except Exception as e:
                _logger.warning("postmortem rank timeline failed: %s", e)
                ranks = None
            if ranks and ranks.get("ranks"):
                bundle["ranks"] = ranks
        os.makedirs(metrics_dir, exist_ok=True)
        safe_id = "".join(c if c.isalnum() or c in "-_." else "_" for c in run_id)
        path = os.path.join(metrics_dir, f"postmortem_{safe_id}.json")
        fd, tmp = tempfile.mkstemp(dir=metrics_dir, prefix=".postmortem_")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(bundle, f, default=_json_fallback)
            os.replace(tmp, path)  # last dump wins: later rungs carry more ring
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _runs.counter_inc("observability.postmortems", 1, reason=reason)
        _logger.warning("wrote postmortem bundle (%s) to %s", reason, path)
        return path
    except Exception as e:
        _logger.warning("postmortem dump failed: %s: %s", type(e).__name__, e)
        return None


def load_postmortem(path: str) -> Dict[str, Any]:
    """Round-trip helper for tests/CI: parse one postmortem bundle."""
    with open(path) as f:
        return json.load(f)
