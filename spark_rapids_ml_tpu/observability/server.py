#
# Live telemetry endpoint — the pull half of the live telemetry plane
# (docs/design.md §6g).
#
# §6d's exporters are PUSH-at-close: a 30-minute streamed fit is a black box
# until its JSONL line lands. This module adds the standard operational
# contract a long-running ML service is expected to honor (MLlib-style
# production deployments, arXiv:1505.06807; the Podracer architectures'
# decoupled monitor-while-computing split, arXiv:2104.06272): a driver-resident
# HTTP endpoint on a stdlib `http.server` daemon thread serving
#
#   /metrics         Prometheus text exposition of the LIVE global registry
#   /healthz         JSON liveness (process token, uptime, open-run count)
#   /runs            JSON index of currently-open Fit/Transform runs
#   /runs/<run_id>   live view of one open run: open-span stack, progress
#                    gauges (pass k/K, batches, ETA), convergence tail, event
#                    tail, full metrics snapshot
#   /runs/<run_id>/ranks  the barrier timeline (docs/design.md §6h): per-rank
#                    start/end per phase, rows/bytes, skew ratios, straggler
#                    flags — assembled from merged worker snapshots mid-run
#
# Opt-in and leak-free by construction: with `observability.http_port` unset
# (`SRML_TPU_METRICS_PORT`) no thread is EVER started. When set, the server is
# reference-counted against open run scopes — FitRun.__enter__ acquires,
# __exit__ releases, and the socket closes with the last release — so a fit
# that returns leaves zero threads and zero sockets behind. A serving process
# that wants the endpoint across fits pins it with `start_metrics_server()` /
# `stop_metrics_server()`. Port 0 binds an ephemeral port; `server_address()`
# exposes the bound (host, port).
#

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Tuple

from .. import config as _config
from ..utils import get_logger

_logger = get_logger("observability.server")

_lock = threading.RLock()
_server: Optional["TelemetryServer"] = None
_refs = 0  # open run scopes holding the server up
_pinned = False  # start_metrics_server() keeps it up across runs

# path-prefix mounts: other driver-resident planes (the serving plane's
# /v1/... inference endpoints, serving/http.py) attach their handlers HERE
# instead of starting a second HTTP server — one socket, one refcounted
# lifecycle, zero threads when nothing is enabled. A mount handler takes
# (method, path, body_bytes_or_None) and returns (status_code, json_doc) or
# (status_code, json_doc, headers_dict) — the 3-tuple form lets a mount set
# response headers (the serving plane's `Retry-After` on 429/503 shedding).
_mounts: dict = {}

# /healthz providers: subsystems with their own liveness story (the serving
# fleet's per-replica health states) contribute a named section to the
# /healthz document, so one probe answers both "is the process up" and "who
# is actually serving". A provider is a zero-arg callable returning a
# JSON-serializable doc; a provider that raises reports its error in place
# (liveness probes must never 500 because one subsystem is sick).
_health_providers: dict = {}

# bound on POST bodies a mount can receive (a predict batch of feature rows
# is comfortably under this; an unbounded read is a trivial memory DoS)
_MAX_BODY_BYTES = 64 << 20


def register_mount(prefix: str, handler: Any) -> None:
    """Attach `handler` for every request whose path starts with `prefix`.
    Longest matching prefix wins when mounts nest."""
    with _lock:
        _mounts[str(prefix)] = handler


def unregister_mount(prefix: str) -> None:
    with _lock:
        _mounts.pop(str(prefix), None)


def register_health_provider(name: str, provider: Any) -> None:
    """Contribute a named section to the /healthz document (e.g. the serving
    fleet's per-replica health view). Re-registering a name replaces it."""
    with _lock:
        _health_providers[str(name)] = provider


def unregister_health_provider(name: str) -> None:
    with _lock:
        _health_providers.pop(str(name), None)


def _health_sections() -> dict:
    with _lock:
        providers = dict(_health_providers)
    out = {}
    for name, provider in providers.items():
        try:
            out[name] = provider()
        except Exception as e:  # a sick subsystem must not break liveness
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _find_mount(path: str):
    with _lock:
        best = None
        for prefix, handler in _mounts.items():
            if path.startswith(prefix) and (
                best is None or len(prefix) > len(best[0])
            ):
                best = (prefix, handler)
        return best[1] if best else None


def _configured_port() -> Optional[int]:
    port = _config.get("observability.http_port")
    if port is None or port == "":
        return None
    try:
        return int(port)
    except (TypeError, ValueError):
        _logger.warning("invalid observability.http_port %r; endpoint disabled",
                        port)
        return None


class _Handler(BaseHTTPRequestHandler):
    """Request handler; every response is built from a snapshot taken under the
    source's own locks, so a scrape can never observe torn state."""

    server_version = "srml-tpu-telemetry/1"

    # stdlib logs every request to stderr by default — a 1 s scrape interval
    # would drown real diagnostics
    def log_message(self, format: str, *args: Any) -> None:  # BaseHTTPRequestHandler contract
        pass

    def _send(self, code: int, body: bytes, content_type: str,
              headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(str(k), str(v))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-write; nothing to clean up

    def _send_json(self, doc: Any, code: int = 200,
                   headers: Optional[dict] = None) -> None:
        from .export import _json_fallback

        body = json.dumps(doc, default=_json_fallback).encode()
        self._send(code, body, "application/json", headers=headers)

    def _dispatch_mount(self, method: str, path: str,
                        body: Optional[bytes]) -> bool:
        """Route to a registered path-prefix mount (the serving plane's /v1/
        endpoints). Returns False when no mount claims the path. A mount may
        return (code, doc) or (code, doc, headers) — the latter carries
        response headers like the shed path's `Retry-After`. Mounts that
        accept a 4th argument get the request headers (the serving plane
        reads `traceparent` there); older 3-arg mounts keep working."""
        handler = _find_mount(path)
        if handler is None:
            return False
        try:
            result = handler(method, path, body, dict(self.headers.items()))
        except TypeError:
            result = handler(method, path, body)
        if len(result) == 3:
            code, doc, headers = result
        else:
            code, doc = result
            headers = None
        self._send_json(doc, int(code), headers=headers)
        return True

    def _read_body(self) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            length = 0
        if length <= 0 or length > _MAX_BODY_BYTES:
            return None
        return self.rfile.read(length)

    def do_POST(self) -> None:  # BaseHTTPRequestHandler contract name
        try:
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            body = self._read_body()
            if not self._dispatch_mount("POST", path, body):
                self._send_json(
                    {"error": "unknown path",
                     "mounts": sorted(_mounts)}, 404,
                )
        except Exception as e:
            try:
                self._send_json({"error": f"{type(e).__name__}: {e}"}, 500)
            except Exception:  # noqa: fence/silent-except — socket already gone
                pass

    def do_GET(self) -> None:  # BaseHTTPRequestHandler contract name
        try:
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if self._dispatch_mount("GET", path, None):
                return
            if path == "/metrics":
                from .export import render_prometheus
                from .runs import global_registry

                text = render_prometheus(global_registry().snapshot())
                self._send(200, text.encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                from .runs import PROCESS_TOKEN, active_runs

                doc = {
                    "status": "ok",
                    "process": PROCESS_TOKEN,
                    "uptime_s": round(
                        time.monotonic() - self.server.started_monotonic, 3
                    ),
                    "open_runs": len(active_runs()),
                }
                doc.update(_health_sections())
                self._send_json(doc)
            elif path == "/runs":
                from .runs import active_runs

                self._send_json({
                    "runs": [r.live_view(summary=True) for r in active_runs()]
                })
            elif path.startswith("/runs/") and path.endswith("/ranks"):
                # barrier timeline (§6h): per-rank start/end per phase, skew
                # ratios and straggler flags from the run's merged snapshots
                from .runs import find_run

                rid = path[len("/runs/"): -len("/ranks")]
                run = find_run(rid)
                if run is None:
                    self._send_json({"error": "no open run with that id"}, 404)
                else:
                    self._send_json(dict(run.rank_view(), run_id=run.run_id))
            elif path.startswith("/runs/"):
                from .runs import find_run

                run = find_run(path[len("/runs/"):])
                if run is None:
                    self._send_json({"error": "no open run with that id"}, 404)
                else:
                    self._send_json(run.live_view())
            elif path == "/traces":
                from .tracing import trace_index

                self._send_json({"traces": trace_index()})
            elif path.startswith("/traces/"):
                from .tracing import get_trace

                doc = get_trace(path[len("/traces/"):])
                if doc is None:
                    self._send_json(
                        {"error": "no retained trace with that id "
                                  "(dropped by sampling, evicted from the "
                                  "ring, or never minted)"}, 404)
                else:
                    self._send_json(doc)
            else:
                self._send_json({"error": "unknown path", "paths": [
                    "/metrics", "/healthz", "/runs", "/runs/<run_id>",
                    "/runs/<run_id>/ranks", "/traces", "/traces/<trace_id>"
                ], "mounts": sorted(_mounts)}, 404)
        except Exception as e:
            # a scrape must never take the process down; report the error to
            # the scraper instead
            try:
                self._send_json(
                    {"error": f"{type(e).__name__}: {e}"}, 500
                )
            except Exception:  # noqa: fence/silent-except — socket already gone
                pass


class TelemetryServer:
    """One HTTP endpoint instance: a ThreadingHTTPServer (daemon worker
    threads) pumped by a single daemon serve_forever thread."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.started_monotonic = time.monotonic()
        # tight poll: shutdown() blocks until serve_forever notices, so the
        # poll interval IS the per-fit close latency for refcounted servers —
        # 5 ms keeps endpoint churn invisible next to any real fit
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.005},
            name="srml-telemetry-server",
            daemon=True,
        )
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    def close(self) -> None:
        """Stop serving and release the socket; joins the pump thread so a
        caller observing close() done observes the thread gone too."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# ------------------------------------------------------------ lifecycle (refs)


def _acquire() -> Optional["TelemetryServer"]:
    global _server
    port = _configured_port()
    if port is None:
        return None
    # loopback by default: the endpoint is unauthenticated, so serving beyond
    # the driver host ("0.0.0.0") is an explicit operator decision
    host = str(_config.get("observability.http_host") or "127.0.0.1")
    with _lock:
        if _server is None:
            try:
                _server = TelemetryServer(port, host=host)
                _logger.info("telemetry endpoint listening on port %d",
                             _server.port)
            except OSError as e:
                _logger.warning(
                    "could not bind telemetry endpoint on %s:%d: %s",
                    host, port, e,
                )
                return None
        return _server


def _release_if_unused() -> None:
    global _server
    with _lock:
        if _server is not None and _refs <= 0 and not _pinned:
            srv, _server = _server, None
            srv.close()


def on_run_start(run: Any) -> None:
    """FitRun.__enter__ hook: hold the endpoint up while the run is open.
    No-ops (and starts nothing) when observability.http_port is unset. The
    acquisition is recorded ON the run so on_run_end releases exactly the
    references this run took — a run that opened before the port was
    configured (or after it was unset) must not release another run's hold."""
    global _refs
    if _configured_port() is None:
        return
    with _lock:
        _refs += 1
    run._telemetry_ref = True
    _acquire()


def on_run_end(run: Any) -> None:
    """FitRun.__exit__ hook: release iff this run acquired; the last release
    closes the socket."""
    global _refs
    if not getattr(run, "_telemetry_ref", False):
        return
    run._telemetry_ref = False
    with _lock:
        if _refs > 0:
            _refs -= 1
    _release_if_unused()


def start_metrics_server(port: Optional[int] = None) -> Optional[Tuple[str, int]]:
    """Pin the endpoint up independently of run scopes (serving processes).
    `port` overrides `observability.http_port` for this process. Returns the
    bound (host, port), or None when no port is configured/bindable."""
    global _pinned
    if port is not None:
        _config.set("observability.http_port", int(port))
    # pin BEFORE acquiring: a run ending concurrently between _acquire() and a
    # later pin would see refs==0 / pinned==False and close the socket we are
    # about to hand back
    with _lock:
        _pinned = True
    srv = _acquire()
    if srv is None:
        with _lock:
            _pinned = False
        return None
    if port not in (None, 0) and srv.port != port:
        # an earlier hold (open run or pin) already bound a different port;
        # rebinding now would yank the socket from under its scrapers, so the
        # existing address wins — the requested port takes effect only once
        # every hold releases and a later acquire rebinds from config
        _logger.warning(
            "telemetry endpoint already bound on port %d; requested port %d "
            "takes effect after the current endpoint closes", srv.port, port,
        )
    return srv.address


def stop_metrics_server() -> None:
    """Unpin and close the endpoint unless open runs still hold it."""
    global _pinned
    with _lock:
        _pinned = False
    _release_if_unused()


def server_address() -> Optional[Tuple[str, int]]:
    """The live endpoint's (host, port), or None when not running."""
    with _lock:
        return _server.address if _server is not None else None


def _reset_for_tests() -> None:
    """Force-close regardless of refcounts (test teardown)."""
    global _server, _refs, _pinned
    with _lock:
        srv, _server = _server, None
        _refs = 0
        _pinned = False
        _mounts.clear()
        _health_providers.clear()
    if srv is not None:
        srv.close()
