#
# Device-performance plane: XLA cost-analysis roofline attribution, HBM
# telemetry, and compile accounting (docs/design.md §6f).
#
# PRs 3-4 made fit and transform LOGICALLY observable (metrics, trace trees,
# recompile sentinel); the system stayed blind at the device level — BENCH_r03's
# est_mfu ≈ 4.6% came from a hand-rolled analytic flop count, and ROADMAP item 3
# makes "MFU/roofline fraction in bench JSON" the success metric for the Pallas
# arc. Three things live here:
#
#   * compiled_kernel — the one choke point for every jitted kernel the library
#     compiles. It wraps jax.jit with an AOT lower().compile() cache keyed by
#     (kernel name, shape/dtype/sharding signature, static values): each NEW
#     signature is compiled exactly once with its wall time recorded
#     (`device.compile_s{kernel=}`), and the compiled executable's
#     cost_analysis() (flops, bytes accessed, transcendentals) and
#     memory_analysis() (argument/output/temp bytes) are captured per
#     executable. Calls then run the cached executable directly and ATTRIBUTE
#     the analyzed flops/bytes to the innermost open trace span, so FitRun /
#     TransformRun span nodes carry real device work, not just wall time.
#     Degrades to the plain jitted call under tracing (vmap/grad/nested jit),
#     on any AOT API failure, or when `observability.device_enabled` is off.
#
#   * HBM telemetry — `local_devices()[*].memory_stats()` sampled at span
#     boundaries (rate-limited) into the `device.hbm_bytes_in_use` gauge plus a
#     per-run `device.hbm_peak_bytes` gauge, cross-checkable against the batch
#     cache's `cache.bytes_resident`. Platforms without memory_stats (CPU,
#     older runtimes) are detected ONCE and the gauges are simply absent — no
#     warning spam.
#
#   * Roofline attribution — analyzed flops/bytes combined with measured span
#     wall time against a per-platform peak table (overridable via
#     `observability.peak_flops` / `observability.peak_bw`) yields achieved
#     FLOP/s, MFU, roofline fraction and a compute-/memory-bound
#     classification per span and per bench scenario (bench.py replaces its
#     analytic `est_mfu` with the measured `mfu` from here, gated
#     direction-aware by ci/bench_check.py).
#
# Accuracy caveats, by construction: jax dispatch is asynchronous, so span
# wall time bounds dispatch+compile on accelerators (an MFU computed from it is
# a lower bound when the caller did not sync); XLA's HLO cost analysis counts a
# dynamic-trip-count while_loop body ONCE, so whole-fit programs (lloyd_fit)
# under-report flops vs per-pass streamed kernels. Both biases are stable
# across rounds, which is what the direction-aware bench gate needs.
#
# The opt-in `observability.profile_dir` hook captures ONE jax.profiler trace
# for the designated pass (`observability.profile_pass`, default 2 — the first
# post-compile steady-state pass) of a streamed fit, once per process per site.
#
# The analyzer (fence/device-analysis-off-plane) bans direct `.cost_analysis()` /
# `.memory_stats()` calls outside this module so the capture contract (and its
# graceful-degrade guarantees) cannot be bypassed.
#

from __future__ import annotations

import contextlib
import functools
import inspect
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Tuple

import weakref

from .. import config as _config
from ..utils import get_logger
from . import runs as _runs

_logger = get_logger("observability.device")

_lock = threading.RLock()

_comm_mod = None


def _comm():
    """Lazy communication-plane import (observability/comm.py imports this
    module lazily for the ICI peak; the reverse edge resolves at call time —
    the same cycle-breaking as runs._device)."""
    global _comm_mod
    if _comm_mod is None:
        from . import comm as cm

        _comm_mod = cm
    return _comm_mod

# every live CompiledKernel, so reset_device_plane can drop executable caches
# (tests; a stale cache would report zero compiles for work a fresh process
# would have compiled)
_kernels: "weakref.WeakSet[CompiledKernel]" = weakref.WeakSet()

# (kernel name, signature key) -> cost record dict; process-global like the
# shape-bucket registry (inference.py) — executables are process-global too
_records: Dict[Tuple[str, Any], Dict[str, Any]] = {}

# membership cap mirroring inference._MAX_TRACKED_SIGS: a fully-ragged caller
# must not grow the record table forever (each unseen signature still counts
# its compile; it just stops being remembered)
_MAX_RECORDS = 4096

# monotone process-wide compile count: unlike len(_records) (capped, clearable
# per test) this NEVER decreases, so a before/after delta is a reliable
# "did the warm path compile anything?" probe (trace plane compile-vs-cached)
_compiles_total = 0

# memory_stats support: None = unknown, False = probed and absent (never
# re-probed, never warned — the graceful-degrade contract), True = live
_hbm_supported: Optional[bool] = None
_hbm_last_sample = 0.0
# consecutive EXCEPTIONS from the probe (distinct from a clean "no stats"
# verdict): transient backend-init errors retry; persistent ones give up
_hbm_probe_errors = 0
_HBM_MAX_PROBE_ERRORS = 3

# per-run HBM peaks, keyed by run_id while the run is open
_run_peaks: Dict[str, int] = {}

# profiler hook: sites already captured this process (one trace per site)
_profiled_sites: set = set()

_errors_logged: set = set()

# per-platform peak table: device_kind substring (lowercase, first match wins)
# -> (peak FLOP/s per chip at parity/f32-equivalent precision, HBM bytes/s per
# chip, ICI/interconnect bytes/s per chip — the comm-plane roofline column,
# docs/design.md §6h). TPU compute/HBM rows follow published chip specs (bf16
# peak halved for the f32-equivalent MXU rate the parity kernels run at); ICI
# rows are published per-chip interchip-interconnect totals; the cpu/gpu rows
# are order-of-magnitude placeholders that make mfu/roofline/comm keys PRESENT
# and comparable across rounds — absolute truth on those backends comes from
# the `observability.peak_flops` / `observability.peak_bw` /
# `observability.peak_ici_bw` overrides.
_PEAK_TABLE: Tuple[Tuple[str, Tuple[float, float, float]], ...] = (
    ("v5 lite", (98e12, 819e9, 200e9)),
    ("v5e", (98e12, 819e9, 200e9)),
    ("v5p", (229e12, 2765e9, 600e9)),
    ("v6", (459e12, 1640e9, 448e9)),
    ("v4", (137e12, 1228e9, 300e9)),
    ("v3", (61e12, 900e9, 100e9)),
    ("tpu", (98e12, 819e9, 200e9)),
    ("gpu", (19.5e12, 1555e9, 600e9)),
    ("cpu", (2e11, 5e10, 1e10)),
)

_peaks_cache: Optional[Tuple[float, float, float, str]] = None


def _enabled() -> bool:
    return bool(_config.get("observability.device_enabled"))


def _log_once(key: str, msg: str, *args: Any) -> None:
    with _lock:
        if key in _errors_logged:
            return
        _errors_logged.add(key)
    _logger.warning(msg, *args)


def reset_device_plane() -> None:
    """Clear all process-global device-plane state (tests)."""
    global _hbm_supported, _hbm_last_sample, _peaks_cache, _hbm_probe_errors
    with _lock:
        _records.clear()
        _run_peaks.clear()
        _profiled_sites.clear()
        _errors_logged.clear()
        _hbm_supported = None
        _hbm_last_sample = 0.0
        _hbm_probe_errors = 0
        _peaks_cache = None
        _sharding_reprs.clear()
        for kernel in list(_kernels):
            kernel._cache.clear()


# ------------------------------------------------------------------ peak table


def _platform_row() -> Tuple[float, float, float, str]:
    """(peak_flops, peak_bw, peak_ici_bw, platform) of the local device kind —
    the raw table row (cached), before any config override."""
    global _peaks_cache
    with _lock:
        cached = _peaks_cache
    if cached is None:
        platform, kind = "unknown", ""
        if "jax" in sys.modules:
            try:
                import jax

                dev = jax.local_devices()[0]
                platform = str(dev.platform)
                kind = str(getattr(dev, "device_kind", "") or "")
            except Exception as e:
                _log_once("peaks", "device probe for peak table failed: %s", e)
        flops, bw, ici = 2e11, 5e10, 1e10  # unknown-platform fallback = cpu row
        hay = f"{kind} {platform}".lower()
        for key, (f, b, i) in _PEAK_TABLE:
            if key in hay:
                flops, bw, ici = f, b, i
                break
        cached = (flops, bw, ici, platform)
        with _lock:
            _peaks_cache = cached
    return cached


def platform_peaks() -> Tuple[float, float, str]:
    """(peak_flops_per_chip, peak_bw_per_chip, platform). Config overrides win;
    otherwise the first _PEAK_TABLE row whose key substring-matches the local
    device kind (then platform)."""
    over_f = float(_config.get("observability.peak_flops") or 0.0)
    over_b = float(_config.get("observability.peak_bw") or 0.0)
    flops, bw, _, platform = _platform_row()
    return (over_f or flops, over_b or bw, platform)


def platform_ici_bw() -> float:
    """Per-chip ICI/interconnect peak bytes/s — the comm-plane roofline column
    (docs/design.md §6h). `observability.peak_ici_bw` overrides the table."""
    over = float(_config.get("observability.peak_ici_bw") or 0.0)
    return over or _platform_row()[2]


def _classify(flops: float, bytes_accessed: float,
              peaks: Tuple[float, float, str]) -> Dict[str, Any]:
    """Roofline classification from analyzed totals: operational intensity vs
    the ridge point of the platform roof."""
    peak_flops, peak_bw, _ = peaks
    ridge = peak_flops / peak_bw if peak_bw > 0 else 0.0
    oi = (flops / bytes_accessed) if bytes_accessed > 0 else None
    bound = "compute" if (oi is not None and oi >= ridge) else "memory"
    ceiling = peak_flops if oi is None else min(peak_flops, oi * peak_bw)
    return {"operational_intensity": oi, "roofline_bound": bound,
            "ceiling_flops_per_s": ceiling}


# ------------------------------------------------------------- compiled_kernel


# repr(sharding) is the expensive part of per-call signature capture, and
# sharding objects are shared across arrays/calls: cache reprs by identity.
# Values keep the sharding object ALIVE so a recycled id() can never alias a
# different sharding to a stale repr (bounded; a few thousand tiny objects).
_sharding_reprs: Dict[int, Tuple[Any, str]] = {}
_MAX_SHARDING_REPRS = 4096


def _sharding_key(x: Any) -> str:
    sh = getattr(x, "sharding", None)
    if sh is None:
        return "host"
    cached = _sharding_reprs.get(id(sh))
    if cached is not None and cached[0] is sh:
        return cached[1]
    try:
        r = repr(sh)
    except Exception:
        r = "?"
    if len(_sharding_reprs) < _MAX_SHARDING_REPRS:
        _sharding_reprs[id(sh)] = (sh, r)
    return r


def _leaf_key(x: Any) -> Tuple[Any, ...]:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype), _sharding_key(x))
    if isinstance(x, (bool, int, float, complex)):
        # python scalars are weak-typed dynamic args: one compile per TYPE,
        # never per value (keying on the value would manufacture a compile
        # storm jit itself does not have)
        return ("s", type(x).__name__)
    return ("o", type(x).__name__, repr(x)[:200])


class CompiledKernel:
    """Instrumented drop-in for a jitted kernel (see module header). The
    wrapped callable preserves jit semantics — same args, statics, donation —
    while owning the AOT executable cache and the cost capture."""

    def __init__(self, name: str, fn: Callable, jit_kwargs: Dict[str, Any]):
        self.name = name
        self._fn = fn
        self._jit = self._make_jit(fn, jit_kwargs)
        self._cache: Dict[Any, Dict[str, Any]] = {}
        self._klock = threading.RLock()
        static_argnums = jit_kwargs.get("static_argnums") or ()
        static_argnames = jit_kwargs.get("static_argnames") or ()
        if isinstance(static_argnums, int):
            static_argnums = (static_argnums,)
        if isinstance(static_argnames, str):
            static_argnames = (static_argnames,)
        try:
            self._sig_obj: Optional[inspect.Signature] = inspect.signature(fn)
            params = list(self._sig_obj.parameters)
            self._params_list = list(self._sig_obj.parameters.values())
            if any(
                p.kind not in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                for p in self._params_list
            ):
                # *args/**kwargs/keyword-only defy canonical positional form
                self._sig_obj = None
                self._params_list = []
        except (TypeError, ValueError):
            self._sig_obj = None
            self._params_list = []
        self._static_idx = set(int(i) for i in static_argnums)
        for nm in static_argnames:
            if nm in params:
                self._static_idx.add(params.index(nm))
        self._static_names = set(static_argnames) | {
            params[i] for i in self._static_idx if i < len(params)
        }
        functools.update_wrapper(self, fn)
        _kernels.add(self)

    @staticmethod
    def _make_jit(fn: Callable, jit_kwargs: Dict[str, Any]):
        import jax

        return jax.jit(fn, **jit_kwargs)

    @property
    def jitted(self):
        """The underlying jax.jit-wrapped function (AOT helpers, tests)."""
        return self._jit

    def __reduce__(self):
        # pickle BY REFERENCE (module attribute lookup), never by value: the
        # executable cache and the PjitFunction inside are not picklable, and
        # a shipped copy would be the wrong object anyway — barrier/UDF
        # closures must resolve to the worker process's own kernel
        return (_resolve_kernel, (self.__module__, self.__qualname__))

    def lower(self, *args: Any, **kwargs: Any):
        return self._jit.lower(*args, **kwargs)

    # ---- signature ----

    def _canon_positional(self, args):
        """Fast path for fully-positional calls — the hot-kernel call shape;
        skips inspect.Signature.bind on every streamed-batch invocation.
        Semantics identical to _canonicalize with empty kwargs."""
        ps = self._params_list
        if len(args) > len(ps):
            return None
        tail = []
        for p in ps[len(args):]:
            if (
                p.name in self._static_names
                and p.default is not inspect.Parameter.empty
            ):
                tail.append(p.default)
            else:
                break  # omitted DYNAMIC default: must stay omitted (baked)
        norm = tuple(args) + tuple(tail)
        statics_key = tuple(
            (ps[i].name, repr(norm[i]))
            for i in sorted(self._static_idx)
            if i < len(norm)
        )
        for p in ps[len(norm):]:
            if (
                p.name in self._static_names
                and p.default is not inspect.Parameter.empty
            ):
                statics_key += ((p.name, repr(p.default)),)
        return norm, statics_key

    def _canonicalize(self, args, kwargs):
        """Normalize a call to ONE positional form so call style (positional
        vs keyword vs omitted-default statics) cannot split the executable
        cache: `predict(X, C)` and `predict(X, C, cosine=False)` must be one
        signature, one compile. Returns (norm_args, statics_key), or None for
        call shapes that defy the canonical positional form (gaps after an
        omitted dynamic default, *args/**kwargs/keyword-only params) — those
        fall back to the style-sensitive split."""
        sig = self._sig_obj
        if sig is None:
            return None
        if not kwargs:
            return self._canon_positional(args)
        try:
            bound = sig.bind(*args, **kwargs)
        except TypeError:
            return None
        arguments = bound.arguments
        norm: List[Any] = []
        seen = set()
        for p in sig.parameters.values():
            if p.kind not in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
                return None
            if p.name in arguments:
                norm.append(arguments[p.name])
                seen.add(p.name)
            elif (
                p.name in self._static_names
                and p.default is not inspect.Parameter.empty
            ):
                # statics are compile-time values: applying the default here
                # is exactly what jit's signature binding does
                norm.append(p.default)
                seen.add(p.name)
            else:
                break  # omitted DYNAMIC default: must stay omitted (baked)
        if any(name not in seen for name in arguments):
            return None
        statics_key = tuple(
            (p.name, repr(arguments.get(p.name, p.default)))
            for p in sig.parameters.values()
            if p.name in self._static_names
            and (
                p.name in arguments
                or p.default is not inspect.Parameter.empty
            )
        )
        return tuple(norm), statics_key

    def _split(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]):
        dyn_args = tuple(
            a for i, a in enumerate(args) if i not in self._static_idx
        )
        dyn_kwargs = {
            k: v for k, v in kwargs.items() if k not in self._static_names
        }
        statics = tuple(
            (f"@{i}", repr(args[i]))
            for i in sorted(self._static_idx)
            if i < len(args)
        ) + tuple(
            (k, repr(v))
            for k, v in sorted(kwargs.items())
            if k in self._static_names
        )
        return dyn_args, dyn_kwargs, statics

    def _signature(self, dyn_args, dyn_kwargs, statics):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            return None  # under trace: inline through the plain jit path
        # trace-affecting config rides in the signature (the trace epoch):
        # a kernel body that reads one of these keys at trace time can never
        # serve a STALE bake — changing the key re-keys the AOT cache and
        # _compile_and_capture re-lowers (lower() always re-traces), reading
        # the new value. This is what licenses the one sanctioned trace-time
        # config read (ops/_precision.py::parity_precision).
        return (tuple(_leaf_key(l) for l in leaves), treedef,
                statics + _trace_epoch())

    # ---- compile + capture ----

    def _compile_and_capture(self, sig, args, kwargs) -> Dict[str, Any]:
        t0 = time.perf_counter()
        lowered = self._jit.lower(*args, **kwargs)
        exe = lowered.compile()
        compile_s = time.perf_counter() - t0
        cost = _extract_cost(exe, lowered)
        record = {
            "kernel": self.name,
            "signature": _sig_str(sig),
            "compile_s": round(compile_s, 6),
            "calls": 0,
            **cost,
        }
        # communication plane (§6h): walk the compiled module's HLO ONCE per
        # signature for collective ops/bytes/replica-groups; None (no HLO
        # surface on this runtime) just means no collective accounting
        try:
            collectives = _comm().collectives_from_executable(exe)
        except Exception as e:
            _log_once(f"comm:{self.name}",
                      "kernel %s: collective extraction failed (%s)",
                      self.name, e)
            collectives = None
        if collectives:
            record["collectives"] = collectives
        global _compiles_total
        with _lock:
            if len(_records) < _MAX_RECORDS:
                _records[(self.name, sig)] = record
            _compiles_total += 1
        _runs.counter_inc("device.compile", 1, kernel=self.name)
        _runs.observe("device.compile_s", compile_s, kernel=self.name)
        if not cost.get("analyzed", False):
            _runs.counter_inc("device.analysis_unavailable", 1, kernel=self.name)
        return {"exe": exe, "record": record}

    def __call__(self, *args: Any, **kwargs: Any):
        if not _enabled():
            return self._jit(*args, **kwargs)
        try:
            canon = self._canonicalize(args, kwargs)
            if canon is not None:
                call_args, statics = canon
                call_kwargs: Dict[str, Any] = {}
                dyn_args = tuple(
                    a for i, a in enumerate(call_args)
                    if i not in self._static_idx
                )
                dyn_kwargs: Dict[str, Any] = {}
            else:
                call_args, call_kwargs = args, kwargs
                dyn_args, dyn_kwargs, statics = self._split(args, kwargs)
            sig = self._signature(dyn_args, dyn_kwargs, statics)
        except Exception as e:
            _log_once(f"sig:{self.name}",
                      "kernel %s: signature capture failed (%s); "
                      "running uninstrumented", self.name, e)
            sig = None
        if sig is None:
            return self._jit(*args, **kwargs)
        entry = self._cache.get(sig)
        if entry is None:
            with self._klock:
                entry = self._cache.get(sig)
                if entry is None:
                    try:
                        entry = self._compile_and_capture(
                            sig, call_args, call_kwargs
                        )
                    except Exception as e:
                        _log_once(f"aot:{self.name}",
                                  "kernel %s: AOT compile/capture failed (%s); "
                                  "falling back to plain jit", self.name, e)
                        entry = {"exe": None, "record": None}
                    self._cache[sig] = entry
        exe, record = entry["exe"], entry["record"]
        if exe is None:
            out = self._jit(*args, **kwargs)
        else:
            try:
                out = exe(*dyn_args, **dyn_kwargs)
            except Exception as e:
                # pytree/static drift between lower() and the call contract of
                # this jax version: disable the AOT path for this signature
                _log_once(f"call:{self.name}",
                          "kernel %s: AOT executable call failed (%s); "
                          "using plain jit for this signature", self.name, e)
                entry["exe"] = None
                out = self._jit(*args, **kwargs)
        if record is not None:
            with _lock:
                record["calls"] += 1
            _attribute_call(self.name, record)
        return out


# config keys whose values a kernel body may read AT TRACE TIME (today only
# parity_precision — ops/_precision.py). Folding the current value into every
# AOT signature makes such reads stale-proof: see CompiledKernel._signature.
# The residual: with the device plane disabled (observability.device_enabled
# off) calls run through plain jax.jit, whose cache does not know the epoch —
# documented in docs/design.md §6j.
_TRACE_EPOCH_KEYS = ("parity_precision",)


def _trace_epoch() -> Tuple[Tuple[str, str], ...]:
    return tuple(
        (f"cfg:{k}", repr(_config.get(k))) for k in _TRACE_EPOCH_KEYS
    )


def _sig_str(sig) -> str:
    leaves, treedef, statics = sig
    arrays = ",".join(
        f"{l[1]}:{l[2]}" for l in leaves if l and l[0] == "a"
    )
    st = ",".join(f"{k}={v}" for k, v in statics)
    return f"[{arrays}]" + (f"{{{st}}}" if st else "")


def _extract_cost(exe: Any, lowered: Any) -> Dict[str, Any]:
    """Flops/bytes/transcendentals + memory breakdown from the compiled
    executable (falling back to the unoptimized-HLO analysis on the Lowered).
    Missing APIs degrade to analyzed=False — gauges/keys absent, no spam."""
    out: Dict[str, Any] = {
        "flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0,
        "analyzed": False,
    }
    ca = None
    for src in (exe, lowered):
        try:
            ca = src.cost_analysis()
        except Exception:
            ca = None
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, Mapping):
            break
        ca = None
    if isinstance(ca, Mapping):
        out["flops"] = max(float(ca.get("flops", 0.0) or 0.0), 0.0)
        out["bytes_accessed"] = max(
            float(ca.get("bytes accessed", 0.0) or 0.0), 0.0
        )
        out["transcendentals"] = max(
            float(ca.get("transcendentals", 0.0) or 0.0), 0.0
        )
        out["analyzed"] = True
    try:
        ma = exe.memory_analysis()
        arg_b = int(getattr(ma, "argument_size_in_bytes", 0))
        out_b = int(getattr(ma, "output_size_in_bytes", 0))
        tmp_b = int(getattr(ma, "temp_size_in_bytes", 0))
        out["argument_bytes"] = arg_b
        out["output_bytes"] = out_b
        out["temp_bytes"] = tmp_b
        out["peak_bytes"] = arg_b + out_b + tmp_b
    except Exception:  # noqa: fence/silent-except — memory_analysis absent here
        pass
    return out


def _attribute_call(kernel: str, record: Mapping[str, Any]) -> None:
    """Per-call metric + span attribution: counters into the fan-out, analyzed
    flops/bytes onto the innermost open span of THIS thread."""
    flops = float(record.get("flops", 0.0))
    bytes_accessed = float(record.get("bytes_accessed", 0.0))
    _runs.counter_inc("device.kernel_calls", 1, kernel=kernel)
    if flops:
        _runs.counter_inc("device.flops_total", int(flops), kernel=kernel)
    if bytes_accessed:
        _runs.counter_inc("device.bytes_total", int(bytes_accessed),
                          kernel=kernel)
    # collective accounting (§6h): per call, each kind's analyzed ops/bytes
    # aggregate like flops do — uniform `comm.*` names across every kernel
    comm_bytes = 0.0
    collectives = record.get("collectives")
    if collectives:
        for kind, st in collectives.items():
            _runs.counter_inc("comm.collective_ops", int(st.get("ops", 0)),
                              kind=kind, kernel=kernel)
            b = int(st.get("bytes", 0))
            if b:
                _runs.counter_inc("comm.collective_bytes", b,
                                  kind=kind, kernel=kernel)
            comm_bytes += b
    stack = _runs._span_stack()
    if not stack:
        return
    node = stack[-1]
    dev = node.attrs.get("device")
    if dev is None:
        dev = node.attrs["device"] = {
            "flops": 0.0, "bytes": 0.0, "transcendentals": 0.0,
            "comm_bytes": 0.0, "calls": 0, "kernels": {},
        }
    dev["flops"] += flops
    dev["bytes"] += bytes_accessed
    dev["transcendentals"] += float(record.get("transcendentals", 0.0))
    dev["comm_bytes"] = dev.get("comm_bytes", 0.0) + comm_bytes
    dev["calls"] += 1
    dev["kernels"][kernel] = dev["kernels"].get(kernel, 0) + 1


def _resolve_kernel(module: str, qualname: str) -> "CompiledKernel":
    """Unpickle hook: resolve a kernel back to the live module-level instance."""
    import importlib

    obj: Any = importlib.import_module(module)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def compiled_kernel(name: str, **jit_kwargs: Any) -> Callable:
    """Decorator factory: `@compiled_kernel("ops.foo", static_argnames=(...))`
    replaces `@functools.partial(jax.jit, static_argnames=(...))` for every
    kernel the library compiles — same call semantics, plus compile accounting,
    cost/memory analysis capture and roofline span attribution."""

    def wrap(fn: Callable) -> CompiledKernel:
        return CompiledKernel(name, fn, jit_kwargs)

    return wrap


# ------------------------------------------------------------- record surface


def kernel_cost_records() -> List[Dict[str, Any]]:
    """Snapshot of every captured (kernel, signature) cost record."""
    with _lock:
        return [dict(r) for r in _records.values()]


def kernel_cost(name: str) -> Optional[Dict[str, Any]]:
    """The most recently COMPILED record for a kernel name (None when the
    kernel never compiled under the device plane)."""
    with _lock:
        recs = [r for (k, _), r in _records.items() if k == name]
    return dict(recs[-1]) if recs else None


def compile_count(name: str) -> int:
    """Distinct compiled signatures recorded for a kernel name."""
    with _lock:
        return sum(1 for (k, _) in _records if k == name)


def compiles_total() -> int:
    """Monotone process-wide compile count (never reset; see the module-level
    `_compiles_total` note). A zero before/after delta across a code path is
    the compile-vs-cached verdict trace execute spans report."""
    with _lock:
        return _compiles_total


def device_report_section(registry: Any = None) -> Optional[Dict[str, Any]]:
    """The `device` section of a run report: peak table in force + the cost
    records of the kernels THIS run actually called (filtered via the run's
    `device.kernel_calls{kernel=}` counters — a long-lived serving process
    must not serialize the whole process-global record table into every
    transform report). Without a registry, every record is returned (the
    process-global surface)."""
    if not _enabled():
        return None
    records = kernel_cost_records()
    run_calls: Optional[Dict[str, Any]] = None
    if registry is not None:
        from .registry import split_label_key

        run_calls = {}
        for key, v in (
            registry.snapshot().get("counters") or {}
        ).items():
            name, labels = split_label_key(key)
            if name == "device.kernel_calls" and labels.get("kernel"):
                run_calls[labels["kernel"]] = v
        records = [r for r in records if r["kernel"] in run_calls]
    # the in-memory record's `calls` is PROCESS-cumulative (it outlives runs);
    # a per-run report must not present it as this run's count — rename it and
    # attach the run-scoped count from the registry
    for r in records:
        r["process_calls"] = r.pop("calls", 0)
        if run_calls is not None:
            r["run_calls"] = run_calls.get(r["kernel"], 0)
    if not records:
        return None
    peak_flops, peak_bw, platform = platform_peaks()
    return {
        "platform": platform,
        "peak_flops": peak_flops,
        "peak_bw": peak_bw,
        "peak_ici_bw": platform_ici_bw(),
        "kernels": records,
    }


# -------------------------------------------------------------- HBM telemetry


def sample_hbm(force: bool = False) -> Optional[int]:
    """Sample local devices' memory_stats() into the hbm gauges; returns total
    bytes in use, or None when unsupported/rate-limited. First probe returning
    no stats on any device marks the platform unsupported permanently: gauges
    simply never appear (no warning spam — CPU is the common case)."""
    global _hbm_supported, _hbm_last_sample, _hbm_probe_errors
    if not _enabled() or not bool(_config.get("observability.hbm_sampling")):
        return None
    if _hbm_supported is False or "jax" not in sys.modules:
        return None
    now = time.monotonic()
    interval = float(_config.get("observability.hbm_sample_interval_s"))
    if not force and now - _hbm_last_sample < interval:
        return None
    _hbm_last_sample = now
    try:
        import jax

        totals = []
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            stats = ms() if callable(ms) else None
            if stats and "bytes_in_use" in stats:
                totals.append(int(stats["bytes_in_use"]))
    except Exception as e:
        # a TRANSIENT probe error (backend still initializing) must not take
        # the unsupported-platform fast path permanently; give up only after
        # several consecutive failures
        _hbm_probe_errors += 1
        _log_once("hbm", "memory_stats sampling failed: %s", e)
        if _hbm_probe_errors >= _HBM_MAX_PROBE_ERRORS:
            _hbm_supported = False
        return None
    _hbm_probe_errors = 0
    if not totals:
        # clean probe, no stats on any device: genuinely unsupported (CPU)
        _hbm_supported = False
        return None
    _hbm_supported = True
    total = sum(totals)
    _runs.gauge_set("device.hbm_bytes_in_use", total)
    _runs._flight().note_hbm(total)
    with _lock:
        for run_id, peak in list(_run_peaks.items()):
            if total > peak:
                _run_peaks[run_id] = total
    return total


def note_run_start(run: Any) -> None:
    """FitRun/TransformRun __enter__ hook: open a per-run HBM peak tracker."""
    total = sample_hbm(force=True)
    with _lock:
        _run_peaks[run.run_id] = total or 0


def note_run_end(run: Any) -> None:
    """Run __exit__ hook: final sample, then land the run-scoped peak gauge in
    THAT run's registry (a global gauge cannot be run-scoped)."""
    sample_hbm(force=True)
    with _lock:
        peak = _run_peaks.pop(run.run_id, None)
    if peak:
        try:
            run.registry.gauge("device.hbm_peak_bytes").set(int(peak))
        except Exception as e:
            _log_once("peak_gauge", "hbm peak gauge failed: %s", e)


# ------------------------------------------------------------ span attribution


def on_span_close(node: Any) -> None:
    """runs.span close hook: roofline-classify any device work attributed to
    the span, and keep the HBM gauge fresh (rate-limited). Must never raise —
    it sits inside every span's finally."""
    try:
        if not _enabled():
            return
        dev = node.attrs.get("device")
        if dev is not None and node.duration_s:
            peaks = platform_peaks()
            achieved = dev["flops"] / node.duration_s
            dev["achieved_flops_per_s"] = achieved
            dev["mfu"] = achieved / peaks[0] if peaks[0] > 0 else 0.0
            cls = _classify(dev["flops"], dev["bytes"], peaks)
            dev["operational_intensity"] = cls["operational_intensity"]
            dev["roofline_bound"] = cls["roofline_bound"]
            ceiling = cls["ceiling_flops_per_s"]
            dev["roofline_frac"] = achieved / ceiling if ceiling > 0 else 0.0
            # comm roofline (§6h): achieved interconnect bandwidth / comm_frac
            # / comm_bound from the span's attributed collective bytes
            if dev.get("comm_bytes"):
                dev.update(_comm().classify_comm(
                    dev["flops"], dev["bytes"], dev["comm_bytes"],
                    node.duration_s, peaks[0], peaks[1], platform_ici_bw(),
                ))
        sample_hbm()
    except Exception as e:
        _log_once("span_close", "device span hook failed: %s", e)


# ----------------------------------------------------------- scenario summary


def scenario_summary(report: Mapping[str, Any],
                     wall_s: Optional[float] = None) -> Dict[str, Any]:
    """Measured MFU + roofline classification for one run report (a bench
    scenario): total analyzed flops/bytes from the run's device counters over
    the scenario wall clock against the PER-CHIP platform peak. cost_analysis
    runs on the compiled (post-SPMD-partitioning) per-device module, so the
    analyzed flops are already per-chip — no further division by chip count
    (doing so would deflate MFU by n_chips on a pod). This REPLACES bench.py's
    analytic est_mfu; mfu here is conservative (wall time includes host work)
    but measured, and the bench gate tracks its direction."""
    counters = (report.get("metrics") or {}).get("counters") or {}
    flops = float(sum(
        v for k, v in counters.items() if k.startswith("device.flops_total")
    ))
    bytes_accessed = float(sum(
        v for k, v in counters.items() if k.startswith("device.bytes_total")
    ))
    compiles = int(sum(
        v for k, v in counters.items()
        if k.startswith("device.compile{") or k == "device.compile"
    ))
    wall = wall_s if wall_s is not None else (report.get("duration_s") or 0.0)
    peaks = platform_peaks()
    mfu = (
        flops / wall / peaks[0]
        if wall and wall > 0 and peaks[0] > 0
        else 0.0
    )
    cls = _classify(flops, bytes_accessed, peaks)
    return {
        "mfu": round(mfu, 6),
        "roofline_bound": cls["roofline_bound"],
        "device_flops": flops,
        "device_bytes": bytes_accessed,
        "device_compiles": compiles,
        "platform": peaks[2],
    }


# -------------------------------------------------------------- profiler hook


@contextlib.contextmanager
def profile_pass(site: str, pass_no: int) -> Iterator[None]:
    """Opt-in jax.profiler capture of ONE designated pass of a streamed fit:
    active only when `observability.profile_dir` is set and `pass_no` equals
    `observability.profile_pass` (default 2 — the first post-compile
    steady-state pass); captures once per site per process. Trace lands in
    `<profile_dir>/<site>/` for xprof/tensorboard."""
    pdir = _config.get("observability.profile_dir")
    if not pdir or int(pass_no) != int(_config.get("observability.profile_pass")):
        yield
        return
    with _lock:
        if site in _profiled_sites:
            yield
            return
        _profiled_sites.add(site)
    import os

    target = os.path.join(str(pdir), site.replace("/", "_").replace(".", "_"))
    try:
        import jax.profiler

        jax.profiler.start_trace(target)
    except Exception as e:
        _log_once(f"profile:{site}", "profiler capture failed for %s: %s",
                  site, e)
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
            _runs.counter_inc("device.profile_captures", 1, site=site)
            _logger.info("wrote profiler trace for %s pass %d to %s",
                         site, pass_no, target)
        except Exception as e:
            _log_once(f"profile_stop:{site}",
                      "profiler stop failed for %s: %s", site, e)
