#
# Trace plane: end-to-end causal request tracing (docs/design.md §6l).
#
# One `RequestTrace` per client request, minted at HTTP ingress (or accepted
# from a W3C `traceparent` header), carried by reference through router
# admission -> replica queue -> micro-batch close -> execute -> scatter-back.
# Spans are appended with raw perf_counter stamps (the clocks the serving
# plane already holds: enqueue_ts, batch open/close) and converted to wall
# time against the trace's birth instant, so parent/child timing is monotonic
# and non-overlapping by construction. A micro-batch span carries fan-in
# links to the N request root spans it served, which is what makes padding
# and occupancy cost attributable per request; fleet actions (hedge, replay,
# steal, shed, expiry) land as causal events that also force tail-keep.
#
# Storage is a bounded per-process ring with tail-based sampling: flagged
# traces (error/hedged/failover/expired/shed) always keep, the rolling
# slowest `tracing.slow_frac` keep as "slow", the rest keep at
# `tracing.sample_rate` by a deterministic hash of the trace id. Kept traces
# export to rotated `trace_reports.jsonl` (PR-4 writer) and serve live on
# `GET /traces` / `/traces/<id>`; exemplars attached to serving latency
# histograms point back into this ring.
#

from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from .. import config as _config
from ..utils import get_logger

_logger = get_logger("observability.tracing")

# Event kinds that force tail-keep, and the flag each one raises.
_FLAG_EVENTS = {
    "hedge_issued": "hedged",
    "hedge_won": "hedged",
    "failover_replay": "failover",
    "queue_steal": "failover",
    "deadline_expired": "expired",
    "tenant_shed": "shed",
    "error": "error",
}

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

_MAX_SPANS = 256     # per trace; beyond this, spans are counted, not stored
_MAX_EVENTS = 512
_SLOW_WINDOW = 512   # rolling durations window for the slow-keep threshold

_lock = threading.RLock()
# ring holds finished RequestTrace objects; their export documents build
# lazily on first read (/traces, JSONL, postmortem) — the request path pays
# for appends and the sampling decision, not for serialization
_ring: "OrderedDict[str, RequestTrace]" = OrderedDict()
_durations: deque = deque(maxlen=_SLOW_WINDOW)
# slow-threshold cache: sorting the 512-entry window on every finish is the
# kind of per-request cost the <2% overhead budget exists to catch, so the
# percentile recomputes at most every _SLOW_RECOMPUTE appends
_SLOW_RECOMPUTE = 16
_slow_cached: Optional[float] = None
_slow_dirty = 0
_slow_frac_at: Optional[float] = None
# per-request config reads cached against config.epoch(): re-resolved only
# after a set()/unset(), not once (or twice) per request
_rate_cached: Optional[float] = None
_rate_epoch = -1
_hot_cfg: Optional[tuple] = None  # (slow_frac, ring_cap, metrics_dir)
_hot_epoch = -1
_tls = threading.local()


# ------------------------------------------------------------------ ids


def mint_trace_id() -> str:
    return os.urandom(16).hex()


def mint_span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """Parsed/minted W3C trace context: 32-hex trace id, 16-hex parent
    span id, sampled flag."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)


def parse_traceparent(value) -> Optional[TraceContext]:
    """Parse a `traceparent` header. Returns None on anything malformed
    (wrong field widths, non-hex, all-zero ids, version ff) — callers count
    and replace, they never reject the request."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, sampled=bool(int(flags, 16) & 1))


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def mint_context() -> TraceContext:
    return TraceContext(mint_trace_id(), mint_span_id())


# ------------------------------------------------------------------ config


def _enabled() -> bool:
    return bool(_config.get("tracing.enabled"))


def sample_rate() -> float:
    """`tracing.sample_rate` resolution (the §6i knob order): config pin
    (set()/env) wins, then the tuning table, then the defaults-module
    constant. The resolved rate is cached against config.epoch() — the
    table path costs ~30us per resolution, which at two calls per request
    (would_keep + finish) would eat the <2% overhead budget on its own. A
    set()/unset() re-resolves immediately; a mid-process table write shows
    up after the next config mutation or reset_tracing()."""
    global _rate_cached, _rate_epoch
    ep = _config.epoch()
    if _rate_cached is not None and _rate_epoch == ep:
        return _rate_cached
    if _config.source("tracing.sample_rate") != "default":
        rate = float(_config.get("tracing.sample_rate"))
    else:
        try:
            from .. import autotune as _autotune
            from ..autotune.defaults import TRACING_SAMPLE_RATE

            tuned = _autotune.lookup("tracing.sample_rate")
            rate = float(tuned) if tuned is not None \
                else float(TRACING_SAMPLE_RATE)
        except Exception:
            rate = float(_config.get("tracing.sample_rate"))
    _rate_cached, _rate_epoch = rate, ep
    return rate


def _hash_sampled(trace_id: str, rate: float) -> bool:
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) / float(0xFFFFFFFF) < rate


def _hot_config() -> tuple:
    """(slow_frac, ring_cap, metrics_dir) re-read only when config.epoch()
    moved — these three are consulted on every single finish."""
    global _hot_cfg, _hot_epoch
    ep = _config.epoch()
    if _hot_cfg is None or _hot_epoch != ep:
        _hot_cfg = (float(_config.get("tracing.slow_frac")),
                    max(1, int(_config.get("tracing.ring_traces"))),
                    _config.get("observability.metrics_dir"))
        _hot_epoch = ep
    return _hot_cfg


def _slow_threshold() -> Optional[float]:
    """Duration above which a trace counts as one of the rolling slowest
    `tracing.slow_frac`; None until the window has data. Cached between
    recomputes (every _SLOW_RECOMPUTE appends, or on a slow_frac change)."""
    global _slow_cached, _slow_dirty, _slow_frac_at
    frac = _hot_config()[0]
    if frac <= 0.0:
        return None
    with _lock:
        if len(_durations) < 8:  # too little history to call anything slow
            return None
        if (_slow_cached is not None and _slow_dirty < _SLOW_RECOMPUTE
                and _slow_frac_at == frac):
            return _slow_cached
        ordered = sorted(_durations)
        idx = max(0, min(len(ordered) - 1,
                         int((1.0 - frac) * (len(ordered) - 1))))
        _slow_cached = ordered[idx]
        _slow_dirty = 0
        _slow_frac_at = frac
        return _slow_cached


# ------------------------------------------------------------------ trace


class RequestTrace:
    """One request's causal record. Thread-safe append; `finish()` is
    idempotent (first caller wins — hedge losers land as dropped appends).

    Spans are held as raw tuples until `document()` materializes them —
    per-span dict building, wall-clock conversion and rounding happen once
    per EXPORT, not once per append on the request path. Span/event attrs
    are captured by reference: callers must not mutate an attrs dict after
    passing it (every call site builds a fresh dict or freezes it first)."""

    __slots__ = ("trace_id", "client_span_id", "root_span_id", "name",
                 "attrs", "_wall_t0", "_pc_t0", "_lock", "_spans", "_events",
                 "_span_ids", "_dropped_spans", "flags", "finished",
                 "status", "keep_reason", "_duration", "_doc")

    def __init__(self, name: str, ctx: Optional[TraceContext] = None,
                 **attrs):
        ctx = ctx or mint_context()
        self.trace_id = ctx.trace_id
        self.client_span_id = ctx.span_id
        self.root_span_id = mint_span_id()
        self.name = name
        self.attrs = dict(attrs)
        self._wall_t0 = time.time()
        self._pc_t0 = time.perf_counter()
        self._lock = threading.Lock()
        # raw span tuples: (sid, parent, name, t0_pc, t1_pc, status,
        #                   attrs, links)
        self._spans: List[tuple] = []
        self._events: List[Dict[str, Any]] = []
        self._span_ids = set()
        self._dropped_spans = 0
        self.flags: set = set()
        self.finished = False
        self.status = None
        self.keep_reason = None
        self._duration: Optional[float] = None
        self._doc: Optional[Dict[str, Any]] = None

    # -- clocks

    def now(self) -> float:
        return time.perf_counter()

    def _wall(self, pc_ts: float) -> float:
        return self._wall_t0 + (pc_ts - self._pc_t0)

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.root_span_id)

    # -- appends

    def add_span(self, name: str, t0_pc: float, t1_pc: float,
             parent_id: Optional[str] = None, attrs: Optional[dict] = None,
             links: Optional[list] = None, status: str = "ok",
             span_id: Optional[str] = None) -> Optional[str]:
        """Append a completed span from raw perf_counter stamps. Returns the
        span id (None once the trace is finished or the span cap is hit)."""
        sid = span_id or mint_span_id()
        with self._lock:
            if self.finished or len(self._spans) >= _MAX_SPANS:
                if not self.finished:
                    self._dropped_spans += 1
                return None
            self._spans.append(
                (sid, parent_id, name, t0_pc, t1_pc, status, attrs, links)
            )
            self._span_ids.add(sid)
        return sid

    def add_event(self, kind: str, t_pc: Optional[float] = None, **fields):
        """Append a causal event; flagged kinds (hedge/replay/steal/shed/
        expiry/error) force tail-keep."""
        entry = {"kind": kind,
                 "ts": round(self._wall(t_pc if t_pc is not None
                                        else time.perf_counter()), 6)}
        entry.update(fields)
        flag = _FLAG_EVENTS.get(kind)
        with self._lock:
            if flag:
                self.flags.add(flag)
            if self.finished or len(self._events) >= _MAX_EVENTS:
                return
            self._events.append(entry)

    def flag(self, reason: str):
        with self._lock:
            self.flags.add(reason)

    # -- terminal

    def finish(self, status: str = "ok"):
        """Close the trace: tail-sampling decision, ring insert, JSONL
        export. Idempotent — the first finish wins. If no caller appended a
        span under `root_span_id` (the in-process predict path has no HTTP
        ingress span), the root is synthesized covering the whole trace."""
        t1 = time.perf_counter()
        with self._lock:
            if self.finished:
                return
            self.finished = True
            if status != "ok":
                self.flags.add("error")
            self.status = status
            if self.root_span_id not in self._span_ids:
                self._spans.insert(0, (self.root_span_id, None, self.name,
                                       self._pc_t0, t1, status,
                                       self.attrs or None, None))
                self._span_ids.add(self.root_span_id)
        _finish_collect(self, t1 - self._pc_t0)

    def document(self, duration: float) -> Dict[str, Any]:
        from .runs import PROCESS_TOKEN

        spans = []
        for sid, parent, name, t0_pc, t1_pc, status, attrs, links in \
                self._spans:
            entry = {
                "span_id": sid,
                "parent_span_id": parent,
                "name": name,
                "start_ts": round(self._wall(t0_pc), 6),
                "duration_s": round(max(0.0, t1_pc - t0_pc), 9),
                "status": status,
            }
            if attrs:
                entry["attrs"] = dict(attrs)
            if links:
                entry["links"] = list(links)
            spans.append(entry)
        doc: Dict[str, Any] = {
            "schema": 1,
            "kind": "trace",
            "trace_id": self.trace_id,
            "traceparent": self.traceparent,
            "name": self.name,
            "start_ts": round(self._wall_t0, 6),
            "duration_s": round(duration, 9),
            "status": self.status or "ok",
            "keep_reason": self.keep_reason,
            "flags": sorted(self.flags),
            "process": PROCESS_TOKEN,
            "spans": spans,
            "events": list(self._events),
        }
        if self.client_span_id:
            doc["client_span_id"] = self.client_span_id
        if self.attrs:
            doc["attrs"] = dict(self.attrs)
        if self._dropped_spans:
            doc["dropped_spans"] = self._dropped_spans
        return doc


# ------------------------------------------------------- collector / ring


def _doc_of(rt: RequestTrace) -> Dict[str, Any]:
    """The trace's export document, built once on first read — a finished
    trace is immutable, so concurrent builders produce identical content."""
    doc = rt._doc
    if doc is None:
        doc = rt._doc = rt.document(rt._duration or 0.0)
    return doc


def _finish_collect(rt: RequestTrace, duration: float):
    global _slow_dirty

    from . import runs as _runs

    reason = None
    if rt.flags:
        reason = sorted(rt.flags)[0]
    else:
        rate = sample_rate()
        if rate >= 1.0:  # keep-everything: the slow label adds nothing
            reason = "sampled"
        else:
            thresh = _slow_threshold()
            if thresh is not None and duration >= thresh:
                reason = "slow"
            elif _hash_sampled(rt.trace_id, rate):
                reason = "sampled"
    if reason is None:
        with _lock:
            _durations.append(duration)
            _slow_dirty += 1
        _runs.counter_inc("tracing.traces_dropped", 1)
        return
    rt.keep_reason = reason
    rt._duration = duration
    _, cap, metrics_dir = _hot_config()
    with _lock:
        _durations.append(duration)
        _slow_dirty += 1
        _ring[rt.trace_id] = rt
        _ring.move_to_end(rt.trace_id)
        while len(_ring) > cap:
            _ring.popitem(last=False)
    _runs.counter_inc("tracing.traces_kept", 1, reason=reason)
    if metrics_dir:
        try:
            from .export import TRACE_REPORT_FILENAME, write_run_report

            write_run_report(_doc_of(rt), metrics_dir,
                             filename=TRACE_REPORT_FILENAME)
        except Exception as e:  # export must never fail the request path
            _logger.warning("trace report write failed: %s: %s",
                            type(e).__name__, e)


def would_keep(rt: Optional[RequestTrace],
               duration: Optional[float] = None) -> bool:
    """Predict the tail-sampling decision for `rt` — used to decide whether
    a histogram exemplar pointing at this trace will resolve. Deterministic
    for the flag and hash arms; the slow arm consults the rolling window."""
    if rt is None:
        return False
    if rt.flags:
        return True
    if _hash_sampled(rt.trace_id, sample_rate()):
        return True
    if duration is not None:
        thresh = _slow_threshold()
        if thresh is not None and duration >= thresh:
            return True
    return False


def start_trace(name: str, ctx: Optional[TraceContext] = None,
                **attrs) -> Optional[RequestTrace]:
    """Mint a trace (or adopt a client context). Returns None when tracing
    is disabled — every call site treats None as 'no tracing'."""
    if not _enabled():
        return None
    return RequestTrace(name, ctx=ctx, **attrs)


def finish_future(rt: Optional[RequestTrace], fut) -> None:
    """Finish `rt` when `fut` resolves (status from the exception type)."""
    if rt is None:
        return

    def _done(f):
        try:
            exc = f.exception()
        except Exception as e:  # cancelled
            exc = e
        rt.finish(status="ok" if exc is None else type(exc).__name__)

    fut.add_done_callback(_done)


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    with _lock:
        rt = _ring.get(trace_id)
    return dict(_doc_of(rt)) if rt is not None else None


def trace_index() -> List[Dict[str, Any]]:
    """Newest-last summaries of the kept ring."""
    with _lock:
        kept = list(_ring.values())
    out = []
    for rt in kept:
        d = _doc_of(rt)
        out.append({
            "trace_id": d["trace_id"],
            "name": d["name"],
            "start_ts": d["start_ts"],
            "duration_s": d["duration_s"],
            "status": d["status"],
            "keep_reason": d["keep_reason"],
            "flags": d["flags"],
            "spans": len(d["spans"]),
            "events": len(d["events"]),
        })
    return out


def ring_snapshot() -> List[Dict[str, Any]]:
    """Full kept-trace docs, oldest-first (flight-recorder postmortems)."""
    with _lock:
        kept = list(_ring.values())
    return [dict(_doc_of(rt)) for rt in kept]


def reset_tracing() -> None:
    global _slow_cached, _slow_dirty, _slow_frac_at
    global _rate_cached, _rate_epoch, _hot_cfg, _hot_epoch
    with _lock:
        _ring.clear()
        _durations.clear()
        _slow_cached = None
        _slow_dirty = 0
        _slow_frac_at = None
        _rate_cached = None
        _rate_epoch = -1
        _hot_cfg = None
        _hot_epoch = -1


# -------------------------------------------- batch-thread annotations

# The execute path (`_predict_padded`) knows things the batcher does not —
# the serving model generation that answered. It runs on the dispatcher
# thread that called it, so a thread-local hand-off is race-free.


def annotate_batch(**attrs) -> None:
    cur = getattr(_tls, "batch_attrs", None)
    if cur is None:
        cur = {}
        _tls.batch_attrs = cur
    cur.update(attrs)


def take_batch_annotations() -> Dict[str, Any]:
    cur = getattr(_tls, "batch_attrs", None)
    _tls.batch_attrs = None
    return cur or {}


__all__ = [
    "TraceContext",
    "RequestTrace",
    "parse_traceparent",
    "format_traceparent",
    "mint_context",
    "mint_trace_id",
    "mint_span_id",
    "start_trace",
    "finish_future",
    "would_keep",
    "sample_rate",
    "get_trace",
    "trace_index",
    "ring_snapshot",
    "reset_tracing",
    "annotate_batch",
    "take_batch_annotations",
]
