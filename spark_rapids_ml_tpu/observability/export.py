#
# Exporters: JSONL run reports + Prometheus textfile — the egress half of the
# observability subsystem (docs/design.md §6d).
#
#   * JSONL: one line per finished FitRun, appended to
#     `<metrics_dir>/fit_reports.jsonl` (config `observability.metrics_dir` /
#     env SRML_TPU_METRICS_DIR). Reports are plain JSON and round-trip through
#     `load_run_reports` — CI's observability smoke tier asserts on the file
#     (ci/test.sh) instead of on process-global counters.
#   * Prometheus: the standard node_exporter textfile-collector handshake —
#     render a registry snapshot in text exposition format and atomically
#     replace `<path>`; a scraper picks it up on its next pass. No server, no
#     new dependency.
#

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any, Dict, Iterator, List, Mapping, Optional

from .registry import MetricsRegistry, split_label_key

RUN_REPORT_FILENAME = "fit_reports.jsonl"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_PREFIX = "srml_tpu_"


def write_run_report(report: Mapping[str, Any], metrics_dir: str) -> str:
    """Append one run report as a JSON line; returns the file path. Creates the
    directory; the append+flush is a single write so concurrent fits from one
    process interleave whole lines."""
    os.makedirs(metrics_dir, exist_ok=True)
    path = os.path.join(metrics_dir, RUN_REPORT_FILENAME)
    line = json.dumps(report, default=_json_fallback)
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
    return path


def _json_fallback(obj: Any) -> Any:
    """Numpy scalars and other number-likes that reach a report (histogram sums
    accumulated from device timings) serialize as plain floats."""
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            return fn()
    return str(obj)


def load_run_reports(path_or_dir: str) -> List[Dict[str, Any]]:
    """Parse a fit_reports.jsonl (or the directory holding one) back to report
    dicts — the round-trip half the acceptance tests assert."""
    path = (
        os.path.join(path_or_dir, RUN_REPORT_FILENAME)
        if os.path.isdir(path_or_dir)
        else path_or_dir
    )
    reports: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                reports.append(json.loads(line))
    return reports


def _prom_name(name: str) -> str:
    return _PROM_PREFIX + _NAME_OK.sub("_", name)


def _prom_labels(labels: Mapping[str, str], extra: Optional[str] = None) -> str:
    parts = [f'{_NAME_OK.sub("_", k)}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(snapshot: Mapping[str, Any]) -> str:
    """Registry snapshot -> Prometheus text exposition format. Counters and
    gauges map directly; histograms emit the classic cumulative _bucket/_sum/
    _count triplet; legacy span totals export as `*_span_seconds_total`."""
    lines: List[str] = []
    seen_types: Dict[str, str] = {}

    def _typed(name: str, kind: str) -> None:
        if seen_types.get(name) != kind:
            lines.append(f"# TYPE {name} {kind}")
            seen_types[name] = kind

    for key, v in sorted((snapshot.get("counters") or {}).items()):
        name, labels = split_label_key(key)
        pname = _prom_name(name) + "_total"
        _typed(pname, "counter")
        lines.append(f"{pname}{_prom_labels(labels)} {v}")
    for key, v in sorted((snapshot.get("gauges") or {}).items()):
        name, labels = split_label_key(key)
        pname = _prom_name(name)
        _typed(pname, "gauge")
        lines.append(f"{pname}{_prom_labels(labels)} {v}")
    for name, secs in sorted((snapshot.get("spans") or {}).items()):
        pname = _prom_name(name) + "_span_seconds_total"
        _typed(pname, "counter")
        lines.append(f"{pname} {secs}")
    for key, st in sorted((snapshot.get("histograms") or {}).items()):
        name, labels = split_label_key(key)
        pname = _prom_name(name)
        _typed(pname, "histogram")
        bounds = list(st.get("bounds") or [])
        cum = 0
        for i, c in enumerate(st["buckets"]):
            cum += c
            le = repr(float(bounds[i])) if i < len(bounds) else "+Inf"
            le_label = 'le="%s"' % le
            lines.append(f"{pname}_bucket{_prom_labels(labels, le_label)} {cum}")
        lines.append(f"{pname}_sum{_prom_labels(labels)} {st['sum']}")
        lines.append(f"{pname}_count{_prom_labels(labels)} {st['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus_textfile(path: str,
                              registry: Optional[MetricsRegistry] = None) -> str:
    """Atomically replace `path` with the registry's current state in text
    exposition format (default: the process-global registry). Atomic because a
    textfile collector may scrape mid-write; write-then-rename means it only
    ever sees whole files."""
    if registry is None:
        from .runs import global_registry

        registry = global_registry()
    text = render_prometheus(registry.snapshot())
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".prom_")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def iter_spans(report: Mapping[str, Any]) -> Iterator[Dict[str, Any]]:
    """Depth-first walk of a report's trace tree (report helpers for tests/CI)."""
    stack = list(report.get("trace") or [])
    while stack:
        node = stack.pop()
        yield node
        stack.extend(node.get("children") or [])
