#
# Exporters: JSONL run reports + Prometheus textfile — the egress half of the
# observability subsystem (docs/design.md §6d/§6e).
#
#   * JSONL: one line per finished run, appended to
#     `<metrics_dir>/fit_reports.jsonl` (FitRun) or
#     `<metrics_dir>/transform_reports.jsonl` (TransformRun) — config
#     `observability.metrics_dir` / env SRML_TPU_METRICS_DIR. Reports are plain
#     JSON and round-trip through `load_run_reports` — CI's observability smoke
#     tiers assert on the files (ci/test.sh) instead of on process-global
#     counters. Files rotate by size (`observability.max_report_bytes`,
#     `observability.max_report_files`) via atomic renames: a serving process
#     transforming forever must not grow one JSONL without bound.
#   * Prometheus: the standard node_exporter textfile-collector handshake —
#     render a registry snapshot in text exposition format and atomically
#     replace `<path>`; a scraper picks it up on its next pass. No server, no
#     new dependency.
#

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any, Dict, Iterator, List, Mapping, Optional

from .. import config as _config
from .registry import MetricsRegistry, split_label_key

RUN_REPORT_FILENAME = "fit_reports.jsonl"
TRANSFORM_REPORT_FILENAME = "transform_reports.jsonl"
TRANSFORM_PARTIALS_FILENAME = "transform_partials.jsonl"
SERVING_REPORT_FILENAME = "serving_reports.jsonl"
TRACE_REPORT_FILENAME = "trace_reports.jsonl"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_PREFIX = "srml_tpu_"


def _rotate_if_needed(path: str) -> None:
    """Size-based JSONL rotation: when the live file reaches
    `observability.max_report_bytes`, shift `path.i` -> `path.(i+1)` (dropping
    the one past `observability.max_report_files`) and `path` -> `path.1`.
    Every step is an atomic rename, so a concurrent `load_run_reports` sees
    whole files; suffix .1 is the newest rotated file, .N the oldest."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return  # no live file yet
    max_bytes = int(_config.get("observability.max_report_bytes") or 0)
    if max_bytes <= 0 or size < max_bytes:
        return
    max_files = max(1, int(_config.get("observability.max_report_files")))
    oldest = f"{path}.{max_files}"
    try:
        os.unlink(oldest)
    except OSError:
        pass
    for i in range(max_files - 1, 0, -1):
        src = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i + 1}")
    os.replace(path, f"{path}.1")


def _rotated_paths(path: str) -> List[str]:
    """All report files for `path`, OLDEST FIRST (…, .2, .1, live) — the order
    that keeps loaded reports chronological across rotations. Generations sort
    NUMERICALLY (int suffix), never as path strings: past 9 rotated files a
    lexicographic sort would interleave `.10` before `.2` and shuffle report
    order (regression-pinned by the >9-generation round-trip test)."""
    suffixes = []
    d, base = os.path.split(path)
    prefix = base + "."
    try:
        for name in os.listdir(d or "."):
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                suffixes.append(int(name[len(prefix):]))
    except OSError:
        pass
    paths = [f"{path}.{i}" for i in sorted(suffixes, reverse=True)]
    if os.path.exists(path):
        paths.append(path)
    return paths


def write_run_report(report: Mapping[str, Any], metrics_dir: str,
                     filename: Optional[str] = None) -> str:
    """Append one run report as a JSON line; returns the file path. Creates the
    directory and rotates by size first; the append+flush is a single write so
    concurrent runs from one process interleave whole lines."""
    os.makedirs(metrics_dir, exist_ok=True)
    path = os.path.join(metrics_dir, filename or RUN_REPORT_FILENAME)
    _rotate_if_needed(path)
    line = json.dumps(report, default=_json_fallback)
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
    return path


def append_transform_partial(entry: Mapping[str, Any], metrics_dir: str) -> str:
    """Durable sidecar for transform partition snapshots that could not reach a
    live driver-side run (real lazy plane: the partition executes after
    transform_on_spark returned, often in another process). One JSON line per
    partition, tagged with the run id (observability/inference.py)."""
    os.makedirs(metrics_dir, exist_ok=True)
    path = os.path.join(metrics_dir, TRANSFORM_PARTIALS_FILENAME)
    _rotate_if_needed(path)
    with open(path, "a") as f:
        f.write(json.dumps(entry, default=_json_fallback) + "\n")
        f.flush()
    return path


def _json_fallback(obj: Any) -> Any:
    """Numpy scalars and other number-likes that reach a report (histogram sums
    accumulated from device timings) serialize as plain floats."""
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            return fn()
    return str(obj)


def load_run_reports(path_or_dir: str,
                     filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse a fit_reports.jsonl (or the directory holding one) back to report
    dicts — the round-trip half the acceptance tests assert. Rotated files
    (`*.jsonl.N`) are read oldest-first before the live file, so report order
    survives rotation.

    Truncated or corrupt lines (a worker killed mid-append, a torn write from
    a crashed process) are SKIPPED and counted via the
    `observability.corrupt_lines` counter instead of raising mid-load — one
    crashed worker must not poison the whole report directory."""
    path = (
        os.path.join(path_or_dir, filename or RUN_REPORT_FILENAME)
        if os.path.isdir(path_or_dir)
        else path_or_dir
    )
    paths = _rotated_paths(path)
    if not paths:
        # preserve the pre-rotation contract: a missing file raises
        paths = [path]
    reports: List[Dict[str, Any]] = []
    n_corrupt = 0
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    n_corrupt += 1
                    continue
                if not isinstance(doc, dict):
                    n_corrupt += 1  # a bare scalar is not a report line
                    continue
                reports.append(doc)
    if n_corrupt:
        from .runs import counter_inc

        counter_inc("observability.corrupt_lines", n_corrupt)
    return reports


def load_transform_reports(path_or_dir: str) -> List[Dict[str, Any]]:
    """`load_run_reports` for the transform plane's JSONL."""
    if os.path.isdir(path_or_dir):
        return load_run_reports(path_or_dir, filename=TRANSFORM_REPORT_FILENAME)
    return load_run_reports(path_or_dir)


def load_transform_partials(path_or_dir: str) -> List[Dict[str, Any]]:
    """Partition-snapshot sidecar lines (see append_transform_partial)."""
    if os.path.isdir(path_or_dir):
        return load_run_reports(path_or_dir, filename=TRANSFORM_PARTIALS_FILENAME)
    return load_run_reports(path_or_dir)


def load_serving_reports(path_or_dir: str) -> List[Dict[str, Any]]:
    """`load_run_reports` for the serving plane's JSONL (one line per serving
    session, written when the ServingRun scope closes — serving/http.py)."""
    if os.path.isdir(path_or_dir):
        return load_run_reports(path_or_dir, filename=SERVING_REPORT_FILENAME)
    return load_run_reports(path_or_dir)


def load_trace_reports(path_or_dir: str) -> List[Dict[str, Any]]:
    """`load_run_reports` for the trace plane's JSONL (one line per KEPT
    trace, written at tail-sampling time — observability/tracing.py)."""
    if os.path.isdir(path_or_dir):
        return load_run_reports(path_or_dir, filename=TRACE_REPORT_FILENAME)
    return load_run_reports(path_or_dir)


def _prom_name(name: str) -> str:
    return _PROM_PREFIX + _NAME_OK.sub("_", name)


def _prom_escape(value: Any) -> str:
    """Prometheus text-format label-VALUE escaping: backslash, double quote and
    newline are the three characters with structural meaning inside a quoted
    label value (exposition format spec). Raw interpolation corrupted the whole
    exposition when a model name or path carried any of them — one bad label
    broke every later line for the scraper. Backslash first, or the other two
    escapes would be double-escaped."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _prom_labels(labels: Mapping[str, str], extra: Optional[str] = None) -> str:
    parts = [
        f'{_NAME_OK.sub("_", k)}="{_prom_escape(v)}"'
        for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(snapshot: Mapping[str, Any]) -> str:
    """Registry snapshot -> Prometheus text exposition format. Counters and
    gauges map directly; histograms emit the classic cumulative _bucket/_sum/
    _count triplet; legacy span totals export as `*_span_seconds_total`."""
    lines: List[str] = []
    seen_types: Dict[str, str] = {}

    def _typed(name: str, kind: str) -> None:
        if seen_types.get(name) != kind:
            lines.append(f"# TYPE {name} {kind}")
            seen_types[name] = kind

    for key, v in sorted((snapshot.get("counters") or {}).items()):
        name, labels = split_label_key(key)
        pname = _prom_name(name) + "_total"
        _typed(pname, "counter")
        lines.append(f"{pname}{_prom_labels(labels)} {v}")
    for key, v in sorted((snapshot.get("gauges") or {}).items()):
        name, labels = split_label_key(key)
        pname = _prom_name(name)
        _typed(pname, "gauge")
        lines.append(f"{pname}{_prom_labels(labels)} {v}")
    for name, secs in sorted((snapshot.get("spans") or {}).items()):
        pname = _prom_name(name) + "_span_seconds_total"
        _typed(pname, "counter")
        lines.append(f"{pname} {secs}")
    for key, st in sorted((snapshot.get("histograms") or {}).items()):
        name, labels = split_label_key(key)
        pname = _prom_name(name)
        _typed(pname, "histogram")
        bounds = list(st.get("bounds") or [])
        exemplars = st.get("exemplars") or []
        cum = 0
        for i, c in enumerate(st["buckets"]):
            cum += c
            le = repr(float(bounds[i])) if i < len(bounds) else "+Inf"
            le_label = 'le="%s"' % le
            line = f"{pname}_bucket{_prom_labels(labels, le_label)} {cum}"
            # OpenMetrics exemplar: `# {trace_id="..."} value timestamp` —
            # the per-bucket trace pointer a p99 spike resolves through
            ex = exemplars[i] if i < len(exemplars) else None
            if ex is not None:
                line += (
                    f' # {{trace_id="{_prom_escape(ex["trace_id"])}"}}'
                    f' {ex["value"]} {ex["ts"]}'
                )
            lines.append(line)
        lines.append(f"{pname}_sum{_prom_labels(labels)} {st['sum']}")
        lines.append(f"{pname}_count{_prom_labels(labels)} {st['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus_textfile(path: str,
                              registry: Optional[MetricsRegistry] = None) -> str:
    """Atomically replace `path` with the registry's current state in text
    exposition format (default: the process-global registry). Atomic because a
    textfile collector may scrape mid-write; write-then-rename means it only
    ever sees whole files."""
    if registry is None:
        from .runs import global_registry

        registry = global_registry()
    text = render_prometheus(registry.snapshot())
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".prom_")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def iter_spans(report: Mapping[str, Any]) -> Iterator[Dict[str, Any]]:
    """Depth-first walk of a report's trace tree (report helpers for tests/CI)."""
    stack = list(report.get("trace") or [])
    while stack:
        node = stack.pop()
        yield node
        stack.extend(node.get("children") or [])
