#
# Communication-plane observability: HLO collective accounting, per-rank skew
# and straggler detection, and the barrier timeline (docs/design.md §6h).
#
# §6d–§6g lit the single-process axis end to end; the DISTRIBUTED axis stayed
# dark: XLA inserts the collectives (the whole point of the one-SPMD-program
# architecture, design.md §1) and nothing measured them, and per-rank skew was
# invisible even though arXiv:1612.01437 identifies straggler/partition-skew
# handling as the dominant cost of distributed Spark ML. Three things live
# here:
#
#   * Collective accounting — the ONE place in the tree that parses optimized
#     HLO text for collective ops (fence/hlo-parse-off-plane bans the dash-spelled
#     opcode patterns everywhere else, exactly like the top-k and
#     cost_analysis bans). `extract_collectives` walks an executable's HLO
#     once per (kernel, signature) — observability/device.py calls it from
#     `_compile_and_capture` — and records op counts, payload bytes (result
#     shape × dtype width) and replica-group shape per kind. Kinds use
#     underscore spellings (`all_reduce`, `all_gather`, `reduce_scatter`,
#     `collective_permute`, `all_to_all`) so callers never need the HLO text
#     forms. Per call, analyzed bytes aggregate as
#     `comm.collective_ops{kind=,kernel=}` / `comm.collective_bytes{...}` and
#     attribute to the innermost open span like flops/bytes do.
#
#   * Comm roofline — analyzed collective bytes over measured span wall time
#     yield achieved interconnect bandwidth; against the per-`device_kind`
#     ICI/link peak column of the roofline table (observability/device.py,
#     override `observability.peak_ici_bw`) that is `comm_frac`, and the
#     span's `comm_bound` verdict says whether the estimated collective time
#     exceeds the compute/memory roofline time — the "is this fit
#     allreduce-shaped or interconnect-bound" question ROADMAP item 2's pod
#     scale-out needs answered before tuning.
#
#   * Rank skew & stragglers — worker-scope snapshots (barrier fit tasks,
#     transform partitions) carry per-rank wall time, rows and bytes per
#     phase (observability/runs.py::WorkerScope.note_phase). On every
#     driver-side snapshot merge the per-phase skew ratio (max/median) lands
#     in the run-scoped `comm.rank_skew{phase=}` gauge, and a rank whose wall
#     time exceeds `observability.straggler_threshold` × median emits ONE
#     `straggler` event into the run's event log, the flight-recorder ring
#     and `comm.stragglers{phase=}`. `rank_timeline` assembles the per-rank
#     barrier timeline (start/end per phase, skew, straggler flags) served
#     live by `/runs/<run_id>/ranks` (observability/server.py), exported in
#     the run report's `ranks` section, and carried by postmortem bundles so
#     a degraded barrier fit's dump shows WHICH rank was slow.
#

from __future__ import annotations

import re
import statistics
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence

from .. import config as _config
from ..utils import get_logger

_logger = get_logger("observability.comm")

# HLO opcode (dash spelling, only legal here) -> canonical kind (underscore
# spelling, what every metric label / record key / caller uses)
_HLO_KINDS: Dict[str, str] = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "collective-permute": "collective_permute",
    "all-to-all": "all_to_all",
}

COLLECTIVE_KINDS = tuple(_HLO_KINDS.values())

# HLO primitive type -> bytes per element (token/opaque types count as 0)
_DTYPE_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# one DEFINITION line: `%name = <shape> <opcode>(...` — an optional -start
# suffix is the async launch (counted); the paired -done op re-references the
# start's result and must NOT match (it would double-count the payload).
# Operand USES of a collective's result (`fusion(... %all-reduce.8 ...)`)
# never match: the opcode must sit between the result shape and its `(`.
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(re.escape(k) for k in _HLO_KINDS) + r")"
    r"(?P<start>-start)?\(",
    re.MULTILINE,
)

_ARRAY_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

# replica_groups={{0,1},{2,3}} (explicit lists) or the iota form
# replica_groups=[2,4]<=[8] (newer XLA)
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[0-9,{}\s]*\}\}|\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)"
)


def _shape_bytes(shape: str) -> int:
    """Payload bytes of one HLO result shape (array or tuple): dtype width ×
    element count, summed over tuple elements. Layout suffixes (`{1,0}`) and
    dynamic-dimension markers are ignored by construction of the regex."""
    total = 0
    for dtype, dims in _ARRAY_RE.findall(shape):
        width = _DTYPE_BYTES.get(dtype)
        if width is None:
            continue
        n = 1
        for d in dims.split(","):
            d = d.strip()
            if d:
                n *= int(d)
        total += width * n
    return total


def extract_collectives(hlo_text: str) -> List[Dict[str, Any]]:
    """Every collective DEFINITION in an optimized-HLO text dump, in program
    order: `{"kind", "bytes", "shape", "replica_groups", "async"}` per op.
    `bytes` is the result-shape payload (the data the collective lands);
    `replica_groups` is the raw group attribute string (iota or explicit),
    empty when the op carries none."""
    out: List[Dict[str, Any]] = []
    for m in _OP_RE.finditer(hlo_text):
        line_end = hlo_text.find("\n", m.end())
        rest = hlo_text[m.end(): line_end if line_end >= 0 else len(hlo_text)]
        g = _GROUPS_RE.search(rest)
        out.append(
            {
                "kind": _HLO_KINDS[m.group("op")],
                "bytes": _shape_bytes(m.group("shape")),
                "shape": m.group("shape"),
                "replica_groups": g.group(1) if g else "",
                "async": bool(m.group("start")),
            }
        )
    return out


def collective_summary(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """Per-kind aggregation of `extract_collectives`:
    `{kind: {"ops": N, "bytes": total, "replica_groups": [distinct...]}}`.
    Kinds with zero ops are absent — an empty dict means a collective-free
    program (the single-device / fully-local case)."""
    summary: Dict[str, Dict[str, Any]] = {}
    for rec in extract_collectives(hlo_text):
        st = summary.setdefault(
            rec["kind"], {"ops": 0, "bytes": 0, "replica_groups": []}
        )
        st["ops"] += 1
        st["bytes"] += rec["bytes"]
        if rec["replica_groups"] and rec["replica_groups"] not in st["replica_groups"]:
            st["replica_groups"].append(rec["replica_groups"])
    return summary


def collectives_from_executable(exe: Any) -> Optional[Dict[str, Dict[str, Any]]]:
    """Collective summary of a compiled executable (its post-SPMD optimized
    module). Returns None when the runtime exposes no HLO text — callers
    (observability/device.py) treat that as "no collective accounting", never
    an error."""
    as_text = getattr(exe, "as_text", None)
    if not callable(as_text):
        return None
    try:
        text = as_text()
    except Exception as e:
        _logger.debug("executable as_text() failed: %s", e)
        return None
    if not text:
        return None
    return collective_summary(text)


def collectives_of_computation(fn: Any, *args: Any,
                               static_argnames: Sequence[str] = ()) -> Dict[str, Dict[str, Any]]:
    """jit → lower → compile `fn` on `args` and summarize its collectives —
    the one source of truth the communication-optimality tests
    (tests/test_collective_counts.py) assert through."""
    import jax

    jitted = jax.jit(fn, static_argnames=tuple(static_argnames))
    exe = jitted.lower(*args).compile()
    return collectives_from_executable(exe) or {}


# ------------------------------------------------------------- comm roofline


def classify_comm(flops: float, hbm_bytes: float, comm_bytes: float,
                  duration_s: float, peak_flops: float, peak_bw: float,
                  peak_ici_bw: float) -> Dict[str, Any]:
    """Comm-roofline verdict for one closed span: achieved interconnect
    bandwidth (analyzed collective bytes over measured wall time), the
    fraction of the ICI/link peak that represents (`comm_frac`), and
    `comm_bound` — True when the roofline-estimated collective time exceeds
    the compute/memory roofline time, i.e. the span's ceiling is the
    interconnect, not the chip. Same caveat as mfu (design.md §6f): wall time
    bounds dispatch on async backends, so both fractions are lower bounds."""
    out: Dict[str, Any] = {
        "comm_bytes": comm_bytes,
        "achieved_ici_bw": None,
        "comm_frac": None,
        "comm_bound": False,
    }
    if comm_bytes <= 0 or duration_s <= 0:
        return out
    achieved = comm_bytes / duration_s
    out["achieved_ici_bw"] = achieved
    if peak_ici_bw > 0:
        out["comm_frac"] = achieved / peak_ici_bw
        est_comm_s = comm_bytes / peak_ici_bw
        est_compute_s = max(
            flops / peak_flops if peak_flops > 0 else 0.0,
            hbm_bytes / peak_bw if peak_bw > 0 else 0.0,
        )
        out["comm_bound"] = est_comm_s > est_compute_s
    return out


# ------------------------------------------- per-rank skew / barrier timeline


def straggler_threshold() -> float:
    try:
        return float(_config.get("observability.straggler_threshold"))
    except (TypeError, ValueError):
        return 1.5


def straggler_min_wall_s() -> float:
    """Absolute wall-time floor under which a rank is never flagged: a ratio
    over millisecond-scale phases is GIL/scheduler jitter, not a straggler —
    without the floor an ordinary barrier fit's ~ms `collect` phase trips the
    1.5x threshold on noise alone."""
    try:
        return float(_config.get("observability.straggler_min_wall_s"))
    except (TypeError, ValueError):
        return 0.25


def _median(values: List[float]) -> float:
    return float(statistics.median(values))


def rank_timeline(workers: Sequence[Mapping[str, Any]],
                  threshold: Optional[float] = None) -> Dict[str, Any]:
    """Assemble merged worker snapshots into the barrier timeline: one entry
    per rank (wall time, start ts, per-phase start/end/rows/bytes, its worst
    skew ratio, straggler flag) plus per-phase max/median skew ratios and the
    straggler rank list. `task` is the implicit whole-scope phase every
    snapshot carries via its `wall_s`. Skew is only defined from 2 ranks up
    (a median of one is the rank itself), and a rank is only FLAGGED when its
    phase wall also clears `observability.straggler_min_wall_s` — a ratio
    over a millisecond-scale phase is scheduling noise, not a straggler."""
    thr = straggler_threshold() if threshold is None else float(threshold)
    min_wall = straggler_min_wall_s()
    per_rank: Dict[Any, Dict[str, Any]] = {}
    for w in workers:
        rank = w.get("rank")
        entry = per_rank.setdefault(rank, {
            "rank": rank,
            "wall_s": None,
            "started_ts": w.get("started_ts"),
            "rows": 0,
            "bytes": 0,
            "phases": {},
            "skew": None,
            "skew_phase": None,
            "straggler": False,
        })
        if w.get("wall_s") is not None:
            entry["wall_s"] = max(entry["wall_s"] or 0.0, float(w["wall_s"]))
        for phase, st in (w.get("phases") or {}).items():
            ph = entry["phases"].setdefault(phase, {
                "wall_s": 0.0, "rows": 0, "bytes": 0,
                "start_ts": None, "end_ts": None,
            })
            ph["wall_s"] += float(st.get("wall_s") or 0.0)
            ph["rows"] += int(st.get("rows") or 0)
            ph["bytes"] += int(st.get("bytes") or 0)
            for key, pick in (("start_ts", min), ("end_ts", max)):
                v = st.get(key)
                if v is not None:
                    ph[key] = v if ph[key] is None else pick(ph[key], v)
        # top-level rows/bytes are the rank's LARGEST phase, not a sum: the
        # same partition rides several phases (collect rows == fit rows), and
        # summing would double-count it in the timeline
        entry["rows"] = max(
            (int(ph["rows"]) for ph in entry["phases"].values()), default=0
        )
        entry["bytes"] = max(
            (int(ph["bytes"]) for ph in entry["phases"].values()), default=0
        )
    # per-phase walls across ranks; named phases FIRST so that on a tied skew
    # ratio the rank's `skew_phase` names the informative phase, not the
    # implicit whole-scope `task` catch-all
    phase_walls: Dict[str, List[Any]] = {}
    for entry in per_rank.values():
        for phase, ph in entry["phases"].items():
            phase_walls.setdefault(phase, []).append(
                (entry["rank"], float(ph["wall_s"]))
            )
    for entry in per_rank.values():
        if entry["wall_s"] is not None:
            phase_walls.setdefault("task", []).append(
                (entry["rank"], float(entry["wall_s"]))
            )
    skew: Dict[str, float] = {}
    stragglers: set = set()
    for phase, pairs in phase_walls.items():
        walls = [wll for _, wll in pairs]
        if len(walls) < 2:
            continue
        med = _median(walls)
        if med <= 0:
            continue
        skew[phase] = round(max(walls) / med, 4)
        for rank, wll in pairs:
            ratio = wll / med
            entry = per_rank[rank]
            if entry["skew"] is None or ratio > entry["skew"]:
                entry["skew"] = round(ratio, 4)
                entry["skew_phase"] = phase
            if ratio > thr and wll >= min_wall:
                entry["straggler"] = True
                stragglers.add(rank)
    ranks = sorted(
        per_rank.values(),
        key=lambda e: (e["rank"] is None, e["rank"]),
    )
    return {
        "ranks": ranks,
        "skew": skew,
        "stragglers": sorted(stragglers, key=lambda r: (r is None, r)),
        "threshold": thr,
    }


def note_worker_merge(run: Any) -> None:
    """FitRun.add_worker_snapshot hook: recompute the rank timeline over the
    run's merged snapshots, land the per-phase skew ratios in the RUN-scoped
    `comm.rank_skew{phase=}` gauges (plus the process-global registry — a
    dashboard scraping /metrics sees skew without joining runs), and emit ONE
    `straggler` event per newly-detected slow rank into the run's event log,
    the flight recorder and `comm.stragglers{phase=}`. Must never raise — it
    sits on the fit-result merge path of a barrier stage that already
    SUCCEEDED.

    Events are emitted from a STREAMING prefix (snapshots merge one at a
    time) and cannot be retracted, so they only fire once >= 3 ranks are
    visible — a max/median over two ranks flags whichever happens to be
    slower, and an early skewed prefix would stamp a permanent false alert
    on a normal rank. The timeline itself (`rank_view`, the report's `ranks`
    section, `/runs/<id>/ranks`) is always recomputed over the full merged
    set: treat events as alerts, the timeline as truth."""
    from . import flight as _flight
    from . import runs as _runs

    timeline = run.rank_view()
    if not timeline["ranks"]:
        return
    regs = [run.registry, _runs.global_registry()]
    for phase, ratio in timeline["skew"].items():
        for reg in regs:
            reg.gauge("comm.rank_skew").set(ratio, phase=phase)
    if len(timeline["ranks"]) < 3:
        return  # prefix too small for a defensible, unretractable alert
    seen = getattr(run, "_straggler_ranks", None)
    if seen is None:
        seen = run._straggler_ranks = set()
    thr = timeline["threshold"]
    for entry in timeline["ranks"]:
        if not entry["straggler"] or entry["rank"] in seen:
            continue
        seen.add(entry["rank"])
        worst_phase = entry.get("skew_phase") or "task"
        event = {
            "ts": round(time.time(), 6),
            "kind": "straggler",
            "rank": entry["rank"],
            "phase": worst_phase,
            "ratio": entry["skew"],
            "threshold": thr,
            "wall_s": entry["wall_s"],
        }
        run.add_event(event)
        _flight.note_event(event)
        for reg in regs:
            reg.counter("comm.stragglers").inc(1, phase=worst_phase)
        _logger.warning(
            "straggler: rank %s ran %.2fx the median in phase '%s' "
            "(threshold %.2fx)", entry["rank"], entry["skew"] or 0.0,
            worst_phase, thr,
        )


# ------------------------------------------------------------- bench summary


def scenario_comm_summary(report: Mapping[str, Any],
                          wall_s: Optional[float] = None) -> Dict[str, Any]:
    """Communication summary of one run report (a bench scenario): total
    analyzed collective ops/bytes from the run's `comm.*` counters, the
    scenario-level `comm_frac` (collective bytes over wall clock against the
    per-chip ICI peak — same wall-clock caveats as `scenario_summary`'s mfu),
    and the worst `comm.rank_skew` gauge when the scenario exercised the
    rank-snapshot plane. bench.py emits these as `<unit>_comm_frac` /
    `<unit>_rank_skew`, gated advisory by ci/bench_check.py."""
    from . import device as _device

    metrics = report.get("metrics") or {}
    counters = metrics.get("counters") or {}
    comm_bytes = float(sum(
        v for k, v in counters.items() if k.startswith("comm.collective_bytes")
    ))
    comm_ops = int(sum(
        v for k, v in counters.items() if k.startswith("comm.collective_ops")
    ))
    wall = wall_s if wall_s is not None else (report.get("duration_s") or 0.0)
    ici = _device.platform_ici_bw()
    comm_frac = (
        round((comm_bytes / wall) / ici, 6)
        if comm_bytes > 0 and wall and wall > 0 and ici > 0
        else None
    )
    skews = [
        v for k, v in (metrics.get("gauges") or {}).items()
        if k.startswith("comm.rank_skew")
    ]
    return {
        "comm_ops": comm_ops,
        "comm_bytes": comm_bytes,
        "comm_frac": comm_frac,
        "rank_skew": round(max(skews), 4) if skews else None,
    }
