#
# Observability subsystem: typed metrics registry, per-fit trace trees, the
# inference-plane mirror (TransformRun + predict dispatch + recompile
# sentinel), driver-side aggregation across the barrier fit plane, and
# exporters (docs/design.md §6d/§6e). `profiling.py` is a thin compat shim over
# this package; new instrumentation should import from here directly.
#
#   registry.py   Counter / Gauge / Histogram (+ quantile) / MetricsRegistry
#   runs.py       write fan-out, structured spans, events, FitRun, worker_scope,
#                 live progress gauges + convergence records
#   inference.py  TransformRun, predict_dispatch, shape buckets + sentinel
#   export.py     JSONL run/transform reports (rotating) + Prometheus textfile
#   device.py     compiled_kernel cost/memory-analysis capture, HBM telemetry,
#                 roofline span attribution, compile accounting, profiler hook
#   server.py     opt-in live HTTP endpoint: /metrics, /healthz, /runs[/<id>],
#                 /runs/<id>/ranks (barrier timeline)
#   flight.py     failure flight recorder: bounded ring buffer + postmortem
#                 bundles (postmortem_<run_id>.json)
#   tracing.py    causal request tracing (§6l): W3C traceparent ids, per-request
#                 span trees with fan-in links, tail-based sampling ring,
#                 trace_reports.jsonl export + /traces live endpoints
#   comm.py       communication plane: HLO collective accounting, comm
#                 roofline, per-rank skew + straggler detection, timeline
#

from .registry import (
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    interpolate_quantile,
    label_key,
    split_label_key,
)
from .runs import (
    PROCESS_TOKEN,
    FitRun,
    WorkerScope,
    active_runs,
    add_span_total,
    convergence,
    counter_inc,
    current_run,
    event,
    find_run,
    fit_run,
    gauge_dec,
    gauge_inc,
    gauge_set,
    global_registry,
    legacy_count,
    note_rank_phase,
    observe,
    progress,
    span,
    worker_scope,
)
from .comm import (
    COLLECTIVE_KINDS,
    collective_summary,
    collectives_from_executable,
    collectives_of_computation,
    extract_collectives,
    rank_timeline,
    scenario_comm_summary,
)
from .inference import (
    TransformRun,
    deliver_partition_snapshot,
    predict_dispatch,
    reset_shape_buckets,
    suppress_transform_runs,
    transform_batch,
    transform_run,
)
from .export import (
    TRACE_REPORT_FILENAME,
    load_run_reports,
    load_serving_reports,
    load_trace_reports,
    load_transform_partials,
    load_transform_reports,
    render_prometheus,
    write_prometheus_textfile,
    write_run_report,
)
from .device import (
    CompiledKernel,
    compiled_kernel,
    kernel_cost,
    kernel_cost_records,
    platform_ici_bw,
    platform_peaks,
    profile_pass,
    sample_hbm,
    scenario_summary,
)
from .server import (
    server_address,
    start_metrics_server,
    stop_metrics_server,
)
from .flight import (
    dump_postmortem,
    load_postmortem,
    reset_flight_recorder,
)
from .tracing import (
    RequestTrace,
    TraceContext,
    format_traceparent,
    get_trace,
    parse_traceparent,
    reset_tracing,
    ring_snapshot,
    start_trace,
    trace_index,
    would_keep,
)

__all__ = [
    "DEFAULT_TIME_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "interpolate_quantile",
    "label_key",
    "split_label_key",
    "PROCESS_TOKEN",
    "FitRun",
    "WorkerScope",
    "active_runs",
    "add_span_total",
    "convergence",
    "counter_inc",
    "current_run",
    "event",
    "find_run",
    "fit_run",
    "gauge_dec",
    "gauge_inc",
    "gauge_set",
    "global_registry",
    "legacy_count",
    "note_rank_phase",
    "observe",
    "progress",
    "span",
    "worker_scope",
    "COLLECTIVE_KINDS",
    "collective_summary",
    "collectives_from_executable",
    "collectives_of_computation",
    "extract_collectives",
    "rank_timeline",
    "scenario_comm_summary",
    "TransformRun",
    "deliver_partition_snapshot",
    "predict_dispatch",
    "reset_shape_buckets",
    "suppress_transform_runs",
    "transform_batch",
    "transform_run",
    "TRACE_REPORT_FILENAME",
    "load_run_reports",
    "load_serving_reports",
    "load_trace_reports",
    "load_transform_partials",
    "load_transform_reports",
    "render_prometheus",
    "write_prometheus_textfile",
    "write_run_report",
    "CompiledKernel",
    "compiled_kernel",
    "kernel_cost",
    "kernel_cost_records",
    "platform_ici_bw",
    "platform_peaks",
    "profile_pass",
    "sample_hbm",
    "scenario_summary",
    "server_address",
    "start_metrics_server",
    "stop_metrics_server",
    "dump_postmortem",
    "load_postmortem",
    "reset_flight_recorder",
    "RequestTrace",
    "TraceContext",
    "format_traceparent",
    "get_trace",
    "parse_traceparent",
    "reset_tracing",
    "ring_snapshot",
    "start_trace",
    "trace_index",
    "would_keep",
]
