#
# Observability subsystem: typed metrics registry, per-fit trace trees, driver-
# side aggregation across the barrier fit plane, and exporters
# (docs/design.md §6d). `profiling.py` is a thin compat shim over this package;
# new instrumentation should import from here directly.
#
#   registry.py  Counter / Gauge / Histogram / MetricsRegistry (+ merge)
#   runs.py      write fan-out, structured spans, events, FitRun, worker_scope
#   export.py    JSONL run reports + Prometheus textfile
#

from .registry import (
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    label_key,
    split_label_key,
)
from .runs import (
    PROCESS_TOKEN,
    FitRun,
    WorkerScope,
    add_span_total,
    counter_inc,
    current_run,
    event,
    fit_run,
    gauge_dec,
    gauge_inc,
    gauge_set,
    global_registry,
    legacy_count,
    observe,
    span,
    worker_scope,
)
from .export import (
    load_run_reports,
    render_prometheus,
    write_prometheus_textfile,
    write_run_report,
)

__all__ = [
    "DEFAULT_TIME_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "label_key",
    "split_label_key",
    "PROCESS_TOKEN",
    "FitRun",
    "WorkerScope",
    "add_span_total",
    "counter_inc",
    "current_run",
    "event",
    "fit_run",
    "gauge_dec",
    "gauge_inc",
    "gauge_set",
    "global_registry",
    "legacy_count",
    "observe",
    "span",
    "worker_scope",
    "load_run_reports",
    "render_prometheus",
    "write_prometheus_textfile",
    "write_run_report",
]
