#
# Typed metrics registry — the storage half of the observability subsystem
# (docs/design.md §6d). The pre-observability `profiling.py` kept two flat
# process-global dicts (name -> float seconds, name -> int count); everything
# that wanted richer semantics had to fake them — the HBM batch cache modeled
# its bytes-resident GAUGE as negative counter increments, and per-batch
# latencies collapsed into a single sum that could never answer "p99 ingest
# time". This module gives each semantic its own type, MLlib-style (fit
# summaries as first-class API, arXiv:1505.06807):
#
#   Counter   monotone event count        (retries, uploads, cache hits)
#   Gauge     set/inc/dec current value   (cache.bytes_resident)
#   Histogram exponential-bucket samples  (per-batch ingest/step seconds)
#   span totals  name -> accumulated seconds (the legacy span_totals surface)
#
# All metrics carry optional LABELS (site=, algo=, pass_=...) serialized into
# the key as `name{k=v,...}`; unlabeled metrics keep their bare name, which is
# what keeps every pre-existing `profiling.counter_totals()` assertion working
# unchanged through the compat shims.
#
# A MetricsRegistry is a plain value container: thread-safe, snapshot-able to
# a JSON-serializable dict, and MERGEABLE — `merge_snapshot` is how the driver
# folds per-barrier-worker snapshots into one fit report (spark/integration.py)
# and how a FitRun's scoped registry stays independent of `reset_counters()`
# on the global one (observability/runs.py).
#

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

# default exponential latency buckets: 100us * 2^i, i in [0, 20) — covers one
# fast device step through a ~52 s pathological batch; the +inf bucket is
# implicit (observations above the last bound land in it)
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = tuple(1e-4 * 2.0 ** i for i in range(20))


# characters with structural meaning in a label key; sanitized out of label
# names/values so split_label_key is a TRUE inverse of label_key — an
# unescaped ','/'=' in a value (e.g. an exception message used as a label)
# would otherwise silently re-key the metric when a worker snapshot merges
_LABEL_STRUCTURAL = str.maketrans({"{": "_", "}": "_", ",": "_", "=": "_"})


def label_key(name: str, labels: Optional[Mapping[str, Any]] = None) -> str:
    """Canonical metric key: `name` or `name{k=v,...}` with sorted label names;
    structural characters in label names/values sanitize to '_'."""
    if not labels:
        return name
    inner = ",".join(
        f"{str(k).translate(_LABEL_STRUCTURAL)}"
        f"={str(labels[k]).translate(_LABEL_STRUCTURAL)}"
        for k in sorted(labels)
    )
    return f"{name}{{{inner}}}"


def split_label_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of label_key (values come back as strings)."""
    if not key.endswith("}") or "{" not in key:
        return key, {}
    name, _, inner = key.partition("{")
    labels: Dict[str, str] = {}
    for part in inner[:-1].split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        labels[k] = v
    return name, labels


class _Metric:
    """Shared per-name metric state: a dict of label-key -> value, guarded by
    the owning registry's lock (metrics never outlive their registry)."""

    kind = "metric"

    def __init__(self, name: str, lock: threading.RLock):
        self.name = name
        self._lock = lock
        self._values: Dict[str, Any] = {}

    def _key(self, labels: Optional[Mapping[str, Any]]) -> str:
        return label_key(self.name, labels)


class Counter(_Metric):
    """Monotone event counter. Negative increments are a type error — that is
    exactly the gauge-as-counter hack this registry exists to retire."""

    kind = "counter"

    def inc(self, n: int = 1, **labels: Any) -> None:
        if n < 0:
            raise ValueError(
                f"Counter '{self.name}' increment must be >= 0 (got {n}); "
                "use a Gauge for values that go down."
            )
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels: Any) -> int:
        with self._lock:
            return self._values.get(self._key(labels), 0)


class Gauge(_Metric):
    """Current-value metric: set to an absolute value or moved by deltas."""

    kind = "gauge"

    def set(self, value: Any, **labels: Any) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def inc(self, n: Any = 1, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def dec(self, n: Any = 1, **labels: Any) -> None:
        self.inc(-n, **labels)

    def value(self, **labels: Any) -> Any:
        with self._lock:
            return self._values.get(self._key(labels), 0)


class Histogram(_Metric):
    """Exponential-bucket histogram. Per label-set state is
    {"count": n, "sum": s, "buckets": [per-bucket counts, len(bounds)+1]} —
    the last slot is the +inf bucket. Bounds are upper-inclusive (`v <= le`),
    Prometheus semantics."""

    kind = "histogram"

    def __init__(self, name: str, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, lock)
        self.bounds: Tuple[float, ...] = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, exemplar: Any = None,
                **labels: Any) -> None:
        v = float(value)
        # leftmost bound with v <= bound; +inf slot otherwise. Bisection is
        # overkill at 20 bounds; a linear scan stays cache-friendly and cheap.
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        key = self._key(labels)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                state = self._values[key] = {
                    "count": 0,
                    "sum": 0.0,
                    "buckets": [0] * (len(self.bounds) + 1),
                    "min": v,
                    "max": v,
                }
            state["count"] += 1
            state["sum"] += v
            state["buckets"][idx] += 1
            # true observed extremes: what q=0.0 / q=1.0 return EXACTLY instead
            # of a bucket-edge interpolation that can overshoot every sample
            if v < state["min"]:
                state["min"] = v
            if v > state["max"]:
                state["max"] = v
            # per-bucket exemplar slot (§6l): one trace_id per bucket,
            # last-write-wins — the freshest trace that landed in this latency
            # band, which is what a /metrics p99 spike resolves through
            if exemplar is not None:
                ex = state.get("exemplars")
                if ex is None:
                    ex = state["exemplars"] = (
                        [None] * (len(self.bounds) + 1))
                ex[idx] = {
                    "value": v,
                    "trace_id": str(exemplar),
                    "labels": dict(labels),
                    "ts": round(time.time(), 6),
                }

    def state(self, **labels: Any) -> Optional[Dict[str, Any]]:
        with self._lock:
            st = self._values.get(self._key(labels))
            if st is None:
                return None
            out = {
                "count": st["count"], "sum": st["sum"],
                "buckets": list(st["buckets"]),
                "min": st.get("min"), "max": st.get("max"),
            }
            ex = st.get("exemplars")
            if ex is not None:
                out["exemplars"] = [
                    dict(e) if e is not None else None for e in ex]
            return out

    def quantile(self, q: float, **labels: Any) -> Optional[float]:
        """Estimated q-quantile with exponential-bucket interpolation (see
        interpolate_quantile). Edge semantics: None when no observations exist
        (an empty histogram has no quantiles — interpolating would fabricate
        one); q<=0.0 returns the true observed minimum and q>=1.0 the true
        observed maximum."""
        st = self.state(**labels)
        if st is None or st["count"] <= 0:
            return None
        return interpolate_quantile(st, q, self.bounds)


class MetricsRegistry:
    """Thread-safe collection of typed metrics + legacy span totals.

    One registry instance backs the process-global metric surface
    (`observability.global_registry()`, which the `profiling` compat shims
    read); every FitRun and barrier-worker scope owns another, fed by the same
    fan-out write path (observability/runs.py), so `reset_counters()` on the
    global registry can never corrupt an in-flight scoped run."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._span_totals: Dict[str, float] = {}

    # ---- get-or-create (kind-checked: one name, one type) ----

    def _get(self, name: str, kind: type, **kw: Any) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = kind(name, self._lock, **kw)
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric '{name}' already registered as {m.kind}, "
                    f"requested {kind.__name__.lower()}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        return self._get(name, Histogram, buckets=buckets)

    def legacy_count(self, name: str, n: int) -> None:
        """Signed increment for the legacy `profiling.count()` surface, which
        never distinguished counters from gauges: positive increments create/
        use a Counter; the first NEGATIVE increment retypes the metric to a
        Gauge carrying its accumulated values — a name's kind is discovered
        from usage, so the historical gauge-as-counter pattern (positive then
        negative increments under one name) keeps its arithmetic."""
        with self._lock:
            m = self._metrics.get(name)
            if isinstance(m, Gauge) or (m is None and n < 0):
                self.gauge(name).inc(n)
            elif (m is None or isinstance(m, Counter)) and n >= 0:
                self.counter(name).inc(n)
            elif isinstance(m, Counter):  # first negative on a counter: retype
                g = Gauge(name, self._lock)
                g._values = dict(m._values)
                self._metrics[name] = g
                g.inc(n)
            else:  # name already a histogram etc.: surface the kind conflict
                self.counter(name).inc(n)

    # ---- legacy span totals (profiling.span_totals surface) ----

    def add_span_total(self, name: str, seconds: float) -> None:
        with self._lock:
            self._span_totals[name] = self._span_totals.get(name, 0.0) + seconds

    def span_totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._span_totals)

    def reset_spans(self) -> None:
        with self._lock:
            self._span_totals.clear()

    # ---- flat read surfaces ----

    def _flat(self, kind: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            for m in self._metrics.values():
                if m.kind != kind:
                    continue
                for key, v in m._values.items():
                    if kind != "histogram":
                        out[key] = v
                        continue
                    st = {"count": v["count"], "sum": v["sum"],
                          "buckets": list(v["buckets"]),
                          "min": v.get("min"), "max": v.get("max"),
                          "bounds": list(m.bounds)}  # type: ignore[attr-defined]
                    ex = v.get("exemplars")
                    if ex is not None:
                        st["exemplars"] = [
                            dict(e) if e is not None else None for e in ex]
                    out[key] = st
        return out

    def counter_totals(self) -> Dict[str, Any]:
        """Counters AND gauges flattened to one name -> value dict — the exact
        legacy `profiling.counter_totals()` surface (pre-observability code
        reported gauges through it as signed counter increments, and its tests
        assert e.g. `totals['cache.bytes_resident'] == 0`)."""
        out = self._flat("counter")
        out.update(self._flat("gauge"))
        return out

    def reset_counters(self) -> None:
        """Clear counter/gauge/histogram VALUES (metric objects and their
        types/buckets survive — a reset must not let a name change kind)."""
        with self._lock:
            for m in self._metrics.values():
                m._values.clear()

    # ---- snapshot / merge ----

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable full state: the payload barrier workers ship to
        the driver and the `metrics` section of a fit report."""
        return {
            "counters": self._flat("counter"),
            "gauges": self._flat("gauge"),
            "histograms": self._flat("histogram"),
            "spans": self.span_totals(),
        }

    def merge_snapshot(self, snap: Mapping[str, Any]) -> None:
        """Fold another registry's snapshot into this one: counters, gauges and
        span totals ADD (a merged gauge is a sum over workers — total bytes
        resident across the pod); histograms merge count/sum/bucket-wise."""
        for key, v in (snap.get("counters") or {}).items():
            name, labels = split_label_key(key)
            self.counter(name).inc(v, **labels)
        for key, v in (snap.get("gauges") or {}).items():
            name, labels = split_label_key(key)
            self.gauge(name).inc(v, **labels)
        for name, secs in (snap.get("spans") or {}).items():
            self.add_span_total(name, secs)
        for key, st in (snap.get("histograms") or {}).items():
            name, labels = split_label_key(key)
            h = self.histogram(name, buckets=st.get("bounds") or DEFAULT_TIME_BUCKETS)
            lkey = label_key(name, labels)
            with self._lock:
                mine = h._values.get(lkey)
                if mine is None:
                    mine = h._values[lkey] = {
                        "count": 0, "sum": 0.0,
                        "buckets": [0] * (len(h.bounds) + 1),
                    }
                mine["count"] += st["count"]
                mine["sum"] += st["sum"]
                for fn, key_mm in ((min, "min"), (max, "max")):
                    other = st.get(key_mm)
                    if other is None:
                        continue
                    ours = mine.get(key_mm)
                    mine[key_mm] = other if ours is None else fn(ours, other)
                theirs: List[int] = list(st["buckets"])
                if len(theirs) == len(mine["buckets"]):
                    mine["buckets"] = [
                        a + b for a, b in zip(mine["buckets"], theirs)
                    ]
                else:  # mismatched bucket layouts: keep count/sum, drop shape
                    mine["buckets"][-1] += sum(theirs)
                # exemplar slots keep last-write-wins across the merge too:
                # per bucket, the later timestamp survives
                theirs_ex = st.get("exemplars")
                if theirs_ex and len(theirs_ex) == len(mine["buckets"]):
                    ex = mine.get("exemplars")
                    if ex is None:
                        ex = mine["exemplars"] = (
                            [None] * len(mine["buckets"]))
                    for i, other in enumerate(theirs_ex):
                        if other is None:
                            continue
                        ours = ex[i]
                        if ours is None or (other.get("ts") or 0) >= (
                                ours.get("ts") or 0):
                            ex[i] = dict(other)


def interpolate_quantile(state: Mapping[str, Any], q: float,
                         bounds: Sequence[float]) -> float:
    """Quantile estimate with WITHIN-bucket interpolation, matched to the
    exponential bucket layout: mass inside a bucket is assumed log-uniform, so
    the estimate is `lo * (hi/lo)**frac` (geometric interpolation — a straight
    linear blend would systematically overestimate low quantiles when bucket
    widths double). The first bucket interpolates linearly from 0; the +inf
    bucket clamps to the largest finite bound (nothing sane to extrapolate to).
    Exact edge semantics: when q*count lands exactly on a bucket's cumulative
    boundary the estimate is that bucket's upper bound — the same
    upper-inclusive convention the buckets themselves use (`v <= le`). States
    that track true observed extremes ("min"/"max" keys, Histogram.observe)
    return them EXACTLY at q<=0.0 / q>=1.0 instead of a bucket-edge estimate;
    legacy states without them keep the interpolated clamp."""
    total = state["count"]
    if total <= 0:
        return math.nan
    q = min(max(float(q), 0.0), 1.0)
    if q <= 0.0 and state.get("min") is not None:
        return float(state["min"])
    if q >= 1.0 and state.get("max") is not None:
        return float(state["max"])
    target = q * total
    bounds = [float(b) for b in bounds]
    seen = 0.0
    for i, c in enumerate(state["buckets"]):
        if c <= 0:
            continue
        if seen + c >= target - 1e-12:
            frac = 0.0 if c == 0 else min(max((target - seen) / c, 0.0), 1.0)
            if i >= len(bounds):  # +inf bucket
                return bounds[-1] if bounds else math.nan
            hi = bounds[i]
            lo = bounds[i - 1] if i > 0 else 0.0
            if lo <= 0.0:
                return hi * frac
            return lo * (hi / lo) ** frac
        seen += c
    return bounds[-1] if bounds else math.nan


def quantile_from_state(state: Mapping[str, Any], q: float,
                        bounds: Sequence[float]) -> float:
    """Approximate quantile from histogram state (upper bound of the bucket the
    q-th sample lands in) — good enough for report summaries; +inf bucket
    reports the largest finite bound."""
    total = state["count"]
    if total <= 0:
        return math.nan
    target = q * total
    seen = 0
    for i, c in enumerate(state["buckets"]):
        seen += c
        if seen >= target and c > 0:
            return float(bounds[i]) if i < len(bounds) else float(bounds[-1])
    return float(bounds[-1])
