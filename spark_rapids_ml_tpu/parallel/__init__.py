from .mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    default_num_workers,
    get_mesh,
    replicate_array,
    row_sharding,
    shard_array,
)
from .partition import PartitionDescriptor, even_partition_sizes, pad_rows
from .bootstrap import init_from_env, init_process_group, reset_process_group
from .partitioner import (
    DataParallelPartitioner,
    Partitioner,
    SPMDPartitioner,
    active_partitioner,
    mesh_of,
    partitioner_for,
    put_device_local,
    replicate_rows,
    reset_partitioner,
    resolve_batch_rows_per_process,
    resolve_feature_axis,
    set_partitioner,
    shard_rows,
    use_partitioner,
)
