from .mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    default_num_workers,
    get_mesh,
    replicate_array,
    row_sharding,
    shard_array,
)
from .partition import PartitionDescriptor, even_partition_sizes, pad_rows
from .bootstrap import init_process_group
