#
# Multi-host process-group bootstrap — the TPU analog of the reference's NCCL-uid
# handshake (reference common/cuml_context.py:75-110: rank 0 creates the uid, the Spark
# barrier allGather distributes it, every rank calls nccl.init).
#
# On TPU pods, jax.distributed.initialize plays that role once per host process: the
# coordinator address takes the place of the NCCL uid, and any hardware-agnostic
# control plane (Spark barrier allGather, a file system rendezvous, GCE metadata) can
# carry it. After initialization, jax.devices() spans the pod and Mesh/pjit handle all
# collective wiring — there is no per-algorithm communicator to inject.
#

from __future__ import annotations

import os
from typing import Callable, Optional

import jax

from ..utils import get_logger

_initialized = False


def init_process_group(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    allgather_fn: Optional[Callable[[str], list]] = None,
) -> None:
    """Initialize the multi-host JAX runtime.

    `allgather_fn` is the pluggable control plane: given this rank's string payload it
    must return every rank's payload in rank order — a Spark BarrierTaskContext.allGather
    fits directly (the reference's bootstrap control plane, cuml_context.py:80-110).
    Rank 0 advertises its address; all ranks then initialize against it.

    No-op on single-process runs (local mode / tests), mirroring how the reference skips
    UCX when nranks == 1 would make it pointless.
    """
    global _initialized
    if _initialized:
        return
    logger = get_logger("bootstrap")

    if coordinator_address is None and allgather_fn is None:
        # env-driven bootstrap: a launcher (mpirun wrapper, k8s pod spec,
        # the CI multihost smoke) exports the rendezvous instead of running
        # a barrier allGather
        coordinator_address = os.environ.get("SRML_TPU_COORDINATOR") or None
        if coordinator_address is not None:
            if num_processes is None:
                num_processes = int(os.environ.get("SRML_TPU_NUM_PROCESSES", "1"))
            if process_id is None:
                process_id = int(os.environ.get("SRML_TPU_PROCESS_ID", "0"))

    if coordinator_address is None and allgather_fn is not None:
        import socket

        my_payload = ""
        if process_id == 0:
            host = socket.gethostbyname(socket.gethostname())
            port = int(os.environ.get("SPARK_RAPIDS_ML_TPU_COORD_PORT", "8476"))
            my_payload = f"{host}:{port}"
        payloads = allgather_fn(my_payload)
        coordinator_address = next(p for p in payloads if p)
        if num_processes is None:
            num_processes = len(payloads)  # the barrier width IS the process count

    if coordinator_address is None or num_processes in (None, 1):
        logger.debug("single-process run; skipping jax.distributed.initialize")
        _initialized = True
        return

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: process %s/%s via %s",
        process_id,
        num_processes,
        coordinator_address,
    )
    _initialized = True


def init_from_env() -> bool:
    """Bootstrap from the SRML_TPU_COORDINATOR / SRML_TPU_NUM_PROCESSES /
    SRML_TPU_PROCESS_ID environment (the control-plane-free launcher path).
    Returns True when a multi-process group was (or already is) up."""
    init_process_group()
    return jax.process_count() > 1


def reset_process_group() -> None:
    """Tear down a (possibly partial) jax.distributed link so a barrier retry can
    re-initialize against a freshly probed coordinator port (the TOCTOU recovery
    in spark/integration.py). Best-effort: shutdown failures are logged, never
    allowed to mask the failure that triggered the reset."""
    global _initialized
    try:
        jax.distributed.shutdown()
    except Exception as e:
        get_logger("bootstrap").debug("jax.distributed.shutdown during reset: %s", e)
    _initialized = False
