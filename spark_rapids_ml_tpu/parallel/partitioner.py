#
# The Partitioner — single owner of every sharding decision (L2; the
# multi-host completion of the mesh runtime, docs/design.md §10).
#
# Before this module, NamedSharding/device_put construction was scattered
# across ~10 files in ops/ and models/, every one assuming a single process
# owning the whole mesh. The Partitioner centralizes that: it owns the Mesh,
# the data/state PartitionSpecs, and the host->device placement entry points,
# so ops and models never build shardings themselves — they ask the active
# Partitioner (or pass its mesh through, which resolves back here via
# `shard_rows`/`replicate_rows`).
#
# The multi-host contract (DrJAX's MapReduce decomposition, arXiv:2403.07128;
# Podracer's per-process feed -> pod-wide SPMD step split, arXiv:2104.06272):
#   * each process stages ONLY its local rows — `shard_inputs` uses
#     jax.make_array_from_process_local_data, so no host ever gathers a
#     global array (that is the perf win at pod scale: ingest bandwidth
#     scales with the pod, collective bytes stay proportional to MODEL size);
#   * the fit program itself is unchanged: XLA inserts the cross-host
#     collectives when the jitted program runs over the pod-spanning mesh,
#     which is why the 2-process emulated fit is bit-identical to the
#     single-process fit (same global array, same mesh, same HLO).
#
# Precedence for "which partitioner is active":
#   1. an explicitly installed partitioner (`set_partitioner` /
#      `use_partitioner`) — the multi-host barrier task installs one built
#      from rendezvous rank info;
#   2. otherwise a cached default DataParallelPartitioner over `num_workers`
#      devices (all addressable devices when unspecified), which reuses
#      mesh.get_mesh's cached default mesh so single-process placement is
#      bit-identical to the pre-Partitioner path.
#

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import config as _config
from .mesh import DATA_AXIS, FEATURE_AXIS, get_mesh

ROW_MULTIPLE = 8  # float32 sublane tile; keeps per-device shards MXU-friendly


class Partitioner:
    """Owns the mesh and every sharding derived from it.

    Subclasses fix the mesh topology (1-D data-parallel, 2-D data x feature).
    All host->device placement in the fit/transform planes funnels through
    `shard` / `replicate` / `shard_inputs` so the multi-host staging rule
    (local rows only) holds everywhere at once.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    # ------------------------------------------------------------ topology

    @property
    def num_workers(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def process_index(self) -> int:
        return int(jax.process_index())

    @property
    def process_count(self) -> int:
        return int(jax.process_count())

    @property
    def is_multiprocess(self) -> bool:
        return self.process_count > 1

    @property
    def local_device_count(self) -> int:
        """Mesh devices addressable by THIS process (== mesh size when
        single-process; the per-host slice of the pod otherwise)."""
        pi = jax.process_index()
        n = sum(1 for d in self.mesh.devices.flat if d.process_index == pi)
        return n or 1

    # ------------------------------------------------------------ shardings

    @property
    def data_axis(self) -> str:
        """Name of the mesh axis rows shard over — the axis every in-program
        collective (psum/all_gather/ppermute) reduces across."""
        return DATA_AXIS

    def data_spec(self, ndim: int = 2) -> PartitionSpec:
        """Rows sharded across the data axis, everything else replicated."""
        return PartitionSpec(*([DATA_AXIS] + [None] * (ndim - 1)))

    def state_spec(self) -> PartitionSpec:
        """Model state (centroids, coefficients, covariance) is replicated —
        this is what makes the fits allreduce-shaped: collective bytes are
        proportional to the state, never to the data."""
        return PartitionSpec()

    def data_sharding(self, ndim: int = 2) -> NamedSharding:
        return NamedSharding(self.mesh, self.data_spec(ndim))

    def state_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.state_spec())

    # ------------------------------------------------------------ placement

    def shard(self, x: Any) -> jax.Array:
        """Place a host array on the mesh with rows on the data axis
        (single-process; for multi-process staging use `shard_inputs`)."""
        return jax.device_put(x, self.data_sharding(np.ndim(x)))

    def replicate(self, x: Any) -> jax.Array:
        return jax.device_put(x, self.state_sharding())

    def put_local(self, x: Any) -> jax.Array:
        """Default-device placement for host-resident block scans that never
        enter the SPMD program (the pairwise streaming device blocks)."""
        import jax.numpy as jnp

        return jax.device_put(jnp.asarray(x))

    def shard_inputs(self, *local_arrays: Optional[np.ndarray]) -> List[Optional[jax.Array]]:
        """Assemble global row-sharded arrays from per-process LOCAL rows.

        Always via `jax.make_array_from_process_local_data`: each process
        stages only the rows it holds; no host gathers a global array. On a
        single process that is exactly a sharded device_put (bit-identical to
        the pre-Partitioner path). Every local array must already be padded
        to the common per-rank height (`local_pad_rows`); `None` entries pass
        through.
        """
        out: List[Optional[jax.Array]] = []
        for a in local_arrays:
            if a is None:
                out.append(None)
                continue
            sh = self.data_sharding(np.ndim(a))
            out.append(jax.make_array_from_process_local_data(sh, a))
        return out

    # ------------------------------------------------------------ staging

    def local_pad_rows(self, max_rank_rows: int) -> int:
        """Common per-rank padded height: every rank pads its local rows to
        this so XLA's equal-shard constraint holds pod-wide (ragged and even
        EMPTY local partitions become zero-weight rows)."""
        chunk = ROW_MULTIPLE * self.local_device_count
        return max(chunk, -(-int(max_rank_rows) // chunk) * chunk)

    def stage_inputs(
        self,
        max_rank_rows: int,
        X_local: np.ndarray,
        *extras_local: Optional[np.ndarray],
    ) -> Tuple[jax.Array, jax.Array, List[Optional[jax.Array]], int]:
        """The dense multi-host staging dance in one place: pad this
        process's local rows (and row-aligned extras) to the common per-rank
        height, mark real rows with a {0,1} weight, and assemble the global
        arrays. Returns (X_global, weight_global, extras_global, pad_to)."""
        pad_to = self.local_pad_rows(max_rank_rows)
        n_local = int(X_local.shape[0])
        w = np.zeros((pad_to,), np.float32)
        w[:n_local] = 1.0
        Xp = np.zeros((pad_to,) + tuple(X_local.shape[1:]), X_local.dtype)
        Xp[:n_local] = X_local
        padded_extras: List[Optional[np.ndarray]] = []
        for e in extras_local:
            if e is None:
                padded_extras.append(None)
                continue
            ep = np.zeros((pad_to,) + tuple(e.shape[1:]), e.dtype)
            ep[:n_local] = e
            padded_extras.append(ep)
        staged = self.shard_inputs(Xp, w, *padded_extras)
        return staged[0], staged[1], staged[2:], pad_to

    # ------------------------------------------------------------ serving

    def replica_device_groups(self, n_replicas: int) -> List[Tuple[Any, ...]]:
        """Disjoint local device groups for the serving fleet's replicas —
        drawn from the partitioner's mesh, not the raw local-device list, so
        a pod-sliced mesh hands each replica its slice of THIS host. With
        fewer local devices than replicas the groups degenerate to single
        devices shared round-robin (the CPU case)."""
        pi = jax.process_index()
        local = [d for d in self.mesh.devices.flat if d.process_index == pi]
        if not local:
            local = list(jax.local_devices())
        n = max(1, int(n_replicas))
        if n >= len(local):
            return [(local[i % len(local)],) for i in range(n)]
        per = len(local) // n
        return [tuple(local[i * per:(i + 1) * per]) for i in range(n)]


class DataParallelPartitioner(Partitioner):
    """1-D data-parallel partitioner: rows across every mesh device, state
    replicated. The default for every estimator."""

    def __init__(self, num_workers: Optional[int] = None, mesh: Optional[Mesh] = None):
        super().__init__(mesh if mesh is not None else get_mesh(num_workers))


class SPMDPartitioner(Partitioner):
    """2-D (data x feature) partitioner for wide-k kNN / feature-sharded
    covariance: rows across the data axis, features optionally across the
    feature axis. State stays replicated across data, sharded across feature
    when the caller opts a tensor in via `feature_spec`."""

    def __init__(self, num_workers: Optional[int] = None,
                 feature_axis: Optional[int] = None,
                 mesh: Optional[Mesh] = None):
        if mesh is None:
            fa = feature_axis if feature_axis is not None else resolve_feature_axis()
            mesh = get_mesh(num_workers, feature_axis=max(1, int(fa)))
        super().__init__(mesh)

    @property
    def feature_axis_size(self) -> int:
        return int(self.mesh.shape.get(FEATURE_AXIS, 1))

    def feature_spec(self, ndim: int = 2) -> PartitionSpec:
        """Rows on data, trailing (feature) dim on the feature axis."""
        if ndim < 2:
            return PartitionSpec(FEATURE_AXIS)
        return PartitionSpec(*([DATA_AXIS] + [None] * (ndim - 2) + [FEATURE_AXIS]))

    def feature_sharding(self, ndim: int = 2) -> NamedSharding:
        return NamedSharding(self.mesh, self.feature_spec(ndim))

    def shard_features(self, x: Any) -> jax.Array:
        """Place with rows on data AND columns on feature — the wide-k kNN /
        feature-sharded covariance layout."""
        return jax.device_put(x, self.feature_sharding(np.ndim(x)))


# --------------------------------------------------------------- active mgmt

_lock = threading.Lock()
_active: Optional[Partitioner] = None
_default_cache: Dict[Tuple[int, int], Partitioner] = {}


def set_partitioner(p: Optional[Partitioner]) -> None:
    """Install the process-wide active partitioner (the barrier task does
    this right after the rendezvous). `None` uninstalls."""
    global _active
    with _lock:
        _active = p


def reset_partitioner() -> None:
    """Drop the active partitioner AND the default cache (tests; and the
    barrier retry path, whose re-rendezvous may change the pod shape)."""
    global _active
    with _lock:
        _active = None
        _default_cache.clear()


@contextlib.contextmanager
def use_partitioner(p: Partitioner):
    """Scoped install — the barrier fit body wraps the fit in this so a
    failed attempt never leaks a stale pod partitioner into retries."""
    global _active
    with _lock:
        prev, _active = _active, p
    try:
        yield p
    finally:
        with _lock:
            _active = prev


def active_partitioner(num_workers: Optional[int] = None) -> Partitioner:
    """The partitioner every sharding decision resolves against.

    An installed partitioner wins unless the caller demands an incompatible
    worker count (an estimator pinned to fewer workers than the pod mesh);
    then — and on plain single-process runs — a cached default
    DataParallelPartitioner over `num_workers` devices is returned."""
    with _lock:
        if _active is not None and (
            num_workers is None or _active.num_workers == num_workers
        ):
            return _active
    mesh = get_mesh(num_workers)  # reuses the cached default mesh
    key = (int(mesh.devices.size), 1)
    with _lock:
        p = _default_cache.get(key)
        if p is None or p.mesh is not mesh:
            p = DataParallelPartitioner(mesh=mesh)
            _default_cache[key] = p
        return p


def partitioner_for(mesh: Optional[Mesh]) -> Partitioner:
    """The partitioner that owns `mesh` — ops that take an explicit mesh
    parameter resolve their placements through this, so a mesh threaded
    through a call chain still lands on Partitioner-owned shardings."""
    if mesh is None:
        return active_partitioner()
    with _lock:
        if _active is not None and _active.mesh is mesh:
            return _active
        key = (int(mesh.devices.size), int(mesh.shape.get(FEATURE_AXIS, 1)))
        p = _default_cache.get(key)
        if p is not None and p.mesh is mesh:
            return p
        p = DataParallelPartitioner(mesh=mesh)
        _default_cache[key] = p
        return p


# --------------------------------------------------------------- helpers

def mesh_of(x: Any) -> Optional[Mesh]:
    """The mesh a placed array lives on, None for single-device arrays —
    replaces the scattered `isinstance(x.sharding, NamedSharding)` probes."""
    sh = getattr(x, "sharding", None)
    if isinstance(sh, NamedSharding):
        return sh.mesh
    return None


def shard_rows(x: Any, mesh: Optional[Mesh] = None) -> jax.Array:
    """Row-shard a host array via the partitioner owning `mesh` (active
    partitioner when None). The migration target for every former
    `shard_array(x, mesh)` call."""
    return partitioner_for(mesh).shard(x)


def replicate_rows(x: Any, mesh: Optional[Mesh] = None) -> jax.Array:
    return partitioner_for(mesh).replicate(x)


def put_device_local(x: Any) -> jax.Array:
    """Default-device placement (host-resident pairwise block scans)."""
    return active_partitioner().put_local(x)


# --------------------------------------------------------------- knobs

def resolve_feature_axis(n: Optional[int] = None, d: Optional[int] = None) -> int:
    """Feature-axis width for SPMDPartitioner meshes. Host-resolution only
    (a partitioner is built per fit, never inside a trace): config pin >
    tuning table (knob `partition.feature_axis`, (n, d)-bucketed) > 1."""
    from .. import autotune as _autotune

    cfg = int(_config.get("partition.feature_axis") or 0)
    if cfg >= 1:
        return cfg
    tuned = _autotune.lookup("partition.feature_axis", n=n, d=d)
    if tuned is not None and int(tuned) >= 1:
        return int(tuned)
    return 1


def resolve_batch_rows_per_process(n: Optional[int] = None,
                                   d: Optional[int] = None) -> int:
    """Per-process row-batch geometry for multi-host streamed ingest: each
    process stages this many LOCAL rows per streamed batch. Config pin >
    tuning table > the single-process `stream_batch_rows` split across the
    pod. Host-resolution only — the value feeds padding geometry, so
    resolving it inside a trace would go stale."""
    from .. import autotune as _autotune

    cfg = int(_config.get("partition.batch_rows_per_process") or 0)
    if cfg >= 1:
        return cfg
    tuned = _autotune.lookup("partition.batch_rows_per_process", n=n, d=d)
    if tuned is not None and int(tuned) >= 1:
        return int(tuned)
    total = int(_config.get("stream_batch_rows"))
    return max(1, total // max(1, jax.process_count()))
