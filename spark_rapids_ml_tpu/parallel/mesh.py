#
# Device-mesh runtime (L2 of the layer map) — the structural replacement for the
# reference's CumlContext NCCL/UCX bootstrap
# (reference python/src/spark_rapids_ml/common/cuml_context.py:36-201).
#
# Where the reference builds an explicit communicator (rank-0 generates an NCCL uid,
# Spark barrier allGather distributes it, nccl.init + inject_comms_on_handle wire it into
# cuML's RAFT handle), the TPU runtime has NO communicator object: the "clique" is a
# jax.sharding.Mesh, and the collectives are inserted by XLA when a jitted program runs
# over sharded arrays (psum / all_gather / ppermute over ICI/DCN). Multi-host process
# groups bootstrap once per process via jax.distributed.initialize (see bootstrap.py) —
# the drop-in analog of the NCCL-uid handshake.
#

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
FEATURE_AXIS = "feature"

_default_mesh: Optional[Mesh] = None


def default_num_workers() -> int:
    """One worker == one addressable TPU device (the reference's 1 worker == 1 GPU,
    params.py:337-371)."""
    return jax.local_device_count()


def get_mesh(num_workers: Optional[int] = None, feature_axis: int = 1) -> Mesh:
    """Build (or fetch) a mesh of `num_workers` data-parallel devices.

    feature_axis > 1 carves the device pool into a 2-D (data, feature) mesh used for
    feature-sharded covariance / wide-model layouts."""
    global _default_mesh
    devices = jax.devices()
    n = num_workers if num_workers is not None else len(devices)
    n = min(n, len(devices))
    if feature_axis == 1 and _default_mesh is not None and _default_mesh.devices.size == n:
        return _default_mesh
    if n % feature_axis != 0:
        raise ValueError(f"num_workers={n} not divisible by feature_axis={feature_axis}")
    dev_array = np.array(devices[:n]).reshape(n // feature_axis, feature_axis)
    mesh = Mesh(dev_array, (DATA_AXIS, FEATURE_AXIS))
    if feature_axis == 1:
        _default_mesh = mesh
    return mesh


def row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*([DATA_AXIS] + [None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_array(x: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place a host array on the mesh with rows sharded across the data axis.

    Back-compat shim: placement is owned by the Partitioner
    (parallel/partitioner.py) — this delegates so a mesh threaded through an
    op still resolves to Partitioner-owned shardings."""
    from .partitioner import shard_rows

    return shard_rows(x, mesh)


def replicate_array(x: np.ndarray, mesh: Mesh) -> jax.Array:
    from .partitioner import replicate_rows

    return replicate_rows(x, mesh)
