#
# Partition bookkeeping + padding (part of L2, SURVEY.md §1).
#
# Structural equivalent of the reference's PartitionDescriptor
# (reference python/src/spark_rapids_ml/utils.py:300-355): there, each barrier task
# allGathers (rank, n_rows, nnz) strings so every cuML MG kernel knows the global data
# layout. Here the global layout is a property of the sharded jax.Array, but two
# TPU-specific concerns remain and live in this module:
#   * ragged partitions: XLA requires equal shard sizes, so rows are padded to a
#     multiple of the worker count and a {0,1} weight vector marks real rows — every op
#     in ops/ is weight-aware (this is SURVEY.md §7 "hard parts: dynamic shapes").
#   * the descriptor itself (sizes per rank, total rows, cols, nnz) still travels to the
#     fit functions, matching the reference's `parts_rank_size` contract
#     (e.g. feature.py:228-253).
#

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass
class PartitionDescriptor:
    """Global data-layout facts shared with every fit kernel
    (reference utils.py:300-355)."""

    parts_rank_size: List[Tuple[int, int]]  # [(rank, n_real_rows_on_rank)]
    m: int  # total real rows
    n: int  # cols
    rank: int = 0
    nnz: int = -1  # total nonzeros for sparse inputs
    padded_m: int = -1  # rows after padding to the mesh

    @classmethod
    def build(
        cls,
        partition_rows: Sequence[int],
        total_cols: int,
        rank: int = 0,
        nnz: int = -1,
        padded_m: int = -1,
    ) -> "PartitionDescriptor":
        parts = [(r, int(sz)) for r, sz in enumerate(partition_rows)]
        m = int(sum(partition_rows))
        n = int(total_cols)
        if padded_m < 0:
            # Callers that skip pad_rows (pre-padded global arrays, ragged
            # barrier partitions) used to leak the -1 sentinel into fit
            # arithmetic. Compute the real padded height: every rank pads its
            # rows to the ragged MAX rounded up to the sublane tile (8), so
            # the global padded height is ranks * that — identical to
            # pad_rows' result for even splits.
            max_rank = max((int(sz) for sz in partition_rows), default=0)
            per_rank = ((max_rank + 7) // 8) * 8
            padded_m = len(parts) * per_rank
        if nnz < 0:
            # dense inputs: every real element is a stored element
            nnz = m * n
        return cls(
            parts_rank_size=parts,
            m=m,
            n=n,
            rank=rank,
            nnz=int(nnz),
            padded_m=int(padded_m),
        )


def pad_rows(
    X: np.ndarray,
    num_workers: int,
    *extra_row_aligned: Optional[np.ndarray],
    row_multiple: int = 8,
) -> Tuple[np.ndarray, np.ndarray, List[Optional[np.ndarray]]]:
    """Pad rows so every mesh worker gets an equal, tile-friendly shard.

    Returns (X_padded, weight, padded_extras) where weight is 1.0 for real rows and 0.0
    for padding. `row_multiple` keeps per-shard rows a multiple of the float32 sublane
    tile (8) so XLA lays shards out MXU-friendly. Extra arrays (labels, sample weights,
    row ids) are padded with zeros to the same length.
    """
    n = X.shape[0]
    chunk = num_workers * row_multiple
    padded = ((n + chunk - 1) // chunk) * chunk
    pad = padded - n
    weight = np.ones((padded,), dtype=X.dtype if X.dtype in (np.float32, np.float64) else np.float32)
    if pad:
        weight[n:] = 0.0
        X = np.concatenate([X, np.zeros((pad,) + X.shape[1:], dtype=X.dtype)], axis=0)
    extras_out: List[Optional[np.ndarray]] = []
    for e in extra_row_aligned:
        if e is None:
            extras_out.append(None)
        elif pad:
            extras_out.append(
                np.concatenate([e, np.zeros((pad,) + e.shape[1:], dtype=e.dtype)], axis=0)
            )
        else:
            extras_out.append(e)
    return X, weight, extras_out


def even_partition_sizes(n_rows: int, num_workers: int) -> List[int]:
    """Row counts per worker for an evenly-split dataset (repartition(num_workers),
    reference core.py:771-772)."""
    base, rem = divmod(n_rows, num_workers)
    return [base + (1 if i < rem else 0) for i in range(num_workers)]
