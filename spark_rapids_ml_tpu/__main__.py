#
# `python -m spark_rapids_ml_tpu script.py [args...]` — run a script (or -m module)
# with the no-import-change interposer pre-installed
# (reference python/src/spark_rapids_ml/__main__.py:25-59).
#

from __future__ import annotations

import runpy
import sys


def main() -> None:
    argv = sys.argv[1:]
    if not argv:
        print(
            "usage: python -m spark_rapids_ml_tpu <script.py> [args...]\n"
            "       python -m spark_rapids_ml_tpu -m <module> [args...]",
            file=sys.stderr,
        )
        raise SystemExit(2)

    import spark_rapids_ml_tpu.install  # noqa: hygiene/unused-import — installs the interposer

    if argv[0] == "-m":
        if len(argv) < 2:
            raise SystemExit("-m requires a module name")
        sys.argv = argv[1:]
        runpy.run_module(argv[1], run_name="__main__", alter_sys=True)
    else:
        sys.argv = argv
        runpy.run_path(argv[0], run_name="__main__")


if __name__ == "__main__":
    main()
