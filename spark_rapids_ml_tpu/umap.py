# Public API module mirroring the reference's `spark_rapids_ml.umap`
# (reference python/src/spark_rapids_ml/umap.py).
from .models.umap import UMAP, UMAPModel

__all__ = ["UMAP", "UMAPModel"]
