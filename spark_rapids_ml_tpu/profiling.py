#
# Compat shims over the observability subsystem (observability/ — docs/design.md
# §6d). This module USED to own two flat process-global dicts (span seconds,
# event counts); it now forwards every call to the typed metrics registry and
# run-scope fan-out in `observability/`, keeping the historical surface —
# span / add_time / span_totals / reset_spans / count / counter_totals /
# reset_counters / trace — byte-compatible for every existing call site and
# test. New instrumentation should import `spark_rapids_ml_tpu.observability`
# directly (Counter/Gauge/Histogram with labels, structured spans, events).
#
# Behavior fixes that ride the migration:
#   * span() records its timing even when the body RAISES (try/finally; the old
#     implementation updated the totals after the `with TraceAnnotation` block,
#     so a failed pass — exactly when the timing matters — recorded nothing).
#     A failed span lands with status=error in the run trace and increments the
#     `span.errors` counter.
#   * jax.profiler resolves ONCE through a module-level lazy cache instead of
#     per call — span() is now cheap enough for per-batch paths (add_time()'s
#     old excuse for existing).
#
# Enable xplane capture with SRML_TPU_TRACE_DIR=/path (see config.py): every
# fit is then traced automatically.
#

from __future__ import annotations

import contextlib
import time as _time
from typing import Dict, Iterator, Optional

from . import observability as _obs
from .utils import get_logger

_logger = get_logger("profiling")

# lazy once-per-process jax.profiler resolution: False = not yet resolved,
# None = unavailable (never retried), module otherwise
_jax_profiler = False


def _get_jax_profiler():
    global _jax_profiler
    if _jax_profiler is False:
        try:
            import jax.profiler as jp
        except Exception:  # pragma: no cover — jax is a hard dep everywhere else
            jp = None
        _jax_profiler = jp
    return _jax_profiler


@contextlib.contextmanager
def span(name: str, verbose: bool = False) -> Iterator[None]:
    """Wall-clock + device-timeline span: the observability structured span
    (trace-tree node + span totals + latency histogram) nested inside a
    jax.profiler.TraceAnnotation so it still shows on xplane timelines."""
    jp = _get_jax_profiler()
    annotation = jp.TraceAnnotation(name) if jp is not None else contextlib.nullcontext()
    with _obs.span(name):
        t0 = _time.perf_counter()
        try:
            with annotation:
                yield
        finally:
            if verbose:
                _logger.info("%s: %.3fs", name, _time.perf_counter() - t0)


def add_time(name: str, seconds: float) -> None:
    """Accumulate seconds under a span name WITHOUT the TraceAnnotation or
    trace-node machinery — the per-batch fallback for call sites that already
    timed themselves. Also feeds the same-named latency histogram, so every
    add_time site gains a per-batch distribution for free."""
    _obs.add_span_total(name, seconds)


def span_totals() -> Dict[str, float]:
    """Accumulated seconds per span name since process start (or last reset)."""
    return _obs.global_registry().span_totals()


def reset_spans() -> None:
    _obs.global_registry().reset_spans()


def count(name: str, n: int = 1) -> None:
    """Monotone event counter (legacy flat surface). The reliability subsystem
    reports retry/resume/degrade/fault totals here, the streamed-ingest tier
    reports `stream.upload_batches`/`stream.upload_bytes`, and the HBM batch
    cache reports `cache.hits`/`cache.misses`/`cache.evictions`
    (`cache.bytes_resident` is a real observability Gauge now — see
    ops/device_cache.py — surfaced through counter_totals() for compat).
    This surface never distinguished counters from gauges, so kind is
    discovered from usage: a name's first negative increment retypes it to a
    gauge carrying its accumulated value — any straggler gauge-as-counter
    call site keeps its arithmetic instead of crashing
    (MetricsRegistry.legacy_count)."""
    _obs.legacy_count(name, n)


def counter_totals() -> Dict[str, int]:
    """Accumulated event counts per name since process start (or last reset);
    includes gauges (by current value) — the historical surface reported
    gauges through this dict as signed increments."""
    return _obs.global_registry().counter_totals()


def reset_counters() -> None:
    _obs.global_registry().reset_counters()


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture an xplane trace into trace_dir (no-op when trace_dir is falsy)."""
    if not trace_dir:
        yield
        return
    import jax.profiler

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _logger.info("wrote profiler trace to %s", trace_dir)
