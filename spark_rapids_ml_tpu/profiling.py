#
# Tracing/profiling hooks — SURVEY.md §5.1 notes the reference has none beyond timed
# logging (with_benchmark wall-clock wrapper) and flags JAX profiler integration as
# the cheap win for the TPU build. This module provides:
#   * span(name): wall-clock span that ALSO shows up on the device timeline via
#     jax.profiler.TraceAnnotation (visible in xplane/tensorboard traces)
#   * start_trace/stop_trace: programmatic xplane capture around a fit
#   * fit-time logging is wired through _TpuCaller when `verbose` is set
#
# Enable capture with SRML_TPU_TRACE_DIR=/path (see config.py): every fit is then
# traced automatically.
#

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

from .utils import get_logger

_logger = get_logger("profiling")
_spans: Dict[str, float] = {}
_counters: Dict[str, int] = {}
# counters are incremented from concurrent barrier-task threads (the local-mode
# fit-plane harness); the lock keeps read-modify-write increments exact
_counters_lock = threading.Lock()


@contextlib.contextmanager
def span(name: str, verbose: bool = False) -> Iterator[None]:
    """Wall-clock + device-timeline span."""
    import jax.profiler

    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    dt = time.perf_counter() - t0
    _spans[name] = _spans.get(name, 0.0) + dt
    if verbose:
        _logger.info("%s: %.3fs", name, dt)


def add_time(name: str, seconds: float) -> None:
    """Accumulate seconds under a span name WITHOUT the TraceAnnotation
    machinery — the per-batch path (streamed ingest timing, ops/streaming.py)
    calls this once per batch, where importing jax.profiler per call would
    cost more than the slice being measured. Shows up in span_totals()
    alongside the context-manager spans."""
    _spans[name] = _spans.get(name, 0.0) + seconds


def span_totals() -> Dict[str, float]:
    """Accumulated seconds per span name since process start (or last reset)."""
    return dict(_spans)


def reset_spans() -> None:
    _spans.clear()


def count(name: str, n: int = 1) -> None:
    """Span-style monotone event counter. The reliability subsystem reports its
    retry/resume/degrade/fault-firing totals here (`reliability.retry`,
    `reliability.retry.<site>`, `reliability.resume[.<site>]`,
    `reliability.degrade.*`, `reliability.fault[.<site>]`) so behavior under
    faults is observable rather than silent. The streamed-ingest tier reports
    `stream.upload_batches` / `stream.upload_bytes` (every host->device batch
    upload) and the HBM batch cache reports `cache.hits` / `cache.misses` /
    `cache.evictions` plus the `cache.bytes_resident` gauge (negative
    increments on eviction/close), so "pass 2 re-uploaded nothing" is an
    assertable fact, not an inference from wall-clock."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0) + n


def counter_totals() -> Dict[str, int]:
    """Accumulated event counts per name since process start (or last reset)."""
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture an xplane trace into trace_dir (no-op when trace_dir is falsy)."""
    if not trace_dir:
        yield
        return
    import jax.profiler

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _logger.info("wrote profiler trace to %s", trace_dir)
