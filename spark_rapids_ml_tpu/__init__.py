#
# spark_rapids_ml_tpu — a TPU-native distributed ML library with the capabilities of
# NVIDIA/spark-rapids-ml: pyspark.ml-style estimators whose fit/transform run as SPMD
# JAX/XLA programs over a TPU device mesh (psum/all_gather over ICI replacing
# NCCL/UCX). See SURVEY.md at the repo root for the structural map of the reference
# this build follows.
#

__version__ = "0.2.0"

# Top-level modules mirror the reference's public layout
# (reference python/src/spark_rapids_ml/__init__.py): feature, clustering,
# classification, regression, knn, umap, tuning, pipeline, metrics.
