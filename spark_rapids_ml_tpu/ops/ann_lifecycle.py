#
# ANN index lifecycle: on-disk persistence, lazy device residency, and
# incremental add/delete with tombstone compaction (docs/design.md §7b).
#
# The selection/Pallas planes made ANN *search* fast; this module makes the
# index itself a managed artifact instead of a fit-once in-memory dict:
#
#   * ON-DISK FORMAT — a versioned directory of one `.npy` file per index
#     array plus a `MANIFEST.json` written LAST via tmp + os.replace (the
#     autotune/table.py atomic-write discipline): the manifest IS the commit
#     point, so a reader never observes a torn index. Arrays load back as
#     copy-on-write memmaps (`np.load(mmap_mode="c")`): load() touches no
#     array bytes — pages fault in as searches (or mutations) reach them.
#   * LAZY DEVICE RESIDENCY — `DeviceIndexCache` uploads one named segment
#     (centers, cells, codes, ...) to HBM on FIRST use and replays it on
#     every later search (backed by ops/device_cache.py::DeviceBatchCache,
#     budget `ann.index_cache_bytes`). Cold-start after load() therefore
#     uploads only what the first search actually probes; mutation
#     invalidates exactly the segments it touched.
#   * INCREMENTAL MAINTENANCE — host-side appends into the dense IVF list
#     layout with BUCKETED capacity (max_cell rounds up to a power of two >=
#     `ann.list_bucket_rows`), so in-slack adds never change the search
#     executable's operand shapes: a live served model absorbs them with
#     zero new `device.compile{kernel=}` entries. Deletes tombstone a slot by
#     writing its `cell_ids` entry to -1 — the same sentinel every probe scan
#     already masks to INVALID_D2 — and compaction re-layouts the lists once
#     tombstones exceed `ann.compact_tombstone_pct` of occupied slots.
#
# Assignment/encoding of added rows runs in HOST numpy on purpose: routing a
# handful of new rows through the device kernels would mint one fresh
# (kernel, shape) AOT compile per add-batch size — exactly the storm the
# bucketed geometry exists to prevent. Add-path assignment quality matches
# the build's (same argmin over the same centers); it is not bit-coupled to
# the device matmul and does not need to be (cell membership is a recall
# knob, not a distance contract).
#

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..observability import span as obs_span
from ..observability.runs import (
    counter_inc as obs_counter_inc,
    gauge_set as obs_gauge_set,
)
from ..utils import get_logger

_logger = get_logger("ops.ann_lifecycle")

ANN_FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"


# --------------------------------------------------------------- on-disk store


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + os.replace — the autotune/table.py discipline; a reader never
    sees a torn file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_array(dirname: str, name: str, arr: np.ndarray) -> Dict[str, Any]:
    """One array -> one mmap-friendly `.npy` segment file, atomically."""
    arr = np.ascontiguousarray(arr)  # noqa: fence/host-staging-copy
    fname = f"{name}.npy"
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.save(f, arr)
        os.replace(tmp, os.path.join(dirname, fname))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return {
        "file": fname,
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "nbytes": int(arr.nbytes),
    }


def save_index(path: str, arrays: Dict[str, np.ndarray], *, algo: str,
               meta: Optional[Dict[str, Any]] = None) -> str:
    """Persist one index as a directory of per-array segment files + a
    manifest. The manifest is written LAST (atomic tmp + os.replace): until it
    lands, a concurrent reader sees the PREVIOUS generation; array files are
    themselves replaced atomically, so re-saving over a live directory is a
    generation bump, not a torn state. Returns the manifest path."""
    with obs_span("ann.index_save", {"algo": algo, "arrays": len(arrays)}):
        os.makedirs(path, exist_ok=True)
        prev_gen = 0
        try:
            prev = read_manifest(path)
            prev_gen = int(prev.get("generation", 0))
        except (FileNotFoundError, ValueError):
            pass
        manifest: Dict[str, Any] = {
            "version": ANN_FORMAT_VERSION,
            "algo": str(algo),
            "generation": prev_gen + 1,
            "updated_ts": round(time.time(), 3),
            "arrays": {},
            "meta": dict(meta or {}),
        }
        for name, arr in arrays.items():
            if arr is None:
                continue
            manifest["arrays"][name] = _write_array(path, name, np.asarray(arr))
        mpath = os.path.join(path, MANIFEST_NAME)
        _atomic_write(
            mpath, json.dumps(manifest, indent=1, sort_keys=True).encode()
        )
    obs_counter_inc("ann.index_saves", 1, algo=str(algo))
    return mpath


def read_manifest(path: str) -> Dict[str, Any]:
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"corrupt ANN index manifest {mpath}: {e}") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("arrays"), dict):
        raise ValueError(f"ANN index manifest {mpath} is not an index manifest")
    if doc.get("version") != ANN_FORMAT_VERSION:
        raise ValueError(
            f"ANN index at {path} has format version {doc.get('version')}; "
            f"this library reads version {ANN_FORMAT_VERSION}"
        )
    return doc


def load_index(path: str, *, mmap: bool = True
               ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Open a saved index: returns ({name: array}, manifest). With mmap=True
    (the default) arrays are copy-on-write memmaps — no array bytes are read
    here; pages fault in lazily as searches reach them, and in-memory
    mutation (incremental add/delete) never writes back to the files (a
    mutated index persists only through an explicit save)."""
    with obs_span("ann.index_load", {"path": os.path.basename(path)}):
        manifest = read_manifest(path)
        arrays: Dict[str, np.ndarray] = {}
        for name, spec in manifest["arrays"].items():
            fpath = os.path.join(path, spec["file"])
            arr = np.load(fpath, mmap_mode="c" if mmap else None)
            if list(arr.shape) != list(spec["shape"]) or str(arr.dtype) != spec["dtype"]:
                raise ValueError(
                    f"ANN index segment {fpath} does not match its manifest "
                    f"entry (shape {list(arr.shape)} vs {spec['shape']}, "
                    f"dtype {arr.dtype} vs {spec['dtype']})"
                )
            arrays[name] = arr
    obs_counter_inc("ann.index_loads", 1, algo=str(manifest.get("algo")))
    return arrays, manifest


# ------------------------------------------------------- lazy device residency


class DeviceIndexCache:
    """Per-index lazy HBM residency: each named segment (centers, cells,
    cell_ids, codes, ...) uploads on FIRST `get` and replays from the device
    cache on every later search — repeated kneighbors calls stop paying the
    host->device index transfer, and an index loaded from disk stages only
    the segments the first search actually touches. Single-owner like the
    underlying DeviceBatchCache (one model object, its search calls)."""

    def __init__(self, budget_bytes: Optional[int] = None):
        from .. import config as _config
        from .device_cache import DeviceBatchCache

        budget = int(
            budget_bytes if budget_bytes is not None
            else _config.get("ann.index_cache_bytes")
        )
        self._cache = DeviceBatchCache(max(budget, 0))

    def get(self, name: str, host_array: Any):
        """The device copy of one segment (uploading on first use)."""
        import jax.numpy as jnp

        key = ("ann_index", name)
        hit = self._cache.get(key, 0)
        if hit is not None:
            return hit[0]
        dev = jnp.asarray(host_array)
        obs_counter_inc("ann.device_loads", 1, attr=name)
        obs_counter_inc(
            "ann.device_load_bytes", int(getattr(host_array, "nbytes", 0)),
            attr=name,
        )
        self._cache.put(key, 0, (dev,))
        return dev

    def invalidate(self, *names: str) -> None:
        """Drop segments a mutation touched; the next search re-uploads."""
        for name in names:
            self._cache.drop_stream(("ann_index", name))

    def close(self) -> None:
        self._cache.close()


# -------------------------------------------------- bucketed list geometry


def resolve_list_bucket_rows() -> int:
    """`ann.list_bucket_rows` resolution: non-zero config pin > tuning table >
    defaults-module floor."""
    from .. import autotune as _autotune
    from .. import config as _config
    from ..autotune.defaults import ANN_LIST_BUCKET_MIN_ROWS

    pinned = int(_config.get("ann.list_bucket_rows") or 0)
    if pinned > 0:
        return pinned
    tuned = _autotune.lookup("ann.list_bucket_rows")
    if tuned:
        return int(tuned)
    return int(ANN_LIST_BUCKET_MIN_ROWS)


def resolve_compact_tombstone_pct() -> int:
    """`ann.compact_tombstone_pct` resolution (config pin > table > default)."""
    from .. import autotune as _autotune
    from .. import config as _config
    from ..autotune.defaults import ANN_COMPACT_TOMBSTONE_PCT

    src_default = ANN_COMPACT_TOMBSTONE_PCT
    if _config.source("ann.compact_tombstone_pct") != "default":
        return int(_config.get("ann.compact_tombstone_pct"))
    tuned = _autotune.lookup("ann.compact_tombstone_pct")
    return int(tuned) if tuned else int(src_default)


def bucket_capacity(rows: int, min_rows: Optional[int] = None) -> int:
    """Power-of-two capacity >= rows, floored at the bucket knob: the bucketed
    geometry is what lets an in-slack add keep every compiled search
    executable's operand shapes — and therefore the AOT cache — unchanged."""
    floor = int(min_rows) if min_rows is not None else resolve_list_bucket_rows()
    rows = max(int(rows), 1)
    cap = 1 << (rows - 1).bit_length()
    return max(cap, floor)


# ------------------------------------------------------ incremental add/delete


class MutableIvfState:
    """Host bookkeeping of a mutable IVF layout: per-item cell assignment,
    per-cell fill pointers (slots [0, fill) are live-or-tombstoned; [fill,
    max_cell) are virgin slack) and the tombstone count the compaction
    trigger watches. Derived from a built layout on first mutation; persists
    through the index store as the `cell_fill` / `item_cells` arrays plus the
    manifest's `tombstones` meta."""

    def __init__(self, item_cells: np.ndarray, cell_fill: np.ndarray,
                 tombstones: int = 0):
        self.item_cells = np.asarray(item_cells, np.int32).copy()
        self.cell_fill = np.asarray(cell_fill, np.int32).copy()
        self.tombstones = int(tombstones)

    @classmethod
    def from_layout(cls, cell_ids: np.ndarray, n_items: int
                    ) -> "MutableIvfState":
        """Reconstruct bookkeeping from a dense layout: fill = highest live
        slot + 1 per cell (fresh builds are hole-free, so this equals the
        cell size), item->cell from one scan of cell_ids."""
        cell_ids = np.asarray(cell_ids)
        nlist, max_cell = cell_ids.shape
        live = cell_ids >= 0
        # fill pointer: one past the last live slot (0 for empty cells)
        rev = live[:, ::-1]
        has = rev.any(axis=1)
        fill = np.where(has, max_cell - rev.argmax(axis=1), 0)
        item_cells = np.full((int(n_items),), -1, np.int32)
        cells_of = np.repeat(np.arange(nlist), max_cell).reshape(nlist, max_cell)
        item_cells[cell_ids[live]] = cells_of[live].astype(np.int32)  # noqa: fence/host-staging-copy
        return cls(item_cells, fill.astype(np.int32), tombstones=0)

    def live_items(self) -> int:
        return int((self.item_cells >= 0).sum())


def ivf_assign_host(X_new: np.ndarray, centers: np.ndarray) -> np.ndarray:
    """Nearest-center assignment in host numpy — the add path's deliberate
    device-free twin of kmeans_predict (see the module header: a device call
    here would compile once per add-batch shape)."""
    X_new = np.asarray(X_new, np.float32)
    centers = np.asarray(centers, np.float32)
    x2 = np.sum(X_new * X_new, axis=1)[:, None]
    c2 = np.sum(centers * centers, axis=1)[None, :]
    d2 = x2 - 2.0 * (X_new @ centers.T) + c2
    return np.argmin(d2, axis=1).astype(np.int32)


def pq_encode_host(resid: np.ndarray, codebooks: np.ndarray) -> np.ndarray:
    """Host PQ encoding of residuals: per-subvector nearest codeword (the
    add-path twin of the streamed encoding passes)."""
    resid = np.asarray(resid, np.float32)
    m, n_codes, sub_d = codebooks.shape
    out = np.zeros((resid.shape[0], m), np.uint8)
    for m_i in range(m):
        sub = resid[:, m_i * sub_d : (m_i + 1) * sub_d]
        cb = codebooks[m_i]
        d2 = (
            np.sum(sub * sub, axis=1)[:, None]
            - 2.0 * (sub @ cb.T)
            + np.sum(cb * cb, axis=1)[None, :]
        )
        out[:, m_i] = np.argmin(d2, axis=1).astype(np.uint8)
    return out


def _grow_layout(attrs: Dict[str, Any], new_max_cell: int) -> None:
    """Re-allocate the dense list arrays at a larger bucketed capacity (one
    new search-executable shape — the amortized cost in-slack adds avoid)."""
    cells = np.asarray(attrs["cells"])
    cell_ids = np.asarray(attrs["cell_ids"])
    nlist, max_cell, d = cells.shape
    grown = np.zeros((nlist, new_max_cell, d), cells.dtype)
    grown[:, :max_cell] = cells
    grown_ids = np.full((nlist, new_max_cell), -1, cell_ids.dtype)
    grown_ids[:, :max_cell] = cell_ids
    attrs["cells"] = grown
    attrs["cell_ids"] = grown_ids
    if "codes" in attrs and attrs.get("codes") is not None:
        codes = np.asarray(attrs["codes"])
        grown_codes = np.zeros(
            (nlist, new_max_cell, codes.shape[2]), codes.dtype
        )
        grown_codes[:, :max_cell] = codes
        attrs["codes"] = grown_codes
    obs_counter_inc("ann.list_grows", 1)


def rebucket_layout(attrs: Dict[str, Any], *, slack_rows: int = 0) -> bool:
    """Round the list capacity up to its bucket (plus optional extra slack):
    called once when an index becomes mutable — paying the single shape
    change BEFORE a model is served is what makes later adds compile-free.
    Returns True when the layout grew."""
    cell_ids = np.asarray(attrs["cell_ids"])
    max_cell = cell_ids.shape[1]
    target = bucket_capacity(max_cell + int(slack_rows))
    if target <= max_cell:
        return False
    _grow_layout(attrs, target)
    return True


def ivf_add(attrs: Dict[str, Any], state: MutableIvfState,
            X_new: np.ndarray, positions: np.ndarray, *,
            cosine: bool = False) -> None:
    """Append rows into the IVF lists. Tombstoned slots are reused first
    (they sit below the fill pointer), then virgin slack; a cell out of both
    grows the whole layout to the next capacity bucket. Mutates `attrs`
    (cells / cell_ids / cell_sizes / codes) and `state` in place; `positions`
    are the new rows' item positions (the caller owns the position->user-id
    mapping)."""
    from .knn import normalize_rows_or_raise

    X_new = np.ascontiguousarray(np.asarray(X_new), np.float32)  # noqa: fence/host-staging-copy
    if cosine:
        X_new = normalize_rows_or_raise(X_new)
    positions = np.asarray(positions, np.int64)
    if X_new.shape[0] != positions.shape[0]:
        raise ValueError(
            f"{X_new.shape[0]} rows but {positions.shape[0]} positions"
        )
    centers = np.asarray(attrs["centers"])
    assign = ivf_assign_host(X_new, centers)

    # capacity: every target cell must fit its new rows in holes + slack
    cell_ids = np.asarray(attrs["cell_ids"])
    nlist, max_cell = cell_ids.shape
    add_counts = np.bincount(assign, minlength=nlist)
    holes = np.zeros((nlist,), np.int64)
    for c in np.unique(assign):
        holes[c] = int((cell_ids[c, : state.cell_fill[c]] < 0).sum())
    free = holes + (max_cell - state.cell_fill)
    if np.any(add_counts > free):
        needed = int((state.cell_fill + np.maximum(add_counts - holes, 0)).max())
        _grow_layout(attrs, bucket_capacity(needed))
        cell_ids = np.asarray(attrs["cell_ids"])
        max_cell = cell_ids.shape[1]

    cells = np.asarray(attrs["cells"])
    cell_sizes = np.asarray(attrs["cell_sizes"])
    codes = attrs.get("codes")
    codebooks = attrs.get("codebooks")
    new_codes = None
    if codes is not None and codebooks is not None:
        new_codes = pq_encode_host(
            X_new - centers[assign], np.asarray(codebooks)
        )
    for c in np.unique(assign):
        rows = np.nonzero(assign == c)[0]
        fill = int(state.cell_fill[c])
        hole_slots = np.nonzero(cell_ids[c, :fill] < 0)[0][: len(rows)]
        n_virgin = len(rows) - len(hole_slots)
        virgin_slots = np.arange(fill, fill + n_virgin)
        slots = np.concatenate([hole_slots, virgin_slots]).astype(np.int64)
        cells[c, slots] = X_new[rows]
        cell_ids[c, slots] = positions[rows]
        if new_codes is not None:
            np.asarray(attrs["codes"])[c, slots] = new_codes[rows]
        state.cell_fill[c] = fill + n_virgin
        state.tombstones -= len(hole_slots)
        cell_sizes[c] += len(rows)
    attrs["cells"] = cells
    attrs["cell_ids"] = cell_ids
    attrs["cell_sizes"] = cell_sizes

    grown_items = np.full(
        (max(int(positions.max()) + 1, len(state.item_cells)),), -1, np.int32
    )
    grown_items[: len(state.item_cells)] = state.item_cells
    grown_items[positions] = assign
    state.item_cells = grown_items
    obs_counter_inc("ann.items_added", int(len(positions)))
    obs_gauge_set("ann.tombstones", max(state.tombstones, 0))


def ivf_delete(attrs: Dict[str, Any], state: MutableIvfState,
               positions: np.ndarray) -> int:
    """Tombstone items by position: their `cell_ids` slots flip to -1 — the
    sentinel every probe scan already masks to INVALID_D2, so deleted items
    vanish from search results with no kernel or shape change. Returns how
    many positions were actually live."""
    positions = np.unique(np.asarray(positions, np.int64))
    cell_ids = np.asarray(attrs["cell_ids"])
    cell_sizes = np.asarray(attrs["cell_sizes"])
    deleted = 0
    for pos in positions:
        if pos < 0 or pos >= len(state.item_cells):
            continue
        c = int(state.item_cells[pos])
        if c < 0:
            continue
        slots = np.nonzero(cell_ids[c] == pos)[0]
        if len(slots) == 0:
            continue
        cell_ids[c, slots] = -1
        cell_sizes[c] -= len(slots)
        state.item_cells[pos] = -1
        state.tombstones += len(slots)
        deleted += 1
    attrs["cell_ids"] = cell_ids
    attrs["cell_sizes"] = cell_sizes
    if deleted:
        obs_counter_inc("ann.items_deleted", deleted)
        obs_gauge_set("ann.tombstones", max(state.tombstones, 0))
    return deleted


def needs_compaction(state: MutableIvfState) -> bool:
    """Compaction trigger: tombstoned slots exceed `ann.compact_tombstone_pct`
    of occupied (live + tombstoned) slots."""
    occupied = state.live_items() + max(state.tombstones, 0)
    if occupied <= 0 or state.tombstones <= 0:
        return False
    pct = resolve_compact_tombstone_pct()
    return 100 * state.tombstones > pct * occupied


def ivf_compact(attrs: Dict[str, Any], state: MutableIvfState) -> None:
    """Re-layout the lists without their tombstoned slots (centers untouched
    — compaction never refits the coarse quantizer). Capacity re-buckets to
    the live maximum, so a heavily-deleted index shrinks its scan width."""
    cells = np.asarray(attrs["cells"])
    cell_ids = np.asarray(attrs["cell_ids"])
    nlist, max_cell, d = cells.shape
    live_sizes = (cell_ids >= 0).sum(axis=1)
    new_max = bucket_capacity(int(live_sizes.max()) if nlist else 1)
    new_cells = np.zeros((nlist, new_max, d), cells.dtype)
    new_ids = np.full((nlist, new_max), -1, cell_ids.dtype)
    codes = attrs.get("codes")
    new_codes = (
        np.zeros((nlist, new_max, np.asarray(codes).shape[2]),
                 np.asarray(codes).dtype)
        if codes is not None else None
    )
    for c in range(nlist):
        slots = np.nonzero(cell_ids[c] >= 0)[0]
        m = len(slots)
        new_cells[c, :m] = cells[c, slots]
        new_ids[c, :m] = cell_ids[c, slots]
        if new_codes is not None:
            new_codes[c, :m] = np.asarray(codes)[c, slots]
    attrs["cells"] = new_cells
    attrs["cell_ids"] = new_ids
    attrs["cell_sizes"] = live_sizes.astype(np.int32)
    if new_codes is not None:
        attrs["codes"] = new_codes
    state.cell_fill = live_sizes.astype(np.int32)
    state.tombstones = 0
    obs_counter_inc("ann.compactions", 1)
    obs_gauge_set("ann.tombstones", 0)
