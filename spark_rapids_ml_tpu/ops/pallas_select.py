#
# Fused Pallas distance+select kernel family (docs/design.md §5c) — the
# roofline-kernel half of the selection plane (ops/selection.py carries the
# strategy knob; this module carries the `pallas_fused` implementation).
#
# The XLA scans materialize the (block, n_items) squared-distance tile in HBM
# before selecting over it: `_exact_knn_scan` writes+reads (block, n) f32 per
# query block, `kmeans_predict` an (n, k) matrix, `_core_mask` a (block, n)
# tile per row block. At the sizes the search family exists for, that traffic
# IS the roofline (the device plane's `roofline_bound=memory` verdicts on the
# distance-scan family), and BENCH_TPU_SESSION_R4 measured a masked Pallas
# XᵀX kernel at ~2x XLA's own two-read HBM roofline on a real v5e. This
# kernel family fuses the distance tile with an in-register running
# top-k / argmin / count-below-eps so the matrix never leaves VMEM — X
# streams through HBM exactly once per scan:
#
#   for each (query block, item tile):   d2 = q2 - 2 Q Xtᵀ + x2     (MXU)
#     reduction=topk    merge the tile into a running (block, k) pool via
#                       k-step extraction (argmin + mask, unrolled — ties
#                       resolve lowest-global-index-first, matching lax.top_k
#                       bit-for-bit)                                 (VPU)
#     reduction=argmin  running argmin is just the k=1 pool — but the KMeans
#                       assignment form streams ROWS against resident
#                       centers, so the argmin closes per row block
#     reduction=count   counts += Σ (d2 <= eps²) & valid             (VPU)
#
# One kernel family serves four call sites: KMeans assignment
# (ops/kmeans.py::kmeans_predict — superseding the small-k loss region of the
# opt-in ops/pallas_kmeans.py Lloyd kernel, whose fused form pays lane
# padding below k~128), exact kNN (ops/knn.py::exact_knn_single and the
# per-shard scans under exact_knn_distributed), the IVF coarse probe
# (ops/ann_streaming.py::streaming_ivfflat_search), and DBSCAN neighborhood
# counting (ops/dbscan.py::_core_mask).
#
# Contracts (the §5b invariants, preserved bit-for-bit):
#   * exact-f32 mode is BIT-IDENTICAL to the select_topk(exact_full) path on
#     returned ids AND distances, tie order included: the kernel computes the
#     same max(q2 - 2·cross + x2, 0) expansion, masks invalid entries to the
#     same large-finite INVALID_D2 sentinel (never inf — kernel-internal inf
#     is confined to extracted-slot masking and pool init, where it only ever
#     feeds compares), clamps at the sentinel, and its k-step extraction
#     prefers the first (lowest-global-index) occurrence of every tie exactly
#     like lax.top_k. k > n_valid therefore returns the same
#     earliest-invalid-id tail as the XLA path.
#   * bf16/int8 distance accumulation (knn.pallas_precision) selects an
#     OVERSAMPLED candidate pool on the fast MXU paths; the caller re-ranks
#     it with ops/knn.py::parity_rerank_sq (exact f32 difference-form
#     distances, exact merge) so returned DISTANCES are bit-equal to
#     exact-f32 — only the id set is approximate. int8 quantizes per row
#     (dynamic symmetric max-abs scales), so it suits normalized/bounded
#     feature spaces; norms ride exact f32 either way.
#   * multi-device runs wrap the single-device pallas_call per-shard under
#     shard_map (the callers' existing merge contracts are untouched:
#     merge_topk stays exact, sentinel semantics preserved).
#
# Every host entry routes through `compiled_kernel`, so compile accounting,
# XLA cost/memory analysis (seeded with a pl.CostEstimate — a pallas custom
# call is otherwise invisible to the cost model) and MFU/roofline span
# attribution work exactly like every other kernel. Off-TPU the kernels run
# the Pallas interpreter, which is what makes the §5c parity property tests
# CPU-runnable in tier-1.
#

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..observability.device import compiled_kernel
from .selection import INVALID_D2

# tile-geometry DEFAULTS live in the knob-registry defaults module
# (autotune/defaults.py — the analyzer's fence/hardcoded-tunable rule bans new literals
# in ops/): the query block bounds the (block, tile) distance tile in VMEM
# (256*1024*4 = 1 MiB) next to one double-buffered X tile (1024*d*4). The
# tuning table (docs/design.md §6i) can override geometry per (platform,
# shape-bucket); tuned values still pass the VMEM-budget shrink below.
# Tests pass explicit odd tiles to exercise ragged edges.
from ..autotune.defaults import (  # re-exported; kmeans/tests import here
    DEFAULT_ASSIGN_BLOCK,
    DEFAULT_ITEM_TILE,
    DEFAULT_QUERY_BLOCK,
    FUSED_ASSIGN_MIN_K,
    MIN_ASSIGN_BLOCK,
    MIN_ITEM_TILE,
    MIN_QUERY_BLOCK,
)

# VMEM ceiling the fused tiles must fit under (the scoped-VMEM budget is
# ~16 MiB; half is left for double buffering and compiler scratch — the
# ops/pallas_kmeans.py lesson that a 4096x512 block blows exactly that
# limit). A hardware property, NOT a tunable. Geometry resolution shrinks
# blocks toward the floors and REFUSES (-> XLA path) when nothing fits: a
# Mosaic compile failure at k in the thousands would crash a predict the
# XLA path handles fine.
_VMEM_BUDGET_BYTES = 8 << 20


def _interpret_default() -> bool:
    """Off-TPU the kernels run the Pallas interpreter: bit-exact, slow — the
    correctness tier that makes CPU tier-1 parity tests real."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover - backend probe must never fail
        return True


def _cost_estimate(flops: float, bytes_accessed: float):
    """Seed XLA's cost model for the pallas custom call (pl.CostEstimate,
    when this jax ships it): without it the device plane's cost_analysis
    sees ~zero flops and the bench's measured-MFU keys read hollow."""
    ce = getattr(pl, "CostEstimate", None)
    if ce is None:  # pragma: no cover - older pallas: no estimate, still runs
        return None
    return ce(
        flops=int(max(flops, 0)),
        bytes_accessed=int(max(bytes_accessed, 0)),
        transcendentals=0,
    )


def _maybe_cost(kwargs: dict, flops: float, bytes_accessed: float) -> dict:
    est = _cost_estimate(flops, bytes_accessed)
    if est is not None:
        kwargs["cost_estimate"] = est
    return kwargs


def topk_fits_vmem(q_block: int, item_tile: int, d: int, k: int) -> bool:
    """Can the running-pool scan place (q_block, item_tile) at this (d, k)?
    ONE working-set formula — `_topk_geometry`'s shrink loop and the
    autotuner's candidate filter (autotune/search.py) both ask this, so the
    two can never drift and admit a geometry Mosaic cannot place."""
    work = (
        q_block * (k + item_tile) * 4 * 4  # concat d2+ids + masked copies
        + q_block * d * 4 + item_tile * d * 4  # Q block + X tile
        + q_block * k * 8  # running pool (d2 + ids)
    )
    return work <= _VMEM_BUDGET_BYTES


def assign_block_fits_vmem(blk: int, d: int, k: int, n_split: int) -> bool:
    """Can the fused assignment place a blk-row block at this (d, k,
    n_split)? Shared by `_assign_geometry` and the autotuner's
    `pallas.assign_block` candidate filter — same no-drift rationale as
    `topk_fits_vmem`."""
    copies = max(1, n_split)  # bf16 splitting materializes n_split copies
    centers_b = k * d * 4 * copies
    tile_b = blk * d * 4 * copies + blk * k * 4 * 2  # X block + d2/onehot
    return centers_b + tile_b <= _VMEM_BUDGET_BYTES


def _topk_geometry(
    nq: int, n: int, d: int, k: int,
    q_block: Optional[int], item_tile: Optional[int],
) -> Tuple[int, int]:
    """(q_block, item_tile) fitting the running-pool scan's VMEM residents:
    Q block + X tile + the (B, k+T) extraction working set (concat d2/ids
    copies). Caller-pinned values pass through untouched (tests exercise
    ragged geometries); unpinned axes halve toward their floors until the
    budget holds — a floor-sized scan always fits for any k the search
    family produces. Fully-unpinned geometry consults the tuning table first
    (`pallas.topk_geometry`, docs/design.md §6i); tuned values are still
    treated as unpinned, so a table entry written on different hardware can
    never hand Mosaic an unplaceable compile."""
    tuned_q = tuned_t = None
    if q_block is None and item_tile is None:
        from .. import autotune as _autotune

        tuned = _autotune.lookup("pallas.topk_geometry", n=n, d=d, k=k)
        if tuned is not None:
            # clamp tuned values into the data like the defaults are
            tuned_q = min(int(tuned[0]), max(nq, 1))
            tuned_t = min(int(tuned[1]), max(n, 1))
    qb = q_block or tuned_q or min(DEFAULT_QUERY_BLOCK, max(nq, 1))
    t = item_tile or tuned_t or min(DEFAULT_ITEM_TILE, max(n, 1))

    if q_block is None:
        while not topk_fits_vmem(qb, t, d, k) and qb > MIN_QUERY_BLOCK:
            qb //= 2
    if item_tile is None:
        while not topk_fits_vmem(qb, t, d, k) and t > MIN_ITEM_TILE:
            t //= 2
    return max(qb, 1), max(t, 1)


def _assign_n_split() -> int:
    """Cross-term passes for the fused assignment. The XLA reference
    (`_sq_dists` with fast=False → pdot) runs at PARITY precision, so on TPU
    the kernel emulates it with the same bf16-split decomposition the fused
    Lloyd uses (`_dot_multipass` — Mosaic rejects the precision attribute
    itself, ops/pallas_kmeans.py header); off-TPU a single pass IS exact
    f32, bit-identical to pdot on the CPU interpreter."""
    if _interpret_default():
        return 1
    from ._precision import parity_precision
    from .pallas_kmeans import _N_SPLIT

    return _N_SPLIT[parity_precision()]


def _assign_geometry(d: int, k: int, n_split: int, n: int) -> Optional[int]:
    """Row-block for the fused assignment, or None when even the smallest
    block cannot fit resident centers + tiles under the VMEM budget — the
    caller must keep the XLA path (which handles any k) rather than hand
    Mosaic a compile it cannot place."""
    floor = min(MIN_ASSIGN_BLOCK, max(n, 1))
    from .. import autotune as _autotune

    tuned = _autotune.lookup("pallas.assign_block", d=d, k=k)
    start = int(tuned) if tuned is not None else DEFAULT_ASSIGN_BLOCK
    blk = min(max(start, floor), max(n, 1))
    while True:
        if assign_block_fits_vmem(blk, d, k, n_split):
            return blk
        if blk <= floor:
            return None
        blk //= 2


def _cross_term(Q: jax.Array, Xt: jax.Array, precision: str) -> jax.Array:
    """(B, T) cross term Q·Xtᵀ at the configured accumulation mode.

    float32: a single dot_general with f32 accumulate — on TPU this is the
    MXU's DEFAULT tier (the FAST contract of `_block_sq_dists`: ranking-class
    matmuls may run single-pass), on the CPU interpreter it is exact f32 and
    therefore bit-identical to the XLA scan's matmul.
    bfloat16: operands rounded to bf16 before a single f32-accumulate pass.
    int8: per-row dynamic symmetric quantization (max-abs / 127) and an
    int8×int8→int32 MXU pass, rescaled into f32."""
    dims = (((1,), (1,)), ((), ()))
    if precision == "bfloat16":
        return jax.lax.dot_general(
            Q.astype(jnp.bfloat16), Xt.astype(jnp.bfloat16), dims,
            preferred_element_type=jnp.float32,
        )
    if precision == "int8":
        s_q = jnp.max(jnp.abs(Q), axis=1, keepdims=True) / 127.0  # (B, 1)
        s_x = jnp.max(jnp.abs(Xt), axis=1, keepdims=True) / 127.0  # (T, 1)
        Qq = jnp.round(Q / jnp.maximum(s_q, 1e-30)).astype(jnp.int8)
        Xq = jnp.round(Xt / jnp.maximum(s_x, 1e-30)).astype(jnp.int8)
        cross = jax.lax.dot_general(
            Qq, Xq, dims, preferred_element_type=jnp.int32
        ).astype(jnp.float32)
        return cross * s_q * s_x.reshape(1, -1)
    return jax.lax.dot_general(
        Q, Xt, dims, preferred_element_type=jnp.float32
    )


# --------------------------------------------------------------------- topk


def _topk_scan_kernel(
    n_items: int, k: int, precision: str,
    q_ref, x_ref, x2m_ref, pool_d2_ref, pool_id_ref,
):
    """One (query block, item tile) step: fused distances + running top-k.

    The pool refs are revisited across the minor (item-tile) grid dimension,
    so the running top-k lives in VMEM for a whole query block. Pool slots
    initialize to (+inf, -1): kernel-internal inf LOSES every tie against the
    INVALID_D2 sentinel real entries carry, which is exactly what makes the
    k > n_valid tail bit-match the XLA path (earliest invalid ids win); inf
    never feeds arithmetic, only compares, so the §5b NaN-factory rule holds.
    The k-step extraction takes the first occurrence of each minimum — pool
    entries (earlier tiles, lower global ids) sit before tile entries, and
    tile lanes are global-id-ordered, so every tie resolves
    lowest-global-index-first, byte-for-byte lax.top_k's order."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        pool_d2_ref[...] = jnp.full_like(pool_d2_ref, jnp.inf)
        pool_id_ref[...] = jnp.full_like(pool_id_ref, -1)

    Q = q_ref[...]  # (B, d)
    Xt = x_ref[...]  # (T, d)
    x2m = x2m_ref[...]  # (1, T): Σx² for valid items, -1 sentinel for masked
    T = Xt.shape[0]
    gids = t * T + jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    # validity = caller mask (x2m >= 0; real norms are always >= 0) AND the
    # ragged-edge bound (the overhang of the last tile reads unspecified
    # memory, which interpret mode may fill with NaN — masked before ranking)
    valid = (x2m >= 0.0) & (gids < n_items)
    x2 = jnp.where(valid, x2m, 0.0)

    q2 = jnp.sum(Q * Q, axis=1, keepdims=True)  # (B, 1)
    cross = _cross_term(Q, Xt, precision)  # (B, T)
    # same op order as _block_sq_dists + mask_invalid + the select_topk clamp:
    # max(.,0), sentinel mask, clamp — bit-parity depends on this sequence
    d2 = jnp.maximum(q2 - 2.0 * cross + x2, 0.0)
    d2 = jnp.where(valid, d2, INVALID_D2)
    d2 = jnp.minimum(d2, INVALID_D2)

    cat_d2 = jnp.concatenate([pool_d2_ref[...], d2], axis=1)  # (B, k+T)
    cat_id = jnp.concatenate(
        [pool_id_ref[...], jnp.broadcast_to(gids, d2.shape)], axis=1
    )
    B, W = cat_d2.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (B, W), 1)
    new_d2, new_id = [], []
    for _ in range(k):  # k static: unrolled extraction
        m = jnp.min(cat_d2, axis=1, keepdims=True)
        am = jnp.argmin(cat_d2, axis=1)  # first occurrence: the tie contract
        sel = cols == am[:, None]
        new_d2.append(m)
        # exactly one lane is selected per row, so the masked sum IS the id
        new_id.append(jnp.sum(jnp.where(sel, cat_id, 0), axis=1, keepdims=True))
        cat_d2 = jnp.where(sel, jnp.inf, cat_d2)  # extracted: loses every tie
    pool_d2_ref[...] = jnp.concatenate(new_d2, axis=1)
    pool_id_ref[...] = jnp.concatenate(new_id, axis=1)


@compiled_kernel(
    "knn.pallas_fused_scan",
    static_argnames=("k", "q_block", "item_tile", "precision", "interpret"),
)
def _fused_topk_scan(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    x2: Optional[jax.Array],
    k: int,
    q_block: int,
    item_tile: int,
    precision: str,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    nq, d = Q.shape
    n = X.shape[0]
    if x2 is None:
        x2 = jnp.sum(X * X, axis=1)  # same reduce as the XLA scan's hoist
    x2m = jnp.where(valid, x2, -1.0)[None, :]  # mask folded into the norm row
    n_qb = -(-nq // q_block)
    n_t = -(-n // item_tile)
    pool_d2, pool_id = pl.pallas_call(
        functools.partial(_topk_scan_kernel, n, k, precision),
        grid=(n_qb, n_t),
        in_specs=[
            pl.BlockSpec((q_block, d), lambda i, t: (i, 0)),
            pl.BlockSpec((item_tile, d), lambda i, t: (t, 0)),
            pl.BlockSpec((1, item_tile), lambda i, t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((q_block, k), lambda i, t: (i, 0)),
            pl.BlockSpec((q_block, k), lambda i, t: (i, 0)),
        ],
        # padded to whole query blocks: the ragged tail block writes its
        # overhang into the pad rows, sliced off below — X is never padded
        # (a host-side pad would copy the dataset at exactly the HBM-filling
        # sizes this kernel exists for, the ops/pallas_kmeans.py lesson)
        out_shape=[
            jax.ShapeDtypeStruct((n_qb * q_block, k), jnp.float32),
            jax.ShapeDtypeStruct((n_qb * q_block, k), jnp.int32),
        ],
        interpret=interpret,
        **_maybe_cost(
            {},
            flops=2.0 * nq * n * d + 2.0 * nq * n * k,
            bytes_accessed=4.0 * (nq * d + n * d + n + 2 * nq * k),
        ),
    )(Q, X, x2m)
    return pool_d2[:nq], pool_id[:nq]


def resolve_topk_geometry(
    nq: int, n: int, d: int, k: int,
    q_block: Optional[int] = None, item_tile: Optional[int] = None,
) -> Tuple[int, int]:
    """HOST-side geometry resolution for the fused top-k scan: tuning table
    (`pallas.topk_geometry`) + the VMEM-budget shrink. Traced code must not
    call this (the table read would bake per-host — rank-divergent SPMD
    programs on a pod); resolve in the host wrapper / shard_map factory and
    hand the pins to `fused_topk_pinned`."""
    return _topk_geometry(int(nq), int(n), int(d), int(k), q_block, item_tile)


def fused_topk_pinned(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    k: int,
    *,
    q_block: int,
    item_tile: int,
    x2: Optional[jax.Array] = None,
    precision: str = "float32",
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """TRACE-PURE core of the fused smallest-k scan: geometry arrives pinned
    (resolve_topk_geometry in a host wrapper), precision arrives resolved —
    no config read, no tuning-table read (tools/analysis purity/*). This is
    the form shard_map bodies call; same output contract as fused_topk."""
    n = X.shape[0]
    k = min(int(k), n)
    if interpret is None:
        interpret = _interpret_default()  # backend probe, not config
    return _fused_topk_scan(
        Q, X, valid, x2, k, int(q_block), int(item_tile), precision, interpret,
    )


def fused_topk(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    k: int,
    *,
    x2: Optional[jax.Array] = None,
    precision: str = "float32",
    q_block: Optional[int] = None,
    item_tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused smallest-k scan: (d2_topk ascending, global ids). Exact-f32 mode
    is bit-identical to the `select_topk(exact_full)` path (ids, distances,
    tie order, k > n_valid tails). bf16/int8 modes return the APPROXIMATE
    pool — callers owe the user a parity_rerank_sq pass (see fused_knn_select
    for the paired form). HOST wrapper: resolves geometry (tuning table +
    VMEM shrink) and delegates to the trace-pure fused_topk_pinned."""
    n = X.shape[0]
    k = min(int(k), n)
    q_block, item_tile = resolve_topk_geometry(
        int(Q.shape[0]), int(n), int(Q.shape[1]), k, q_block, item_tile
    )
    return fused_topk_pinned(
        Q, X, valid, k, q_block=q_block, item_tile=item_tile, x2=x2,
        precision=precision, interpret=interpret,
    )


def oversample_width(k: int, n: int, precision: str) -> int:
    """Candidate-pool width for the approximate-compute modes: bf16/int8
    ranking error can push the true k-th winner just past the boundary, so
    the pool oversamples (k + max(8, k/4), clamped to n) before the exact
    re-rank cuts it back to k. float32 needs no slack — it IS exact."""
    if precision == "float32":
        return min(int(k), n)
    return min(n, int(k) + max(8, int(k) // 4))


# -------------------------------------------------------------------- probe


def fused_probe(
    Q: jax.Array,
    centers: jax.Array,
    nprobe: int,
    *,
    center_norms: Optional[jax.Array] = None,
) -> jax.Array:
    """IVF coarse probe: ids of the nprobe nearest cells per query. ALWAYS
    exact f32 (the probe list bounds recall for the whole search — the §5b
    rule that the coarse probe never goes approximate), bit-identical to the
    `select_topk(cd2, nprobe, exact_full)` probe."""
    nlist = centers.shape[0]
    ones = jnp.ones((nlist,), bool)
    _, probe = fused_topk(
        Q, centers, ones, min(int(nprobe), nlist),
        x2=center_norms, precision="float32",
    )
    return probe


# ------------------------------------------------------------------- argmin


def _assign_kernel(n_rows: int, n_split: int, x_ref, c_ref, c2_ref, out_ref):
    """KMeans assignment row block: fused distances + argmin over resident
    centers. The argmin closes within the block (centers all fit one tile),
    so the output streams out per block and no (n, k) tensor ever exists.
    Computes the FULL clamped d2 (including the x2 row term the argmin
    technically cancels): max(d2, 0) can clamp several centers of a
    duplicate-heavy row to exactly 0, and dropping x2 would re-order those
    ties against `kmeans_predict`'s argmin — full-form keeps bit-parity.
    The cross term runs at n_split bf16-split passes (_assign_n_split): the
    XLA reference ranks at PARITY precision, not FAST, and the fused path
    must not silently demote it."""
    from .pallas_kmeans import _dot_multipass

    Xb = x_ref[...]  # (B, d)
    C = c_ref[...]  # (k, d)
    c2 = c2_ref[...]  # (1, k)
    x2 = jnp.sum(Xb * Xb, axis=1, keepdims=True)
    cross = _dot_multipass(Xb, C, (((1,), (1,)), ((), ())), n_split)
    d2 = jnp.maximum(x2 - 2.0 * cross + c2, 0.0)
    # overhang rows of the last block read unspecified memory; their argmin
    # lands in the output pad rows, sliced off at the host — but NaN must not
    # reach argmin (NaN never sorts), so the edge rows are zeroed first
    b = pl.program_id(0)
    rows = b * Xb.shape[0] + jax.lax.broadcasted_iota(
        jnp.int32, (Xb.shape[0], 1), 0
    )
    d2 = jnp.where(rows < n_rows, d2, 0.0)
    out_ref[...] = jnp.argmin(d2, axis=1).astype(jnp.int32)[:, None]


@compiled_kernel(
    "kmeans.pallas_assign",
    static_argnames=("block", "n_split", "interpret"),
)
def _fused_assign(
    X: jax.Array,
    centers: jax.Array,
    block: int,
    n_split: int,
    interpret: bool,
) -> jax.Array:
    n, d = X.shape
    k = centers.shape[0]
    c2 = jnp.sum(centers * centers, axis=1)[None, :]  # the XLA kernel's c2
    n_b = -(-n // block)
    out = pl.pallas_call(
        functools.partial(_assign_kernel, n, n_split),
        grid=(n_b,),
        in_specs=[
            pl.BlockSpec((block, d), lambda b: (b, 0)),
            pl.BlockSpec((k, d), lambda b: (0, 0)),
            pl.BlockSpec((1, k), lambda b: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, 1), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((n_b * block, 1), jnp.int32),
        interpret=interpret,
        **_maybe_cost(
            {},
            flops=2.0 * n * k * d * (max(1, n_split) * (max(1, n_split) + 1) // 2),
            bytes_accessed=4.0 * (n * d + k * d + n),
        ),
    )(X, centers, c2)
    return out[:n, 0]


def fused_assign(
    X: jax.Array,
    centers: jax.Array,
    *,
    block: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused nearest-center assignment (argmin reduction): X streams through
    once, matching `argmin(_sq_dists(X, centers))` — bit-identical off-TPU
    (single-pass f32 == pdot on CPU), parity-class (bf16-split emulation of
    the pdot pass structure) on TPU. Raises when no row block fits VMEM —
    `use_fused_assign` gates that case to the XLA path before routing."""
    if interpret is None:
        interpret = _interpret_default()
    n, d = X.shape
    n_split = _assign_n_split()
    if block is None:
        block = _assign_geometry(d, int(centers.shape[0]), n_split, int(n))
        if block is None:
            raise ValueError(
                "fused assignment does not fit the VMEM budget at "
                f"k={int(centers.shape[0])}, d={d} — use the XLA path"
            )
    return _fused_assign(X, centers, block, n_split, interpret)


def use_fused_assign(
    k: int, d: Optional[int] = None, strategy: Optional[str] = None
) -> bool:
    """Should KMeans assignment run the fused kernel? `pallas_fused`
    explicitly → yes (any platform; interpret off-TPU). `auto` → TPU and
    k >= FUSED_ASSIGN_MIN_K, the measured win boundary where the MXU lane
    padding vanishes and XLA's (n, k) intermediates approach the size of X
    (the documented small-k loss region of ops/pallas_kmeans.py). Either
    way, a (k, d) whose resident centers + smallest row block cannot fit
    the VMEM budget stays on the XLA path (which handles any k) — even an
    explicit request must not hand Mosaic an unplaceable compile."""
    from . import selection as _sel
    from .. import config as _config

    s = strategy or str(_config.get("knn.selection"))
    if s not in ("pallas_fused", "auto"):
        return False
    if s == "auto":
        if _sel._backend() != "tpu":
            # auto off-TPU: XLA always — return before any probe so a CPU
            # predict never pays (or counter-pollutes) a table consult
            return False
        # min_k gate BEFORE the geometry probe: the probe can trigger a
        # pallas.assign_block table consult (and, in online search mode, a
        # whole measurement sweep) that a below-threshold k would discard
        min_k = FUSED_ASSIGN_MIN_K
        from .. import autotune as _autotune

        tuned = _autotune.lookup("assign.fused_min_k", d=d)
        if tuned is not None:
            min_k = int(tuned)
        if int(k) < min_k:
            return False
    if d is not None and not assign_block_fits_vmem(
        # placeability = the FLOOR block fits (what _assign_geometry's
        # shrink bottoms out at); asking the predicate directly keeps the
        # gate free of a second pallas.assign_block table consult per call
        MIN_ASSIGN_BLOCK, int(d), int(k), _assign_n_split()
    ):
        return False
    return True


# -------------------------------------------------------------------- count


def _count_kernel(n_items: int, precision: str,
                  q_ref, x_ref, x2m_ref, eps2_ref, out_ref):
    """DBSCAN neighborhood counting: counts += Σ (d2 <= eps²) & valid per
    item tile; the counts ref is revisited across the minor grid dimension."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    Q = q_ref[...]
    Xt = x_ref[...]
    x2m = x2m_ref[...]
    T = Xt.shape[0]
    gids = t * T + jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    valid = (x2m >= 0.0) & (gids < n_items)
    x2 = jnp.where(valid, x2m, 0.0)
    q2 = jnp.sum(Q * Q, axis=1, keepdims=True)
    cross = _cross_term(Q, Xt, precision)
    d2 = jnp.maximum(q2 - 2.0 * cross + x2, 0.0)
    eps2 = eps2_ref[0, 0]
    hit = (d2 <= eps2) & valid  # invalid lanes (incl. NaN overhang) never count
    out_ref[...] += jnp.sum(hit.astype(jnp.int32), axis=1, keepdims=True)


@compiled_kernel(
    "dbscan.pallas_count",
    static_argnames=("q_block", "item_tile", "precision", "interpret"),
)
def _fused_count(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    eps2: jax.Array,
    q_block: int,
    item_tile: int,
    precision: str,
    interpret: bool,
) -> jax.Array:
    nq, d = Q.shape
    n = X.shape[0]
    x2 = jnp.sum(X * X, axis=1)
    x2m = jnp.where(valid, x2, -1.0)[None, :]
    n_qb = -(-nq // q_block)
    n_t = -(-n // item_tile)
    counts = pl.pallas_call(
        functools.partial(_count_kernel, n, precision),
        grid=(n_qb, n_t),
        in_specs=[
            pl.BlockSpec((q_block, d), lambda i, t: (i, 0)),
            pl.BlockSpec((item_tile, d), lambda i, t: (t, 0)),
            pl.BlockSpec((1, item_tile), lambda i, t: (0, t)),
            pl.BlockSpec((1, 1), lambda i, t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((q_block, 1), lambda i, t: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_qb * q_block, 1), jnp.int32),
        interpret=interpret,
        **_maybe_cost(
            {},
            flops=2.0 * nq * n * d,
            bytes_accessed=4.0 * (nq * d + n * d + n + nq),
        ),
    )(Q, X, x2m, jnp.asarray(eps2, jnp.float32).reshape(1, 1))
    return counts[:nq, 0]


def fused_count_below(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    eps2,
    *,
    precision: str = "float32",
    q_block: Optional[int] = None,
    item_tile: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Count-below-eps reduction: per query row, how many VALID items sit
    within eps² (self included when Q is X). eps2 rides as a runtime operand,
    so one compiled signature serves every eps. Bit-identical counts to the
    `_core_mask` XLA scan in f32 mode. Tile geometry resolves through the
    same VMEM-budget shrink as the topk scan (k=0 — no running pool), so a
    wide-d scan can never hand Mosaic an unplaceable compile."""
    if interpret is None:
        interpret = _interpret_default()
    q_block, item_tile = _topk_geometry(
        int(Q.shape[0]), int(X.shape[0]), int(Q.shape[1]), 0,
        q_block, item_tile,
    )
    return _fused_count(
        Q, X, valid, eps2, q_block, item_tile, precision, interpret,
    )


def use_fused_count(n_items: int, strategy: Optional[str] = None) -> bool:
    """Should a neighborhood-count scan run fused? Same gate shape as the
    kNN sites: explicit `pallas_fused` always, `auto` on TPU once the item
    width clears knn.pallas_min_items."""
    from . import selection as _sel
    from .. import config as _config

    s = strategy or str(_config.get("knn.selection"))
    if s == "pallas_fused":
        return True
    if s == "auto":
        return _sel._fused_auto(int(n_items))
    return False
