#
# Logistic regression fit kernels — the TPU-native replacement for
# cuml.linear_model.logistic_regression_mg.LogisticRegressionMG (reference
# classification.py:989-1052: a C++ quasi-Newton (L-BFGS/OWL-QN) solver with the
# gradient allreduce over NCCL, configured with linesearch_max_iter=20,
# lbfgs_memory=10, penalty_normalized=False).
#
# TPU formulation: the loss/gradient over row-sharded data is ONE jitted function —
# jax.value_and_grad of the weighted cross-entropy; the contraction over the sharded
# row axis makes XLA emit the psum (where cuML put its NCCL allreduce). The optimizer
# loop is a lax.while_loop around optax.lbfgs (memory 10, zoom linesearch ≤20 steps —
# the reference's cuML settings).
#
# L1/elastic-net uses FISTA proximal gradient instead of OWL-QN: same distributed
# gradient, soft-threshold prox on coefficients (not intercept), Lipschitz constant
# from a one-pass Gram + power iteration. OWL-QN's orthant projections are branchy;
# FISTA is pure matrix arithmetic — the TPU-friendly way to the same objective.
#
# Objective (Spark parity): (1/Σw)·Σᵢ wᵢ·CE(yᵢ, xᵢ) + λ(α‖β‖₁ + (1-α)/2·‖β‖²),
# penalty on σ-scaled coefficients when standardization=True (implemented by
# optimizing β_s with effective coefficients β_s/σ — no scaled data copy; XLA fuses
# the divide into the logits matmul).
#

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..observability.device import compiled_kernel
from ._precision import pdot
from .linalg import power_iteration_lmax, weighted_moments

LINESEARCH_MAX_STEPS = 20  # reference classification.py:1046-1052
LBFGS_MEMORY = 10


def _binomial_loss_fn(X, y, w, scale, reg_l2, fit_intercept):
    """Returns f(params) for params = [coef_s (d,), intercept]. y in {0,1}."""
    wsum = jnp.sum(w)

    def loss(params):
        coef_s, b = params[:-1], params[-1]
        z = pdot(X, coef_s / scale) + jnp.where(fit_intercept, b, 0.0)
        # stable log-loss: softplus(z) - y*z
        ce = jnp.sum(w * (jax.nn.softplus(z) - y * z)) / wsum
        return ce + 0.5 * reg_l2 * jnp.sum(coef_s * coef_s)

    return loss


def _multinomial_loss_fn(X, y_onehot, w, scale, reg_l2, fit_intercept):
    """params = (k, d+1): rows [coef_s_k..., intercept_k]."""
    wsum = jnp.sum(w)

    def loss(params):
        coef_s, b = params[:, :-1], params[:, -1]
        z = pdot(X, (coef_s / scale).T) + jnp.where(fit_intercept, b, 0.0)
        logz = jax.nn.log_softmax(z, axis=1)
        ce = -jnp.sum(w * jnp.sum(y_onehot * logz, axis=1)) / wsum
        return ce + 0.5 * reg_l2 * jnp.sum(coef_s * coef_s)

    return loss


def _run_lbfgs(loss, params0, max_iter: int, tol: float):
    """jitted L-BFGS loop (optax) with objective-decrease + gradient stopping, the
    stopping style of the reference's QN solver."""
    opt = optax.lbfgs(
        memory_size=LBFGS_MEMORY,
        linesearch=optax.scale_by_zoom_linesearch(max_linesearch_steps=LINESEARCH_MAX_STEPS),
    )
    value_and_grad = optax.value_and_grad_from_state(loss)

    def cond(state):
        _, opt_state, it, delta, gnorm = state
        return jnp.logical_and(
            it < max_iter, jnp.logical_and(delta > tol, gnorm > tol)
        )

    def body(state):
        params, opt_state, it, _, _ = state
        value, grad = value_and_grad(params, state=opt_state)
        updates, opt_state = opt.update(
            grad, opt_state, params, value=value, grad=grad, value_fn=loss
        )
        new_params = optax.apply_updates(params, updates)
        new_value = optax.tree_utils.tree_get(opt_state, "value")
        delta = jnp.abs(value - new_value) / jnp.maximum(jnp.abs(new_value), 1.0)
        # tree_norm arrived in optax 0.2.4; tree_l2_norm is the older spelling
        _tree_norm = getattr(
            optax.tree_utils, "tree_norm", optax.tree_utils.tree_l2_norm
        )
        gnorm = _tree_norm(grad)
        return new_params, opt_state, it + 1, delta, gnorm

    state0 = (
        params0,
        opt.init(params0),
        0,
        jnp.array(jnp.inf, params0.dtype),
        jnp.array(jnp.inf, params0.dtype),
    )
    params, _, n_iter, _, _ = jax.lax.while_loop(cond, body, state0)
    return params, n_iter


@compiled_kernel("logistic.qn_fit",
                 static_argnames=("fit_intercept", "max_iter", "multinomial"))
def _qn_fit(
    X, y_enc, w, scale, reg_l2, fit_intercept: bool, max_iter: int, tol, multinomial: bool
):
    if multinomial:
        loss = _multinomial_loss_fn(X, y_enc, w, scale, reg_l2, fit_intercept)
        params0 = jnp.zeros((y_enc.shape[1], X.shape[1] + 1), X.dtype)
    else:
        loss = _binomial_loss_fn(X, y_enc, w, scale, reg_l2, fit_intercept)
        params0 = jnp.zeros((X.shape[1] + 1,), X.dtype)
    params, n_iter = _run_lbfgs(loss, params0, max_iter, tol)
    return params, n_iter, loss(params)


def _accelerated_prox_loop(smooth, prox, params0, step, max_iter: int, tol):
    """The shared FISTA/projected-gradient machinery: Nesterov-accelerated
    proximal steps with relative-movement stopping. `prox` is the soft-threshold
    for elastic net and the box clip for bound constraints."""
    grad_fn = jax.grad(smooth)

    def cond(state):
        _, _, _, it, delta = state
        return jnp.logical_and(it < max_iter, delta > tol)

    def body(state):
        pk, zk, tk, it, _ = state
        p_next = prox(zk - step * grad_fn(zk))
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        z_next = p_next + ((tk - 1.0) / t_next) * (p_next - pk)
        delta = jnp.max(jnp.abs(p_next - pk)) / (jnp.max(jnp.abs(p_next)) + 1e-12)
        return p_next, z_next, t_next, it + 1, delta

    dtype = params0.dtype
    state0 = (params0, params0, jnp.array(1.0, dtype), 0, jnp.array(jnp.inf, dtype))
    params, _, _, n_iter, _ = jax.lax.while_loop(cond, body, state0)
    return params, n_iter


@compiled_kernel("logistic.fista_fit",
                 static_argnames=("fit_intercept", "max_iter", "multinomial"))
def _fista_fit(
    X, y_enc, w, scale, reg_l1, reg_l2, lipschitz, fit_intercept: bool, max_iter: int,
    tol, multinomial: bool,
):
    """Proximal-gradient elastic-net fit; prox applies only to coefficient entries."""
    if multinomial:
        smooth = _multinomial_loss_fn(X, y_enc, w, scale, reg_l2, fit_intercept)
        params0 = jnp.zeros((y_enc.shape[1], X.shape[1] + 1), X.dtype)
        coef_mask = jnp.concatenate(
            [jnp.ones((y_enc.shape[1], X.shape[1])), jnp.zeros((y_enc.shape[1], 1))], axis=1
        ).astype(X.dtype)
    else:
        smooth = _binomial_loss_fn(X, y_enc, w, scale, reg_l2, fit_intercept)
        params0 = jnp.zeros((X.shape[1] + 1,), X.dtype)
        coef_mask = jnp.concatenate(
            [jnp.ones((X.shape[1],)), jnp.zeros((1,))]
        ).astype(X.dtype)

    step = 1.0 / lipschitz

    def prox(p):
        soft = jnp.sign(p) * jnp.maximum(jnp.abs(p) - step * reg_l1, 0.0)
        return jnp.where(coef_mask > 0, soft, p)

    params, n_iter = _accelerated_prox_loop(smooth, prox, params0, step, max_iter, tol)
    return params, n_iter, smooth(params) + reg_l1 * jnp.sum(jnp.abs(params * coef_mask))


@compiled_kernel("logistic.projected_fit",
                 static_argnames=("fit_intercept", "max_iter", "multinomial"))
def _projected_fit(
    X, y_enc, w, scale, reg_l2, lipschitz, fit_intercept: bool, max_iter: int,
    tol, multinomial: bool, lb, ub,
):
    """Box-constrained fit: accelerated projected gradient (the same loop as
    _fista_fit with the prox of the box indicator = clip). `lb`/`ub` are full
    params-shaped bounds in the STANDARDIZED space (coef entries pre-multiplied by
    sigma; intercept entries unscaled; +-inf where unbounded). Spark exposes this
    as lowerBounds/upperBoundsOnCoefficients/Intercepts and solves it with
    L-BFGS-B — projection onto the box is the TPU-friendly route to the same
    optimum."""
    if multinomial:
        smooth = _multinomial_loss_fn(X, y_enc, w, scale, reg_l2, fit_intercept)
        params0 = jnp.zeros((y_enc.shape[1], X.shape[1] + 1), X.dtype)
    else:
        smooth = _binomial_loss_fn(X, y_enc, w, scale, reg_l2, fit_intercept)
        params0 = jnp.zeros((X.shape[1] + 1,), X.dtype)

    step = 1.0 / lipschitz

    def proj(p):
        return jnp.clip(p, lb, ub)

    params, n_iter = _accelerated_prox_loop(
        smooth, proj, proj(params0), step, max_iter, tol
    )
    return params, n_iter, smooth(params)


@compiled_kernel("logistic.gram_lmax")
def _gram_lmax(X, w, scale):
    """λ_max of (X/σ)ᵀW(X/σ)/Σw via one sharded Gram pass + power iteration."""
    wsum = jnp.sum(w)
    Xs = X / scale
    G = pdot((Xs * w[:, None]).T, Xs) / wsum
    return power_iteration_lmax(G)


def logreg_fit(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    n_classes: int,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    multinomial: bool,
    bounds: "tuple | None" = None,
) -> Dict[str, Any]:
    """Full fit; returns Spark-layout model attributes:
    coefficients (k_rows, d) and intercepts (k_rows,) with k_rows = 1 for binomial.

    `bounds` = (lb_coef, ub_coef, lb_icpt, ub_icpt) in ORIGINAL coefficient space
    ((k_rows, d) matrices / (k_rows,) vectors, None where unbounded) switches on the
    box-constrained projected fit — the reference maps these Spark params to None
    (unsupported, classification.py:694-698); here they run natively."""
    d = X.shape[1]
    if standardize:
        _, var, _ = weighted_moments(X, w)
        scale = jnp.sqrt(var)
        scale = jnp.where(scale <= 0.0, 1.0, scale)
    else:
        scale = jnp.ones((d,), X.dtype)

    reg_l1 = reg * l1_ratio
    reg_l2 = reg * (1.0 - l1_ratio)

    if multinomial:
        y_enc = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=X.dtype) * (
            (w > 0)[:, None]
        )
    else:
        y_enc = y

    icpt_bounded = False
    if bounds is not None:
        if reg_l1 > 0.0:
            raise ValueError(
                "Coefficient bounds support only L2 regularization "
                "(elasticNetParam must be 0.0), matching Spark."
            )
        lb_c, ub_c, lb_i, ub_i = bounds
        k_rows = n_classes if multinomial else 1
        inf = jnp.inf

        def _mat(v, fill, name):
            if v is None:
                return jnp.full((k_rows, d), fill, X.dtype)
            arr = np.asarray(v, np.float32)
            if arr.ndim == 1 and k_rows == 1:
                arr = arr.reshape(1, -1)
            if arr.shape != (k_rows, d):
                raise ValueError(
                    f"{name} must have shape ({k_rows}, {d}) "
                    f"(numCoefficientSets x numFeatures), got {arr.shape}."
                )
            return jnp.asarray(arr)

        def _vec(v, fill, name):
            if v is None:
                return jnp.full((k_rows,), fill, X.dtype)
            arr = np.asarray(v, np.float32).reshape(-1)
            if arr.shape != (k_rows,):
                raise ValueError(
                    f"{name} must have length {k_rows} (numCoefficientSets), "
                    f"got {arr.shape[0]}."
                )
            return jnp.asarray(arr)

        lbm_raw = _mat(lb_c, -inf, "lowerBoundsOnCoefficients")
        ubm_raw = _mat(ub_c, inf, "upperBoundsOnCoefficients")
        lbi = _vec(lb_i, -inf, "lowerBoundsOnIntercepts")
        ubi = _vec(ub_i, inf, "upperBoundsOnIntercepts")
        if bool(jnp.any(lbm_raw > ubm_raw)) or bool(jnp.any(lbi > ubi)):
            raise ValueError(
                "Each lower bound must be <= the matching upper bound."
            )
        # constraint l <= coef <= u in original space <=> l*sigma <= coef_s <= u*sigma
        lbm = lbm_raw * scale[None, :]
        ubm = ubm_raw * scale[None, :]
        icpt_bounded = lb_i is not None or ub_i is not None
        if icpt_bounded and not fit_intercept:
            raise ValueError(
                "Intercept bounds require fitIntercept=True (an unbounded, "
                "unfitted intercept cannot honor them)."
            )
        lb_full = jnp.concatenate([lbm, lbi[:, None]], axis=1)
        ub_full = jnp.concatenate([ubm, ubi[:, None]], axis=1)
        if not multinomial:
            lb_full, ub_full = lb_full[0], ub_full[0]
        lmax = _gram_lmax(X, w, scale)
        lipschitz = (0.5 if multinomial else 0.25) * lmax + reg_l2 + 1e-12
        params, n_iter, obj = _projected_fit(
            X, y_enc, w, scale, reg_l2, lipschitz, bool(fit_intercept),
            int(max_iter), float(tol), bool(multinomial), lb_full, ub_full,
        )
    elif reg_l1 > 0.0:
        lmax = _gram_lmax(X, w, scale)
        lipschitz = (0.5 if multinomial else 0.25) * lmax + reg_l2 + 1e-12
        params, n_iter, obj = _fista_fit(
            X, y_enc, w, scale, reg_l1, reg_l2, lipschitz, bool(fit_intercept),
            int(max_iter), float(tol), bool(multinomial),
        )
    else:
        params, n_iter, obj = _qn_fit(
            X, y_enc, w, scale, reg_l2, bool(fit_intercept), int(max_iter),
            float(tol), bool(multinomial),
        )

    params = np.asarray(params, dtype=np.float64)
    scale_h = np.asarray(scale, dtype=np.float64)
    if multinomial:
        coef = params[:, :-1] / scale_h
        intercept = params[:, -1]
        # Spark centers multinomial intercepts (reference classification.py:1135-1147)
        # — but never when the user bounded them (centering would break the box)
        if fit_intercept and not icpt_bounded:
            intercept = intercept - intercept.mean()
    else:
        coef = (params[:-1] / scale_h).reshape(1, -1)
        intercept = params[-1:]
    return {
        "coefficients": coef.astype(np.float32),
        "intercepts": intercept.astype(np.float32),
        "n_iter": int(n_iter),
        "objective": float(obj),
    }


@compiled_kernel("logistic.decision", static_argnames=("multinomial",))
def logreg_decision(X, coef, intercept, multinomial: bool):
    """Raw margins: (n,) for binomial single-vector, (n,k) for multinomial."""
    if multinomial:
        return pdot(X, coef.T) + intercept
    return pdot(X, coef[0]) + intercept[0]
