#
# k-nearest-neighbor kernels — the TPU-native replacement for
# cuml.neighbors.nearest_neighbors_mg.NearestNeighborsMG (reference knn.py:683-774:
# exact kNN with the query-block all-to-all over UCX endpoints and a distributed
# top-k merge inside cuML) and for the cuVS ANN indexes (reference knn.py:1510-1690).
#
# TPU formulation (P4 all-to-all, SURVEY.md §2.7):
#   * items live row-sharded across the mesh; each device scans ITS shard against the
#     (replicated or gathered) query block — an (nq, n_shard) distance matmul on the
#     MXU — and keeps a local top-k with GLOBAL item ids,
#   * one all_gather of the per-device top-k candidates over ICI (k·n_devices per
#     query — tiny next to the data) replaces cuML's UCX endpoint mesh,
#   * a final replicated top-k merge gives the global neighbors.
# Queries are processed in fixed-size blocks (lax.map) to bound the distance-matrix
# footprint in HBM.
#
# IVF-Flat: our own kmeans partitions the items into nlist cells, padded to a common
# cell size (static shapes); search probes the nprobe nearest cells with a masked
# distance scan — the cuVS ivf_flat equivalent re-expressed as dense gathers+matmuls.
#

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ._precision import FAST, pdot
from ..parallel.mesh import DATA_AXIS


def _block_sq_dists(Q: jax.Array, X: jax.Array) -> jax.Array:
    """(nq, n) squared euclidean distances (FAST precision: ranking tolerates bf16
    passes; exact distances are recomputed at parity precision only for the winners)."""
    q2 = jnp.sum(Q * Q, axis=1, keepdims=True)
    x2 = jnp.sum(X * X, axis=1)
    d2 = q2 - 2.0 * jnp.matmul(Q, X.T, precision=FAST) + x2
    return jnp.maximum(d2, 0.0)


@functools.partial(jax.jit, static_argnames=("k", "block"))
def exact_knn_single(
    Q: jax.Array, X: jax.Array, valid: jax.Array, k: int, block: int = 1024
) -> Tuple[jax.Array, jax.Array]:
    """Single-shard exact kNN: blocked scan, returns (distances², indices)."""
    nq = Q.shape[0]
    pad = (-nq) % block
    Qp = jnp.pad(Q, ((0, pad), (0, 0)))

    def scan_block(qb):
        d2 = _block_sq_dists(qb, X)
        d2 = jnp.where(valid[None, :], d2, jnp.inf)
        neg, idx = jax.lax.top_k(-d2, k)
        return -neg, idx

    d2b, idxb = jax.lax.map(scan_block, Qp.reshape(-1, block, Q.shape[1]))
    return d2b.reshape(-1, k)[:nq], idxb.reshape(-1, k)[:nq]


def exact_knn_distributed(
    mesh: Mesh,
    Q: np.ndarray,
    X_sharded: jax.Array,
    valid_sharded: jax.Array,
    k: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Distributed exact kNN over the mesh: local shard scans + all_gather top-k merge.

    Returns host (distances, global indices); distances are EUCLIDEAN (sqrt'd),
    matching the reference's returned distances (knn.py:783-802)."""
    n_total = X_sharded.shape[0]
    n_dev = mesh.devices.size
    shard_rows = n_total // n_dev
    k_eff = min(k, n_total)
    # a shard can hold fewer than k rows; the all-gathered candidate pool
    # (n_dev * k_local >= min(k_eff, n_total)) still covers the global top-k
    k_local = min(k_eff, shard_rows)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,  # post-all_gather results are replicated; size-1 aux axes
        # defeat the static replication checker
    )
    def _local_then_merge(q, x_local, valid_local):
        rank = jax.lax.axis_index(DATA_AXIS)
        d2, idx = exact_knn_single(q, x_local, valid_local, k_local)
        gidx = idx + rank * shard_rows
        # all-to-all candidate exchange over ICI (the UCX replacement)
        d2_all = jax.lax.all_gather(d2, DATA_AXIS, axis=1)  # (nq, n_dev, k_local)
        gidx_all = jax.lax.all_gather(gidx, DATA_AXIS, axis=1)
        d2_all = d2_all.reshape(d2.shape[0], -1)
        gidx_all = gidx_all.reshape(d2.shape[0], -1)
        neg, pos = jax.lax.top_k(-d2_all, k_eff)
        return -neg, jnp.take_along_axis(gidx_all, pos, axis=1)

    d2, gidx = _local_then_merge(jnp.asarray(Q), X_sharded, valid_sharded)
    return np.sqrt(np.asarray(d2)), np.asarray(gidx)


# ---------------------------------------------------------------------------
# IVF-Flat / IVF-PQ
# ---------------------------------------------------------------------------


def ivfflat_build(
    X: jax.Array, w: jax.Array, nlist: int, max_iter: int, seed: int,
    return_assign: bool = False,
) -> Dict[str, np.ndarray]:
    """Partition items into nlist cells via our kmeans; lay cells out densely padded
    to the max cell size (static shapes for the probe scan)."""
    from .kmeans import kmeans_fit, kmeans_predict

    fitted = kmeans_fit(
        X, w, k=nlist, max_iter=max_iter, tol=1e-4, init="k-means||",
        init_steps=2, seed=seed,
    )
    centers = fitted["cluster_centers"]
    assign = np.asarray(kmeans_predict(X, jnp.asarray(centers)))
    valid = np.asarray(w) > 0
    n, d = X.shape
    cell_sizes = np.bincount(assign[valid], minlength=nlist)
    max_cell = max(int(cell_sizes.max()), 1)
    cells = np.zeros((nlist, max_cell, d), dtype=np.float32)
    cell_ids = np.full((nlist, max_cell), -1, dtype=np.int64)
    Xh = np.asarray(X)
    fill = np.zeros(nlist, dtype=np.int64)
    for i in np.nonzero(valid)[0]:
        c = assign[i]
        cells[c, fill[c]] = Xh[i]
        cell_ids[c, fill[c]] = i
        fill[c] += 1
    out = {
        "centers": centers,
        "cells": cells,
        "cell_ids": cell_ids,
        "cell_sizes": cell_sizes.astype(np.int32),
    }
    if return_assign:
        out["assign"] = assign
    return out


def ivfpq_build(
    X: jax.Array,
    w: jax.Array,
    nlist: int,
    m_subvectors: int,
    n_bits: int,
    max_iter: int,
    seed: int,
) -> Dict[str, np.ndarray]:
    """IVF-PQ index: coarse kmeans cells + per-subspace product-quantization
    codebooks over the residuals (the cuVS ivf_pq equivalent, reference
    knn.py:1510-1524, re-expressed as dense kmeans + gathers).

    Returns centers (nlist,d), codebooks (m, 2^bits, d/m), codes (nlist, max_cell, m)
    uint8, cell_ids."""
    from .kmeans import kmeans_fit, kmeans_predict

    n, d = X.shape
    if d % m_subvectors != 0:
        raise ValueError(f"n features {d} not divisible by pq m={m_subvectors}")
    if not 1 <= n_bits <= 8:
        raise ValueError(f"n_bits must be in [1, 8] (uint8 codes), got {n_bits}")
    sub_d = d // m_subvectors
    n_codes = 2**n_bits
    flat = ivfflat_build(X, w, nlist, max_iter, seed, return_assign=True)
    coarse = flat["centers"]

    # residuals of real rows w.r.t. their coarse center (assignment reused from the
    # flat build — no second distance pass)
    assign = flat.pop("assign")
    Xh = np.asarray(X)
    valid = np.asarray(w) > 0
    resid = Xh - coarse[assign]

    codebooks = np.zeros((m_subvectors, n_codes, sub_d), np.float32)
    codes_flat = np.zeros((n, m_subvectors), np.uint8)
    rv = resid[valid]
    wv = jnp.ones((rv.shape[0],), jnp.float32)
    for m_i in range(m_subvectors):
        sub = rv[:, m_i * sub_d : (m_i + 1) * sub_d].astype(np.float32)
        k_eff = min(n_codes, sub.shape[0])
        fitted = kmeans_fit(
            jnp.asarray(sub), wv, k=k_eff, max_iter=max_iter, tol=1e-4,
            init="k-means||", init_steps=2, seed=seed + m_i,
        )
        cb = np.zeros((n_codes, sub_d), np.float32)
        cb[:k_eff] = fitted["cluster_centers"]
        if k_eff < n_codes:
            cb[k_eff:] = 1e18  # unused codes: unreachable
        codebooks[m_i] = cb
        all_sub = resid[:, m_i * sub_d : (m_i + 1) * sub_d].astype(np.float32)
        codes_flat[:, m_i] = np.asarray(
            kmeans_predict(jnp.asarray(all_sub), jnp.asarray(cb))
        ).astype(np.uint8)

    # lay codes out per cell, padded like the flat cells
    cell_ids = flat["cell_ids"]
    max_cell = cell_ids.shape[1]
    codes = np.zeros((nlist, max_cell, m_subvectors), np.uint8)
    pos = cell_ids >= 0
    codes[pos] = codes_flat[cell_ids[pos]]
    return {
        "centers": coarse,
        "codebooks": codebooks,
        "codes": codes,
        "cell_ids": cell_ids,
        "cell_sizes": flat["cell_sizes"],
        "cells": flat["cells"],  # kept for optional exact refine
    }


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "block"))
def ivfpq_search(
    Q: jax.Array,
    centers: jax.Array,  # (nlist, d)
    codebooks: jax.Array,  # (m, n_codes, sub_d)
    codes: jax.Array,  # (nlist, max_cell, m) uint8
    cell_ids: jax.Array,  # (nlist, max_cell)
    k: int,
    nprobe: int,
    block: int = 256,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Asymmetric-distance (ADC) probe search: per query, build the (m, n_codes)
    lookup table of residual-subvector distances to each probed cell's center, then
    score codes by LUT gathers. The LUT uses the ‖a‖²-2ab+‖b‖² expansion (no
    (…, n_codes, sub_d) broadcast intermediate) and queries run in blocks to bound
    HBM. Returns (approx euclidean distances, item ids, flat candidate positions)."""
    nlist, max_cell, m = codes.shape
    n_codes, sub_d = codebooks.shape[1], codebooks.shape[2]
    nq, d = Q.shape
    cb2 = jnp.sum(codebooks * codebooks, axis=-1)  # (m, n_codes)
    k_eff = min(k, nprobe * max_cell)

    def search_block(qb):
        bq = qb.shape[0]
        cd2 = _block_sq_dists(qb, centers)  # (bq, nlist)
        _, probe = jax.lax.top_k(-cd2, nprobe)  # (bq, nprobe)

        qres = qb[:, None, :] - centers[probe]  # (bq, nprobe, d)
        qsub = qres.reshape(bq, nprobe, m, sub_d)
        # LUT[bq, nprobe, m, n_codes] = ‖qsub‖² - 2·qsub·cb + ‖cb‖²
        cross = jnp.einsum("qpms,mcs->qpmc", qsub, codebooks, precision=FAST)
        q2 = jnp.sum(qsub * qsub, axis=-1)[..., None]
        lut = jnp.maximum(q2 - 2.0 * cross + cb2[None, None], 0.0)

        cell_codes = codes[probe].astype(jnp.int32)  # (bq, nprobe, max_cell, m)
        lut_t = jnp.swapaxes(lut, 2, 3)  # (bq, nprobe, n_codes, m)
        d2 = jnp.sum(
            jnp.take_along_axis(lut_t, cell_codes, axis=2), axis=-1
        )  # (bq, nprobe, max_cell)

        probed_ids = cell_ids[probe]
        flat_ids = probed_ids.reshape(bq, -1)
        flat_d2 = jnp.where(flat_ids >= 0, d2.reshape(bq, -1), jnp.inf)
        neg, pos = jax.lax.top_k(-flat_d2, k_eff)
        ids = jnp.take_along_axis(flat_ids, pos, axis=1)
        dists = jnp.sqrt(jnp.maximum(-neg, 0.0))
        probe_of_pos = jnp.take_along_axis(probe, pos // max_cell, axis=1)
        flat_pos = probe_of_pos * max_cell + pos % max_cell
        return jnp.where(ids >= 0, dists, jnp.inf), ids, flat_pos

    pad = (-nq) % block
    Qp = jnp.pad(Q, ((0, pad), (0, 0)))
    db, ib, pb = jax.lax.map(search_block, Qp.reshape(-1, block, d))
    return (
        db.reshape(-1, k_eff)[:nq],
        ib.reshape(-1, k_eff)[:nq],
        pb.reshape(-1, k_eff)[:nq],
    )


@functools.partial(jax.jit, static_argnames=("k",))
def pq_refine(
    Q: jax.Array,
    cells: jax.Array,  # (nlist, max_cell, d) raw item vectors
    cand_ids_flat: jax.Array,  # (nq, kc) positions into the flattened cell layout
    cand_item_ids: jax.Array,  # (nq, kc) item ids (-1 invalid)
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Exact re-ranking of the ADC candidates (the reference's ivf_pq refine step,
    knn.py:1642-1666): gather the raw vectors of the top candidates, recompute true
    euclidean distances, take the final top-k."""
    nq, kc = cand_item_ids.shape
    flat_items = cells.reshape(-1, cells.shape[-1])
    vecs = flat_items[jnp.maximum(cand_ids_flat, 0)]  # (nq, kc, d)
    d2 = jnp.sum((vecs - Q[:, None, :]) ** 2, axis=-1)
    d2 = jnp.where(cand_item_ids >= 0, d2, jnp.inf)
    k_eff = min(k, kc)
    neg, pos = jax.lax.top_k(-d2, k_eff)
    ids = jnp.take_along_axis(cand_item_ids, pos, axis=1)
    dists = jnp.sqrt(jnp.maximum(-neg, 0.0))
    return jnp.where(ids >= 0, dists, jnp.inf), ids


@functools.partial(jax.jit, static_argnames=("k", "nprobe"))
def ivfflat_search(
    Q: jax.Array,
    centers: jax.Array,
    cells: jax.Array,
    cell_ids: jax.Array,
    k: int,
    nprobe: int,
) -> Tuple[jax.Array, jax.Array]:
    """Probe the nprobe nearest cells per query; masked scan + top-k.
    Returns (euclidean distances, item ids), id -1 where fewer than k found."""
    nlist, max_cell, d = cells.shape

    cd2 = _block_sq_dists(Q, centers)  # (nq, nlist)
    _, probe = jax.lax.top_k(-cd2, nprobe)  # (nq, nprobe)

    probed_items = cells[probe]  # (nq, nprobe, max_cell, d)
    probed_ids = cell_ids[probe]  # (nq, nprobe, max_cell)
    nq = Q.shape[0]
    flat_items = probed_items.reshape(nq, nprobe * max_cell, d)
    flat_ids = probed_ids.reshape(nq, nprobe * max_cell)

    d2 = jnp.sum((flat_items - Q[:, None, :]) ** 2, axis=-1)
    d2 = jnp.where(flat_ids >= 0, d2, jnp.inf)
    k_eff = min(k, nprobe * max_cell)
    neg, pos = jax.lax.top_k(-d2, k_eff)
    ids = jnp.take_along_axis(flat_ids, pos, axis=1)
    dists = jnp.sqrt(jnp.maximum(-neg, 0.0))
    dists = jnp.where(ids >= 0, dists, jnp.inf)
    return dists, ids
