#
# k-nearest-neighbor kernels — the TPU-native replacement for
# cuml.neighbors.nearest_neighbors_mg.NearestNeighborsMG (reference knn.py:683-774:
# exact kNN with the query-block all-to-all over UCX endpoints and a distributed
# top-k merge inside cuML) and for the cuVS ANN indexes (reference knn.py:1510-1690).
#
# TPU formulation (P4 all-to-all, SURVEY.md §2.7):
#   * items live row-sharded across the mesh; each device scans ITS shard against the
#     (replicated or gathered) query block — an (nq, n_shard) distance matmul on the
#     MXU — and keeps a local top-k with GLOBAL item ids,
#   * one all_gather of the per-device top-k candidates over ICI (k·n_devices per
#     query — tiny next to the data) replaces cuML's UCX endpoint mesh,
#   * a final replicated top-k merge gives the global neighbors.
# Queries are processed in fixed-size blocks (lax.map) to bound the distance-matrix
# footprint in HBM.
#
# IVF-Flat: our own kmeans partitions the items into nlist cells, padded to a common
# cell size (static shapes); search probes the nprobe nearest cells with a masked
# distance scan — the cuVS ivf_flat equivalent re-expressed as dense gathers+matmuls.
#
# Selection plane: EVERY top-k below routes through ops/selection.py
# (exact_full | exact_tiled | approx behind `knn.selection`; merges stay
# exact). Invalid candidates mask to the large-finite INVALID_D2 sentinel, not
# inf (inf − inf in a downstream recomputation is a NaN factory); the -1-id /
# inf-distance OUTPUT contract of the search entry points is restored at the
# boundary from the id mask. Item norms (x2 = Σ X²) are hoisted out of the
# per-block scans: computed once per kernel invocation, or passed in
# precomputed (models cache them on the fitted model / built index).
#

from __future__ import annotations

import contextlib
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from ..utils.jax_compat import pvary, shard_map

from ._precision import FAST
from ..parallel.mesh import DATA_AXIS
from . import selection as _sel
from .selection import INVALID_D2, mask_invalid, merge_topk, select_topk
from ..observability.device import compiled_kernel


def _block_sq_dists(
    Q: jax.Array, X: jax.Array, x2: Optional[jax.Array] = None
) -> jax.Array:
    """(nq, n) squared euclidean distances (FAST precision: ranking tolerates bf16
    passes; exact distances are recomputed at parity precision only for the winners).
    `x2` is the precomputed item-norm term Σ X² — pass it to keep the norm out
    of a per-block scan (fit/build time caches it; kernels compute it once)."""
    q2 = jnp.sum(Q * Q, axis=1, keepdims=True)
    if x2 is None:
        x2 = jnp.sum(X * X, axis=1)
    d2 = q2 - 2.0 * jnp.matmul(Q, X.T, precision=FAST) + x2
    return jnp.maximum(d2, 0.0)


def _span_or_null(name: str, attrs, tracing: bool):
    """Host-side selection/re-rank spans; no-op inside a trace (a trace-time
    span would record compile-time, not search time)."""
    if tracing:
        return contextlib.nullcontext()
    from .. import observability as _obs

    return _obs.span(name, attrs)


def _count_x2(x2, site: str, tracing: bool) -> None:
    """Norm-hoist telemetry: did this search recompute the item-norm term or
    ride a cached one? (tests assert refit invalidation + zero per-block
    recomputation from these counters)"""
    if tracing:
        return
    from .. import observability as _obs

    _obs.counter_inc(
        "knn.x2_cached" if x2 is not None else "knn.x2_recompute", 1, site=site
    )


@compiled_kernel(
    "knn.exact_scan",
    static_argnames=("k", "block", "strategy", "tile", "recall_target"),
)
def _exact_knn_scan(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    x2: Optional[jax.Array],
    k: int,
    block: int,
    strategy: str,
    tile: int,
    recall_target: float,
) -> Tuple[jax.Array, jax.Array]:
    """Blocked exact-kNN scan: FAST-precision distances + the configured
    selection per query block. x2 is hoisted out of the per-block scan —
    computed once here when the caller holds no cache."""
    nq = Q.shape[0]
    if x2 is None:
        x2 = jnp.sum(X * X, axis=1)
    pad = (-nq) % block
    Qp = jnp.pad(Q, ((0, pad), (0, 0)))

    def scan_block(qb):
        d2 = _block_sq_dists(qb, X, x2)
        d2 = mask_invalid(d2, valid[None, :])
        return select_topk(
            d2, k, strategy=strategy, tile=tile, recall_target=recall_target
        )

    d2b, idxb = jax.lax.map(scan_block, Qp.reshape(-1, block, Q.shape[1]))
    return d2b.reshape(-1, k)[:nq], idxb.reshape(-1, k)[:nq]


@compiled_kernel("knn.parity_rerank_sq", static_argnames=("k",))
def parity_rerank_sq(
    Q: jax.Array, X: jax.Array, valid: jax.Array, cand_idx: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array]:
    """Parity-precision re-rank of a winner pool: gather the candidate
    vectors, recompute SQUARED distances exactly (full-f32 difference form —
    no bf16 passes, no expansion cancellation), exact top-k. The approx
    selection strategy pairs with this so returned distances stay exact while
    only the id set is approximate (recall >= knn.recall_target)."""
    vecs = X[cand_idx]  # (nq, kc, d)
    d2 = jnp.sum((vecs - Q[:, None, :]) ** 2, axis=-1)
    d2 = mask_invalid(d2, valid[cand_idx])
    return merge_topk(d2, cand_idx, k)


def exact_knn_single(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    k: int,
    block: int = 1024,
    *,
    x2: Optional[jax.Array] = None,
    strategy: Optional[str] = None,
    model_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Single-shard exact kNN: blocked scan, returns (distances², indices).

    Selection strategy comes from `knn.selection` (resolved HERE, outside the
    trace, so a config change can never be baked stale into a cached trace).
    Under `approx`, the scan selects a winner pool with approx_max_k and a
    parity-precision re-rank restores exact distances — the id set carries the
    recall target, the values don't. This is a FUSABLE site: `pallas_fused`
    (explicit, or `auto` on TPU past knn.pallas_min_items) runs the fused
    distance+select scan (ops/pallas_select.py) — bit-identical in f32 mode;
    under `knn.pallas_precision` bf16/int8 the fused pool re-ranks through
    the same parity_rerank_sq invariant as approx."""
    n = X.shape[0]
    k = min(int(k), n)
    strategy, tile, rt = _sel.resolve(n, k, strategy, fusable=True)
    tracing = _sel.is_tracing(Q, X, valid)
    if not tracing:
        _sel.record_selection(strategy, site="exact_knn", model=model_name)
    precision = q_block = item_tile = None
    if strategy == "pallas_fused":
        from . import pallas_select as _ps

        precision = _sel.resolve_fused_precision(None)
        kc = _ps.oversample_width(k, n, precision)
        q_block, item_tile = _ps.resolve_topk_geometry(
            int(Q.shape[0]), n, int(Q.shape[1]), kc
        )
    return _exact_knn_resolved(
        Q, X, valid, k, block, x2, strategy, tile, rt,
        precision=precision, q_block=q_block, item_tile=item_tile,
    )


def _exact_knn_resolved(
    Q: jax.Array,
    X: jax.Array,
    valid: jax.Array,
    k: int,
    block: int,
    x2: Optional[jax.Array],
    strategy: str,
    tile: int,
    rt: float,
    *,
    precision: Optional[str] = None,
    q_block: Optional[int] = None,
    item_tile: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """TRACE-PURE core of exact_knn_single: every knob — strategy, tile,
    recall target, fused precision, fused geometry — arrives concrete from a
    host-side resolution (exact_knn_single, or the shard_map factory
    `_knn_local_then_merge_fn`). No config read, no tuning-table read
    (tools/analysis purity/*): this is the form traced bodies may call."""
    n = X.shape[0]
    k = min(int(k), n)
    tracing = _sel.is_tracing(Q, X, valid)
    _count_x2(x2, "exact_knn", tracing)
    if strategy == "pallas_fused":
        from .pallas_select import fused_topk_pinned, oversample_width

        if precision == "float32":
            # exact mode: the fused scan IS the answer (bit-identical)
            with _span_or_null(
                "knn.select", {"strategy": strategy, "k": k}, tracing
            ):
                return fused_topk_pinned(
                    Q, X, valid, k, q_block=q_block, item_tile=item_tile,
                    x2=x2, precision=precision,
                )
        # approximate accumulation: oversampled pool + the §5b re-rank
        # invariant — returned distances stay exact-f32, ids carry the
        # approximation (the same contract as the approx strategy)
        kc = oversample_width(k, n, precision)
        with _span_or_null(
            "knn.select",
            {"strategy": strategy, "k": kc, "precision": precision},
            tracing,
        ):
            _, idx = fused_topk_pinned(
                Q, X, valid, kc, q_block=q_block, item_tile=item_tile,
                x2=x2, precision=precision,
            )
        with _span_or_null("knn.rerank", {"k": k}, tracing):
            if not tracing:
                from .. import observability as _obs

                _obs.counter_inc(
                    "knn.rerank_calls", 1, site="exact_knn",
                    precision=precision,
                )
            d2c, idc = parity_rerank_sq(Q, X, valid, idx, k)
            if kc == k:
                return d2c, idc
            # canonicalize through the k-shaped parity computation: the
            # oversampled-pool rerank runs at width kc, where XLA's reduce
            # vectorization can differ from the k-shaped program by 1 ulp.
            # Re-deriving the returned distances at width k makes the §5c
            # invariant exactly idempotent — returned (d2, ids) ARE
            # parity_rerank_sq(returned ids) bit-for-bit, the property the
            # tier-1 property tests assert
            return parity_rerank_sq(Q, X, valid, idc, k)
    if strategy == "approx":
        with _span_or_null("knn.select", {"strategy": strategy, "k": k}, tracing):
            _, idx = _exact_knn_scan(
                Q, X, valid, x2, k, block, strategy, tile, rt
            )
        with _span_or_null("knn.rerank", {"k": k}, tracing):
            if not tracing:
                from .. import observability as _obs

                _obs.counter_inc("knn.rerank_calls", 1, site="exact_knn")
            return parity_rerank_sq(Q, X, valid, idx, k)
    return _exact_knn_scan(Q, X, valid, x2, k, block, strategy, tile, rt)


def exact_knn_distributed(
    mesh: Mesh,
    Q: np.ndarray,
    X_sharded: jax.Array,
    valid_sharded: jax.Array,
    k: int,
    x2_sharded: Optional[jax.Array] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Distributed exact kNN over the mesh: local shard scans + all_gather top-k merge.

    Returns host (distances, global indices); distances are EUCLIDEAN (sqrt'd),
    matching the reference's returned distances (knn.py:783-802)."""
    n_total = X_sharded.shape[0]
    n_dev = mesh.devices.size
    shard_rows = n_total // n_dev
    k_eff = min(k, n_total)
    # a shard can hold fewer than k rows; the all-gathered candidate pool
    # (n_dev * k_local >= min(k_eff, n_total)) still covers the global top-k
    k_local = min(k_eff, shard_rows)
    # telemetry AND knob resolution fire HERE, on the host: the per-shard
    # scan runs inside the shard_map trace, where counters are suppressed and
    # config/tuning-table reads are banned (purity/* — a per-rank table read
    # could trace DIVERGENT programs across pod hosts). The factory receives
    # the fully resolved bundle. (fusable: the per-shard scan holds Q and its
    # X shard, so pallas_fused applies — one single-device pallas_call per
    # shard under shard_map)
    resolved = _sel.resolve(shard_rows, k_local, None, fusable=True)
    _sel.record_selection(resolved[0], site="exact_knn_distributed")
    _count_x2(x2_sharded, "exact_knn_distributed", False)

    merge = _knn_local_then_merge_fn(
        mesh, shard_rows, k_local, k_eff, with_x2=x2_sharded is not None,
        nq=int(np.asarray(Q).shape[0]), d=int(X_sharded.shape[1]),
        resolved=resolved,
    )
    if x2_sharded is not None:
        d2, gidx = merge(jnp.asarray(Q), X_sharded, valid_sharded, x2_sharded)
    else:
        d2, gidx = merge(jnp.asarray(Q), X_sharded, valid_sharded)
    return np.sqrt(np.asarray(d2)), np.asarray(gidx)


def _knn_local_then_merge_fn(
    mesh: Mesh, shard_rows: int, k_local: int, k_eff: int,
    with_x2: bool = False, *,
    nq: Optional[int] = None, d: Optional[int] = None,
    resolved: Optional[Tuple[str, int, float]] = None,
):
    """The shard-mapped local-topk + all_gather merge step, exposed so tests can
    lower it and assert the compiled collective structure (one gather batch, no
    quadratic exchange). The candidate MERGE stays exact (merge_topk). THIS
    factory is the host boundary for the shard body: strategy/tile/recall
    (`resolved`, else resolved here) and — for pallas_fused — precision and
    scan geometry all resolve BEFORE the trace, and the body calls the
    trace-pure _exact_knn_resolved (purity/*: a config or tuning-table read
    inside shard_map would bake per-host, tracing divergent programs across
    pod ranks)."""
    strategy, tile, rt = (
        resolved if resolved is not None
        else _sel.resolve(shard_rows, k_local, None, fusable=True)
    )
    precision = q_block = item_tile = None
    if strategy == "pallas_fused":
        from . import pallas_select as _ps

        precision = _sel.resolve_fused_precision(None)
        kc = _ps.oversample_width(k_local, shard_rows, precision)
        # nq/d default for legacy callers (tests lowering the factory with
        # exact strategies never reach here)
        q_block, item_tile = _ps.resolve_topk_geometry(
            nq if nq is not None else shard_rows,
            shard_rows, d if d is not None else 1, kc,
        )
    from ..parallel.partitioner import partitioner_for

    part = partitioner_for(mesh)
    in_specs = (part.state_spec(), part.data_spec(2), part.data_spec(1))
    if with_x2:
        in_specs = in_specs + (part.data_spec(1),)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=part.state_spec(),
        check_vma=False,  # post-all_gather results are replicated; size-1 aux axes
        # defeat the static replication checker
    )
    def _local_then_merge(q, x_local, valid_local, *maybe_x2):
        rank = jax.lax.axis_index(DATA_AXIS)
        x2_local = maybe_x2[0] if maybe_x2 else None
        d2, idx = _exact_knn_resolved(
            q, x_local, valid_local, k_local, 1024, x2_local,
            strategy, tile, rt,
            precision=precision, q_block=q_block, item_tile=item_tile,
        )
        gidx = idx + rank * shard_rows
        # all-to-all candidate exchange over ICI (the UCX replacement)
        d2_all = jax.lax.all_gather(d2, DATA_AXIS, axis=1)  # (nq, n_dev, k_local)
        gidx_all = jax.lax.all_gather(gidx, DATA_AXIS, axis=1)
        d2_all = d2_all.reshape(d2.shape[0], -1)
        gidx_all = gidx_all.reshape(d2.shape[0], -1)
        return merge_topk(d2_all, gidx_all, k_eff)

    return _local_then_merge


# ---------------------------------------------------------------------------
# IVF-Flat / IVF-PQ
# ---------------------------------------------------------------------------


def center_norms_sq(centers) -> np.ndarray:
    """Σ centers² computed ON DEVICE with the same reduce the probe kernels
    use, so a cached norm is bitwise the value the kernel would recompute.
    Cached on built IVF layouts (the norm-hoist satellite: built once per
    build, invalidated by construction on refit since every build emits a
    fresh dict)."""
    c = jnp.asarray(np.asarray(centers, dtype=np.float32))
    return np.asarray(jnp.sum(c * c, axis=1))


def ivfflat_build(
    X: jax.Array, w: jax.Array, nlist: int, max_iter: int, seed: int,
    return_assign: bool = False,
) -> Dict[str, np.ndarray]:
    """Partition items into nlist cells via our kmeans; lay cells out densely padded
    to the max cell size (static shapes for the probe scan)."""
    from .kmeans import kmeans_fit, kmeans_predict

    # ANN builds have no sample weights: w is purely the pad mask, so the
    # masked (weight-stream-free) Lloyd kernel is eligible under the mask opt-in
    fitted = kmeans_fit(
        X, w, k=nlist, max_iter=max_iter, tol=1e-4, init="k-means||",
        init_steps=2, seed=seed, unit_weight=True,
    )
    centers = fitted["cluster_centers"]
    assign = np.asarray(kmeans_predict(X, jnp.asarray(centers)))
    valid = np.asarray(w) > 0
    cells, cell_ids, cell_sizes = layout_cells(np.asarray(X), assign, nlist, valid)
    out = {
        "centers": centers,
        "center_norms": center_norms_sq(centers),
        "cells": cells,
        "cell_ids": cell_ids,
        "cell_sizes": cell_sizes,
    }
    if return_assign:
        out["assign"] = assign
    return out


def normalize_rows_or_raise(Xb: np.ndarray) -> np.ndarray:
    """Host-side row normalization for the cosine tier; zero-norm rows raise
    (Spark/cuML cosine semantics). THE single definition of the zero-row
    contract for host arrays — layout_cells and the streamed ANN builds
    (ops/ann_streaming.py) all route through it."""
    norms = np.linalg.norm(Xb, axis=1, keepdims=True)
    if len(norms) and float(norms.min()) <= 0.0:
        raise ValueError(
            "Cosine distance is not defined for zero-length vectors; the input "
            "contains an all-zero feature row."
        )
    return (Xb / np.maximum(norms, 1e-30)).astype(np.float32)


def layout_cells(
    Xh: np.ndarray,
    assign: np.ndarray,
    nlist: int,
    valid: "np.ndarray | None" = None,
    normalize: bool = False,
):
    """Dense (nlist, max_cell, d) cell layout with -1 id sentinels — shared by the
    in-core and streamed (ops/ann_streaming.py) IVF builds so the sentinel/offset
    conventions the probe scans depend on cannot diverge. Vectorized: stable-sort
    rows by cell, then each row's slot is its sorted position minus the cell's
    start offset (the former per-row Python loop was O(n) interpreted —
    disqualifying at 10M items). `normalize=True` writes unit rows (the cosine
    tier) into the gather temp that already exists — no extra dataset copy."""
    n, d = Xh.shape
    valid_idx = np.arange(n) if valid is None else np.nonzero(valid)[0]
    cell_sizes = np.bincount(assign[valid_idx], minlength=nlist)
    max_cell = max(int(cell_sizes.max()), 1)
    cells = np.zeros((nlist, max_cell, d), dtype=np.float32)
    cell_ids = np.full((nlist, max_cell), -1, dtype=np.int64)
    order = np.argsort(assign[valid_idx], kind="stable")
    sorted_rows = valid_idx[order]
    sorted_cells = assign[sorted_rows]
    within = np.arange(len(sorted_rows)) - np.repeat(
        np.concatenate([[0], np.cumsum(cell_sizes)[:-1]]), cell_sizes
    )
    gathered = Xh[sorted_rows]
    if normalize:
        gathered = normalize_rows_or_raise(gathered)
    elif gathered.dtype != np.float32:
        # cast inside the gather temp that already exists: callers hand Xh in
        # its source dtype (the streamed build no longer pre-converts the
        # whole dataset — that was a second full-dense host copy)
        gathered = gathered.astype(np.float32)
    cells[sorted_cells, within] = gathered
    cell_ids[sorted_cells, within] = sorted_rows
    return cells, cell_ids, cell_sizes.astype(np.int32)


def ivfpq_build(
    X: jax.Array,
    w: jax.Array,
    nlist: int,
    m_subvectors: int,
    n_bits: int,
    max_iter: int,
    seed: int,
) -> Dict[str, np.ndarray]:
    """IVF-PQ index: coarse kmeans cells + per-subspace product-quantization
    codebooks over the residuals (the cuVS ivf_pq equivalent, reference
    knn.py:1510-1524, re-expressed as dense kmeans + gathers).

    Returns centers (nlist,d), codebooks (m, 2^bits, d/m), codes (nlist, max_cell, m)
    uint8, cell_ids."""
    from .kmeans import kmeans_fit, kmeans_predict

    n, d = X.shape
    if d % m_subvectors != 0:
        raise ValueError(f"n features {d} not divisible by pq m={m_subvectors}")
    if not 1 <= n_bits <= 8:
        raise ValueError(f"n_bits must be in [1, 8] (uint8 codes), got {n_bits}")
    sub_d = d // m_subvectors
    n_codes = 2**n_bits
    flat = ivfflat_build(X, w, nlist, max_iter, seed, return_assign=True)
    coarse = flat["centers"]

    # residuals of real rows w.r.t. their coarse center (assignment reused from the
    # flat build — no second distance pass)
    assign = flat.pop("assign")
    Xh = np.asarray(X)
    valid = np.asarray(w) > 0
    resid = Xh - coarse[assign]

    codebooks = np.zeros((m_subvectors, n_codes, sub_d), np.float32)
    codes_flat = np.zeros((n, m_subvectors), np.uint8)
    rv = resid[valid]
    wv = jnp.ones((rv.shape[0],), jnp.float32)
    for m_i in range(m_subvectors):
        sub = rv[:, m_i * sub_d : (m_i + 1) * sub_d].astype(np.float32)  # noqa: fence/host-staging-copy
        k_eff = min(n_codes, sub.shape[0])
        fitted = kmeans_fit(
            jnp.asarray(sub), wv, k=k_eff, max_iter=max_iter, tol=1e-4,
            init="k-means||", init_steps=2, seed=seed + m_i, unit_weight=True,
        )
        cb = np.zeros((n_codes, sub_d), np.float32)
        cb[:k_eff] = fitted["cluster_centers"]
        if k_eff < n_codes:
            cb[k_eff:] = 1e18  # unused codes: unreachable
        codebooks[m_i] = cb
        all_sub = resid[:, m_i * sub_d : (m_i + 1) * sub_d].astype(np.float32)  # noqa: fence/host-staging-copy
        codes_flat[:, m_i] = np.asarray(
            kmeans_predict(jnp.asarray(all_sub), jnp.asarray(cb))
        ).astype(np.uint8)

    # lay codes out per cell, padded like the flat cells
    cell_ids = flat["cell_ids"]
    max_cell = cell_ids.shape[1]
    codes = np.zeros((nlist, max_cell, m_subvectors), np.uint8)
    pos = cell_ids >= 0
    codes[pos] = codes_flat[cell_ids[pos]]
    return {
        "centers": coarse,
        "center_norms": flat["center_norms"],
        "codebooks": codebooks,
        "codes": codes,
        "cell_ids": cell_ids,
        "cell_sizes": flat["cell_sizes"],
        "cells": flat["cells"],  # kept for optional exact refine
    }


@compiled_kernel(
    "knn.ivfpq_search",
    static_argnames=("k", "nprobe", "block", "strategy", "tile", "recall_target"),
)
def _ivfpq_search_impl(
    Q: jax.Array,
    centers: jax.Array,
    codebooks: jax.Array,
    codes: jax.Array,
    cell_ids: jax.Array,
    center_norms: Optional[jax.Array],
    k: int,
    nprobe: int,
    block: int,
    strategy: str,
    tile: int,
    recall_target: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    nlist, max_cell, m = codes.shape
    n_codes, sub_d = codebooks.shape[1], codebooks.shape[2]
    nq, d = Q.shape
    cb2 = jnp.sum(codebooks * codebooks, axis=-1)  # (m, n_codes)
    k_eff = min(k, nprobe * max_cell)

    def search_block(qb):
        bq = qb.shape[0]
        cd2 = _block_sq_dists(qb, centers, center_norms)  # (bq, nlist)
        _, probe = select_topk(cd2, nprobe, strategy="exact_full")  # (bq, nprobe)

        qres = qb[:, None, :] - centers[probe]  # (bq, nprobe, d)
        qsub = qres.reshape(bq, nprobe, m, sub_d)
        # LUT[bq, nprobe, m, n_codes] = ‖qsub‖² - 2·qsub·cb + ‖cb‖²
        cross = jnp.einsum("qpms,mcs->qpmc", qsub, codebooks, precision=FAST)
        q2 = jnp.sum(qsub * qsub, axis=-1)[..., None]
        lut = jnp.maximum(q2 - 2.0 * cross + cb2[None, None], 0.0)

        cell_codes = codes[probe].astype(jnp.int32)  # (bq, nprobe, max_cell, m)  # noqa: fence/host-staging-copy
        lut_t = jnp.swapaxes(lut, 2, 3)  # (bq, nprobe, n_codes, m)
        d2 = jnp.sum(
            jnp.take_along_axis(lut_t, cell_codes, axis=2), axis=-1
        )  # (bq, nprobe, max_cell)

        probed_ids = cell_ids[probe]
        flat_ids = probed_ids.reshape(bq, -1)
        flat_d2 = mask_invalid(d2.reshape(bq, -1), flat_ids >= 0)
        d2_sel, pos = select_topk(
            flat_d2, k_eff, strategy=strategy, tile=tile,
            recall_target=recall_target,
        )
        ids = jnp.take_along_axis(flat_ids, pos, axis=1)
        dists = jnp.sqrt(d2_sel)
        probe_of_pos = jnp.take_along_axis(probe, pos // max_cell, axis=1)
        flat_pos = probe_of_pos * max_cell + pos % max_cell
        return jnp.where(ids >= 0, dists, jnp.inf), ids, flat_pos

    pad = (-nq) % block
    Qp = jnp.pad(Q, ((0, pad), (0, 0)))
    db, ib, pb = jax.lax.map(search_block, Qp.reshape(-1, block, d))
    return (
        db.reshape(-1, k_eff)[:nq],
        ib.reshape(-1, k_eff)[:nq],
        pb.reshape(-1, k_eff)[:nq],
    )


def ivfpq_search(
    Q: jax.Array,
    centers: jax.Array,  # (nlist, d)
    codebooks: jax.Array,  # (m, n_codes, sub_d)
    codes: jax.Array,  # (nlist, max_cell, m) uint8
    cell_ids: jax.Array,  # (nlist, max_cell)
    k: int,
    nprobe: int,
    block: int = 256,
    *,
    center_norms: Optional[jax.Array] = None,
    strategy: Optional[str] = None,
    model_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Asymmetric-distance (ADC) probe search: per query, build the (m, n_codes)
    lookup table of residual-subvector distances to each probed cell's center, then
    score codes by LUT gathers. The LUT uses the ‖a‖²-2ab+‖b‖² expansion (no
    (…, n_codes, sub_d) broadcast intermediate) and queries run in blocks to bound
    HBM. The candidate select (width nprobe·max_cell) takes the configured
    selection strategy; distances are ADC approximations either way, so the
    exact refine (pq_refine) remains the accuracy stage.
    Returns (approx euclidean distances, item ids, flat candidate positions)."""
    max_cell = codes.shape[1]
    k_eff = min(k, nprobe * max_cell)
    strategy, tile, rt = _sel.resolve(nprobe * max_cell, k_eff, strategy)
    if not _sel.is_tracing(Q, centers, codes):
        _sel.record_selection(strategy, site="ivfpq_search", model=model_name)
        _count_x2(center_norms, "ivfpq_search", False)
    return _ivfpq_search_impl(
        Q, centers, codebooks, codes, cell_ids, center_norms,
        k, nprobe, block, strategy, tile, rt,
    )


@compiled_kernel("knn.pq_refine", static_argnames=("k",))
def pq_refine(
    Q: jax.Array,
    cells: jax.Array,  # (nlist, max_cell, d) raw item vectors
    cand_ids_flat: jax.Array,  # (nq, kc) positions into the flattened cell layout
    cand_item_ids: jax.Array,  # (nq, kc) item ids (-1 invalid)
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Exact re-ranking of the ADC candidates (the reference's ivf_pq refine step,
    knn.py:1642-1666): gather the raw vectors of the top candidates, recompute true
    euclidean distances, take the final top-k (always exact — this IS the
    re-rank stage)."""
    nq, kc = cand_item_ids.shape
    flat_items = cells.reshape(-1, cells.shape[-1])
    vecs = flat_items[jnp.maximum(cand_ids_flat, 0)]  # (nq, kc, d)
    d2 = jnp.sum((vecs - Q[:, None, :]) ** 2, axis=-1)
    d2 = mask_invalid(d2, cand_item_ids >= 0)
    k_eff = min(k, kc)
    d2_sel, ids = merge_topk(d2, cand_item_ids, k_eff)
    dists = jnp.sqrt(d2_sel)
    return jnp.where(ids >= 0, dists, jnp.inf), ids


@compiled_kernel(
    "knn.ivfflat_search",
    static_argnames=("k", "nprobe", "block", "strategy", "tile", "recall_target"),
)
def _ivfflat_search_impl(
    Q: jax.Array,
    centers: jax.Array,
    cells: jax.Array,
    cell_ids: jax.Array,
    center_norms: Optional[jax.Array],
    k: int,
    nprobe: int,
    block: int,
    strategy: str,
    tile: int,
    recall_target: float,
) -> Tuple[jax.Array, jax.Array]:
    nlist, max_cell, d = cells.shape
    nq = Q.shape[0]
    k_eff = min(k, nprobe * max_cell)

    def search_block(qb):
        bq = qb.shape[0]
        cd2 = _block_sq_dists(qb, centers, center_norms)  # (bq, nlist)
        _, probe = select_topk(cd2, nprobe, strategy="exact_full")  # (bq, nprobe)
        probed_items = cells[probe]  # (bq, nprobe, max_cell, d)
        probed_ids = cell_ids[probe]
        flat_items = probed_items.reshape(bq, nprobe * max_cell, d)
        flat_ids = probed_ids.reshape(bq, nprobe * max_cell)
        d2 = jnp.sum((flat_items - qb[:, None, :]) ** 2, axis=-1)
        d2 = mask_invalid(d2, flat_ids >= 0)
        d2_sel, pos = select_topk(
            d2, k_eff, strategy=strategy, tile=tile, recall_target=recall_target
        )
        ids = jnp.take_along_axis(flat_ids, pos, axis=1)
        dists = jnp.sqrt(d2_sel)
        return jnp.where(ids >= 0, dists, jnp.inf), ids

    pad = (-nq) % block
    Qp = jnp.pad(Q, ((0, pad), (0, 0)))
    db, ib = jax.lax.map(search_block, Qp.reshape(-1, block, d))
    return db.reshape(-1, k_eff)[:nq], ib.reshape(-1, k_eff)[:nq]


def ivfflat_search(
    Q: jax.Array,
    centers: jax.Array,
    cells: jax.Array,
    cell_ids: jax.Array,
    k: int,
    nprobe: int,
    block: int = 64,
    *,
    center_norms: Optional[jax.Array] = None,
    strategy: Optional[str] = None,
    model_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Probe the nprobe nearest cells per query; masked scan + configured
    selection over the nprobe·max_cell candidate width (the cell scan keeps
    the exact f32 difference-form distances, so approx here only approximates
    the id set, never the returned values). Queries run in fixed-size blocks
    (lax.map) so the probed-cell gather is (block, nprobe, max_cell, d).
    Returns (euclidean distances, item ids), id -1 where fewer than k found."""
    max_cell = cells.shape[1]
    k_eff = min(k, nprobe * max_cell)
    strategy, tile, rt = _sel.resolve(nprobe * max_cell, k_eff, strategy)
    if not _sel.is_tracing(Q, centers, cells):
        _sel.record_selection(strategy, site="ivfflat_search", model=model_name)
        _count_x2(center_norms, "ivfflat_search", False)
    return _ivfflat_search_impl(
        Q, centers, cells, cell_ids, center_norms,
        k, nprobe, block, strategy, tile, rt,
    )


# ---------------------------------------------------------------------------
# CAGRA-class graph index (the cuVS cagra equivalent, reference knn.py:1513-1524)
# ---------------------------------------------------------------------------
#
# Build: a fixed-degree kNN graph — exact for small item sets, IVF-Flat-assisted for
# large ones (cuVS builds its graph from an IVF-PQ/NN-descent pass the same way).
# Search: greedy beam traversal re-expressed with static shapes for XLA: a fixed-size
# candidate pool per query; each iteration expands the best unvisited node, gathers
# its fixed-degree adjacency row, scores the neighbors (gather + fused distance), and
# re-top-ks the pool. Duplicate ids are neutralized by a sort-adjacent-compare pass
# (they get distance=INVALID_D2 + visited=True so they neither rank nor re-expand).
# All iterations are a lax.fori_loop over purely dense ops — no dynamic frontier.


def cagra_build(
    X: jax.Array,
    w: jax.Array,
    graph_degree: int = 32,
    nlist: int = 0,
    seed: int = 42,
    exact_threshold: int = 32768,
) -> Dict[str, np.ndarray]:
    """Build the fixed-degree neighbor graph. Returns {"items", "graph",
    "item_norms_sq"} over the COMPACTED valid rows (padding rows are dropped so
    graph node ids align 1:1 with the caller's item row positions). The cached
    item norms feed cagra_search so queries never recompute Σ items²."""
    valid = np.asarray(w) > 0
    Xv = np.asarray(X)[valid].astype(np.float32)  # noqa: fence/host-staging-copy
    n_real = Xv.shape[0]
    deg = min(graph_degree, max(n_real - 1, 1))
    Xj = jnp.asarray(Xv)
    ones = jnp.ones((n_real,), jnp.float32)

    if n_real <= exact_threshold:
        _, idx = exact_knn_single(Xj, Xj, jnp.ones((n_real,), bool), deg + 1)
        idx = np.asarray(idx)
    else:
        if nlist <= 0:
            nlist = max(int(np.sqrt(n_real)), 8)
        index = ivfflat_build(Xj, ones, nlist=nlist, max_iter=10, seed=seed)
        _, idx = ivfflat_search(
            Xj,
            jnp.asarray(index["centers"]),
            jnp.asarray(index["cells"]),
            jnp.asarray(index["cell_ids"]),
            k=deg + 1,
            nprobe=max(2, nlist // 8),
            center_norms=jnp.asarray(index["center_norms"]),
        )
        idx = np.asarray(idx)

    # drop self-edges (usually slot 0); compact each row back to `deg` entries
    rows = np.arange(n_real)[:, None]
    not_self = idx != rows
    # stable partition: self (or any overflow) pushed to the end, then cut
    order = np.argsort(~not_self, axis=1, kind="stable")
    graph = np.take_along_axis(idx, order, axis=1)[:, :deg].astype(np.int32)  # noqa: fence/host-staging-copy
    graph = np.maximum(graph, 0)  # any -1 from an undersized IVF probe -> node 0
    graph = _optimize_graph_reverse_edges(Xv, graph, deg)
    return {"items": Xv, "graph": graph, "item_norms_sq": center_norms_sq(Xv)}


def _optimize_graph_reverse_edges(
    Xv: np.ndarray, graph: np.ndarray, deg: int
) -> np.ndarray:
    """Graph optimization (the role of cuVS cagra's optimize step): augment the
    forward kNN edges with REVERSE edges, then keep each node's `deg` closest
    distinct neighbors. Reverse edges give low-in-degree nodes entry points the
    greedy beam can actually reach — pure-forward kNN graphs strand hub-adjacent
    points. Fully vectorized: one lexsort over the doubled edge list."""
    n = Xv.shape[0]
    heads = np.repeat(np.arange(n, dtype=np.int64), graph.shape[1])
    tails = graph.reshape(-1).astype(np.int64)
    d = np.linalg.norm(Xv[heads] - Xv[tails], axis=1)
    all_h = np.concatenate([heads, tails])
    all_t = np.concatenate([tails, heads])
    all_d = np.concatenate([d, d])
    keep = all_h != all_t
    all_h, all_t, all_d = all_h[keep], all_t[keep], all_d[keep]

    # dedupe (h, t) pairs keeping the min distance, then rank per head by distance
    key = all_h * n + all_t
    o = np.lexsort((all_d, key))
    key_s = key[o]
    first = np.concatenate([[True], key_s[1:] != key_s[:-1]])
    h2, t2, d2 = all_h[o][first], all_t[o][first], all_d[o][first]
    o2 = np.lexsort((d2, h2))
    h3, t3 = h2[o2], t2[o2]
    counts = np.bincount(h3, minlength=n)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    within = np.arange(len(h3)) - np.repeat(starts, counts)
    sel = within < deg
    out = graph.copy()  # nodes with < deg merged edges keep their forward fill
    out[h3[sel], within[sel]] = t3[sel].astype(np.int32)  # noqa: fence/host-staging-copy
    return out


@compiled_kernel(
    "knn.cagra_search",
    static_argnames=(
        "k", "itopk", "iterations", "search_width", "strategy", "tile",
        "recall_target",
    ),
)
def _cagra_search_impl(
    Q: jax.Array,
    items: jax.Array,
    graph: jax.Array,
    x2: Optional[jax.Array],
    k: int,
    itopk: int,
    iterations: int,
    search_width: int,
    strategy: str,
    tile: int,
    recall_target: float,
) -> Tuple[jax.Array, jax.Array]:
    n, d = items.shape
    deg = graph.shape[1]
    nq = Q.shape[0]
    itopk_eff = min(itopk, n)
    if x2 is None:
        x2 = jnp.sum(items * items, axis=1)

    def dists_to(ids):  # ids (nq, m) -> squared distances (nq, m)
        vecs = items[ids]  # gather
        cross = jnp.einsum("qmd,qd->qm", vecs, Q, precision=FAST)
        q2 = jnp.sum(Q * Q, axis=1, keepdims=True)
        return jnp.maximum(q2 - 2.0 * cross + x2[ids], 0.0)

    # entry points: an even stride over the items (randomization-free, shape-static)
    ids0 = jnp.linspace(0, n - 1, itopk_eff).astype(jnp.int32)
    ids0 = jnp.broadcast_to(ids0, (nq, itopk_eff))
    d20 = dists_to(ids0)
    visited0 = jnp.zeros((nq, itopk_eff), bool)

    width = max(1, min(search_width, itopk_eff))

    def body(_, state):
        ids, d2, visited = state
        # expand the `width` best unvisited pool entries (exact select: the
        # pool is the loop-carried state — an approximate pick here compounds
        # per iteration, which no recall target bounds)
        expand_key = mask_invalid(d2, ~visited)
        _, best = select_topk(expand_key, width, strategy="exact_full")
        visited = visited | (
            jnp.sum(jax.nn.one_hot(best, itopk_eff, dtype=jnp.int32), axis=1) > 0
        )
        best_ids = jnp.take_along_axis(ids, best, axis=1)  # (nq, width)
        nbrs = graph[best_ids].reshape(nq, width * deg)
        nd2 = dists_to(nbrs)

        all_ids = jnp.concatenate([ids, nbrs], axis=1)
        all_d2 = jnp.concatenate([d2, nd2], axis=1)
        all_vis = jnp.concatenate(
            [visited, jnp.zeros((nq, width * deg), bool)], axis=1
        )

        # duplicate suppression: sort by id; any entry equal to its left neighbor is
        # a duplicate -> INVALID_D2 (never ranks) + visited (never re-expands).
        # Stable sort keeps the pool's copy (with its visited flag) first.
        order = jnp.argsort(all_ids, axis=1, stable=True)
        sid = jnp.take_along_axis(all_ids, order, axis=1)
        sd2 = jnp.take_along_axis(all_d2, order, axis=1)
        svis = jnp.take_along_axis(all_vis, order, axis=1)
        dup = jnp.concatenate(
            [jnp.zeros((nq, 1), bool), sid[:, 1:] == sid[:, :-1]], axis=1
        )
        sd2 = jnp.where(dup, INVALID_D2, sd2)
        svis = svis | dup

        new_d2, pos = select_topk(sd2, itopk_eff, strategy="exact_full")
        new_ids = jnp.take_along_axis(sid, pos, axis=1)
        new_vis = jnp.take_along_axis(svis, pos, axis=1)
        return new_ids, new_d2, new_vis

    ids, d2, _ = jax.lax.fori_loop(0, iterations, body, (ids0, d20, visited0))
    k_eff = min(k, itopk_eff)
    d2_sel, pos = select_topk(
        d2, k_eff, strategy=strategy, tile=tile, recall_target=recall_target
    )
    out_ids = jnp.take_along_axis(ids, pos, axis=1)
    return jnp.sqrt(d2_sel), out_ids


def cagra_search(
    Q: jax.Array,
    items: jax.Array,  # (n, d)
    graph: jax.Array,  # (n, deg) int32
    k: int,
    itopk: int = 64,
    iterations: int = 32,
    search_width: int = 1,
    *,
    x2: Optional[jax.Array] = None,
    strategy: Optional[str] = None,
    model_name: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Greedy beam search over the neighbor graph. `search_width` (cuVS param of
    the same name) expands the W best unvisited pool entries per iteration — the
    gathers batch W*deg neighbors, so width converts iteration latency into MXU/
    gather throughput at equal total expansions. The in-loop pool maintenance
    selects exactly (loop-carried state); the configured strategy applies to
    the final k-of-itopk select. Cached `x2` (built index item norms) keeps
    Σ items² out of the per-search recompute.

    Returns (euclidean distances, item ids), shapes (nq, min(k, itopk))."""
    itopk_eff = min(itopk, items.shape[0])
    k_eff = min(k, itopk_eff)
    strategy, tile, rt = _sel.resolve(itopk_eff, k_eff, strategy)
    if not _sel.is_tracing(Q, items, graph):
        _sel.record_selection(strategy, site="cagra_search", model=model_name)
        _count_x2(x2, "cagra_search", False)
    return _cagra_search_impl(
        Q, items, graph, x2, k, itopk, iterations, search_width,
        strategy, tile, rt,
    )


def exact_knn_ring(
    mesh: Mesh,
    Q_sharded: jax.Array,  # (nq_padded, d) row-sharded queries
    X_sharded: jax.Array,  # (n_padded, d) row-sharded items
    valid_sharded: jax.Array,  # (n_padded,) bool
    k: int,
    x2_sharded: Optional[jax.Array] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Ring-allreduce exact kNN: BOTH queries and items stay sharded. Each device
    keeps its query block resident and the item shards rotate around the ring via
    ppermute; a running top-k merges after every hop. Peak per-device memory is
    one query block x one item shard — unlike the all_gather merge
    (exact_knn_distributed), nothing global ever materializes, so this is the path
    for query sets too large to replicate (the structural analog of cuML NN-MG's
    UCX block exchange, reference knn.py:763-774, laid onto the ICI ring).

    The item-norm term rotates WITH the shard (computed once pre-loop when no
    cache is passed), so no hop recomputes it; per-hop candidate selection
    takes the configured strategy (with a per-hop parity re-rank under approx
    — the shard is resident, so exactness costs one small gather), and the
    running merge stays exact.

    Returns host (distances, global item indices) for the real (unpadded) rows."""
    n_total = X_sharded.shape[0]
    n_dev = mesh.devices.size
    shard_rows = n_total // n_dev
    k_eff = min(k, n_total)
    # a shard may hold fewer than k rows; per-hop candidates are capped at the
    # shard size and the running pool still converges to the global top-k
    k_hop = min(k_eff, shard_rows)
    strategy, tile, rt = _sel.resolve(shard_rows, k_hop, None)
    _sel.record_selection(strategy, site="exact_knn_ring")
    _count_x2(x2_sharded, "exact_knn_ring", False)

    from ..parallel.partitioner import partitioner_for

    part = partitioner_for(mesh)
    in_specs = (part.data_spec(2), part.data_spec(2), part.data_spec(1))
    if x2_sharded is not None:
        in_specs = in_specs + (part.data_spec(1),)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(part.data_spec(2), part.data_spec(2)),
    )
    def _ring(q_local, x_local, valid_local, *maybe_x2):
        rank = jax.lax.axis_index(DATA_AXIS)
        nq_local = q_local.shape[0]
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        # the norm term is computed ONCE (or passed in cached) and rotates
        # with its shard — no hop recomputes Σ x²
        x2_local = (
            maybe_x2[0] if maybe_x2 else jnp.sum(x_local * x_local, axis=1)
        )

        def hop(h, state):
            x_cur, valid_cur, x2_cur, best_d2, best_idx = state
            # owner rank of the shard currently held: it started at `rank` and has
            # moved h hops along the ring
            owner = (rank - h) % n_dev
            d2 = _block_sq_dists(q_local, x_cur, x2_cur)
            d2 = mask_invalid(d2, valid_cur[None, :])
            hop_d2, idx = select_topk(
                d2, k_hop, strategy=strategy, tile=tile, recall_target=rt
            )
            if strategy == "approx":
                # the shard is resident: restore exact distances for the
                # approx winner pool before it enters the running merge
                hop_d2, idx = parity_rerank_sq(
                    q_local, x_cur, valid_cur, idx, k_hop
                )
            gidx = idx + owner * shard_rows
            # merge the hop's candidates into the running top-k (always exact)
            cat_d2 = jnp.concatenate([best_d2, hop_d2], axis=1)
            cat_idx = jnp.concatenate([best_idx, gidx], axis=1)
            best_d2, best_idx = merge_topk(cat_d2, cat_idx, k_eff)
            # rotate the item shard one hop along the ring
            x_next = jax.lax.ppermute(x_cur, DATA_AXIS, perm)
            valid_next = jax.lax.ppermute(valid_cur, DATA_AXIS, perm)
            x2_next = jax.lax.ppermute(x2_cur, DATA_AXIS, perm)
            return x_next, valid_next, x2_next, best_d2, best_idx

        # the running top-k derives from axis_index (varying over the mesh axis);
        # mark the literal init values varying too so the loop carry types agree
        init = (
            x_local,
            valid_local,
            x2_local,
            pvary(
                jnp.full((nq_local, k_eff), INVALID_D2, q_local.dtype),
                (DATA_AXIS,),
            ),
            pvary(jnp.full((nq_local, k_eff), -1, jnp.int32), (DATA_AXIS,)),
        )
        _, _, _, best_d2, best_idx = jax.lax.fori_loop(0, n_dev, hop, init)
        return best_d2, best_idx

    if x2_sharded is not None:
        d2, gidx = _ring(Q_sharded, X_sharded, valid_sharded, x2_sharded)
    else:
        d2, gidx = _ring(Q_sharded, X_sharded, valid_sharded)
    return np.sqrt(np.maximum(np.asarray(d2), 0.0)), np.asarray(gidx)
