#
# Out-of-core fitting: streamed sufficient-statistics accumulation.
#
# The reference fits datasets larger than device memory through RMM UVM/SAM managed
# memory (reference utils.py:184-241, SURVEY.md §2.5 last row). TPUs have no UVM;
# the TPU-native answer (SURVEY.md §7 "hard parts") is to stream host batches through
# the device and ACCUMULATE the model-sufficient statistics on device:
#   * PCA / LinearRegression: (XᵀWX, XᵀWy, Σwx, Σwy, Σw) accumulate exactly —
#     the fit result is IDENTICAL to the in-core path, with device residency bounded
#     by two batches (double-buffered prefetch) + the d×d stats,
#   * KMeans: per-pass Lloyd over batches (minibatch-free exact variant: each
#     iteration streams all batches, accumulating one-hotᵀX sums and counts).
# Estimators switch to this path automatically when the padded design matrix would
# exceed `config` threshold SRML_TPU_STREAM_THRESHOLD_BYTES (see core/estimator.py).
#

from __future__ import annotations

from collections import deque
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import (
    convergence as obs_convergence,
    progress as obs_progress,
    span as obs_span,
)
from ..observability.device import compiled_kernel, profile_pass
from ..reliability import (
    StreamBatchError,
    fault_point,
    is_device_error,
    is_transient,
    resumable_accumulate,
)
from ._precision import pdot
from .ingest import StagingPool, stage_block


# ----------------------------------------------------------------- fused chains
#
# A "chain" is the featurize prefix of a fused featurize->fit pipeline
# (pipeline.py::_try_fused_fit, docs/design.md §6k): a tuple of host-side
# ("scale", mean, std) / ("project", components) ops applied ON DEVICE inside
# every accumulator kernel — after the in-program ingest cast, before any
# statistic — so the intermediate (scaled / projected X) exists only inside
# the compiled program: it never round-trips to host and never materializes a
# second HBM copy. The expressions are EXACTLY the staged transforms'
# (StandardScalerModel: (X - mean) / std; PCAModel: pdot(X, components.T));
# bit-parity with the staged path is the contract the fuser ships under.


def chain_out_dim(d: int, chain_ops) -> int:
    """Feature width after the chain (a projection rewrites it to its
    component count; scaling preserves it)."""
    for op in chain_ops or ():
        if op[0] == "project":
            d = int(np.asarray(op[1]).shape[0])
    return d


def _prep_chain(chain_ops, dt):
    """Split host chain ops into the (static kinds, device operand arrays)
    pair the accumulator kernels take. Operands are staged once per fit in
    compute dtype — the staged transforms' own operand dtype."""
    if not chain_ops:
        return (), ()
    kinds = []
    arrays = []
    for op in chain_ops:
        kinds.append(str(op[0]))
        arrays.extend(jnp.asarray(np.asarray(a, dtype=dt)) for a in op[1:])
    return tuple(kinds), tuple(arrays)


def _apply_chain(X, dt, chain, chain_arrays):
    """The FIRST fused step of every accumulator kernel: the in-program
    ingest cast (identity when the batch already arrived in compute dtype)
    followed by the featurize chain."""
    X = X.astype(dt)
    i = 0
    for kind in chain:
        if kind == "scale":
            mean, std = chain_arrays[i], chain_arrays[i + 1]
            i += 2
            X = (X - mean) / std
        elif kind == "project":
            comps = chain_arrays[i]
            i += 1
            X = pdot(X, comps.T)
        else:
            raise ValueError(f"unknown chain op '{kind}'")
    return X


def _prefetch(iterable, depth: int = 1, site: Optional[str] = None, start_batch: int = 0):
    """Double-buffered batch pipeline: keep `depth` extra batches in flight so the
    host slice/pad/device_put of batch i+1 overlaps the device accumulation of
    batch i (jax dispatch is async; the DMA rides a separate engine on TPU). This
    is the streamed-ingest overlap the reference gets implicitly from UVM
    prefetching. Peak device residency is depth+1 batches — depth=1 is true
    double buffering (the out-of-core batch-size guidance assumes 2 live
    batches; a larger depth trades HBM for pipeline slack).

    Exception transparency: with a `site`, a failure the reliability ladder
    handles (transient host/I-O errors, device errors) raised while REFILLING
    the buffer is wrapped in a StreamBatchError carrying the batch ordinal
    (offset by `start_batch` on resumed streams), so the checkpoint-resume layer
    (reliability/checkpoint.py) sees where the pipeline broke instead of a bare
    mid-pipeline exception. Param/programming errors (ValueError-class) keep
    their original type — they are API surface, not pipeline weather."""
    it = iter(iterable)
    buf: deque = deque()
    pulled = start_batch

    def _refill() -> bool:
        nonlocal pulled
        try:
            buf.append(next(it))
        except StopIteration:
            return False
        except StreamBatchError:
            raise  # already carries its site/batch context
        except Exception as e:
            if site is None or not (is_transient(e) or is_device_error(e)):
                raise
            raise StreamBatchError(site, pulled, e) from e
        pulled += 1
        return True

    for _ in range(depth):
        if not _refill():
            break
    while buf:
        yield buf.popleft()
        _refill()


def _batch_stream(n: int, batch_rows: int, mesh, slicer, start_row: int = 0,
                  site: str = "ingest", cache=None, cache_key=None):
    """THE out-of-core ingest loop, shared by every streamed fit: `slicer(s, e)`
    returns row-aligned HOST arrays — X first, the weight vector LAST — for rows
    [s, e); this pads to the mesh (zero-weighting pad rows), shards, and yields
    device tuples. The ragged tail keeps its natural size: it compiles one extra
    accumulator entry ONCE and reuses it every pass (padding it to batch_rows
    instead was measured to upload a nearly-all-zeros full batch per pass when
    n % batch_rows is small). `start_row` (a batch boundary) re-opens the stream
    mid-pass for checkpoint-resume; `site` names the fault-injection point
    (reliability/faults.py) planted before each batch is sliced.

    With a `cache` (ops/device_cache.py) + `cache_key`, batches already HBM-
    resident replay without touching the host; fresh batches are retained after
    upload, budget permitting. The fault point fires BEFORE the cache lookup so
    replayed batches stay fault-injectable, and every actual upload is counted
    (`stream.upload_batches`/`stream.upload_bytes`) and timed
    (`stream.ingest_s.<site>` in span_totals) — the evidence that passes 2..N
    of a cached fit stop paying host->device ingest."""
    from ..parallel.partition import pad_rows
    from ..parallel.partitioner import partitioner_for

    from .device_cache import cached_build

    part = partitioner_for(mesh) if mesh is not None else None
    for s in range(start_row, n, batch_rows):
        e = min(s + batch_rows, n)
        batch_index = s // batch_rows
        fault_point(site, batch=batch_index)

        def build(s=s, e=e):
            arrays = slicer(s, e)
            if part is not None:
                X_, *extras = arrays
                Xp, pad_w, extras_p = pad_rows(X_, part.num_workers, *extras)
                *mid, wv = extras_p
                out = [part.shard(Xp)]
                out += [part.shard(a) for a in mid]
                out.append(part.shard(pad_w * wv))
                return tuple(out)
            return tuple(jnp.asarray(a) for a in arrays)

        yield cached_build(cache, cache_key, batch_index, site, build)


def _accumulate_stream(carry, accum, n, batch_rows, mesh, slicer, site: str = "ingest",
                       cache=None, cache_key=None,
                       progress_phase: Optional[str] = None):
    """Checkpoint-resumable streamed accumulation, shared by every streamed fit:
    fold `accum(carry, batch_tuple) -> carry` over the prefetched batch stream,
    snapshotting (carry, cursor) every reliability.checkpoint_batches batches so
    a transient batch failure resumes from the last snapshot instead of
    restarting the pass (reliability/checkpoint.py) — resumed results are
    bit-identical to the fault-free pass. `cache`/`cache_key` (multi-pass fits:
    one cache handle across all passes) replay HBM-resident batches instead of
    re-uploading; a resumed stream replays hits and re-uploads misses through
    the same cursor arithmetic.

    Every folded batch publishes the live batch-progress gauge
    `fit.progress{phase=<progress_phase>}` (done/total + EMA ETA — §6g), so a
    mid-pass fit is visible through /runs/<id>. The counter restarts each pass
    and clamps at the total on a checkpoint-resume replay (progress is
    advisory telemetry, never an accounting surface)."""
    total_batches = max(1, -(-n // batch_rows))
    phase = progress_phase or f"{site}.batches"
    state = {"done": 0}

    def accum_with_progress(c, batch):
        c = accum(c, batch)
        state["done"] = min(state["done"] + 1, total_batches)
        obs_progress(phase, state["done"], total_batches, unit="batches")
        return c

    def factory(start_row: int):
        state["done"] = min(start_row // batch_rows, total_batches)
        return _prefetch(
            _batch_stream(n, batch_rows, mesh, slicer, start_row=start_row, site=site,
                          cache=cache, cache_key=cache_key),
            site=site,
            start_batch=start_row // batch_rows,
        )

    return resumable_accumulate(
        site, factory, accum_with_progress, carry, batch_rows, n
    )


# Every streamed accumulator donates its carry (argnum 0): the per-batch carry
# update then reuses the old stats buffers instead of allocating a fresh set
# per batch. Batch operands are NEVER donated — cached batches (device_cache)
# must survive the call to replay on later passes. The checkpoint-resume layer
# snapshots carry COPIES for the same reason (reliability/checkpoint.py).
@compiled_kernel("streaming.accum_linreg", static_argnames=("chain",),
                 donate_argnums=(0,))
def _accum_linreg(carry, X, y, w, chain_arrays=(), chain=()):
    A, b, sx, sy, sw = carry
    dt = A.dtype
    X = _apply_chain(X, dt, chain, chain_arrays)
    y = y.astype(dt)
    w = w.astype(dt)
    Xw = X * w[:, None]
    return (
        A + pdot(Xw.T, X),
        b + pdot(Xw.T, y),
        sx + pdot(w, X),
        sy + jnp.sum(w * y),
        sw + jnp.sum(w),
    )


@compiled_kernel("streaming.accum_cov", static_argnames=("chain",),
                 donate_argnums=(0,))
def _accum_cov(carry, X, w, chain_arrays=(), chain=()):
    S2, sx, sw = carry
    dt = S2.dtype
    X = _apply_chain(X, dt, chain, chain_arrays)
    w = w.astype(dt)
    return (
        S2 + pdot((X * w[:, None]).T, X),
        sx + pdot(w, X),
        sw + jnp.sum(w),
    )


def streaming_linreg_stats(
    X: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
    chain_ops=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Streamed (XᵀWX, XᵀWy, x̄, ȳ, Σw): the same statistics as
    ops/linear.linreg_sufficient_stats but with O(batch) device residency.
    Each batch is device_put (sharded over the mesh when given) and accumulated.
    dtype follows float32 (float64 additionally needs jax x64 mode, matching the
    in-core path's device behavior). `chain_ops` fuses a featurize prefix into
    the per-batch program (docs/design.md §6k)."""
    from .device_cache import batch_cache

    dt = np.float32 if float32 else np.float64
    n = X.shape[0]
    d = chain_out_dim(X.shape[1], chain_ops)
    kinds, chain_arrays = _prep_chain(chain_ops, dt)
    A = jnp.zeros((d, d), dt)
    b = jnp.zeros((d,), dt)
    sx = jnp.zeros((d,), dt)
    sy = jnp.zeros((), dt)
    sw = jnp.zeros((), dt)
    carry = (A, b, sx, sy, sw)

    pool = StagingPool()
    ones = np.ones((min(batch_rows, n),), dt) if w is None else None

    def slicer(s, e):
        return (
            stage_block(X, s, e, dt, pool, slot="X"),
            stage_block(y, s, e, dt, pool, slot="y"),
            ones[: e - s]
            if w is None
            else stage_block(w, s, e, dt, pool, slot="w"),
        )

    with batch_cache() as cache:
        ckey = (
            cache.stream_key(
                tuple(a for a in (X, y, w) if a is not None), batch_rows, mesh
            )
            if cache is not None
            else None
        )
        carry = _accumulate_stream(
            carry,
            lambda c, batch: _accum_linreg(c, *batch, chain_arrays, kinds),
            n, batch_rows, mesh, slicer, cache=cache, cache_key=ckey,
            progress_phase="linreg.batches",
        )
    A, b, sx, sy, sw = carry
    return A, b, sx / sw, sy / sw, sw


def streaming_covariance(
    X: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
    chain_ops=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Streamed weighted covariance (cov, mean, Σw) for PCA — the same math as
    ops/linalg.weighted_covariance, dtype per `float32` (see
    streaming_linreg_stats). `chain_ops` fuses a featurize prefix into the
    per-batch program; the active HBM batch-cache scope is shared, so the
    other passes of a fused chain replay these batches."""
    from .device_cache import batch_cache

    dt = np.float32 if float32 else np.float64
    n = X.shape[0]
    d = chain_out_dim(X.shape[1], chain_ops)
    kinds, chain_arrays = _prep_chain(chain_ops, dt)
    carry = (
        jnp.zeros((d, d), dt),
        jnp.zeros((d,), dt),
        jnp.zeros((), dt),
    )

    pool = StagingPool()
    ones = np.ones((min(batch_rows, n),), dt) if w is None else None

    def slicer(s, e):
        return (
            stage_block(X, s, e, dt, pool, slot="X"),
            ones[: e - s]
            if w is None
            else stage_block(w, s, e, dt, pool, slot="w"),
        )

    with batch_cache() as cache:
        ckey = (
            cache.stream_key(
                tuple(a for a in (X, w) if a is not None), batch_rows, mesh
            )
            if cache is not None
            else None
        )
        carry = _accumulate_stream(
            carry,
            lambda c, batch: _accum_cov(c, *batch, chain_arrays, kinds),
            n, batch_rows, mesh, slicer, cache=cache, cache_key=ckey,
            progress_phase="pca.batches",
        )
    S2, sx, sw = carry
    mean = sx / sw
    cov = (S2 - sw * jnp.outer(mean, mean)) / (sw - 1.0)
    return cov, mean, sw


def streaming_moments(
    X: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
    chain_ops=None,
) -> Tuple[np.ndarray, np.ndarray, float]:
    """Streamed weighted feature moments -> (mean, var, Σw), Spark Summarizer
    semantics (variance normalized by Σw-1, matching ops/linalg
    weighted_moments and the streamed-logreg standardization pass). This is
    the StandardScaler fit statistic; `chain_ops` lets a fused pipeline
    compute the moments of an already-chained (e.g. projected) feature space.
    Shares the active HBM batch-cache scope: in a fused chain the fit passes
    that follow replay the batches this pass uploaded."""
    from .device_cache import batch_cache

    dt = np.float32 if float32 else np.float64
    n = X.shape[0]
    d = chain_out_dim(X.shape[1], chain_ops)
    kinds, chain_arrays = _prep_chain(chain_ops, dt)
    carry = (jnp.zeros((d,), dt), jnp.zeros((d,), dt), jnp.zeros((), dt))

    pool = StagingPool()
    ones = np.ones((min(batch_rows, n),), dt) if w is None else None

    def slicer(s, e):
        return (
            stage_block(X, s, e, dt, pool, slot="X"),
            ones[: e - s]
            if w is None
            else stage_block(w, s, e, dt, pool, slot="w"),
        )

    with batch_cache() as cache:
        ckey = (
            cache.stream_key(
                tuple(a for a in (X, w) if a is not None), batch_rows, mesh
            )
            if cache is not None
            else None
        )
        carry = _accumulate_stream(
            carry,
            lambda c, batch: _accum_moments(c, *batch, chain_arrays, kinds),
            n, batch_rows, mesh, slicer, cache=cache, cache_key=ckey,
            progress_phase="scaler.batches",
        )
    sx, sxx, sw_j = carry
    wsum = float(sw_j)
    mean = np.asarray(sx) / wsum
    var = np.maximum((np.asarray(sxx) - wsum * mean * mean) / (wsum - 1.0), 0.0)
    return mean, var, wsum


def _kahan_add(acc, comp, term):
    """One compensated-summation step: returns (acc', comp') with the low-order
    bits the naive add would drop carried in `comp`. Accumulation error stays
    O(1) ulps over ANY number of batches instead of growing O(n_batches) —
    float32 device accumulation then matches the effective precision of the
    pre-donation float64 HOST accumulation it replaced (the per-batch terms
    were always float32; only their summation ever benefited from float64).
    XLA does not reassociate IEEE float ops, so the cancellation survives jit."""
    y = term - comp
    t = acc + y
    return t, (t - acc) - y


@compiled_kernel(
    "streaming.logreg_value_grad",
    static_argnames=("fit_intercept", "multinomial", "chain"),
    donate_argnums=(0, 1, 2, 3),
)
def _logreg_accum_value_grad(
    acc_v, comp_v, acc_g, comp_g, params, X, y_enc, w, scale, chain_arrays,
    fit_intercept, multinomial, chain=(),
):
    """One batch of the UNNORMALIZED cross-entropy value+grad folded into the
    running device accumulators (no /Σw, no penalty — the caller normalizes and
    adds the L2 term once). The per-batch loss form mirrors
    ops/logistic._binomial_loss_fn / _multinomial_loss_fn so the streamed
    objective is the in-core objective. The whole carry (accumulators + Kahan
    compensations) is donated: each batch update reuses the buffers in place of
    a fresh allocation, and the running loss/grad never round-trips to host
    mid-pass."""
    dt = acc_g.dtype
    X = _apply_chain(X, dt, chain, chain_arrays)
    y_enc = y_enc.astype(dt)
    w = w.astype(dt)

    def f(p):
        if multinomial:
            coef_s, b = p[:, :-1], p[:, -1]
            z = pdot(X, (coef_s / scale).T) + jnp.where(fit_intercept, b, 0.0)
            return -jnp.sum(w * jnp.sum(y_enc * jax.nn.log_softmax(z, axis=1), axis=1))
        coef_s, b = p[:-1], p[-1]
        z = pdot(X, coef_s / scale) + jnp.where(fit_intercept, b, 0.0)
        return jnp.sum(w * (jax.nn.softplus(z) - y_enc * z))

    v, g = jax.value_and_grad(f)(params)
    acc_v, comp_v = _kahan_add(acc_v, comp_v, v)
    acc_g, comp_g = _kahan_add(acc_g, comp_g, g)
    return acc_v, comp_v, acc_g, comp_g


@compiled_kernel("streaming.accum_moments", static_argnames=("chain",),
                 donate_argnums=(0,))
def _accum_moments(carry, X, w, chain_arrays=(), chain=()):
    sx, sxx, sw = carry
    dt = sx.dtype
    X = _apply_chain(X, dt, chain, chain_arrays)
    w = w.astype(dt)
    return (sx + pdot(w, X), sxx + pdot(w, X * X), sw + jnp.sum(w))


def _strong_wolfe(f, x, fx, gx, p, max_steps: int, c1=1e-4, c2=0.9):
    """Strong-Wolfe line search (zoom), scipy-style: each trial costs one full
    streamed data pass. Returns (alpha, f_new, g_new, n_evals); when the budget
    runs out it falls back to the best SUFFICIENT-DECREASE (Armijo) point seen —
    never to an objective-increasing trial — and signals failure with alpha=0 if
    no trial achieved sufficient decrease at all (the caller stops rather than
    step uphill). The reference's QN solver caps linesearch at 20 the same way."""
    d0 = float(np.vdot(gx, p))
    if d0 >= 0:  # not a descent direction (numerical breakdown): bail
        return 0.0, fx, gx, 0

    def phi(alpha):
        fv, gv = f(x + alpha * p)
        return fv, gv, float(np.vdot(gv, p))

    def armijo(alpha, f_a):
        return f_a <= fx + c1 * alpha * d0

    if max_steps <= 0:
        return 0.0, fx, gx, 0
    best = None  # best Armijo-satisfying trial: (alpha, f, g)
    alpha_prev, f_prev = 0.0, fx
    alpha = 1.0
    n_evals = 0
    lo = hi = None
    f_lo = None
    for i in range(max_steps):
        f_a, g_a, d_a = phi(alpha)
        n_evals += 1
        if armijo(alpha, f_a) and (best is None or f_a < best[1]):
            best = (alpha, f_a, g_a)
        if not armijo(alpha, f_a) or (i > 0 and f_a >= f_prev):
            lo, hi, f_lo = alpha_prev, alpha, f_prev
            break
        if abs(d_a) <= -c2 * d0:
            return alpha, f_a, g_a, n_evals
        if d_a >= 0:
            lo, hi, f_lo = alpha, alpha_prev, f_a
            break
        alpha_prev, f_prev = alpha, f_a
        alpha *= 2.0
    else:
        # expansion budget exhausted with every trial Armijo-passing: return the
        # LAST EVALUATED point (alpha has already been doubled past it — returning
        # alpha would pair an unevaluated step with stale f/g and corrupt the
        # L-BFGS curvature history)
        return alpha_prev, f_a, g_a, n_evals

    # zoom phase
    while n_evals < max_steps:
        mid = 0.5 * (lo + hi)
        f_m, g_m, d_m = phi(mid)
        n_evals += 1
        if not armijo(mid, f_m) or f_m >= f_lo:
            hi = mid
        else:
            if best is None or f_m < best[1]:
                best = (mid, f_m, g_m)
            if abs(d_m) <= -c2 * d0:
                return mid, f_m, g_m, n_evals
            if d_m * (hi - lo) >= 0:
                hi = lo
            lo, f_lo = mid, f_m
    if best is None:
        return 0.0, fx, gx, n_evals  # no sufficient decrease anywhere: signal stop
    return best[0], best[1], best[2], n_evals


def streaming_logreg_fit(
    X: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    n_classes: int,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    multinomial: bool,
    batch_rows: int,
    mesh=None,
    float32: bool = True,
    chain_ops=None,
):
    """Out-of-core distributed L-BFGS logistic regression: X stays HOST-resident;
    each objective/gradient evaluation streams batches through the device and
    accumulates the unnormalized loss and gradient (sharded over the mesh when
    given — the per-batch contraction carries the gradient psum exactly where the
    in-core path does). The L-BFGS two-loop recursion and strong-Wolfe zoom line
    search run on host over the SMALL parameter vector (memory 10, linesearch
    <= 20 evals — the reference's QN settings, classification.py:1046-1052).

    This is the LogisticRegression analog of the reference's UVM/SAM
    larger-than-device-memory fitting (reference utils.py:184-241): BASELINE
    config 3 (500M x 256) cannot stage the design matrix in HBM.

    Solver dispatch mirrors the in-core logreg_fit: elasticNetParam > 0 runs a
    streamed FISTA (full-pass smooth gradient + host prox/Nesterov updates, the
    Lipschitz constant from a streamed Gram pass); otherwise distributed L-BFGS.

    Pass counts (docs/performance.md): L-BFGS costs 1 + ~2-4 streamed passes per
    iteration (one per line-search objective evaluation); FISTA costs exactly
    1 + n_iter passes plus one Gram pass (+1 moments pass when standardizing).
    ONE batch cache (ops/device_cache.py) spans every pass of the fit — the
    moments/Gram passes populate it and each value_and_grad evaluation replays
    from HBM, so only pass 1 (plus whatever exceeds the cache budget) pays
    host->device ingest; with the cache disabled every batch re-uploads per
    pass, the original out-of-core contract. The ragged tail batch compiles one
    extra accumulator entry once and reuses it every pass."""
    from .device_cache import batch_cache

    with batch_cache() as cache:
        return _streaming_logreg_fit(
            X, y, w, n_classes, reg, l1_ratio, fit_intercept, standardize,
            max_iter, tol, multinomial, batch_rows, mesh, float32, cache,
            chain_ops,
        )


def _streaming_logreg_fit(
    X, y, w, n_classes, reg, l1_ratio, fit_intercept, standardize, max_iter,
    tol, multinomial, batch_rows, mesh, float32, cache, chain_ops=None,
):
    dt = np.float32 if float32 else np.float64
    n = X.shape[0]
    d = chain_out_dim(X.shape[1], chain_ops)
    kinds, chain_arrays = _prep_chain(chain_ops, dt)
    reg_l1 = reg * l1_ratio
    reg_l2 = reg * (1.0 - l1_ratio)
    ckey = (
        cache.stream_key(
            tuple(a for a in (X, y, w) if a is not None), batch_rows, mesh
        )
        if cache is not None
        else None
    )

    pool = StagingPool()
    ones = np.ones((min(batch_rows, n),), dt) if w is None else None

    def _slicer(s, e):
        return (
            stage_block(X, s, e, dt, pool, slot="X"),
            stage_block(y, s, e, dt, pool, slot="y"),
            ones[: e - s]
            if w is None
            else stage_block(w, s, e, dt, pool, slot="w"),
        )

    # streamed standardization moments (Spark Summarizer wsum-1 variance,
    # matching ops/linalg.weighted_moments)
    if standardize:
        carry = (jnp.zeros((d,), dt), jnp.zeros((d,), dt), jnp.zeros((), dt))
        with obs_span("logreg.moments"):
            carry = _accumulate_stream(
                carry,
                lambda c, batch: _accum_moments(
                    c, batch[0], batch[2], chain_arrays, kinds
                ),
                n, batch_rows, mesh, _slicer, cache=cache, cache_key=ckey,
                progress_phase="logreg.moments",
            )
        sx, sxx, sw_j = carry
        wsum = float(sw_j)
        mean = np.asarray(sx) / wsum
        var = np.maximum(
            (np.asarray(sxx) - wsum * mean * mean) / (wsum - 1.0), 0.0
        )
        scale_h = np.sqrt(var)
        scale_h[scale_h <= 0.0] = 1.0
    else:
        scale_h = np.ones((d,), dt)
        wsum = float(np.sum(w)) if w is not None else float(n)
    scale = jnp.asarray(scale_h.astype(dt))

    if multinomial:
        shape = (n_classes, d + 1)
    else:
        shape = (d + 1,)

    _step_no = [0]

    def value_and_grad(params_flat: np.ndarray):
        # one objective/gradient evaluation == one full streamed pass: a
        # `logreg.step` span per pass in the fit trace, with its per-batch
        # `stream.ingest` uploads (if any) as children
        _step_no[0] += 1
        # profile_pass: opt-in jax.profiler capture of ONE designated pass
        # (observability.profile_dir / profile_pass — docs/design.md §6f)
        with profile_pass("logreg.step", _step_no[0]):
            with obs_span("logreg.step", {"pass": _step_no[0]}):
                return _value_and_grad(params_flat)

    def _value_and_grad(params_flat: np.ndarray):
        params = jnp.asarray(params_flat.reshape(shape).astype(dt))

        def _accum_vg(carry, batch):
            Xb, yb, wb = batch
            y_enc = (
                jax.nn.one_hot(yb.astype(jnp.int32), n_classes, dtype=Xb.dtype)
                * (wb > 0)[:, None]
                if multinomial
                else yb
            )
            # Kahan-compensated device accumulation with the carry DONATED
            # (buffer reuse per batch); functional from the caller's view — the
            # resume layer's snapshots are copies (reliability/checkpoint.py),
            # never aliases of a buffer a later batch will donate
            return _logreg_accum_value_grad(
                *carry, params, Xb, y_enc, wb, scale, chain_arrays,
                bool(fit_intercept), bool(multinomial), kinds,
            )

        acc_v, _, acc_g, _ = _accumulate_stream(
            (
                jnp.zeros((), dt), jnp.zeros((), dt),
                jnp.zeros(shape, dt), jnp.zeros(shape, dt),
            ),
            _accum_vg,
            n, batch_rows, mesh, _slicer, cache=cache, cache_key=ckey,
            # phase is per-accumulation kind, not per-fit: blending the cheap
            # moments/gram passes into this EMA would corrupt the gradient
            # pass's ETA by the ratio of their per-batch costs
            progress_phase="logreg.grad",
        )
        coef_s = params_flat.reshape(shape)[..., :-1]
        value = float(acc_v) / wsum + 0.5 * reg_l2 * float(np.sum(coef_s * coef_s))
        grad = np.asarray(acc_g, np.float64) / wsum
        grad[..., :-1] += reg_l2 * coef_s
        return value, grad.reshape(-1)

    if reg_l1 > 0.0:
        # ---- streamed FISTA (elastic net): the in-core _fista_fit with the
        # smooth gradient evaluated by streamed passes; prox/Nesterov updates on
        # the small host parameter vector. Lipschitz from one streamed Gram pass
        # (the same (0.5|0.25)*lmax + reg_l2 bound as ops/logistic.py:311-312).
        from .linalg import power_iteration_lmax

        carry = (jnp.zeros((d, d), dt), jnp.zeros((d,), dt), jnp.zeros((), dt))
        # X/scale rides the fused program as one more ("scale", 0, scale)
        # chain link — (x - 0)/scale is bit-equal to x/scale, and the scaled
        # batch never materializes outside the accumulator
        gram_kinds = kinds + ("scale",)
        gram_arrays = chain_arrays + (jnp.zeros((d,), dt), scale)
        with obs_span("logreg.gram"):
            carry = _accumulate_stream(
                carry,
                lambda c, batch: _accum_cov(
                    c, batch[0], batch[2], gram_arrays, gram_kinds
                ),
                n, batch_rows, mesh, _slicer, cache=cache, cache_key=ckey,
                progress_phase="logreg.gram",
            )
        S2, _, sw_g = carry
        lmax = float(power_iteration_lmax(S2 / sw_g))
        lipschitz = (0.5 if multinomial else 0.25) * lmax + reg_l2 + 1e-12
        step = 1.0 / lipschitz
        coef_mask = np.ones(shape, np.float64)
        coef_mask[..., -1] = 0.0  # intercept entries are never penalized

        def prox(pv):
            soft = np.sign(pv) * np.maximum(np.abs(pv) - step * reg_l1, 0.0)
            return np.where(coef_mask > 0, soft, pv)

        pk = np.zeros(shape, np.float64)
        zk = pk.copy()
        tk = 1.0
        n_iter = 0
        for it in range(int(max_iter)):
            fv, g = value_and_grad(zk.reshape(-1))
            p_next = prox(zk - step * g.reshape(shape))
            t_next = 0.5 * (1.0 + np.sqrt(1.0 + 4.0 * tk * tk))
            zk = p_next + ((tk - 1.0) / t_next) * (p_next - pk)
            delta = float(
                np.max(np.abs(p_next - pk)) / (np.max(np.abs(p_next)) + 1e-12)
            )
            pk, tk = p_next, t_next
            n_iter = it + 1
            # §6g: loss here is the SMOOTH objective at the momentum point
            # (what the streamed pass evaluated); the L1 term is added once at
            # the end, so the record tracks descent direction, not the exact
            # composite objective
            obs_progress("logreg.iters", n_iter, int(max_iter), unit="iters")
            obs_convergence(
                "logreg", n_iter, loss=fv,
                grad_norm=float(np.linalg.norm(g)), delta=delta,
                solver="fista",
            )
            if delta <= tol:
                break
        x = pk.reshape(-1)
        fx, _ = value_and_grad(x)
        fx += reg_l1 * float(np.sum(np.abs(pk * coef_mask)))
        return _finish_logreg(
            x, shape, scale_h, fit_intercept, multinomial, n_iter, fx
        )

    # ---- host L-BFGS (two-loop recursion, memory 10) ----
    m = 10
    x = np.zeros(int(np.prod(shape)), np.float64)
    fx, gx = value_and_grad(x)
    s_hist: list = []
    y_hist: list = []
    n_iter = 0
    for it in range(int(max_iter)):
        gnorm = float(np.linalg.norm(gx))
        if gnorm <= tol:
            break
        # two-loop recursion
        q = gx.copy()
        alphas = []
        for s_i, y_i in zip(reversed(s_hist), reversed(y_hist)):
            rho_i = 1.0 / float(np.vdot(y_i, s_i))
            a_i = rho_i * float(np.vdot(s_i, q))
            q -= a_i * y_i
            alphas.append((a_i, rho_i))
        if s_hist:
            gamma = float(np.vdot(s_hist[-1], y_hist[-1])) / float(
                np.vdot(y_hist[-1], y_hist[-1])
            )
            q *= gamma
        for (a_i, rho_i), s_i, y_i in zip(reversed(alphas), s_hist, y_hist):
            b_i = rho_i * float(np.vdot(y_i, q))
            q += (a_i - b_i) * s_i
        p = -q
        alpha, f_new, g_new, _ = _strong_wolfe(
            value_and_grad, x, fx, gx, p, max_steps=20
        )
        if alpha == 0.0:
            break
        x_new = x + alpha * p
        s_i = x_new - x
        y_i = g_new - gx
        if float(np.vdot(s_i, y_i)) > 1e-10:
            s_hist.append(s_i)
            y_hist.append(y_i)
            if len(s_hist) > m:
                s_hist.pop(0)
                y_hist.pop(0)
        delta = abs(fx - f_new) / max(abs(f_new), 1.0)
        x, fx, gx = x_new, f_new, g_new
        n_iter = it + 1
        obs_progress("logreg.iters", n_iter, int(max_iter), unit="iters")
        obs_convergence(
            "logreg", n_iter, loss=fx,
            grad_norm=float(np.linalg.norm(gx)), delta=delta, solver="lbfgs",
        )
        if delta <= tol:
            break

    return _finish_logreg(x, shape, scale_h, fit_intercept, multinomial, n_iter, fx)


def _finish_logreg(x, shape, scale_h, fit_intercept, multinomial, n_iter, fx):
    """Un-standardize + Spark intercept centering, shared by both streamed solvers
    (same finishing as ops/logistic.logreg_fit)."""
    params = x.reshape(shape)
    if multinomial:
        coef = params[:, :-1] / scale_h
        intercept = params[:, -1]
        if fit_intercept:
            intercept = intercept - intercept.mean()
    else:
        coef = (params[:-1] / scale_h).reshape(1, -1)
        intercept = params[-1:]
    return {
        "coefficients": coef.astype(np.float32),
        "intercepts": intercept.astype(np.float32),
        "n_iter": int(n_iter),
        "objective": float(fx),
    }


@compiled_kernel("streaming.accum_kmeans", static_argnames=("cosine", "chain"),
                 donate_argnums=(0,))
def _accum_kmeans(carry, centers, X, w, chain_arrays=(), cosine: bool = False,
                  chain=()):
    """One batch of a streamed Lloyd iteration: accumulate per-cluster weighted sums,
    counts and inertia against FIXED centers."""
    sums, counts, inertia = carry
    dt = sums.dtype
    X = _apply_chain(X, dt, chain, chain_arrays)
    w = w.astype(dt)
    if cosine:
        d2 = 1.0 - pdot(X, centers.T)
    else:
        x2 = jnp.sum(X * X, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1)
        d2 = jnp.maximum(x2 - 2.0 * pdot(X, centers.T) + c2, 0.0)
    assign = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    onehot = jax.nn.one_hot(assign, centers.shape[0], dtype=X.dtype) * w[:, None]
    return (
        sums + pdot(onehot.T, X),
        counts + jnp.sum(onehot, axis=0),
        inertia + jnp.sum(w * min_d2),
    )


def streaming_kmeans_fit(
    X: np.ndarray,
    w: Optional[np.ndarray],
    k: int,
    max_iter: int,
    tol: float,
    seed: int,
    batch_rows: int,
    mesh=None,
    metric: str = "euclidean",
    init_sample_rows: int = 1 << 18,
    float32: bool = True,
    chain_ops=None,
):
    """Out-of-core EXACT Lloyd: each iteration streams every batch through the device
    against fixed centers and accumulates (Σ one-hotᵀWX, counts, inertia); centers
    update once per full pass, so iterates match in-core Lloyd on the same init
    (not a minibatch approximation). Device residency is one batch + (k, d) stats
    plus whatever the HBM batch cache retains: ONE cache (ops/device_cache.py)
    spans every Lloyd iteration, so iteration 1 uploads and iterations 2..N
    replay from HBM (prefix-cached when the dataset exceeds the budget) — the
    KMeans analog of the reference's UVM/SAM large-dataset path
    (reference utils.py:184-241). Initialization runs in-core k-means|| on a row
    subsample bounded by `init_sample_rows`."""
    from .device_cache import batch_cache

    with batch_cache() as cache:
        return _streaming_kmeans_fit(
            X, w, k, max_iter, tol, seed, batch_rows, mesh, metric,
            init_sample_rows, float32, cache, chain_ops,
        )


def _streaming_kmeans_fit(
    X, w, k, max_iter, tol, seed, batch_rows, mesh, metric, init_sample_rows,
    float32, cache, chain_ops=None,
):
    from .kmeans import _normalize_rows, kmeans_init

    dt = np.float32 if float32 else np.float64
    n, d = X.shape
    cosine = metric == "cosine"
    if cosine and chain_ops:
        raise ValueError(
            "cosine KMeans is not fuse-eligible (host-side normalization); "
            "the pipeline fuser must leave it staged"
        )
    d = chain_out_dim(d, chain_ops)
    kinds, chain_arrays = _prep_chain(chain_ops, dt)
    # the cache key pins the RAW sources: a None weight materializes to the
    # same implicit all-ones below, so leaving it out of the key lets every
    # pass — and every candidate of a CV loop over the same X — replay the
    # same HBM-resident batches
    ckey = (
        cache.stream_key(
            tuple(a for a in (X, w) if a is not None), batch_rows, mesh
        )
        if cache is not None
        else None
    )
    if w is None:
        w = np.ones((n,), dt)

    # init on a subsample (rows are not assumed shuffled: use a strided sample)
    with obs_span("kmeans.init", {"sample_rows": min(n, init_sample_rows)}):
        step = max(1, n // min(n, init_sample_rows))
        # strided: never contiguous past step 1, and k-means|| owns the buffer
        Xs = np.ascontiguousarray(X[::step], dtype=dt)  # noqa: fence/host-staging-copy
        ws = np.ascontiguousarray(w[::step], dtype=dt)  # noqa: fence/host-staging-copy
        Xs_j = jnp.asarray(Xs if not cosine else np.asarray(
            Xs / np.maximum(np.linalg.norm(Xs, axis=1, keepdims=True), 1e-30)))
        if kinds:
            # same in-program expressions the per-batch accumulators run, so
            # the init sample sees bit-identical features to the staged path
            Xs_j = _apply_chain(Xs_j, dt, kinds, chain_arrays)
        centers = jnp.asarray(
            kmeans_init(Xs_j, jnp.asarray(ws), k, "k-means||", 2, seed)
        )
        if cosine:
            centers = _normalize_rows(centers)

    pool = StagingPool()

    def _slicer(s, e):
        if cosine:
            # normalization mutates: the block must own its buffer
            Xb = stage_block(X, s, e, dt, pool, slot="X", force_copy=True)
            norms = np.linalg.norm(Xb, axis=1, keepdims=True)
            if np.any(norms <= 0):
                raise ValueError(
                    "Cosine distance is not defined for zero-length vectors."
                )
            np.divide(Xb, norms, out=Xb)
            return Xb, stage_block(w, s, e, dt, pool, slot="w")
        return (
            stage_block(X, s, e, dt, pool, slot="X"),
            stage_block(w, s, e, dt, pool, slot="w"),
        )

    inertia = np.inf
    n_iter = 0
    for it in range(max_iter):
        carry = (
            jnp.zeros((k, d), dt),
            jnp.zeros((k,), dt),
            jnp.zeros((), dt),
        )
        # one Lloyd iteration == one full streamed pass: a `kmeans.step` span
        # per pass (pass 1 carries the jit compile of the batch accumulator),
        # with any `stream.ingest` uploads it triggered as child spans; the
        # designated pass may additionally capture a jax.profiler trace
        # (observability.profile_dir — docs/design.md §6f)
        with profile_pass("kmeans.step", it + 1), \
                obs_span("kmeans.step", {"pass": it + 1, "compile": it == 0}):
            carry = _accumulate_stream(
                carry,
                lambda c, batch, centers=centers: _accum_kmeans(
                    c, centers, batch[0], batch[1], chain_arrays, cosine, kinds
                ),
                n, batch_rows, mesh, _slicer, cache=cache, cache_key=ckey,
                progress_phase="kmeans.batches",
            )
        sums, counts, inertia_j = carry
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1.0)[:, None],
            centers,
        )
        if cosine:
            new_centers = _normalize_rows(new_centers)
        shift2 = float(jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1)))
        centers = new_centers
        inertia = float(inertia_j)
        n_iter = it + 1
        # live telemetry (§6g): pass-level progress gauge + per-iteration
        # convergence record, both visible mid-fit through /runs/<run_id>
        obs_progress("kmeans.passes", n_iter, max_iter, unit="passes")
        obs_convergence(
            "kmeans", n_iter, inertia=inertia,
            center_shift=float(np.sqrt(shift2)),
        )
        if shift2 <= tol * tol:
            break

    return {
        "cluster_centers": np.asarray(centers),
        "inertia": inertia,
        "n_iter": n_iter,
    }
