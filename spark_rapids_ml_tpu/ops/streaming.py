#
# Out-of-core fitting: streamed sufficient-statistics accumulation.
#
# The reference fits datasets larger than device memory through RMM UVM/SAM managed
# memory (reference utils.py:184-241, SURVEY.md §2.5 last row). TPUs have no UVM;
# the TPU-native answer (SURVEY.md §7 "hard parts") is to stream host batches through
# the device and ACCUMULATE the model-sufficient statistics on device:
#   * PCA / LinearRegression: (XᵀWX, XᵀWy, Σwx, Σwy, Σw) accumulate exactly —
#     the fit result is IDENTICAL to the in-core path, with device residency bounded
#     by one batch + the d×d stats,
#   * KMeans: per-pass Lloyd over batches (minibatch-free exact variant: each
#     iteration streams all batches, accumulating one-hotᵀX sums and counts).
# Estimators switch to this path automatically when the padded design matrix would
# exceed `config` threshold SRML_TPU_STREAM_THRESHOLD_BYTES (see core/estimator.py).
#

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ._precision import pdot


@jax.jit
def _accum_linreg(carry, X, y, w):
    A, b, sx, sy, sw = carry
    Xw = X * w[:, None]
    return (
        A + pdot(Xw.T, X),
        b + pdot(Xw.T, y),
        sx + pdot(w, X),
        sy + jnp.sum(w * y),
        sw + jnp.sum(w),
    )


@jax.jit
def _accum_cov(carry, X, w):
    S2, sx, sw = carry
    return (
        S2 + pdot((X * w[:, None]).T, X),
        sx + pdot(w, X),
        sw + jnp.sum(w),
    )


def streaming_linreg_stats(
    X: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Streamed (XᵀWX, XᵀWy, x̄, ȳ, Σw): the same statistics as
    ops/linear.linreg_sufficient_stats but with O(batch) device residency.
    Each batch is device_put (sharded over the mesh when given) and accumulated.
    dtype follows float32 (float64 additionally needs jax x64 mode, matching the
    in-core path's device behavior)."""
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    dt = np.float32 if float32 else np.float64
    d = X.shape[1]
    A = jnp.zeros((d, d), dt)
    b = jnp.zeros((d,), dt)
    sx = jnp.zeros((d,), dt)
    sy = jnp.zeros((), dt)
    sw = jnp.zeros((), dt)
    carry = (A, b, sx, sy, sw)

    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        Xb = np.ascontiguousarray(X[s:e], dtype=dt)
        yb = np.ascontiguousarray(y[s:e], dtype=dt)
        wb = (
            np.ones((e - s,), dt)
            if w is None
            else np.ascontiguousarray(w[s:e], dtype=dt)
        )
        if mesh is not None:
            Xb, pad_w, (yb_p, wb_p) = pad_rows(Xb, mesh.devices.size, yb, wb)
            Xb = shard_array(Xb, mesh)
            yb = shard_array(yb_p, mesh)
            wb = shard_array(pad_w * wb_p, mesh)
        carry = _accum_linreg(carry, jnp.asarray(Xb), jnp.asarray(yb), jnp.asarray(wb))
    A, b, sx, sy, sw = carry
    return A, b, sx / sw, sy / sw, sw


def streaming_covariance(
    X: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Streamed weighted covariance (cov, mean, Σw) for PCA — the same math as
    ops/linalg.weighted_covariance, dtype per `float32` (see streaming_linreg_stats)."""
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    dt = np.float32 if float32 else np.float64
    d = X.shape[1]
    carry = (
        jnp.zeros((d, d), dt),
        jnp.zeros((d,), dt),
        jnp.zeros((), dt),
    )
    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        Xb = np.ascontiguousarray(X[s:e], dtype=dt)
        wb = (
            np.ones((e - s,), dt)
            if w is None
            else np.ascontiguousarray(w[s:e], dtype=dt)
        )
        if mesh is not None:
            Xb, pad_w, (wb_p,) = pad_rows(Xb, mesh.devices.size, wb)
            Xb = shard_array(Xb, mesh)
            wb = shard_array(pad_w * wb_p, mesh)
        carry = _accum_cov(carry, jnp.asarray(Xb), jnp.asarray(wb))
    S2, sx, sw = carry
    mean = sx / sw
    cov = (S2 - sw * jnp.outer(mean, mean)) / (sw - 1.0)
    return cov, mean, sw


@functools.partial(jax.jit, static_argnames=("fit_intercept", "multinomial"))
def _logreg_batch_value_grad(params, X, y_enc, w, scale, fit_intercept, multinomial):
    """UNNORMALIZED batch cross-entropy value+grad (no /Σw, no penalty): batches
    accumulate exactly; the caller normalizes and adds the L2 term once. The
    per-batch loss form mirrors ops/logistic._binomial_loss_fn /
    _multinomial_loss_fn so the streamed objective is the in-core objective."""

    def f(p):
        if multinomial:
            coef_s, b = p[:, :-1], p[:, -1]
            z = pdot(X, (coef_s / scale).T) + jnp.where(fit_intercept, b, 0.0)
            return -jnp.sum(w * jnp.sum(y_enc * jax.nn.log_softmax(z, axis=1), axis=1))
        coef_s, b = p[:-1], p[-1]
        z = pdot(X, coef_s / scale) + jnp.where(fit_intercept, b, 0.0)
        return jnp.sum(w * (jax.nn.softplus(z) - y_enc * z))

    return jax.value_and_grad(f)(params)


@jax.jit
def _accum_moments(carry, X, w):
    sx, sxx, sw = carry
    return (sx + pdot(w, X), sxx + pdot(w, X * X), sw + jnp.sum(w))


def _strong_wolfe(f, x, fx, gx, p, max_steps: int, c1=1e-4, c2=0.9):
    """Strong-Wolfe line search (zoom), scipy-style: each trial costs one full
    streamed data pass. Returns (alpha, f_new, g_new, n_evals); falls back to the
    last trial point if the conditions never both hold within max_steps (the
    reference's QN solver caps linesearch at 20 the same way)."""
    d0 = float(np.vdot(gx, p))
    if d0 >= 0:  # not a descent direction (numerical breakdown): bail
        return 0.0, fx, gx, 0

    def phi(alpha):
        fv, gv = f(x + alpha * p)
        return fv, gv, float(np.vdot(gv, p))

    alpha_prev, f_prev = 0.0, fx
    alpha = 1.0
    n_evals = 0
    lo = hi = None
    f_lo = g_lo = None
    for i in range(max_steps):
        f_a, g_a, d_a = phi(alpha)
        n_evals += 1
        if f_a > fx + c1 * alpha * d0 or (i > 0 and f_a >= f_prev):
            lo, hi, f_lo = alpha_prev, alpha, f_prev
            break
        if abs(d_a) <= -c2 * d0:
            return alpha, f_a, g_a, n_evals
        if d_a >= 0:
            lo, hi, f_lo = alpha, alpha_prev, f_a
            break
        alpha_prev, f_prev = alpha, f_a
        alpha *= 2.0
    else:
        return alpha, f_a, g_a, n_evals  # ran out of expansion steps

    # zoom phase
    best = (alpha, f_a, g_a)
    while n_evals < max_steps:
        mid = 0.5 * (lo + hi)
        f_m, g_m, d_m = phi(mid)
        n_evals += 1
        if f_m > fx + c1 * mid * d0 or f_m >= f_lo:
            hi = mid
        else:
            if abs(d_m) <= -c2 * d0:
                return mid, f_m, g_m, n_evals
            if d_m * (hi - lo) >= 0:
                hi = lo
            lo, f_lo = mid, f_m
        if f_m < best[1]:
            best = (mid, f_m, g_m)
    return best[0], best[1], best[2], n_evals


def streaming_logreg_fit(
    X: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    n_classes: int,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    multinomial: bool,
    batch_rows: int,
    mesh=None,
    float32: bool = True,
):
    """Out-of-core distributed L-BFGS logistic regression: X stays HOST-resident;
    each objective/gradient evaluation streams batches through the device and
    accumulates the unnormalized loss and gradient (sharded over the mesh when
    given — the per-batch contraction carries the gradient psum exactly where the
    in-core path does). The L-BFGS two-loop recursion and strong-Wolfe zoom line
    search run on host over the SMALL parameter vector (memory 10, linesearch
    <= 20 evals — the reference's QN settings, classification.py:1046-1052).

    This is the LogisticRegression analog of the reference's UVM/SAM
    larger-than-device-memory fitting (reference utils.py:184-241): BASELINE
    config 3 (500M x 256) cannot stage the design matrix in HBM. L2/no-penalty
    only (the FISTA L1 path needs a different streamed loop); callers route
    l1_ratio > 0 in-core."""
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    if reg * l1_ratio > 0.0:
        raise ValueError(
            "streaming_logreg_fit supports only L2/no-penalty "
            "(elasticNetParam must be 0)."
        )
    dt = np.float32 if float32 else np.float64
    n, d = X.shape
    reg_l2 = reg * (1.0 - l1_ratio)

    def _batches():
        for s in range(0, n, batch_rows):
            e = min(s + batch_rows, n)
            Xb = np.ascontiguousarray(X[s:e], dtype=dt)
            yb = np.ascontiguousarray(y[s:e], dtype=dt)
            wb = (
                np.ones((e - s,), dt)
                if w is None
                else np.ascontiguousarray(w[s:e], dtype=dt)
            )
            if mesh is not None:
                Xb, pad_w, (yb_p, wb_p) = pad_rows(Xb, mesh.devices.size, yb, wb)
                Xb = shard_array(Xb, mesh)
                yb = shard_array(yb_p, mesh)
                wb = shard_array(pad_w * wb_p, mesh)
            yield jnp.asarray(Xb), jnp.asarray(yb), jnp.asarray(wb)

    # streamed standardization moments (Spark Summarizer wsum-1 variance,
    # matching ops/linalg.weighted_moments)
    if standardize:
        carry = (jnp.zeros((d,), dt), jnp.zeros((d,), dt), jnp.zeros((), dt))
        for Xb, _, wb in _batches():
            carry = _accum_moments(carry, Xb, wb)
        sx, sxx, sw_j = carry
        wsum = float(sw_j)
        mean = np.asarray(sx) / wsum
        var = np.maximum(
            (np.asarray(sxx) - wsum * mean * mean) / (wsum - 1.0), 0.0
        )
        scale_h = np.sqrt(var)
        scale_h[scale_h <= 0.0] = 1.0
    else:
        scale_h = np.ones((d,), dt)
        wsum = float(np.sum(w)) if w is not None else float(n)
    scale = jnp.asarray(scale_h.astype(dt))

    if multinomial:
        shape = (n_classes, d + 1)
    else:
        shape = (d + 1,)

    def value_and_grad(params_flat: np.ndarray):
        params = jnp.asarray(params_flat.reshape(shape).astype(dt))
        acc_v = 0.0
        acc_g = np.zeros(shape, np.float64)
        for Xb, yb, wb in _batches():
            y_enc = (
                jax.nn.one_hot(yb.astype(jnp.int32), n_classes, dtype=Xb.dtype)
                * (wb > 0)[:, None]
                if multinomial
                else yb
            )
            v, g = _logreg_batch_value_grad(
                params, Xb, y_enc, wb, scale, bool(fit_intercept), bool(multinomial)
            )
            acc_v += float(v)
            acc_g += np.asarray(g, np.float64)
        coef_s = params_flat.reshape(shape)[..., :-1]
        value = acc_v / wsum + 0.5 * reg_l2 * float(np.sum(coef_s * coef_s))
        grad = acc_g / wsum
        grad[..., :-1] += reg_l2 * coef_s
        return value, grad.reshape(-1)

    # ---- host L-BFGS (two-loop recursion, memory 10) ----
    m = 10
    x = np.zeros(int(np.prod(shape)), np.float64)
    fx, gx = value_and_grad(x)
    s_hist: list = []
    y_hist: list = []
    n_iter = 0
    for it in range(int(max_iter)):
        gnorm = float(np.linalg.norm(gx))
        if gnorm <= tol:
            break
        # two-loop recursion
        q = gx.copy()
        alphas = []
        for s_i, y_i in zip(reversed(s_hist), reversed(y_hist)):
            rho_i = 1.0 / float(np.vdot(y_i, s_i))
            a_i = rho_i * float(np.vdot(s_i, q))
            q -= a_i * y_i
            alphas.append((a_i, rho_i))
        if s_hist:
            gamma = float(np.vdot(s_hist[-1], y_hist[-1])) / float(
                np.vdot(y_hist[-1], y_hist[-1])
            )
            q *= gamma
        for (a_i, rho_i), s_i, y_i in zip(reversed(alphas), s_hist, y_hist):
            b_i = rho_i * float(np.vdot(y_i, q))
            q += (a_i - b_i) * s_i
        p = -q
        alpha, f_new, g_new, _ = _strong_wolfe(
            value_and_grad, x, fx, gx, p, max_steps=20
        )
        if alpha == 0.0:
            break
        x_new = x + alpha * p
        s_i = x_new - x
        y_i = g_new - gx
        if float(np.vdot(s_i, y_i)) > 1e-10:
            s_hist.append(s_i)
            y_hist.append(y_i)
            if len(s_hist) > m:
                s_hist.pop(0)
                y_hist.pop(0)
        delta = abs(fx - f_new) / max(abs(f_new), 1.0)
        x, fx, gx = x_new, f_new, g_new
        n_iter = it + 1
        if delta <= tol:
            break

    params = x.reshape(shape)
    if multinomial:
        coef = params[:, :-1] / scale_h
        intercept = params[:, -1]
        if fit_intercept:
            intercept = intercept - intercept.mean()
    else:
        coef = (params[:-1] / scale_h).reshape(1, -1)
        intercept = params[-1:]
    return {
        "coefficients": coef.astype(np.float32),
        "intercepts": intercept.astype(np.float32),
        "n_iter": int(n_iter),
        "objective": float(fx),
    }


@functools.partial(jax.jit, static_argnames=("cosine",))
def _accum_kmeans(carry, centers, X, w, cosine: bool = False):
    """One batch of a streamed Lloyd iteration: accumulate per-cluster weighted sums,
    counts and inertia against FIXED centers."""
    sums, counts, inertia = carry
    if cosine:
        d2 = 1.0 - pdot(X, centers.T)
    else:
        x2 = jnp.sum(X * X, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1)
        d2 = jnp.maximum(x2 - 2.0 * pdot(X, centers.T) + c2, 0.0)
    assign = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    onehot = jax.nn.one_hot(assign, centers.shape[0], dtype=X.dtype) * w[:, None]
    return (
        sums + pdot(onehot.T, X),
        counts + jnp.sum(onehot, axis=0),
        inertia + jnp.sum(w * min_d2),
    )


def streaming_kmeans_fit(
    X: np.ndarray,
    w: Optional[np.ndarray],
    k: int,
    max_iter: int,
    tol: float,
    seed: int,
    batch_rows: int,
    mesh=None,
    metric: str = "euclidean",
    init_sample_rows: int = 1 << 18,
    float32: bool = True,
):
    """Out-of-core EXACT Lloyd: each iteration streams every batch through the device
    against fixed centers and accumulates (Σ one-hotᵀWX, counts, inertia); centers
    update once per full pass, so iterates match in-core Lloyd on the same init
    (not a minibatch approximation). Device residency is one batch + (k, d) stats —
    the KMeans analog of the reference's UVM/SAM large-dataset path
    (reference utils.py:184-241). Initialization runs in-core k-means|| on a row
    subsample bounded by `init_sample_rows`."""
    from .kmeans import _normalize_rows, kmeans_init
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    dt = np.float32 if float32 else np.float64
    n, d = X.shape
    cosine = metric == "cosine"
    if w is None:
        w = np.ones((n,), dt)

    # init on a subsample (rows are not assumed shuffled: use a strided sample)
    step = max(1, n // min(n, init_sample_rows))
    Xs = np.ascontiguousarray(X[::step], dtype=dt)
    ws = np.ascontiguousarray(w[::step], dtype=dt)
    Xs_j = jnp.asarray(Xs if not cosine else np.asarray(
        Xs / np.maximum(np.linalg.norm(Xs, axis=1, keepdims=True), 1e-30)))
    centers = jnp.asarray(
        kmeans_init(Xs_j, jnp.asarray(ws), k, "k-means||", 2, seed)
    )
    if cosine:
        centers = _normalize_rows(centers)

    inertia = np.inf
    n_iter = 0
    for it in range(max_iter):
        carry = (
            jnp.zeros((k, d), dt),
            jnp.zeros((k,), dt),
            jnp.zeros((), dt),
        )
        for s in range(0, n, batch_rows):
            e = min(s + batch_rows, n)
            Xb = np.ascontiguousarray(X[s:e], dtype=dt)
            if cosine:
                norms = np.linalg.norm(Xb, axis=1, keepdims=True)
                if np.any(norms <= 0):
                    raise ValueError(
                        "Cosine distance is not defined for zero-length vectors."
                    )
                Xb = Xb / norms
            wb = np.ascontiguousarray(w[s:e], dtype=dt)
            if mesh is not None:
                Xb, pad_w, (wb_p,) = pad_rows(Xb, mesh.devices.size, wb)
                Xb = shard_array(Xb, mesh)
                wb = shard_array(pad_w * wb_p, mesh)
            carry = _accum_kmeans(
                carry, centers, jnp.asarray(Xb), jnp.asarray(wb), cosine
            )
        sums, counts, inertia_j = carry
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1.0)[:, None],
            centers,
        )
        if cosine:
            new_centers = _normalize_rows(new_centers)
        shift2 = float(jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1)))
        centers = new_centers
        inertia = float(inertia_j)
        n_iter = it + 1
        if shift2 <= tol * tol:
            break

    return {
        "cluster_centers": np.asarray(centers),
        "inertia": inertia,
        "n_iter": n_iter,
    }
