#
# Out-of-core fitting: streamed sufficient-statistics accumulation.
#
# The reference fits datasets larger than device memory through RMM UVM/SAM managed
# memory (reference utils.py:184-241, SURVEY.md §2.5 last row). TPUs have no UVM;
# the TPU-native answer (SURVEY.md §7 "hard parts") is to stream host batches through
# the device and ACCUMULATE the model-sufficient statistics on device:
#   * PCA / LinearRegression: (XᵀWX, XᵀWy, Σwx, Σwy, Σw) accumulate exactly —
#     the fit result is IDENTICAL to the in-core path, with device residency bounded
#     by one batch + the d×d stats,
#   * KMeans: per-pass Lloyd over batches (minibatch-free exact variant: each
#     iteration streams all batches, accumulating one-hotᵀX sums and counts).
# Estimators switch to this path automatically when the padded design matrix would
# exceed `config` threshold SRML_TPU_STREAM_THRESHOLD_BYTES (see core/estimator.py).
#

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ._precision import pdot


@jax.jit
def _accum_linreg(carry, X, y, w):
    A, b, sx, sy, sw = carry
    Xw = X * w[:, None]
    return (
        A + pdot(Xw.T, X),
        b + pdot(Xw.T, y),
        sx + pdot(w, X),
        sy + jnp.sum(w * y),
        sw + jnp.sum(w),
    )


@jax.jit
def _accum_cov(carry, X, w):
    S2, sx, sw = carry
    return (
        S2 + pdot((X * w[:, None]).T, X),
        sx + pdot(w, X),
        sw + jnp.sum(w),
    )


def streaming_linreg_stats(
    X: np.ndarray,
    y: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Streamed (XᵀWX, XᵀWy, x̄, ȳ, Σw): the same statistics as
    ops/linear.linreg_sufficient_stats but with O(batch) device residency.
    Each batch is device_put (sharded over the mesh when given) and accumulated.
    dtype follows float32 (float64 additionally needs jax x64 mode, matching the
    in-core path's device behavior)."""
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    dt = np.float32 if float32 else np.float64
    d = X.shape[1]
    A = jnp.zeros((d, d), dt)
    b = jnp.zeros((d,), dt)
    sx = jnp.zeros((d,), dt)
    sy = jnp.zeros((), dt)
    sw = jnp.zeros((), dt)
    carry = (A, b, sx, sy, sw)

    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        Xb = np.ascontiguousarray(X[s:e], dtype=dt)
        yb = np.ascontiguousarray(y[s:e], dtype=dt)
        wb = (
            np.ones((e - s,), dt)
            if w is None
            else np.ascontiguousarray(w[s:e], dtype=dt)
        )
        if mesh is not None:
            Xb, pad_w, (yb_p, wb_p) = pad_rows(Xb, mesh.devices.size, yb, wb)
            Xb = shard_array(Xb, mesh)
            yb = shard_array(yb_p, mesh)
            wb = shard_array(pad_w * wb_p, mesh)
        carry = _accum_linreg(carry, jnp.asarray(Xb), jnp.asarray(yb), jnp.asarray(wb))
    A, b, sx, sy, sw = carry
    return A, b, sx / sw, sy / sw, sw


def streaming_covariance(
    X: np.ndarray,
    w: Optional[np.ndarray],
    batch_rows: int,
    mesh=None,
    float32: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Streamed weighted covariance (cov, mean, Σw) for PCA — the same math as
    ops/linalg.weighted_covariance, dtype per `float32` (see streaming_linreg_stats)."""
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    dt = np.float32 if float32 else np.float64
    d = X.shape[1]
    carry = (
        jnp.zeros((d, d), dt),
        jnp.zeros((d,), dt),
        jnp.zeros((), dt),
    )
    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        Xb = np.ascontiguousarray(X[s:e], dtype=dt)
        wb = (
            np.ones((e - s,), dt)
            if w is None
            else np.ascontiguousarray(w[s:e], dtype=dt)
        )
        if mesh is not None:
            Xb, pad_w, (wb_p,) = pad_rows(Xb, mesh.devices.size, wb)
            Xb = shard_array(Xb, mesh)
            wb = shard_array(pad_w * wb_p, mesh)
        carry = _accum_cov(carry, jnp.asarray(Xb), jnp.asarray(wb))
    S2, sx, sw = carry
    mean = sx / sw
    cov = (S2 - sw * jnp.outer(mean, mean)) / (sw - 1.0)
    return cov, mean, sw


@functools.partial(jax.jit, static_argnames=("cosine",))
def _accum_kmeans(carry, centers, X, w, cosine: bool = False):
    """One batch of a streamed Lloyd iteration: accumulate per-cluster weighted sums,
    counts and inertia against FIXED centers."""
    sums, counts, inertia = carry
    if cosine:
        d2 = 1.0 - pdot(X, centers.T)
    else:
        x2 = jnp.sum(X * X, axis=1, keepdims=True)
        c2 = jnp.sum(centers * centers, axis=1)
        d2 = jnp.maximum(x2 - 2.0 * pdot(X, centers.T) + c2, 0.0)
    assign = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    onehot = jax.nn.one_hot(assign, centers.shape[0], dtype=X.dtype) * w[:, None]
    return (
        sums + pdot(onehot.T, X),
        counts + jnp.sum(onehot, axis=0),
        inertia + jnp.sum(w * min_d2),
    )


def streaming_kmeans_fit(
    X: np.ndarray,
    w: Optional[np.ndarray],
    k: int,
    max_iter: int,
    tol: float,
    seed: int,
    batch_rows: int,
    mesh=None,
    metric: str = "euclidean",
    init_sample_rows: int = 1 << 18,
    float32: bool = True,
):
    """Out-of-core EXACT Lloyd: each iteration streams every batch through the device
    against fixed centers and accumulates (Σ one-hotᵀWX, counts, inertia); centers
    update once per full pass, so iterates match in-core Lloyd on the same init
    (not a minibatch approximation). Device residency is one batch + (k, d) stats —
    the KMeans analog of the reference's UVM/SAM large-dataset path
    (reference utils.py:184-241). Initialization runs in-core k-means|| on a row
    subsample bounded by `init_sample_rows`."""
    from .kmeans import _normalize_rows, kmeans_init
    from ..parallel.mesh import shard_array
    from ..parallel.partition import pad_rows

    dt = np.float32 if float32 else np.float64
    n, d = X.shape
    cosine = metric == "cosine"
    if w is None:
        w = np.ones((n,), dt)

    # init on a subsample (rows are not assumed shuffled: use a strided sample)
    step = max(1, n // min(n, init_sample_rows))
    Xs = np.ascontiguousarray(X[::step], dtype=dt)
    ws = np.ascontiguousarray(w[::step], dtype=dt)
    Xs_j = jnp.asarray(Xs if not cosine else np.asarray(
        Xs / np.maximum(np.linalg.norm(Xs, axis=1, keepdims=True), 1e-30)))
    centers = jnp.asarray(
        kmeans_init(Xs_j, jnp.asarray(ws), k, "k-means||", 2, seed)
    )
    if cosine:
        centers = _normalize_rows(centers)

    inertia = np.inf
    n_iter = 0
    for it in range(max_iter):
        carry = (
            jnp.zeros((k, d), dt),
            jnp.zeros((k,), dt),
            jnp.zeros((), dt),
        )
        for s in range(0, n, batch_rows):
            e = min(s + batch_rows, n)
            Xb = np.ascontiguousarray(X[s:e], dtype=dt)
            if cosine:
                norms = np.linalg.norm(Xb, axis=1, keepdims=True)
                if np.any(norms <= 0):
                    raise ValueError(
                        "Cosine distance is not defined for zero-length vectors."
                    )
                Xb = Xb / norms
            wb = np.ascontiguousarray(w[s:e], dtype=dt)
            if mesh is not None:
                Xb, pad_w, (wb_p,) = pad_rows(Xb, mesh.devices.size, wb)
                Xb = shard_array(Xb, mesh)
                wb = shard_array(pad_w * wb_p, mesh)
            carry = _accum_kmeans(
                carry, centers, jnp.asarray(Xb), jnp.asarray(wb), cosine
            )
        sums, counts, inertia_j = carry
        new_centers = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1.0)[:, None],
            centers,
        )
        if cosine:
            new_centers = _normalize_rows(new_centers)
        shift2 = float(jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1)))
        centers = new_centers
        inertia = float(inertia_j)
        n_iter = it + 1
        if shift2 <= tol * tol:
            break

    return {
        "cluster_centers": np.asarray(centers),
        "inertia": inertia,
        "n_iter": n_iter,
    }
