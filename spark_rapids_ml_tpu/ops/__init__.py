# JAX/XLA compute kernels (L1 of the layer map) — the TPU-native replacement for the
# reference's cuML/cuVS/treelite native backends (SURVEY.md §2.5).
