#
# True sparse device kernels — the TPU-native replacement for the reference's CSR
# training path (reference classification.py:1002-1055 trains LogisticRegressionMG
# directly on CSR; CSR ingest core.py:220-265; int64 index escalation for >1e9 nnz
# classification.py:960-966).
#
# TPU has no native CSR. The TPU-first formulation is ELL (padded row-wise) storage:
#   values  (n, r)  float   — r = max nonzeros per row
#   indices (n, r)  int32/64 — column ids, padding entries point at column 0 with
#                              value 0 so they contribute nothing
# Every sparse contraction becomes a dense-shaped gather/scatter XLA shards cleanly
# over the row axis of the mesh:
#   X v    = sum_r values[:, r] * v[indices[:, r]]            (gather  + reduce)
#   Xᵀ r   = scatter-add of values * r into a (d,) vector     (the transpose pass;
#            under SPMD the replicated output is all-reduced — psum where the
#            reference's NCCL allreduce sat)
# Memory is O(n·r) = O(nnz) for bounded row skew — never O(n·d).
#
# Solvers are MATRIX-FREE: logistic regression reuses the L-BFGS/FISTA machinery with
# gather-based losses (autodiff turns the gather into the scatter-add transpose);
# linear regression solves the normal equations by conjugate gradients with a centered
# matvec closure — the d×d Gram matrix is never materialized, so d can be large too.
#

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..observability.device import compiled_kernel

# int32 column/row indices are escalated to int64 past this many nonzeros, mirroring
# the reference's nnz>INT32_MAX fallback (classification.py:960-966)
INT32_LIMIT = 2**31 - 1


def csr_to_ell(
    csr: Any, float32: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized scipy CSR -> ELL conversion (no Python per-row loop).

    Returns (values (n, r), indices (n, r)). Padding cells are (0.0, col 0)."""
    csr = csr.tocsr()
    n, _ = csr.shape
    dtype = np.float32 if float32 else np.float64
    counts = np.diff(csr.indptr)
    r = int(counts.max()) if n else 0
    r = max(r, 1)
    idx_dtype = np.int64 if (csr.nnz > INT32_LIMIT or n > INT32_LIMIT) else np.int32
    if idx_dtype == np.int32 and dtype == np.float32 and csr.nnz:
        from ..native import csr_to_ell as native_csr_to_ell

        native = native_csr_to_ell(csr.indptr, csr.indices, csr.data, n, r)
        if native is not None:  # OpenMP host kernel (native/src/srml_native.cpp)
            return native
    values = np.zeros((n, r), dtype=dtype)
    indices = np.zeros((n, r), dtype=idx_dtype)
    if csr.nnz:
        rows = np.repeat(np.arange(n), counts)
        offsets = np.arange(csr.nnz) - np.repeat(csr.indptr[:-1], counts)
        values[rows, offsets] = csr.data
        indices[rows, offsets] = csr.indices
    return values, indices


def pad_ell_rows(
    values: np.ndarray,
    indices: np.ndarray,
    num_workers: int,
    *extra_row_aligned: Optional[np.ndarray],
    row_multiple: int = 8,
):
    """Row-pad ELL arrays to an equal, tile-friendly shard per worker (the sparse twin
    of parallel/partition.py pad_rows). Returns (values, indices, weight, extras)."""
    n = values.shape[0]
    chunk = num_workers * row_multiple
    padded = ((n + chunk - 1) // chunk) * chunk
    pad = padded - n
    weight = np.ones((padded,), dtype=values.dtype)
    if pad:
        weight[n:] = 0.0
        values = np.concatenate(
            [values, np.zeros((pad, values.shape[1]), values.dtype)], axis=0
        )
        indices = np.concatenate(
            [indices, np.zeros((pad, indices.shape[1]), indices.dtype)], axis=0
        )
    extras = []
    for e in extra_row_aligned:
        if e is None:
            extras.append(None)
        elif pad:
            extras.append(np.concatenate([e, np.zeros((pad,) + e.shape[1:], e.dtype)]))
        else:
            extras.append(e)
    return values, indices, weight, extras


# ---- ELL primitive contractions (all jit-inlined into the solvers) ----


@compiled_kernel("sparse.ell_matvec")
def ell_matvec(values: jax.Array, indices: jax.Array, v: jax.Array) -> jax.Array:
    """X @ v -> (n,)."""
    return jnp.sum(values * v[indices], axis=1)


@compiled_kernel("sparse.ell_matmat")
def ell_matmat(values: jax.Array, indices: jax.Array, M: jax.Array) -> jax.Array:
    """X @ M -> (n, k) for M (d, k)."""
    return jnp.einsum("nr,nrk->nk", values, M[indices])


def ell_rmatvec(values: jax.Array, indices: jax.Array, r: jax.Array, d: int) -> jax.Array:
    """Xᵀ @ r -> (d,). Scatter-add; XLA all-reduces the replicated output shards."""
    contrib = (values * r[:, None]).reshape(-1)
    return jnp.zeros((d,), values.dtype).at[indices.reshape(-1)].add(contrib)


def ell_rmatmat(values: jax.Array, indices: jax.Array, R: jax.Array, d: int) -> jax.Array:
    """Xᵀ @ R -> (d, k) for R (n, k)."""
    k = R.shape[1]
    contrib = (values[:, :, None] * R[:, None, :]).reshape(-1, k)
    return jnp.zeros((d, k), values.dtype).at[indices.reshape(-1)].add(contrib)


@compiled_kernel("sparse.weighted_moments", static_argnames=("d",))
def sparse_weighted_moments(
    values: jax.Array, indices: jax.Array, w: jax.Array, d: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(mean, var, wsum) per column with the unbiased (wsum-1) denominator — the
    sparse twin of ops/linalg.weighted_moments. Implicit zeros count toward the
    moments exactly as the dense kernel counts them."""
    wsum = jnp.sum(w)
    s1 = ell_rmatvec(values, indices, w, d)
    s2 = ell_rmatvec(values * values, indices, w, d)
    mean = s1 / wsum
    var = (s2 - wsum * mean * mean) / jnp.maximum(wsum - 1.0, 1.0)
    return mean, jnp.maximum(var, 0.0), wsum


def _matvec_lmax(matvec, d: int, dtype, n_steps: int = 16) -> jax.Array:
    """Matrix-free power iteration for the largest eigenvalue (FISTA Lipschitz)."""

    def body(i, v):
        v = matvec(v)
        return v / (jnp.linalg.norm(v) + 1e-30)

    v = jax.lax.fori_loop(0, n_steps, body, jnp.ones((d,), dtype) / jnp.sqrt(d))
    return jnp.dot(v, matvec(v))


# ---- sparse logistic regression (matrix-free L-BFGS / FISTA) ----


def _sparse_binomial_loss(values, indices, y, w, scale, reg_l2, fit_intercept):
    wsum = jnp.sum(w)

    def loss(params):
        coef_s, b = params[:-1], params[-1]
        z = ell_matvec(values, indices, coef_s / scale) + jnp.where(
            fit_intercept, b, 0.0
        )
        ce = jnp.sum(w * (jax.nn.softplus(z) - y * z)) / wsum
        return ce + 0.5 * reg_l2 * jnp.sum(coef_s * coef_s)

    return loss


def _sparse_multinomial_loss(values, indices, y_onehot, w, scale, reg_l2, fit_intercept):
    wsum = jnp.sum(w)

    def loss(params):
        coef_s, b = params[:, :-1], params[:, -1]
        z = ell_matmat(values, indices, (coef_s / scale).T) + jnp.where(
            fit_intercept, b, 0.0
        )
        logz = jax.nn.log_softmax(z, axis=1)
        ce = -jnp.sum(w * jnp.sum(y_onehot * logz, axis=1)) / wsum
        return ce + 0.5 * reg_l2 * jnp.sum(coef_s * coef_s)

    return loss


@compiled_kernel("sparse.qn_fit",
                 static_argnames=("d", "fit_intercept", "max_iter", "multinomial"))
def _sparse_qn_fit(
    values, indices, y_enc, w, scale, reg_l2, d: int, fit_intercept: bool,
    max_iter: int, tol, multinomial: bool,
):
    from .logistic import _run_lbfgs

    if multinomial:
        loss = _sparse_multinomial_loss(
            values, indices, y_enc, w, scale, reg_l2, fit_intercept
        )
        params0 = jnp.zeros((y_enc.shape[1], d + 1), values.dtype)
    else:
        loss = _sparse_binomial_loss(
            values, indices, y_enc, w, scale, reg_l2, fit_intercept
        )
        params0 = jnp.zeros((d + 1,), values.dtype)
    params, n_iter = _run_lbfgs(loss, params0, max_iter, tol)
    return params, n_iter, loss(params)


@compiled_kernel("sparse.fista_fit",
                 static_argnames=("d", "fit_intercept", "max_iter", "multinomial"))
def _sparse_fista_fit(
    values, indices, y_enc, w, scale, reg_l1, reg_l2, lipschitz, d: int,
    fit_intercept: bool, max_iter: int, tol, multinomial: bool,
):
    if multinomial:
        smooth = _sparse_multinomial_loss(
            values, indices, y_enc, w, scale, reg_l2, fit_intercept
        )
        params0 = jnp.zeros((y_enc.shape[1], d + 1), values.dtype)
        coef_mask = jnp.concatenate(
            [jnp.ones((y_enc.shape[1], d)), jnp.zeros((y_enc.shape[1], 1))], axis=1
        ).astype(values.dtype)
    else:
        smooth = _sparse_binomial_loss(
            values, indices, y_enc, w, scale, reg_l2, fit_intercept
        )
        params0 = jnp.zeros((d + 1,), values.dtype)
        coef_mask = jnp.concatenate([jnp.ones((d,)), jnp.zeros((1,))]).astype(
            values.dtype
        )

    grad_fn = jax.grad(smooth)
    step = 1.0 / lipschitz

    def prox(p):
        soft = jnp.sign(p) * jnp.maximum(jnp.abs(p) - step * reg_l1, 0.0)
        return jnp.where(coef_mask > 0, soft, p)

    def cond(state):
        _, _, _, it, delta = state
        return jnp.logical_and(it < max_iter, delta > tol)

    def body(state):
        pk, zk, tk, it, _ = state
        p_next = prox(zk - step * grad_fn(zk))
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        z_next = p_next + ((tk - 1.0) / t_next) * (p_next - pk)
        delta = jnp.max(jnp.abs(p_next - pk)) / (jnp.max(jnp.abs(p_next)) + 1e-12)
        return p_next, z_next, t_next, it + 1, delta

    state0 = (params0, params0, jnp.array(1.0, values.dtype), 0,
              jnp.array(jnp.inf, values.dtype))
    params, _, _, n_iter, _ = jax.lax.while_loop(cond, body, state0)
    return params, n_iter, smooth(params) + reg_l1 * jnp.sum(jnp.abs(params * coef_mask))


def sparse_logreg_fit(
    values: jax.Array,
    indices: jax.Array,
    d: int,
    y: jax.Array,
    w: jax.Array,
    n_classes: int,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    multinomial: bool,
) -> Dict[str, Any]:
    """Sparse twin of ops/logistic.logreg_fit — same objective, Spark-layout attrs.
    Standardization divides by the column std only (no centering — centering a sparse
    matrix would densify it; the reference's sparse path has the same convention,
    classification.py:1018-1028)."""
    if standardize:
        _, var, _ = sparse_weighted_moments(values, indices, w, d)
        scale = jnp.sqrt(var)
        scale = jnp.where(scale <= 0.0, 1.0, scale)
    else:
        scale = jnp.ones((d,), values.dtype)

    reg_l1 = reg * l1_ratio
    reg_l2 = reg * (1.0 - l1_ratio)

    if multinomial:
        y_enc = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=values.dtype) * (
            (w > 0)[:, None]
        )
    else:
        y_enc = y

    if reg_l1 > 0.0:
        wsum = jnp.sum(w)

        def gram_mv(v):
            xv = ell_matvec(values, indices, v / scale)
            return ell_rmatvec(values, indices, w * xv, d) / scale / wsum

        lmax = _matvec_lmax(gram_mv, d, values.dtype)
        lipschitz = (0.5 if multinomial else 0.25) * lmax + reg_l2 + 1e-12
        params, n_iter, obj = _sparse_fista_fit(
            values, indices, y_enc, w, scale, reg_l1, reg_l2, lipschitz, int(d),
            bool(fit_intercept), int(max_iter), float(tol), bool(multinomial),
        )
    else:
        params, n_iter, obj = _sparse_qn_fit(
            values, indices, y_enc, w, scale, reg_l2, int(d), bool(fit_intercept),
            int(max_iter), float(tol), bool(multinomial),
        )

    params = np.asarray(params, dtype=np.float64)
    scale_h = np.asarray(scale, dtype=np.float64)
    if multinomial:
        coef = params[:, :-1] / scale_h
        intercept = params[:, -1]
        if fit_intercept:
            intercept = intercept - intercept.mean()
    else:
        coef = (params[:-1] / scale_h).reshape(1, -1)
        intercept = params[-1:]
    return {
        "coefficients": coef.astype(np.float32),
        "intercepts": intercept.astype(np.float32),
        "n_iter": int(n_iter),
        "objective": float(obj),
    }


# ---- sparse linear regression (matrix-free CG / FISTA on normal equations) ----


@compiled_kernel("sparse.linreg_solve",
                 static_argnames=("d", "fit_intercept", "max_iter", "l1_zero"))
def _sparse_linreg_solve(
    values, indices, y, w, scale, d: int, reg, l1_ratio, fit_intercept: bool,
    max_iter: int, tol, l1_zero: bool,
):
    """Solve min 1/(2n)Σw(y - Xβ - b)² + λ(α‖β‖₁ + (1-α)/2‖β‖²) in σ-scaled space
    without materializing XᵀX. The centered+scaled Gram matvec is
      Aₛ v = D⁻¹ (Xᵀ W X - n x̄ x̄ᵀ) D⁻¹ v / n
    computed as two ELL passes plus rank-one mean corrections."""
    wsum = jnp.sum(w)
    xbar = ell_rmatvec(values, indices, w, d) / wsum
    ybar = jnp.sum(w * y) / wsum

    def gram_mv(v):
        u = v / scale
        xv = ell_matvec(values, indices, u)
        av = ell_rmatvec(values, indices, w * xv, d)
        if fit_intercept:
            av = av - wsum * xbar * jnp.dot(xbar, u)
        return (av / scale) / wsum

    by = ell_rmatvec(values, indices, w * y, d)
    if fit_intercept:
        by = by - wsum * xbar * ybar
    bs = (by / scale) / wsum

    l1 = reg * l1_ratio
    l2 = reg * (1.0 - l1_ratio)

    if l1_zero:
        # OLS/Ridge: CG on (Aₛ + λI) β = bₛ
        coef_s, _ = jax.scipy.sparse.linalg.cg(
            lambda v: gram_mv(v) + reg * v, bs, tol=1e-10, maxiter=200
        )
        n_iter = jnp.array(1, jnp.int32)
    else:
        L = _matvec_lmax(gram_mv, d, values.dtype) + l2 + 1e-12
        step = 1.0 / L

        def soft(x, t):
            return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)

        def cond(state):
            _, _, _, it, delta = state
            return jnp.logical_and(it < max_iter, delta > tol)

        def body(state):
            wk, zk, tk, it, _ = state
            grad = gram_mv(zk) - bs + l2 * zk
            w_next = soft(zk - step * grad, step * l1)
            t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
            z_next = w_next + ((tk - 1.0) / t_next) * (w_next - wk)
            delta = jnp.max(jnp.abs(w_next - wk)) / (jnp.max(jnp.abs(w_next)) + 1e-12)
            return w_next, z_next, t_next, it + 1, delta

        w0 = jnp.zeros((d,), values.dtype)
        state = (w0, w0, jnp.array(1.0, values.dtype), 0,
                 jnp.array(jnp.inf, values.dtype))
        coef_s, _, _, n_iter, _ = jax.lax.while_loop(cond, body, state)

    coef = coef_s / scale
    intercept = jnp.where(fit_intercept, ybar - jnp.dot(xbar, coef), 0.0)
    return coef, intercept, n_iter


def sparse_linreg_fit(
    values: jax.Array,
    indices: jax.Array,
    d: int,
    y: jax.Array,
    w: jax.Array,
    reg: float,
    l1_ratio: float,
    fit_intercept: bool,
    standardize: bool,
    max_iter: int,
    tol: float,
    extra_param_sets: Optional[List[Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """Sparse twin of ops/linear.linreg_fit. The moments pass is shared across param
    maps (single-pass fitMultiple); each map re-solves matrix-free."""
    if standardize:
        _, var, _ = sparse_weighted_moments(values, indices, w, d)
        scale = jnp.sqrt(var)
        scale = jnp.where(scale <= 0.0, 1.0, scale)
    else:
        scale = jnp.ones((d,), values.dtype)

    param_sets = extra_param_sets if extra_param_sets is not None else [
        {"alpha": reg, "l1_ratio": l1_ratio, "fit_intercept": fit_intercept,
         "max_iter": max_iter, "tol": tol}
    ]
    results = []
    for p in param_sets:
        p_reg = float(p.get("alpha", reg))
        p_l1r = float(p.get("l1_ratio", l1_ratio))
        coef, intercept, n_iter = _sparse_linreg_solve(
            values, indices, y, w, scale, int(d),
            jnp.asarray(p_reg, values.dtype), jnp.asarray(p_l1r, values.dtype),
            bool(p.get("fit_intercept", fit_intercept)),
            int(p.get("max_iter", max_iter)),
            float(p.get("tol", tol)),
            l1_zero=(p_reg == 0.0 or p_l1r == 0.0),
        )
        results.append(
            {
                "coefficients": np.asarray(coef),
                "intercept": float(intercept),
                "n_iter": int(n_iter),
            }
        )
    return results
