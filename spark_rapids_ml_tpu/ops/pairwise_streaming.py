#
# Out-of-core blocked-pairwise tier: exact kNN and DBSCAN with the DATASET
# HOST-RESIDENT — the broadcast-replicate leg of the UVM/SAM replacement
# (reference utils.py:184-241 gives cuML managed memory so its brute-force
# paths can exceed device memory; DBSCAN broadcasts the entire dataset to every
# worker, reference clustering.py:1103-1163; exact NN-MG scans all items per
# query batch, reference knn.py:763-774).
#
# TPU formulation: the device only ever sees a (query_block, item_block)
# distance tile plus O(block) running state. Both operand sets stream from host
# through the double-buffered `_prefetch` pipeline (ops/streaming.py) so the
# host slice/device_put of tile i+1 overlaps the matmul of tile i:
#   * exact kNN: running top-k merge per query block (concat + top_k on device),
#   * DBSCAN: streamed eps-neighbor counting (core mask), then min-label
#     propagation rounds — device computes per-tile min CORE-neighbor labels,
#     the hook + pointer-jump contraction runs on host numpy between rounds
#     (O(n) host work vs the O(n*d*n/blk) device pass it steers).
#
# Cost model (why query blocks are large): one full sweep moves
# ceil(n_q / query_block) * n_items * d * 4 bytes host->device. DBSCAN pays one
# sweep for the core mask + one per propagation round (typically <= ~10 with
# pointer jumping) + one for borders. The in-core paths (ops/knn.py,
# ops/dbscan.py) stay the fast path below stream_threshold_bytes; the model
# layer routes (models/dbscan.py, models/knn.py).
#
# Distances use the same FAST-precision `_block_sq_dists` as the in-core scans,
# so streamed-vs-incore results agree rank-for-rank away from exact ties.
#

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jax_compat import shard_map

from ..observability import (
    convergence as obs_convergence,
    counter_inc as obs_counter_inc,
    progress as obs_progress,
    span as obs_span,
)
from ..reliability import RetryPolicy, fault_point
from . import selection as _sel
from .knn import _block_sq_dists
from .selection import INVALID_D2, mask_invalid, merge_topk, select_topk
from .streaming import _prefetch
from ..observability.device import compiled_kernel

_I32MAX = np.iinfo(np.int32).max


@compiled_kernel("pairwise.tile_norms")
def _tile_norms(xb: jax.Array) -> jax.Array:
    """Σ x² of one item tile — computed ONCE at tile upload (and retained in
    the HBM batch cache alongside the tile), with the same reduce the distance
    kernels use, so cached replays are bitwise the in-kernel value. This is
    the streamed half of the norm hoist: no query-block sweep recomputes it
    (`knn.x2_tile_computes` counts actual computations; cached tiles add
    none)."""
    return jnp.sum(xb * xb, axis=1)


def _cached_tile(cache, cache_key, batch_index, build):
    """Item-block flavor of the shared cache-or-upload protocol
    (device_cache.cached_build): the fault point has already fired — replayed
    tiles stay fault-injectable."""
    from .device_cache import cached_build

    return cached_build(cache, cache_key, batch_index, "pairwise", build)


def _shard_blocks(X: np.ndarray, block: int, mesh, extras=None, cache=None,
                  cache_key=None):
    """Mesh variant of `_device_blocks`: each item block is SHARDED over the
    data axis (host->device traffic stays one copy of the data per sweep; the
    per-tile merge rides ICI collectives instead), row-aligned extras shard the
    same way. `block` must be a mesh-size multiple."""
    from ..parallel.partitioner import partitioner_for

    part = partitioner_for(mesh)
    n = X.shape[0]

    def gen():
        for s in range(0, n, block):
            e = min(s + block, n)
            fault_point("pairwise", batch=s // block)

            def build(s=s, e=e):
                xb = np.zeros((block,) + X.shape[1:], np.float32)
                xb[: e - s] = X[s:e]
                xd = part.shard(xb)
                obs_counter_inc("knn.x2_tile_computes")
                devs = [xd, _tile_norms(xd)]  # norm rides the cached tuple
                for a in extras or ():
                    ab = np.zeros((block,) + a.shape[1:], a.dtype)
                    ab[: e - s] = a[s:e]
                    devs.append(part.shard(ab))
                return (s, e - s, *devs)

            yield _cached_tile(cache, cache_key, s // block, build)

    return _prefetch(gen(), depth=1, site="pairwise")


@functools.lru_cache(maxsize=8)
def _mk_tile_topk_mesh(mesh, block: int, k: int, strategy: str, tile: int,
                       recall_target: float):
    """Sharded-items tile merge: local top-k per shard (configured selection
    strategy), all_gather the candidate pools over ICI, fold into the
    replicated running top-k (always exact — merge_topk) — the same
    local-then-merge shape as ops/knn.py::_knn_local_then_merge_fn."""
    from ..parallel.mesh import DATA_AXIS
    from ..parallel.partitioner import partitioner_for

    part = partitioner_for(mesh)
    n_dev = mesh.devices.size
    shard_rows = block // n_dev
    k_loc = min(k, shard_rows)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            part.state_spec(), part.data_spec(2), part.data_spec(1),
            part.state_spec(), part.state_spec(), part.state_spec(),
            part.state_spec(),
        ),
        out_specs=(part.state_spec(), part.state_spec()),
        check_vma=False,
    )
    def f(qb, xb_local, x2_local, nv, base, best_d, best_i):
        rank = jax.lax.axis_index(DATA_AXIS)
        grow = rank * shard_rows + jnp.arange(shard_rows, dtype=jnp.int32)
        d2 = _block_sq_dists(qb, xb_local, x2_local)
        d2 = mask_invalid(d2, (grow < nv)[None, :])
        d2_sel, pos = select_topk(
            d2, k_loc, strategy=strategy, tile=tile, recall_target=recall_target
        )
        ids = base + grow[pos]
        d_all = jax.lax.all_gather(d2_sel, DATA_AXIS, axis=1)
        i_all = jax.lax.all_gather(ids, DATA_AXIS, axis=1)
        cat_d = jnp.concatenate([best_d, d_all.reshape(qb.shape[0], -1)], axis=1)
        cat_i = jnp.concatenate([best_i, i_all.reshape(qb.shape[0], -1)], axis=1)
        return merge_topk(cat_d, cat_i, k)

    return f


@functools.lru_cache(maxsize=8)
def _mk_tile_count_mesh(mesh, block: int):
    from ..parallel.mesh import DATA_AXIS
    from ..parallel.partitioner import partitioner_for

    part = partitioner_for(mesh)
    n_dev = mesh.devices.size
    shard_rows = block // n_dev

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            part.state_spec(), part.data_spec(2), part.data_spec(1),
            part.state_spec(), part.state_spec(),
        ),
        out_specs=part.state_spec(),
        check_vma=False,
    )
    def f(qb, xb_local, x2_local, nv, eps2):
        rank = jax.lax.axis_index(DATA_AXIS)
        grow = rank * shard_rows + jnp.arange(shard_rows, dtype=jnp.int32)
        d2 = _block_sq_dists(qb, xb_local, x2_local)
        cnt = jnp.sum((d2 <= eps2) & (grow < nv)[None, :], axis=1).astype(jnp.int32)
        return jax.lax.psum(cnt, DATA_AXIS)

    return f


@functools.lru_cache(maxsize=8)
def _mk_tile_minlabel_mesh(mesh, block: int):
    from ..parallel.mesh import DATA_AXIS
    from ..parallel.partitioner import partitioner_for

    part = partitioner_for(mesh)
    n_dev = mesh.devices.size
    shard_rows = block // n_dev

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            part.state_spec(), part.data_spec(2), part.data_spec(1),
            part.data_spec(1), part.data_spec(1),
            part.state_spec(), part.state_spec(),
        ),
        out_specs=part.state_spec(),
        check_vma=False,
    )
    def f(qb, xb_local, x2_local, labels_local, core_local, nv, eps2):
        rank = jax.lax.axis_index(DATA_AXIS)
        grow = rank * shard_rows + jnp.arange(shard_rows, dtype=jnp.int32)
        d2 = _block_sq_dists(qb, xb_local, x2_local)
        neigh = (d2 <= eps2) & core_local[None, :] & (grow < nv)[None, :]
        m = jnp.min(jnp.where(neigh, labels_local[None, :], _I32MAX), axis=1)
        return jax.lax.pmin(m, DATA_AXIS)

    return f


def _mesh_or_none(mesh):
    return mesh if (mesh is not None and mesh.devices.size > 1) else None


def _round_block(block: int, mesh) -> int:
    n_dev = mesh.devices.size
    return max(n_dev, ((block + n_dev - 1) // n_dev) * n_dev)


def _device_blocks(X: np.ndarray, block: int, extras=None, cache=None,
                   cache_key=None):
    """Yield (start, n_valid, device_block, *device_extras) with the ragged tail
    zero-padded to `block` (ONE compiled tile shape for the whole stream).
    `extras`: list of row-aligned host arrays uploaded alongside (labels, masks)."""
    from ..parallel.partitioner import put_device_local

    n = X.shape[0]

    def gen():
        for s in range(0, n, block):
            e = min(s + block, n)
            fault_point("pairwise", batch=s // block)

            def build(s=s, e=e):
                xb = np.zeros((block,) + X.shape[1:], np.float32)
                xb[: e - s] = X[s:e]
                xd = put_device_local(xb)
                obs_counter_inc("knn.x2_tile_computes")
                devs = [xd, _tile_norms(xd)]  # norm rides the cached tuple
                for a in extras or ():
                    ab = np.zeros((block,) + a.shape[1:], a.dtype)
                    ab[: e - s] = a[s:e]
                    devs.append(put_device_local(ab))
                return (s, e - s, *devs)

            yield _cached_tile(cache, cache_key, s // block, build)

    return _prefetch(gen(), depth=1, site="pairwise")


@compiled_kernel("pairwise.tile_topk_merge",
                 static_argnames=("k", "strategy", "tile", "recall_target"))
def _tile_topk_merge(qb, xb, x2b, nv_items, base_id, best_d, best_i, k: int,
                     strategy: str, tile: int, recall_target: float):
    """Merge one (qb, xb) tile into the per-query running top-k: configured
    selection over the tile's candidates (the wide axis — where the strategy
    wins), then an exact fold into the carried pool (an approximate fold
    would drop carried candidates, compounding per tile)."""
    d2 = _block_sq_dists(qb, xb, x2b)
    iv = jnp.arange(xb.shape[0]) < nv_items
    d2 = mask_invalid(d2, iv[None, :])
    cand_d, pos = select_topk(
        d2, min(k, xb.shape[0]), strategy=strategy, tile=tile,
        recall_target=recall_target,
    )
    cand_i = base_id + pos
    cat_d = jnp.concatenate([best_d, cand_d], axis=1)
    cat_i = jnp.concatenate([best_i, cand_i], axis=1)
    return merge_topk(cat_d, cat_i, k)


def streaming_exact_knn(
    Q: np.ndarray,
    X: np.ndarray,
    k: int,
    query_block: int = 4096,
    item_block: int = 131072,
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact kNN with HOST-RESIDENT items: returns (euclidean distances, item
    row indices), matching ops/knn.py::exact_knn_single rank-for-rank (same
    FAST-precision distance form) at any dataset size. Device residency is one
    query block + one item block + the (query_block, k) running state. With a
    multi-device `mesh`, item blocks shard over the data axis (one host copy of
    the data per sweep; the per-tile candidate merge all_gathers over ICI).

    The item stream is swept once PER QUERY BLOCK — the HBM batch cache
    (ops/device_cache.py) retains the tiles the first sweep uploads, so the
    remaining ceil(nq/query_block)-1 sweeps replay from HBM (prefix-cached when
    the item set exceeds the budget)."""
    from .device_cache import batch_cache

    n, d = X.shape
    k_eff = min(k, n)
    nq = Q.shape[0]
    mesh = _mesh_or_none(mesh)
    strategy, sel_tile, rt = _sel.resolve(min(item_block, n), k_eff, None)
    _sel.record_selection(strategy, site="pairwise_knn")
    with batch_cache() as cache:
        if mesh is not None:
            item_block = _round_block(item_block, mesh)
            ckey = (
                cache.stream_key((X,), item_block, mesh, site="pairwise")
                if cache is not None
                else None
            )
            tile = _mk_tile_topk_mesh(
                mesh, item_block, k_eff, strategy, sel_tile, rt
            )

            def merge(qb, xb, x2b, nv, s, bd, bi):
                return tile(qb, xb, x2b, jnp.int32(nv), jnp.int32(s), bd, bi)

            def blocks():
                return _shard_blocks(
                    X, item_block, mesh, cache=cache, cache_key=ckey
                )
        else:
            ckey = (
                cache.stream_key((X,), item_block, None, site="pairwise")
                if cache is not None
                else None
            )

            def merge(qb, xb, x2b, nv, s, bd, bi):
                return _tile_topk_merge(
                    qb, xb, x2b, nv, s, bd, bi, k_eff, strategy, sel_tile, rt
                )

            def blocks():
                return _device_blocks(X, item_block, cache=cache, cache_key=ckey)

        out_d = np.empty((nq, k_eff), np.float32)
        out_i = np.empty((nq, k_eff), np.int64)
        policy = RetryPolicy.from_config()
        for qs in range(0, nq, query_block):
            qe = min(qs + query_block, nq)

            def _scan_query_block(qs=qs, qe=qe):
                # running state re-initializes per attempt, so a transient tile
                # failure replays this query block exactly (deterministic merge)
                qb = jnp.asarray(np.ascontiguousarray(Q[qs:qe], np.float32))  # noqa: fence/host-staging-copy
                best_d = jnp.full((qe - qs, k_eff), INVALID_D2, jnp.float32)
                best_i = jnp.full((qe - qs, k_eff), -1, jnp.int32)
                for s, nv, xb, x2b in blocks():
                    best_d, best_i = merge(qb, xb, x2b, nv, s, best_d, best_i)
                ids = np.asarray(best_i).astype(np.int64)
                if strategy == "approx":
                    # the re-rank invariant (design.md §5b) holds out-of-core
                    # too: the winner pool's FAST expansion distances are
                    # replaced by exact f32 distances recomputed against the
                    # HOST items (the pool is (block, k) — the gather is tiny
                    # next to the sweep), then re-sorted
                    with obs_span(
                        "knn.rerank", {"start": qs, "rows": qe - qs}
                    ):
                        qh = np.ascontiguousarray(Q[qs:qe], np.float32)  # noqa: fence/host-staging-copy
                        vecs = X[ids].astype(np.float32, copy=False)  # noqa: fence/host-staging-copy
                        d2 = ((qh[:, None, :] - vecs) ** 2).sum(-1)
                        order = np.argsort(d2, axis=1, kind="stable")
                        ids = np.take_along_axis(ids, order, axis=1)
                        out_d[qs:qe] = np.sqrt(
                            np.take_along_axis(d2, order, axis=1)
                        )
                else:
                    out_d[qs:qe] = np.sqrt(np.asarray(best_d))
                out_i[qs:qe] = ids

            # one trace span per query-block sweep over the item stream: the
            # per-fit report then attributes time to sweeps (with any item-tile
            # `stream.ingest` uploads as children) instead of one opaque scan
            with obs_span(
                "pairwise.query_block", {"start": qs, "rows": qe - qs}
            ):
                policy.run(_scan_query_block, site="pairwise")
            obs_progress(
                "pairwise.query_blocks", -(-qe // query_block),
                -(-nq // query_block), unit="blocks",
            )
    return out_d, out_i


@compiled_kernel("pairwise.tile_count")
def _tile_count(qb, xb, x2b, nv_items, eps2):
    d2 = _block_sq_dists(qb, xb, x2b)
    iv = jnp.arange(xb.shape[0]) < nv_items
    return jnp.sum((d2 <= eps2) & iv[None, :], axis=1).astype(jnp.int32)


@compiled_kernel("pairwise.tile_min_core_label")
def _tile_min_core_label(qb, xb, x2b, labels_b, core_b, nv_items, eps2):
    d2 = _block_sq_dists(qb, xb, x2b)
    iv = jnp.arange(xb.shape[0]) < nv_items
    neigh = (d2 <= eps2) & core_b[None, :] & iv[None, :]
    return jnp.min(jnp.where(neigh, labels_b[None, :], _I32MAX), axis=1)


def _streamed_min_core_labels(
    X: np.ndarray,
    labels: np.ndarray,
    core: np.ndarray,
    eps2: float,
    query_block: int,
    item_block: int,
    mesh=None,
    cache=None,
) -> np.ndarray:
    """One full streamed sweep: per row, min label among its CORE eps-neighbors
    (int32 max where none) — the out-of-core analog of
    ops/dbscan.py::_min_core_neighbor_labels. The tile key includes the labels/
    core arrays, so tiles replay across the query blocks of ONE round and the
    next round's fresh labels naturally LRU-evict them."""
    n = X.shape[0]
    ckey = (
        cache.stream_key((X, labels, core), item_block, mesh, site="pairwise")
        if cache is not None
        else None
    )
    if mesh is not None:
        tile_fn = _mk_tile_minlabel_mesh(mesh, item_block)

        def tile(qb, xb, x2b, lb, cb, nv):
            return tile_fn(qb, xb, x2b, lb, cb, jnp.int32(nv), jnp.float32(eps2))

        def blocks():
            return _shard_blocks(
                X, item_block, mesh, extras=[labels, core],
                cache=cache, cache_key=ckey,
            )
    else:
        def tile(qb, xb, x2b, lb, cb, nv):
            return _tile_min_core_label(qb, xb, x2b, lb, cb, nv, eps2)

        def blocks():
            return _device_blocks(
                X, item_block, extras=[labels, core],
                cache=cache, cache_key=ckey,
            )

    mins = np.full((n,), _I32MAX, np.int32)
    policy = RetryPolicy.from_config()
    for qs in range(0, n, query_block):
        qe = min(qs + query_block, n)

        def _minlabel_query_block(qs=qs, qe=qe):
            qb = jnp.asarray(np.ascontiguousarray(X[qs:qe], np.float32))  # noqa: fence/host-staging-copy
            acc = jnp.full((qe - qs,), _I32MAX, jnp.int32)
            for s, nv, xb, x2b, lb, cb in blocks():
                acc = jnp.minimum(acc, tile(qb, xb, x2b, lb, cb, nv))
            mins[qs:qe] = np.asarray(acc)

        policy.run(_minlabel_query_block, site="pairwise")
    return mins


def streaming_dbscan_fit_predict(
    X: np.ndarray,
    eps: float,
    min_samples: int,
    metric: str = "euclidean",
    max_rounds: int = 64,
    query_block: int = 8192,
    item_block: int = 131072,
    mesh=None,
) -> np.ndarray:
    """DBSCAN with the dataset host-resident; labels match
    ops/dbscan.py::dbscan_fit_predict (noise = -1, clusters compacted in
    first-appearance order). The propagation loop is host-driven: each round
    pays one streamed pairwise sweep, then the hook + two pointer-jumping
    contractions run in numpy (exactly ops/dbscan.py::_hook_and_jump's math).

    ONE batch cache spans the whole fit: the core-mask pass and every
    propagation round sweep the same item tiles per query block, so tiles
    upload once per (round, labels) key and replay from HBM across that
    round's query blocks, with LRU eviction as rounds retire their labels."""
    from .device_cache import batch_cache

    with batch_cache() as cache:
        return _streaming_dbscan_fit_predict(
            X, eps, min_samples, metric, max_rounds, query_block, item_block,
            mesh, cache,
        )


def _streaming_dbscan_fit_predict(
    X, eps, min_samples, metric, max_rounds, query_block, item_block, mesh, cache,
):
    from .dbscan import _compact_labels

    X = np.ascontiguousarray(np.asarray(X), dtype=np.float32)  # noqa: fence/host-staging-copy
    n = X.shape[0]
    if metric == "cosine":
        norms = np.linalg.norm(X, axis=1, keepdims=True)
        if float(norms.min()) <= 0.0:
            raise ValueError(
                "Cosine distance is not defined for zero-length vectors; the "
                "input contains an all-zero feature row."
            )
        # one host-side normalized copy; unavoidable without it: every tile
        # would renormalize the same rows ceil(n/query_block) times
        X = X / np.maximum(norms, 1e-30)
        eps2 = 2.0 * float(eps)
    else:
        eps2 = float(eps) * float(eps)

    mesh = _mesh_or_none(mesh)
    if mesh is not None:
        item_block = _round_block(item_block, mesh)
    count_key = (
        cache.stream_key((X,), item_block, mesh, site="pairwise")
        if cache is not None
        else None
    )
    if mesh is not None:
        count_fn = _mk_tile_count_mesh(mesh, item_block)

        def count_tile(qb, xb, x2b, nv):
            return count_fn(qb, xb, x2b, jnp.int32(nv), jnp.float32(eps2))

        def count_blocks():
            return _shard_blocks(
                X, item_block, mesh, cache=cache, cache_key=count_key
            )
    else:
        def count_tile(qb, xb, x2b, nv):
            return _tile_count(qb, xb, x2b, nv, eps2)

        def count_blocks():
            return _device_blocks(
                X, item_block, cache=cache, cache_key=count_key
            )

    # pass 1: streamed core mask
    core = np.empty((n,), bool)
    policy = RetryPolicy.from_config()
    for qs in range(0, n, query_block):
        qe = min(qs + query_block, n)

        def _core_query_block(qs=qs, qe=qe):
            qb = jnp.asarray(np.ascontiguousarray(X[qs:qe], np.float32))  # noqa: fence/host-staging-copy
            acc = jnp.zeros((qe - qs,), jnp.int32)
            for s, nv, xb, x2b in count_blocks():
                acc = acc + count_tile(qb, xb, x2b, nv)
            core[qs:qe] = np.asarray(acc) >= int(min_samples)

        policy.run(_core_query_block, site="pairwise")
        obs_progress(
            "dbscan.core_blocks", -(-qe // query_block),
            -(-n // query_block), unit="blocks",
        )

    # min-label propagation with host-side hook + pointer jumping
    labels = np.arange(n, dtype=np.int32)
    mins = None
    converged = False
    for round_no in range(max_rounds):
        mins = _streamed_min_core_labels(
            X, labels, core, eps2, query_block, item_block, mesh=mesh,
            cache=cache,
        )
        new = np.where(core, np.minimum(labels, mins), labels).astype(np.int32)
        new = new[new]
        new = new[new]
        # §6g: round-level progress (total = the max_rounds bound; the loop
        # usually converges much earlier) + a convergence record tracking how
        # many labels the round still moved
        obs_progress("dbscan.rounds", round_no + 1, max_rounds, unit="rounds")
        obs_convergence(
            "dbscan", round_no + 1,
            labels_changed=int(np.count_nonzero(new != labels)),
        )
        if np.array_equal(new, labels):
            converged = True
            break
        labels = new

    # border pass + compaction, shared with the in-core path. On the converged
    # exit the last round's `mins` was computed from exactly these labels, so
    # re-streaming the dataset (the dominant cost unit) would recompute it
    # verbatim; only the max_rounds-exhausted path needs a fresh sweep.
    if converged and mins is not None:
        border_min = mins
    else:
        border_min = _streamed_min_core_labels(
            X, labels, core, eps2, query_block, item_block, mesh=mesh,
            cache=cache,
        )
    out = np.full((n,), -1, dtype=np.int64)
    out[core] = labels[core]
    border = (~core) & (border_min < _I32MAX)
    out[border] = border_min[border]
    return _compact_labels(out)
