#
# HBM-resident batch cache for multi-pass streamed fits.
#
# The reference gets implicit cross-pass data reuse from cuDF/UVM residency on
# GPU (reference utils.py:184-241: once a managed-memory page is on device it
# stays there across Lloyd iterations and L-BFGS evaluations). The TPU rebuild
# has no UVM: every pass of a multi-pass streamed fit re-ran the full host
# slice -> pad -> shard_array ingest, so multi-pass fits were ingest-bound
# rather than compute-bound (arXiv:1612.01437 identifies exactly this
# host<->accelerator traffic as the dominant cost of Spark ML loops; DrJAX,
# arXiv:2403.07128, keeps sharded operands device-resident across MapReduce
# rounds the same way).
#
# This module makes the reuse explicit: on pass 1 of a multi-pass streamed fit
# the sharded device tuples yielded by ops/streaming._batch_stream (and the
# pairwise/item-tile generators) are RETAINED in HBM; passes 2..N replay them
# without touching the host. Contract:
#
#   * whole-batch granularity — a batch is cached as the exact tuple the
#     stream yielded, so replayed passes run the identical device ops on the
#     identical buffers and results are BIT-IDENTICAL to pure streaming
#     (tests/test_device_cache.py asserts this per estimator),
#   * keyed by (dataset identity, batch geometry, mesh shape) — dataset
#     identity pins the source host arrays for the cache lifetime so Python
#     id() reuse can never alias two datasets to one key,
#   * HBM byte budget (`cache.hbm_budget_bytes` / SRML_TPU_CACHE_BUDGET) with
#     LRU eviction ACROSS streams and prefix semantics WITHIN one: when a
#     dataset exceeds the budget the leading batches stay resident and the
#     tail streams every pass — that fraction of uploads is still saved, and
#     a stream never evicts its own batches (sequential replay would thrash),
#   * transparent to reliability: fault-injection sites fire before the cache
#     lookup (replayed batches are still fault-injectable) and checkpoint-
#     resume replays hits and misses through the same cursor arithmetic.
#
# Lifecycle: core/estimator.py opens a `batch_cache()` scope around each
# streamed fit and frees it at fit exit; ops-level multi-pass loops call
# `batch_cache()` themselves and transparently reuse the estimator's scope
# when one is active (direct ops calls get a fit-local cache instead).
#
# Observability (observability/ registry; legacy profiling.counter_totals()
# still surfaces everything): `cache.hits`, `cache.misses`, `cache.evictions`
# are monotone Counters; `cache.bytes_resident` is a REAL Gauge (inc on
# retain, dec on evict/close — it was negative counter increments before the
# typed registry existed, where a missed decrement was undetectable by type).
# Evictions also land as structured `cache_evict` events in the active FitRun.
# Host->device uploads are counted by the stream itself
# (`stream.upload_batches` / `stream.upload_bytes`) and each upload appears as
# a `stream.ingest` span in the fit trace tree, so "pass 2+ performs zero
# uploads" is directly assertable from a fit report.
#

from __future__ import annotations

import contextlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterator, Optional, Sequence, Tuple

from .. import config as _config
from .. import observability as _obs
from .. import profiling
from ..utils import get_logger

_logger = get_logger("ops.device_cache")

_tls = threading.local()

# (stream_key, batch_index) -> (batch_tuple, nbytes)
_EntryKey = Tuple[Any, int]


class DeviceBatchCache:
    """Single-owner (one fit, one thread) replay cache of streamed device
    batches. Use through `batch_cache()`; the raw class is exposed for the
    unit tests that pin down hit/miss/eviction accounting."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self.bytes_resident = 0
        self._entries: "OrderedDict[_EntryKey, Tuple[tuple, int]]" = OrderedDict()
        # stream key -> source host arrays: pins the sources so id() reuse
        # cannot alias a freed dataset's key to a new array's key while this
        # cache lives
        self._key_pins: Dict[Any, Sequence[Any]] = {}
        # stream key -> pin count: a pinned stream's entries are NEVER evicted
        # (the serving plane pins a model's weights for the duration of each
        # in-flight batch; before this existed nothing stopped LRU pressure
        # from evicting a tuple a concurrent reader still referenced)
        self._pin_counts: Dict[Any, int] = {}

    def stream_key(self, arrays: Sequence[Any], batch_rows: int, mesh,
                   site: str = "ingest") -> Any:
        """Identity of one replayable stream: the source arrays (by pinned
        id), the batch geometry, and the mesh TOPOLOGY — axis shape and names,
        not just the device set: two meshes over the same devices shard
        differently, and a tuple sharded for one must never replay on the
        other."""
        mesh_id: Tuple[Any, ...]
        if mesh is None:
            mesh_id = ("nomesh",)
        else:
            mesh_id = (
                tuple(mesh.devices.shape),
                tuple(str(a) for a in mesh.axis_names),
                tuple(int(d.id) for d in mesh.devices.flat),
            )
        key = (site, tuple(id(a) for a in arrays), int(batch_rows), mesh_id)
        self._key_pins.setdefault(key, tuple(arrays))
        return key

    def contains(self, stream_key: Any, batch_index: int) -> bool:
        """Residency probe: no hit/miss counting, no LRU touch (stats views
        must not promote an entry they only looked at)."""
        return (stream_key, batch_index) in self._entries

    def get(self, stream_key: Any, batch_index: int) -> Optional[tuple]:
        """Resident batch tuple, or None (counted as hit/miss)."""
        entry = self._entries.get((stream_key, batch_index))
        if entry is None:
            profiling.count("cache.misses")
            return None
        self._entries.move_to_end((stream_key, batch_index))
        profiling.count("cache.hits")
        return entry[0]

    def pin(self, stream_key: Any) -> None:
        """Hold this stream's entries resident: eviction skips pinned streams
        (counted as `cache.evict_skipped_pinned`). Pins nest — a stream is
        evictable again only once every pin() has been matched by unpin()."""
        self._pin_counts[stream_key] = self._pin_counts.get(stream_key, 0) + 1

    def unpin(self, stream_key: Any) -> None:
        n = self._pin_counts.get(stream_key, 0) - 1
        if n <= 0:
            self._pin_counts.pop(stream_key, None)
        else:
            self._pin_counts[stream_key] = n

    def is_pinned(self, stream_key: Any) -> bool:
        return self._pin_counts.get(stream_key, 0) > 0

    def put(self, stream_key: Any, batch_index: int, batch: tuple) -> bool:
        """Retain a freshly-streamed batch. Evicts LRU entries of OTHER
        streams under budget pressure; never evicts the inserting stream's own
        batches (prefix semantics: cache the head, stream the tail) and never
        evicts a PINNED stream's batches (a reader is mid-flight on them —
        each skip counts `cache.evict_skipped_pinned`)."""
        if (stream_key, batch_index) in self._entries:
            return True  # a resumed pass replayed a batch already resident
        nbytes = sum(int(getattr(a, "nbytes", 0)) for a in batch)
        if nbytes > self.budget_bytes:
            return False
        # skipped pinned entries count ONCE per put() — the eviction loop
        # rescans from the head every pass, and re-counting the same pinned
        # entry each pass would overstate pin pressure E-fold
        skip_counted: set = set()
        while self.bytes_resident + nbytes > self.budget_bytes:
            victim = None
            for k in self._entries:
                if k[0] == stream_key:
                    continue
                if self.is_pinned(k[0]):
                    if k not in skip_counted:
                        skip_counted.add(k)
                        profiling.count("cache.evict_skipped_pinned")
                    continue
                victim = k
                break
            if victim is None:
                return False  # only own-prefix/pinned entries remain: stream
            self._evict(victim)
        self._entries[(stream_key, batch_index)] = (batch, nbytes)
        self.bytes_resident += nbytes
        _obs.gauge_inc("cache.bytes_resident", nbytes)
        return True

    def replace(self, stream_key: Any, batch_index: int, batch: tuple) -> bool:
        """Swap one entry's tuple in place, PRESERVING its pin counts — the
        serving plane's weight refresh (§7b) runs while other batches may
        hold pins on the same stream; a drop_stream + put would pop the pin
        bookkeeping and leave the fresh weights evictable mid-batch."""
        key = (stream_key, batch_index)
        old = self._entries.pop(key, None)
        if old is not None:
            _, old_bytes = old
            self.bytes_resident -= old_bytes
            _obs.gauge_dec("cache.bytes_resident", old_bytes)
        return self.put(stream_key, batch_index, batch)

    def _evict(self, entry_key: _EntryKey) -> None:
        _, nbytes = self._entries.pop(entry_key)
        self.bytes_resident -= nbytes
        profiling.count("cache.evictions")
        _obs.gauge_dec("cache.bytes_resident", nbytes)
        _obs.event("cache_evict", nbytes=nbytes, site=str(entry_key[0][0]))

    def resident_batches(self) -> int:
        return len(self._entries)

    def drop_stream(self, stream_key: Any) -> int:
        """Release every entry of one stream (lifecycle free — NOT counted as
        eviction pressure) and its source/pin bookkeeping. Returns the bytes
        released. The serving plane uses this when a model unregisters."""
        freed = 0
        for ek in [k for k in self._entries if k[0] == stream_key]:
            _, nbytes = self._entries.pop(ek)
            freed += nbytes
        if freed:
            self.bytes_resident -= freed
            _obs.gauge_dec("cache.bytes_resident", freed)
        self._key_pins.pop(stream_key, None)
        self._pin_counts.pop(stream_key, None)
        return freed

    def close(self) -> None:
        """Drop every device reference (the HBM frees once the accumulators
        release their last use) and unpin the sources. Not counted as
        evictions — lifecycle frees are not budget pressure."""
        if self.bytes_resident:
            _obs.gauge_dec("cache.bytes_resident", self.bytes_resident)
        self.bytes_resident = 0
        self._entries.clear()
        self._key_pins.clear()
        self._pin_counts.clear()


def cached_build(cache: Optional[DeviceBatchCache], cache_key: Any,
                 batch_index: int, site: str, build: Any) -> tuple:
    """THE cache-or-upload protocol, shared by every streamed batch/tile
    generator (ops/streaming.py::_batch_stream, the pairwise item-block
    generators): a resident batch replays as-is; otherwise `build()` runs the
    host slice/pad/upload, its cost lands in `stream.ingest_s.<site>`
    (span_totals) and the `stream.upload_batches`/`stream.upload_bytes`
    counters, and the fresh batch is retained budget-permitting. One
    implementation so the "zero pass-2 uploads" accounting CI asserts on can
    never drift between the tiers. The caller's fault point fires BEFORE this
    (replayed batches stay fault-injectable)."""
    import time

    if cache is not None:
        hit = cache.get(cache_key, batch_index)
        if hit is not None:
            return hit
    t0 = time.perf_counter()
    # structured span: each actual upload is a `stream.ingest` node in the fit
    # trace tree (child of the pass that triggered it), on top of the legacy
    # per-site totals + per-batch latency histogram add_time feeds below
    with _obs.span("stream.ingest", {"site": site, "batch": batch_index}):
        batch = build()
    # srml-metric: stream.ingest_s — per-site span family (dynamic suffix)
    profiling.add_time(f"stream.ingest_s.{site}", time.perf_counter() - t0)
    profiling.count("stream.upload_batches")
    profiling.count(
        "stream.upload_bytes",
        sum(int(a.nbytes) for a in batch if hasattr(a, "nbytes")),
    )
    if cache is not None:
        cache.put(cache_key, batch_index, batch)
    return batch


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def active_cache() -> Optional[DeviceBatchCache]:
    """The innermost open batch_cache() scope on this thread, if any."""
    stack = _stack()
    return stack[-1] if stack else None


@contextlib.contextmanager
def batch_cache() -> Iterator[Optional[DeviceBatchCache]]:
    """Per-fit cache scope. The OUTERMOST scope owns the cache (creates it
    from config, frees it on exit — core/estimator.py opens one around each
    streamed fit); nested scopes (the multi-pass loops in ops/) reuse the
    owner's cache so one fit's passes share residency. Yields None when
    `cache.enabled` is off or the budget is <= 0 — callers then stream every
    pass, the pre-cache behavior."""
    existing = active_cache()
    if existing is not None:
        yield existing
        return
    if not bool(_config.get("cache.enabled")):
        yield None
        return
    # the byte budget (the cache-head/stream-tail prefix split) is a tuning-
    # table knob (`cache.budget_bytes`, docs/design.md §6i); config set()/env
    # on cache.hbm_budget_bytes still win, per the resolution-order contract
    from .. import autotune as _autotune

    tuned = _autotune.lookup("cache.budget_bytes")
    budget = (
        int(tuned) if tuned is not None
        else int(_config.get("cache.hbm_budget_bytes") or 0)
    )
    if budget <= 0:
        yield None
        return
    cache = DeviceBatchCache(budget)
    _stack().append(cache)
    try:
        yield cache
    finally:
        _stack().remove(cache)
        cache.close()
