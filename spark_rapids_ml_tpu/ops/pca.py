#
# PCA fit/transform kernels — the TPU-native replacement for cuml.decomposition.pca_mg
# (reference feature.py:228-269 calls PCAMG.fit with partition descriptors; the
# covariance allreduce happens inside cuML over NCCL).
#
# TPU formulation: one sharded pass builds the dxd covariance from sufficient
# statistics (ops/linalg.py, psum over ICI implicit in the sharded contraction), then a
# replicated symmetric eigendecomposition extracts the top-k components. For d up to a
# few thousand the eigh is tiny next to the covariance matmul, which is the MXU-bound
# hot loop.
#
# Parity notes:
#   * component signs canonicalized so each component's max-|.| element is positive —
#     the reference's signFlip (deprecated/native/src/rapidsml_jni.cu:35) / sklearn
#     svd_flip convention.
#   * transform does NOT center: Spark's PCA projects raw rows, and the reference adds
#     the projected mean back onto cuML's centered output to match
#     (reference feature.py:438-451). We project raw rows directly.
#

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .linalg import weighted_covariance


@functools.partial(jax.jit, static_argnames=("k",))
def _pca_from_cov(cov: jax.Array, k: int):
    eigvals, eigvecs = jnp.linalg.eigh(cov)  # ascending
    # top-k, descending
    vals = eigvals[::-1][:k]
    vecs = eigvecs[:, ::-1][:, :k].T  # (k, d)
    # sign canonicalization: max-|.| element of each component positive
    idx = jnp.argmax(jnp.abs(vecs), axis=1)
    signs = jnp.sign(vecs[jnp.arange(k), idx])
    signs = jnp.where(signs == 0, 1.0, signs)
    vecs = vecs * signs[:, None]
    total_var = jnp.trace(cov)
    return vals, vecs, total_var


def pca_fit(X: jax.Array, w: jax.Array, k: int) -> Dict[str, np.ndarray]:
    """Distributed PCA fit. X: (padded_m, d) rows sharded over the mesh; w: padding/
    sample weights. Returns host-side model attributes (the analog of the model row the
    reference collects, feature.py:260-285)."""
    cov, mean, wsum = weighted_covariance(X, w)
    return pca_attrs_from_cov(cov, mean, wsum, k)


def pca_attrs_from_cov(
    cov: jax.Array, mean: jax.Array, wsum: jax.Array, k: int
) -> Dict[str, np.ndarray]:
    """Model attributes from a (possibly streamed, ops/streaming.py) covariance."""
    vals, vecs, total_var = _pca_from_cov(cov, k)
    n = float(wsum)
    vals_h = np.asarray(vals, dtype=np.float64)
    return {
        "mean": np.asarray(mean),
        "components": np.asarray(vecs),
        "explained_variance": vals_h,
        "explained_variance_ratio": vals_h / float(total_var),
        "singular_values": np.sqrt(np.maximum(vals_h, 0.0) * (n - 1.0)),
    }


@jax.jit
def pca_transform(X: jax.Array, components: jax.Array) -> jax.Array:
    """Spark-parity projection of raw (uncentered) rows: X @ Vᵀ."""
    from ._precision import pdot

    return pdot(X, components.T)
