#
# PCA fit/transform kernels — the TPU-native replacement for cuml.decomposition.pca_mg
# (reference feature.py:228-269 calls PCAMG.fit with partition descriptors; the
# covariance allreduce happens inside cuML over NCCL).
#
# TPU formulation: one sharded pass builds the dxd covariance from sufficient
# statistics (ops/linalg.py, psum over ICI implicit in the sharded contraction), then a
# replicated symmetric eigendecomposition extracts the top-k components. For d up to a
# few thousand the eigh is tiny next to the covariance matmul, which is the MXU-bound
# hot loop.
#
# Parity notes:
#   * component signs canonicalized so each component's max-|.| element is positive —
#     the reference's signFlip (deprecated/native/src/rapidsml_jni.cu:35) / sklearn
#     svd_flip convention.
#   * transform does NOT center: Spark's PCA projects raw rows, and the reference adds
#     the projected mean back onto cuML's centered output to match
#     (reference feature.py:438-451). We project raw rows directly.
#

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.device import compiled_kernel
from .linalg import weighted_covariance


@compiled_kernel("pca.from_cov", static_argnames=("k",))
def _pca_from_cov(cov: jax.Array, k: int):
    eigvals, eigvecs = jnp.linalg.eigh(cov)  # ascending
    # top-k, descending
    vals = eigvals[::-1][:k]
    vecs = eigvecs[:, ::-1][:, :k].T  # (k, d)
    # sign canonicalization: max-|.| element of each component positive
    idx = jnp.argmax(jnp.abs(vecs), axis=1)
    signs = jnp.sign(vecs[jnp.arange(k), idx])
    signs = jnp.where(signs == 0, 1.0, signs)
    vecs = vecs * signs[:, None]
    total_var = jnp.trace(cov)
    return vals, vecs, total_var


def use_fused_gram(n_cols: int, unit_weight: bool, dtype=jnp.float32) -> bool:
    """Whether the fused one-X-read pallas Gram kernel (ops/pallas_xtwx.py) should
    carry this covariance/normal-equation fit.

    The SEMANTIC requirements — prefix-mask unit weights, a feature width inside
    the kernel's VMEM budget, f32 data (the kernel accumulates via bf16 splits
    into f32; an f64 fit must keep the XLA path the user asked for) — are never
    overridable. The `pallas_xtwx` config only steers the remaining heuristics:
    "0" forces the XLA path, "1" skips the TPU-platform check (tests/interpret),
    "auto" requires a real TPU backend."""
    from .. import config as _config

    mode = str(_config.get("pallas_xtwx")).lower()
    if mode not in ("0", "false", "off", "1", "true", "on", "auto"):
        raise ValueError(
            f"pallas_xtwx must be '0', '1' or 'auto', got '{mode}'."
        )
    if mode in ("0", "false", "off"):
        return False
    from .pallas_xtwx import MAX_FUSED_COLS

    if not (
        unit_weight
        and n_cols <= MAX_FUSED_COLS
        and jnp.dtype(dtype) == jnp.float32
    ):
        return False
    if mode in ("1", "true", "on"):
        return True
    return jax.devices()[0].platform == "tpu"


def covariance_for_fit(
    X: jax.Array, w: jax.Array, mesh=None, unit_weight: bool = False
):
    """Covariance dispatch for estimator fits: the fused pallas kernel when the
    measured win applies (see use_fused_gram), else the XLA sufficient-statistics
    pass. Both return (cov, mean, wsum) with identical semantics."""
    if use_fused_gram(X.shape[1], unit_weight, dtype=X.dtype):
        from ._precision import parity_precision
        from .pallas_xtwx import covariance_prefix_mask

        # force-on ("1") off-TPU is the tests' escape hatch: Mosaic can't lower
        # for CPU/GPU backends, so run the kernel's interpreter there
        interpret = jax.devices()[0].platform != "tpu"
        return covariance_prefix_mask(
            X, w, mesh=mesh, precision=parity_precision(), interpret=interpret
        )
    return weighted_covariance(X, w)


def pca_fit(
    X: jax.Array, w: jax.Array, k: int, mesh=None, unit_weight: bool = False
) -> Dict[str, np.ndarray]:
    """Distributed PCA fit. X: (padded_m, d) rows sharded over the mesh; w: padding/
    sample weights. Returns host-side model attributes (the analog of the model row the
    reference collects, feature.py:260-285)."""
    cov, mean, wsum = covariance_for_fit(X, w, mesh=mesh, unit_weight=unit_weight)
    return pca_attrs_from_cov(cov, mean, wsum, k)


def pca_attrs_from_cov(
    cov: jax.Array, mean: jax.Array, wsum: jax.Array, k: int
) -> Dict[str, np.ndarray]:
    """Model attributes from a (possibly streamed, ops/streaming.py) covariance."""
    vals, vecs, total_var = _pca_from_cov(cov, k)
    n = float(wsum)
    vals_h = np.asarray(vals, dtype=np.float64)
    return {
        "mean": np.asarray(mean),
        "components": np.asarray(vecs),
        "explained_variance": vals_h,
        "explained_variance_ratio": vals_h / float(total_var),
        "singular_values": np.sqrt(np.maximum(vals_h, 0.0) * (n - 1.0)),
    }


@compiled_kernel("pca.transform")
def pca_transform(X: jax.Array, components: jax.Array) -> jax.Array:
    """Spark-parity projection of raw (uncentered) rows: X @ Vᵀ."""
    from ._precision import pdot

    return pdot(X, components.T)
