#
# Pallas TPU kernel: fused Gram accumulation — S2 = XᵀX, s1 = colsum(X) over the
# valid-row prefix, in ONE streaming read of X.
#
# This is the hot op of the PCA covariance fit (the TPU replacement for PCAMG.fit's
# in-cuML covariance allreduce, reference python/src/spark_rapids_ml/feature.py:228-253)
# and — via `normal_eq_prefix_mask` — of the unit-weight normal-equation LinReg fit
# (the XᵀWy term rides along as a tile-aligned (blk/128, 128) label operand, NOT the
# (blk, 1) layout documented below as poison, so one X read yields XᵀX, Xᵀy, and yᵀy
# together; reference regression.py:548-558). Two measured facts (v5e, 12M x 128 f32,
# steady-state
# marginal rate — single calls carry ~67 ms of tunnel dispatch+sync overhead) shape the
# design:
#
#   * The XLA formulation (ops/linalg.py::weighted_covariance) runs at ~16 ms/pass:
#     the lhs (w-scaled X) and rhs (X) stream from HBM independently, so X crosses
#     HBM twice — XLA is AT its own two-read roofline (~740 GB/s), and no XLA
#     rewrite gets below it.
#   * A w vector operand is poison for the pallas kernel: a (blk, 1) f32 block pads
#     to 128 lanes in VMEM, so its tile footprint equals the X block itself and the
#     DMA does a layout-converting scatter — measured 25.7 ms/pass WITH the w operand
#     vs 8.2 ms/pass (93% of the single-read HBM roofline) without it.
#
# Hence: the kernel takes NO weight vector. Row validity is a runtime scalar
# `n_valid` (rows >= n_valid are masked in-kernel via iota compare) — exactly the
# shape of the repo's padding contract, where pad_rows (parallel/partition.py) places
# all padding at the end, so every shard's mask is a {1…1,0…0} prefix mask and
# n_valid = sum(w_local). True per-sample weights fall back to the XLA path.
#
# f32 parity precision is emulated in-kernel via bf16 splitting exactly as in
# ops/pallas_kmeans.py (Mosaic rejects the precision attribute on this toolchain):
# measured 1348 M rows/s at HIGH (3-pass), 722 M rows/s at HIGHEST (6-pass) vs the
# 119 M rows/s this path replaced.
#
# Single-device pallas_call; multi-device wraps per-shard under shard_map + psum
# (the same pattern as ops/pallas_histogram.py / ops/pallas_kmeans.py).
#

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_kmeans import _N_SPLIT, _block_rows, _dot_multipass

# largest feature width the fused kernel accepts: S2 (d, d) plus a double-buffered
# (blk, d) block must fit the ~16 MiB scoped-VMEM budget with the multipass bf16
# copies (d=512: 1 MiB S2 + 2x1 MiB blocks + splits)
MAX_FUSED_COLS = 512


def _xtx_kernel(n_split, nv_ref, s_ref, x_ref, s2_ref, s1_ref):
    """One row block: S2 += Xbᵀ Xb, s1 += colsum(Xb) over valid rows.

    nv_ref holds the runtime valid-row count (rows past it are masked — the ragged
    tail block also loads unspecified values from past the array edge, which the
    same mask zeroes before any arithmetic). s_ref is a CSE guard: pallas_call is
    opaque to XLA, so chaining a varying scalar through it is the only way a
    benchmark loop of identical passes doesn't collapse to one (bench.py uses it;
    production passes 0)."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        s2_ref[...] = jnp.zeros_like(s2_ref) + s_ref[0, 0]
        s1_ref[...] = jnp.zeros_like(s1_ref)

    Xb = x_ref[...]  # (B, d)
    row0 = b * Xb.shape[0]
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (Xb.shape[0], 1), 0)
    # select, don't multiply: the edge block's unspecified region can be NaN
    Xb = jnp.where(rows < nv_ref[0, 0], Xb, 0.0)

    s2_ref[...] += _dot_multipass(Xb, Xb, (((0,), (0,)), ((), ())), n_split)
    s1_ref[...] += jnp.sum(Xb, axis=0)[None, :]


@functools.partial(jax.jit, static_argnames=("interpret", "blk", "n_split"))
def _xtx_jit(X, n_valid, cse_guard, interpret: bool, blk: int, n_split: int):
    n, d = X.shape
    s2, s1 = pl.pallas_call(
        functools.partial(_xtx_kernel, n_split),
        grid=((n + blk - 1) // blk,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
            pl.BlockSpec((blk, d), lambda b: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, d), lambda b: (0, 0)),
            pl.BlockSpec((1, d), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(n_valid, jnp.int32).reshape(1, 1),
        jnp.asarray(cse_guard, jnp.float32).reshape(1, 1),
        X,
    )
    return s2, s1[0]


def xtx_pallas(
    X: jax.Array,
    n_valid,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
    interpret: bool = False,
    blk: int | None = None,
    cse_guard=0.0,
):
    """Single-device fused (XᵀX, colsum) over the first `n_valid` rows, one X read.
    Traceable (jit/shard_map-safe); n_valid may be a runtime scalar."""
    n_split = _N_SPLIT[precision]
    return _xtx_jit(
        X,
        n_valid,
        cse_guard,
        interpret,
        blk if blk else _block_rows(X.shape[1], n_split),
        n_split,
    )


def _xtxy_kernel(n_split, nv_ref, s_ref, x_ref, y_ref, s2_ref, s1_ref, xty_ref, ys_ref):
    """One row block of the fused NORMAL-EQUATION pass: S2 += XbᵀXb,
    s1 += colsum(Xb), xty += Xbᵀyb, ys += [Σy, Σy²] — all from one HBM read of X.

    The label enters as a TILE-ALIGNED (blk/128, 128) second operand, NOT as the
    (blk, 1) column the module header documents as poison (3x measured slowdown)
    and NOT as a column appended to X ([X|y] would widen the X block to d+1,
    breaking 128-lane alignment and paying a second lane-tile of VMEM+DMA per
    row). In-kernel it is relayouted to a (1, blk) row — a 16 KiB shuffle per
    2 MiB X block — and XᵀY is one (1,blk)x(blk,d) MXU matmul at the same
    multipass-bf16 precision as S2. Covers `gram_and_xty`'s role for unit-weight
    fits (the header's "unwirable" note predates this layout)."""
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        s2_ref[...] = jnp.zeros_like(s2_ref) + s_ref[0, 0]
        s1_ref[...] = jnp.zeros_like(s1_ref)
        xty_ref[...] = jnp.zeros_like(xty_ref)
        ys_ref[...] = jnp.zeros_like(ys_ref)

    Xb = x_ref[...]  # (B, d)
    B = Xb.shape[0]
    row0 = b * B
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (B, 1), 0)
    # select, don't multiply: the edge block's unspecified region can be NaN
    Xb = jnp.where(rows < nv_ref[0, 0], Xb, 0.0)

    yrow = y_ref[...].reshape(1, B)  # (B/128, 128) -> one long row
    yrows = row0 + jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    yrow = jnp.where(yrows < nv_ref[0, 0], yrow, 0.0)

    s2_ref[...] += _dot_multipass(Xb, Xb, (((0,), (0,)), ((), ())), n_split)
    s1_ref[...] += jnp.sum(Xb, axis=0)[None, :]
    xty_ref[...] += _dot_multipass(yrow, Xb, (((1,), (0,)), ((), ())), n_split)
    ys_ref[...] += jnp.concatenate(
        [jnp.sum(yrow, keepdims=True), jnp.sum(yrow * yrow, keepdims=True)], axis=1
    )


@functools.partial(jax.jit, static_argnames=("interpret", "blk", "n_split"))
def _xtxy_jit(X, y, n_valid, cse_guard, interpret: bool, blk: int, n_split: int):
    n, d = X.shape
    # y rides in 128-lane tiles aligned to the X row blocks; pad to a lane
    # multiple (an O(n) copy of the 1-D label — ~1/d of the X read)
    lanes = 128
    n_pad = ((n + lanes - 1) // lanes) * lanes
    y2d = jnp.pad(y.astype(jnp.float32), (0, n_pad - n)).reshape(-1, lanes)
    s2, s1, xty, ys = pl.pallas_call(
        functools.partial(_xtxy_kernel, n_split),
        grid=((n + blk - 1) // blk,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
            pl.BlockSpec((1, 1), lambda b: (0, 0)),
            pl.BlockSpec((blk, d), lambda b: (b, 0)),
            pl.BlockSpec((blk // lanes, lanes), lambda b: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, d), lambda b: (0, 0)),
            pl.BlockSpec((1, d), lambda b: (0, 0)),
            pl.BlockSpec((1, d), lambda b: (0, 0)),
            pl.BlockSpec((1, 2), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.asarray(n_valid, jnp.int32).reshape(1, 1),
        jnp.asarray(cse_guard, jnp.float32).reshape(1, 1),
        X,
        y2d,
    )
    return s2, s1[0], xty[0], ys[0, 0], ys[0, 1]


def xtxy_pallas(
    X: jax.Array,
    y: jax.Array,
    n_valid,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
    interpret: bool = False,
    blk: int | None = None,
    cse_guard=0.0,
):
    """Single-device fused (XᵀX, colsum(X), Xᵀy, Σy, Σy²) over the first
    `n_valid` rows in ONE X read. Traceable; n_valid may be a runtime scalar."""
    n_split = _N_SPLIT[precision]
    b = blk if blk else _block_rows(X.shape[1], n_split)
    b = max(128, (b // 128) * 128)  # the y operand tiles at 128 rows per lane-row
    return _xtxy_jit(X, y, n_valid, cse_guard, interpret, b, n_split)


def normal_eq_prefix_mask(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    mesh=None,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
    interpret: bool = False,
    cse_guard=0.0,
):
    """Fused normal-equation sufficient statistics for UNIT-WEIGHT data under the
    repo's padding contract: returns (A=XᵀX, b=Xᵀy, x̄, ȳ, Σw, Σy²) — the tuple
    `ops/linear.py::linreg_sufficient_stats` produces, plus yᵀy (for R²/objective
    without another pass) — while reading X from HBM ONCE instead of the XLA
    path's twice (lhs and rhs stream independently; see module header).

    Same eligibility contract as `covariance_prefix_mask`: w must be a {0,1}
    prefix mask per shard (parallel/partition.py::pad_rows places padding at the
    global end). Per-sample weights use the XLA path; callers gate on
    `use_fused_gram` (ops/pca.py). Reference role: the cuML normal-equation
    Gram/XᵀY allreduce inside LinearRegressionMG.fit
    (reference python/src/spark_rapids_ml/regression.py:548-558).
    """
    if mesh is not None and mesh.devices.size > 1:
        from ..utils.jax_compat import shard_map

        from ..parallel.mesh import DATA_AXIS
        from ..parallel.partitioner import partitioner_for

        part = partitioner_for(mesh)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(part.data_spec(2), part.data_spec(1), part.data_spec(1)),
            out_specs=(
                part.state_spec(),
                part.state_spec(),
                part.state_spec(),
                part.state_spec(),
            ),
            check_vma=False,
        )
        def run(x_local, y_local, w_local):
            nv = jnp.sum(w_local.astype(jnp.int32))
            s2, s1, xty, ysum, yty = xtxy_pallas(
                x_local, y_local, nv, precision=precision, interpret=interpret,
                cse_guard=cse_guard,
            )
            return (
                jax.lax.psum(s2, DATA_AXIS),
                jax.lax.psum(s1, DATA_AXIS),
                jax.lax.psum(xty, DATA_AXIS),
                jax.lax.psum(
                    jnp.stack([ysum, yty, nv.astype(jnp.float32)]), DATA_AXIS
                ),
            )

        s2, s1, xty, packed = run(X, y, w)
        ysum, yty, wsum = packed[0], packed[1], packed[2]
    else:
        nv = jnp.sum(w.astype(jnp.int32))
        s2, s1, xty, ysum, yty = xtxy_pallas(
            X, y, nv, precision=precision, interpret=interpret, cse_guard=cse_guard
        )
        wsum = nv.astype(jnp.float32)

    xbar = s1 / wsum
    ybar = ysum / wsum
    return s2, xty, xbar, ybar, wsum, yty


def covariance_prefix_mask(
    X: jax.Array,
    w: jax.Array,
    mesh=None,
    precision: jax.lax.Precision = jax.lax.Precision.HIGHEST,
    interpret: bool = False,
    cse_guard=0.0,
):
    """Fused covariance for UNIT-WEIGHT data under the repo's padding contract.

    Drop-in for ops/linalg.py::weighted_covariance — same (cov, mean, wsum) with the
    unbiased (Σw - 1) denominator — REQUIRING w to be a {0,1} mask whose zeros form a
    suffix of each shard (what parallel/partition.py::pad_rows produces: padding sits
    at the global end, so only the last shard has a zero suffix). Per-sample weights
    or non-suffix masks must use the XLA path; callers gate on that (models/feature.py).
    n_valid per shard is sum(w_local) — an O(n) read of w, ~1% of the X read.
    """
    if mesh is not None and mesh.devices.size > 1:
        from ..utils.jax_compat import shard_map

        from ..parallel.mesh import DATA_AXIS
        from ..parallel.partitioner import partitioner_for

        part = partitioner_for(mesh)

        @functools.partial(
            shard_map,
            mesh=mesh,
            in_specs=(part.data_spec(2), part.data_spec(1)),
            out_specs=(part.state_spec(), part.state_spec(), part.state_spec()),
            check_vma=False,
        )
        def run(x_local, w_local):
            nv = jnp.sum(w_local.astype(jnp.int32))
            s2, s1 = xtx_pallas(
                x_local, nv, precision=precision, interpret=interpret,
                cse_guard=cse_guard,
            )
            return (
                jax.lax.psum(s2, DATA_AXIS),
                jax.lax.psum(s1, DATA_AXIS),
                jax.lax.psum(nv.astype(jnp.float32), DATA_AXIS),
            )

        s2, s1, wsum = run(X, w)
    else:
        nv = jnp.sum(w.astype(jnp.int32))
        s2, s1 = xtx_pallas(
            X, nv, precision=precision, interpret=interpret, cse_guard=cse_guard
        )
        wsum = nv.astype(jnp.float32)

    mean = s1 / wsum
    cov = (s2 - wsum * jnp.outer(mean, mean)) / (wsum - 1.0)
    return cov, mean, wsum
