#
# Matmul precision policy.
#
# On TPU the MXU's DEFAULT precision computes f32 dots via bfloat16 passes — fast, but
# off by ~2^-8, which breaks parity with the reference's fp32/fp64 cuML results (and
# this build's XLA CPU backend shows the same behavior). Statistics that feed model
# attributes (covariance, Gram, gradients, projections) therefore pin
# Precision.HIGHEST (6-pass bf16 ≙ full f32 on MXU). Ops where throughput matters more
# than the last bits (distance matrices in kNN/KMeans assignment) may choose lower
# precision explicitly.
#

import jax

PARITY = jax.lax.Precision.HIGHEST
FAST = jax.lax.Precision.DEFAULT


def pdot(a, b):
    """Parity-precision matmul."""
    import jax.numpy as jnp

    return jnp.matmul(a, b, precision=PARITY)
