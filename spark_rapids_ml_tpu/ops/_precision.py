#
# Matmul precision policy.
#
# On TPU the MXU's DEFAULT precision computes f32 dots via bfloat16 passes — fast, but
# off by ~2^-8, which breaks parity with the reference's fp32/fp64 cuML results (and
# this build's XLA CPU backend shows the same behavior). Statistics that feed model
# attributes (covariance, Gram, gradients, projections) therefore run at the
# config-selected parity precision (`parity_precision`: HIGHEST by default, HIGH as
# a measured 2x opt-in — read at first trace). Ops where throughput matters more
# than the last bits (distance matrices in kNN/KMeans assignment) may choose lower
# precision explicitly.
#

import jax

FAST = jax.lax.Precision.DEFAULT


def parity_precision() -> jax.lax.Precision:
    """The precision for model-attribute matmuls, from the process config
    (`parity_precision`): HIGHEST (6-pass bf16 ≙ full f32) by default; HIGH
    (3-pass, ~2x faster on MXU at ~2^-22 error) as a measured opt-in."""
    from .. import config as _config

    # trace-time read, sanctioned: compiled_kernel folds parity_precision
    # into every AOT cache signature (observability/device.py::_trace_epoch),
    # so a config change re-keys + re-traces — the bake can never go stale
    value = str(_config.get("parity_precision")).lower()  # noqa: purity/config-read — trace-epoch keyed
    if value == "high":
        return jax.lax.Precision.HIGH
    if value == "highest":
        return jax.lax.Precision.HIGHEST
    raise ValueError(
        f"parity_precision must be 'highest' or 'high', got '{value}'."
    )


def pdot(a, b):
    """Parity-precision matmul."""
    import jax.numpy as jnp

    return jnp.matmul(a, b, precision=parity_precision())
